"""Hybrid data plane (``MINIPS_HIER`` ``agg=mesh``,
train/mesh_plane.MeshAggregator + the sharded-PS psH lane) — PR17
acceptance:

- MeshAggregator units: the sorted-keys contract (callers searchsorted
  into the returned keys, including the dedup kernel's no-coalesce
  early-out), degenerate-tier bitwise equivalence with THE shared f64
  dedup kernel, key-space refusal, stats shape;
- stamp folding: a MESH-aggregated flush carries the same hmin/floor
  claims the host f64 path ships — consistency semantics do not depend
  on the reduce backend;
- the 3-rank BSP lockstep drills: degenerate one-device mesh is
  BITWISE equal to ``agg=host``; the device tiers (f32 exact, blk8 +
  residual repay) are BITWISE equal to the flat wire; armed-idle
  (``group=1,agg=mesh``) is bitwise equal to off with all-zero
  counters (HYBRID-IDLE);
- whole-host failure domains: ``expand_to_domains`` units, the
  membership quorum's domain-expanded slow verdicts, and the in-proc
  domain-demotion state machine (leader force-flush → direct; member
  under a dead leader → election fallback replay; the latch is
  sticky — no re-entry this incarnation);
- trainer ``hybrid_stats``: None when off/host-backend, all-zero when
  armed-idle, all-numeric always (the wire_record schema contract);
- the slow tier: seeded SIGKILL of a mesh MEMBER mid-run — the whole
  host group demotes as ONE domain, survivors re-enter direct push and
  finish bitwise with zero lost steps; the flight boxes carry
  ``hier_domain_down``.
"""

from __future__ import annotations

import json
import os
import sys
import threading

import numpy as np
import pytest

from minips_tpu.balance.control_plane import (SuspicionQuorum,
                                              expand_to_domains)
from minips_tpu.balance.hier import HierConfig
from minips_tpu.balance.membership import Membership
from minips_tpu.train.mesh_plane import MeshAggregator
from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                         sum_duplicate_keys)
from tests.test_hier import _LockstepCons, _mk_tables, run_hier_lockstep

# ------------------------------------------------- aggregator units


def test_mesh_agg_degenerate_is_the_host_kernel_sorted(monkeypatch):
    """The degenerate (one-device) tier IS the shared f64 dedup kernel
    in deposit order — including the kernel's no-coalesce early-out,
    which returns the ORIGINAL (unsorted) pairing: reduce() contracts
    SORTED keys, so the tier must restore the order callers
    searchsorted into."""
    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    agg = MeshAggregator(32, 2, slots=2)
    assert agg.m == 1 and agg.mesh is None
    # no duplicates anywhere -> the kernel early-outs unsorted
    agg.deposit(0, np.array([7, 3], np.int64),
                np.arange(4, dtype=np.float32).reshape(2, 2))
    agg.deposit(1, np.array([5, 1], np.int64),
                np.arange(4, 8, dtype=np.float32).reshape(2, 2))
    k, rows, rk, rr = agg.reduce()
    assert k.tolist() == [1, 3, 5, 7]          # SORTED, the contract
    np.testing.assert_array_equal(rows, np.array(
        [[6.0, 7.0], [2.0, 3.0], [4.0, 5.0], [0.0, 1.0]], np.float32))
    assert rk.size == 0 and rr.size == 0       # exact tier: no residual
    # duplicates across slots -> bitwise what the f64 kernel ships
    ks = np.array([3, 7, 3], np.int64)
    gs = np.full((3, 2), 0.1, np.float32)
    agg.deposit(0, ks, gs)
    agg.deposit(1, np.array([7], np.int64),
                np.full((1, 2), 0.2, np.float32))
    k2, rows2, _, _ = agg.reduce()
    ek, eg, _ = sum_duplicate_keys(
        np.concatenate([ks, [7]]),
        np.concatenate([gs, np.full((1, 2), 0.2, np.float32)]), 2)
    assert k2.tolist() == sorted(ek.tolist()) == [3, 7]
    np.testing.assert_array_equal(rows2, eg)
    st = agg.stats()
    assert st["backend"] == "host-degenerate"
    assert st["comm"] == "float32"             # what it ships, exactly
    assert st["reduces"] == 2 and st["rows_reduced"] == 6
    assert st["collective_bytes"] == 0         # nothing crossed devices


def test_mesh_agg_refuses_keys_outside_the_space_and_empty_reduce():
    agg = MeshAggregator(16, 2, slots=1)
    with pytest.raises(ValueError, match="key space"):
        agg.deposit(0, np.array([16], np.int64),
                    np.zeros((1, 2), np.float32))
    with pytest.raises(ValueError, match="key space"):
        agg.deposit(0, np.array([-1], np.int64),
                    np.zeros((1, 2), np.float32))
    agg.deposit(0, np.zeros(0, np.int64), np.zeros((0, 2), np.float32))
    k, rows, rk, rr = agg.reduce()             # nothing staged
    assert k.size == 0 and rows.shape == (0, 2)
    assert rk.size == 0 and rr.shape == (0, 2)
    assert agg.reduces == 0                    # an idle flush is free
    with pytest.raises(ValueError, match="comm"):
        MeshAggregator(16, 2, slots=2, comm="int4")


def test_mesh_agg_device_tier_matches_host_kernel(monkeypatch):
    """The REAL device path (conftest arms 8 host devices): COO stage →
    segment-sum densify → reduce-scatter. The f32 tier must match the
    host kernel's sums on disjoint-per-slot keys, and the grow-only
    stack length must never shrink (the compile-thrash guard)."""
    monkeypatch.delenv("MINIPS_HIER_MESH_DEVS", raising=False)
    agg = MeshAggregator(32, 4, slots=2, comm="float32")
    assert agg.m == 2 and agg.stats()["backend"] == "mesh"
    rng = np.random.default_rng(17)
    k0 = np.array([1, 9, 1, 30], np.int64)     # in-slot duplicate
    g0 = rng.standard_normal((4, 4)).astype(np.float32)
    k1 = np.array([9, 2], np.int64)            # cross-slot duplicate
    g1 = rng.standard_normal((2, 4)).astype(np.float32)
    agg.deposit(0, k0, g0)
    agg.deposit(1, k1, g1)
    k, rows, rk, rr = agg.reduce()
    ek, eg, _ = sum_duplicate_keys(np.concatenate([k0, k1]),
                                   np.concatenate([g0, g1]), 4)
    order = np.argsort(ek, kind="stable")
    assert k.tolist() == ek[order].tolist()
    np.testing.assert_allclose(rows, eg[order], rtol=0, atol=1e-6)
    assert rk.size == 0                        # f32: exact, no residual
    assert agg.collective_bytes > 0            # the exchange is counted
    L0 = agg._L
    agg.deposit(0, np.array([5], np.int64), np.ones((1, 4), np.float32))
    agg.reduce()
    assert agg._L == L0                        # grow-only, never shrinks
    assert agg.peak_stage_bytes > 0


# ------------------------------------------------------- stamp folding


def test_mesh_aggregate_stamp_is_min_over_contributors(monkeypatch):
    """Same drill as the host-path stamp test (tests/test_hier.py) with
    the MESH backend flushing: the psP head must carry the identical
    hmin = min over contributors' clocks and the identical boundary
    floor claims — the reduce backend is invisible to consistency."""
    from tests.conftest import mk_loopback_buses

    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "ms", "group=2,agg=mesh")
        t0 = tables[0]                       # leader of group {0, 1}
        sent = []
        real_send = t0.bus.send

        def spy(dest, kind, head, blob=b"", **kw):
            if kind.startswith("psP:"):
                sent.append((dest, dict(head)))
            return real_send(dest, kind, head, blob=blob, **kw)

        t0.bus.send = spy
        _LockstepCons.clocks = [5, 3, 5]
        k0 = np.array([65, 70], np.int64)
        g0 = np.ones((2, 2), np.float32)
        t0._hier_contribute(0, 2, k0, g0)    # my own slice, clk 5
        k1 = np.array([72, 80], np.int64)
        g1 = np.full((2, 2), 2.0, np.float32)
        blob = k1.tobytes() + g1.tobytes()
        t0._on_hier(1, {"op": "c", "o": 2, "n": 2, "clk": 3,
                        "__blob__": blob, **t0._cfg_header()})
        t0._on_hier(1, {"op": "b", "f": 9})
        t0.hier_boundary()                   # own floor = clk + 1 = 6
        aggs = [h for _, h in sent if "hmin" in h]
        assert len(aggs) == 1, sent
        head = aggs[0]
        assert head["hmin"] == 3             # min(5, 3) — backend-free
        floors = dict(zip(head["hfr"], head["hfv"]))
        assert floors == {0: 6, 1: 9}
        assert t0.hier_counters["agg_frames"] == 1
        assert t0.hier_counters["agg_rows"] == 4
        # and the mesh backend demonstrably did the reduce
        assert t0.hier_counters["mesh_reduces"] == 1
        assert t0.hier_counters["mesh_agg_fallbacks"] == 0
        assert t0._hier_mesh is not None
        assert t0._hier_mesh.stats()["backend"] == "host-degenerate"
    finally:
        for b in buses:
            b.close()


# -------------------------------------------------- lockstep bitwise


@pytest.fixture(scope="module")
def flat_lockstep():
    return run_hier_lockstep("")


def test_hybrid_degenerate_mesh_is_bitwise_equal_to_host_agg(
        flat_lockstep, monkeypatch):
    """Satellite pin: a one-device mesh (``MINIPS_HIER_MESH_DEVS=1``)
    runs the SAME f64 dedup kernel the host backend runs, in the same
    deposit order — bitwise equal to ``agg=host`` (which is itself
    pinned bitwise to the flat wire), with the mesh lane engaged."""
    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    flat, _ = flat_lockstep
    host_stats: dict = {}
    host, lost_h = run_hier_lockstep("group=2", stats=host_stats)
    mesh_stats: dict = {}
    mesh, lost_m = run_hier_lockstep("group=2,agg=mesh",
                                     stats=mesh_stats)
    assert lost_h == [0, 0, 0] and lost_m == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(host[r], mesh[r])
        np.testing.assert_array_equal(flat[r], mesh[r])
    assert mesh_stats["mesh_reduces"] > 0      # the backend engaged
    assert mesh_stats["mesh_agg_fallbacks"] == 0
    assert mesh_stats["domain_demotions"] == 0
    assert mesh_stats["agg_frames"] == host_stats["agg_frames"]
    assert mesh_stats["l2_tx_bytes"] == host_stats["l2_tx_bytes"]
    assert host_stats["mesh_reduces"] == 0     # host backend: none


def test_hybrid_device_f32_tier_is_bitwise_equal_to_flat(
        flat_lockstep, monkeypatch):
    """THE tentpole bitwise pin, exact tier: shm pre-reduce → device
    reduce-scatter over the (conftest-armed) host mesh, f32 comm —
    bitwise the flat wire's state, reduces on REAL devices."""
    monkeypatch.delenv("MINIPS_HIER_MESH_DEVS", raising=False)
    monkeypatch.setenv("MINIPS_HIER_MESH_COMM", "float32")
    flat, _ = flat_lockstep
    stats: dict = {}
    mesh, lost = run_hier_lockstep("group=2,agg=mesh", stats=stats)
    assert lost == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], mesh[r])
    assert stats["mesh_reduces"] > 0
    assert stats["mesh_agg_fallbacks"] == 0


def test_hybrid_device_blk8_tier_is_bitwise_equal_to_flat(
        flat_lockstep, monkeypatch):
    """THE tentpole bitwise pin, quantized tier: the blk8 exchange's
    quantization error comes back as reduce()'s residual and — with an
    exact push wire — is repaid f32 within the SAME flush, so the
    owner's applied state is bitwise the flat wire's."""
    monkeypatch.delenv("MINIPS_HIER_MESH_DEVS", raising=False)
    monkeypatch.setenv("MINIPS_HIER_MESH_COMM", "blk8")
    flat, _ = flat_lockstep
    stats: dict = {}
    mesh, lost = run_hier_lockstep("group=2,agg=mesh", stats=stats)
    assert lost == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], mesh[r])
    assert stats["mesh_reduces"] > 0
    assert stats["mesh_agg_fallbacks"] == 0


def test_hybrid_armed_idle_is_bitwise_equal_to_off(flat_lockstep,
                                                   monkeypatch):
    """HYBRID-IDLE: ``group=1,agg=mesh`` arms the plane but every
    group is a singleton — no flush ever runs, state is bitwise off,
    and every counter is zero (the zeros-when-idle contract the
    wire_record hybrid block rides)."""
    monkeypatch.delenv("MINIPS_HIER_MESH_DEVS", raising=False)
    flat, _ = flat_lockstep
    stats: dict = {}
    idle, lost = run_hier_lockstep("group=1,agg=mesh", stats=stats)
    assert lost == [0, 0, 0]
    for r in range(3):
        np.testing.assert_array_equal(flat[r], idle[r])
    assert all(v == 0 for v in stats.values()), stats


# --------------------------------------------------- failure domains


def test_expand_to_domains_is_contiguous_and_pure():
    assert expand_to_domains({3}, 2, 4) == {2, 3}
    assert expand_to_domains({0}, 2, 5) == {0, 1}
    assert expand_to_domains({4}, 2, 5) == {4}      # tail singleton
    assert expand_to_domains({2}, 2, 3) == {2}
    assert expand_to_domains({0, 5}, 3, 7) == {0, 1, 2, 3, 4, 5}
    assert expand_to_domains({2}, 1, 4) == {2}      # group<=1 identity
    assert expand_to_domains({2}, 0, 4) == {2}
    assert expand_to_domains(set(), 4, 8) == set()


def _mk_membership_stub(n: int, live: set, group: int) -> Membership:
    """A Membership with exactly the state ``_update_slow_verdicts``
    reads — the quorum-logic unit rig (tests/test_fail_slow.py's
    convention), no trainer or wire behind it."""
    mb = object.__new__(Membership)
    mb._lock = threading.Lock()
    mb._slow_lock = threading.Lock()
    mb.live = set(live)
    mb.dead = set()
    mb.left = set()
    mb.n = n
    mb.slow_quorum = SuspicionQuorum(0)
    mb._domain_group = group
    mb._slow_verdicts = set()
    mb._slow_since = {}
    mb.counters = {"slow_verdicts": 0}
    return mb


def test_membership_slow_verdict_expands_to_the_whole_domain():
    """A quorum-corroborated slow verdict against ONE mesh member
    implicates its whole contiguous host group — and clears with it:
    domain verdicts are recomputed from the base set every pass, never
    latched (the demotion bias must lift when the corroboration
    does)."""
    mb = _mk_membership_stub(4, {0, 1, 2, 3}, group=2)
    # 3 of 4 live ranks corroborate rank 3 (quorum_needed = 3)
    mb.slow_quorum.mark_local(3, True)
    mb.slow_quorum.vote(1, [3])
    mb.slow_quorum.vote(2, [3])
    mb._update_slow_verdicts()
    assert mb.slow_view() == {2, 3}            # 3's verdict drags 2
    assert mb.counters["slow_verdicts"] == 2
    # one voter retracts -> below quorum -> base verdict clears AND
    # the domain expansion lifts with it
    mb.slow_quorum.vote(1, [])
    mb._update_slow_verdicts()
    assert mb.slow_view() == set()
    assert mb._slow_since == {}
    # domains off (group=1): the same ballots convict only rank 3
    mb2 = _mk_membership_stub(4, {0, 1, 2, 3}, group=1)
    mb2.slow_quorum.mark_local(3, True)
    mb2.slow_quorum.vote(1, [3])
    mb2.slow_quorum.vote(2, [3])
    mb2._update_slow_verdicts()
    assert mb2.slow_view() == {3}


def test_membership_domain_expansion_skips_dead_ranks():
    """The expansion implicates LIVE peers only — a dead domain peer
    is the death quorum's problem, not a slow verdict."""
    mb = _mk_membership_stub(4, {0, 1, 3}, group=2)
    mb.dead = {2}
    mb.slow_quorum.mark_local(3, True)
    mb.slow_quorum.vote(1, [3])
    mb._update_slow_verdicts()                 # quorum of {0,1,3} = 2
    assert mb.slow_view() == {3}               # 2 is dead: not dragged


# --------------------------------------- in-proc domain demotion


def test_domain_demote_leader_force_flushes_then_goes_direct(
        monkeypatch):
    """A mesh MEMBER dies: the leader's whole host is one failure
    domain — the latch trips, the leader force-flushes its buckets
    (its own contributions have no retained copy; the flush is their
    only exit), goes direct, and never re-enters this incarnation."""
    from tests.conftest import mk_loopback_buses

    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "dd", "group=2,agg=mesh")
        t0 = tables[0]                       # leader of group {0, 1}
        _LockstepCons.clocks = [1, 1, 1]
        t0._hier_contribute(0, 2, np.array([65, 70], np.int64),
                            np.ones((2, 2), np.float32))
        assert t0._hier_buckets              # mass pending in-tree
        t0._dead_ranks.add(1)                # the member is convicted
        t0._hier_poll()
        assert t0._hier_domain_down and t0._hier_direct
        h = t0.hier_counters
        assert h["domain_demotions"] == 1
        assert h["fallbacks"] == 1
        assert h["agg_frames"] == 1          # the force-flush shipped
        assert not t0._hier_buckets
        # sticky: polls neither re-demote nor re-enter the tree
        t0._hier_poll()
        assert h["domain_demotions"] == 1 and t0._hier_direct
    finally:
        for b in buses:
            b.close()


def test_domain_demote_member_replays_when_the_leader_is_the_dead_one(
        monkeypatch):
    """The dead rank IS the leader: the member's domain latch trips
    and the election fallback replays the retained window direct —
    zero lost steps, the floor waiver rides after the re-pushes."""
    from tests.conftest import mk_loopback_buses

    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "dm", "group=2,agg=mesh")
        t1 = tables[1]                       # member under leader 0
        _LockstepCons.clocks = [1, 1, 1]
        t1._hier_contribute(0, 2, np.array([72, 80], np.int64),
                            np.ones((2, 2), np.float32))
        assert len(t1._hier_retained) == 1
        t1._dead_ranks.add(0)                # the LEADER is convicted
        t1._hier_poll()
        assert t1._hier_domain_down and t1._hier_direct
        h = t1.hier_counters
        assert h["domain_demotions"] == 1
        assert h["fallbacks"] == 1
        assert h["repushed_steps"] == 1      # the window replayed
        assert not t1._hier_retained
        with t1._hier_lock:
            assert t1._hier_leader == 1      # leads itself now
        # sticky even though the new leader (itself) is live
        t1._hier_poll()
        assert h["domain_demotions"] == 1 and t1._hier_direct
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------ trainer-level stats


def test_trainer_hybrid_stats_off_vs_idle_vs_engaged(monkeypatch):
    """wire_record's ``hybrid`` block contract: None when hier is off
    OR the host backend is configured; ALL-ZERO when armed-idle;
    all-NUMERIC always, so sweep tooling diffs arms field-by-field."""
    tr = object.__new__(ShardedPSTrainer)
    tr.hier_cfg = None
    assert tr.hybrid_stats() is None           # hier off
    tr.hier_cfg = HierConfig.parse("group=2")
    assert tr.hybrid_stats() is None           # host f64 backend
    tr.hier_cfg = HierConfig.parse("group=1,agg=mesh")
    tr.tables = {}
    st = tr.hybrid_stats()
    assert st is not None
    assert all(isinstance(v, int) for v in st.values()), st
    assert all(v == 0 for v in st.values()), st
    assert set(st) == {"backend_mesh", "mesh_reduces", "rows_reduced",
                       "mesh_collective_bytes", "peak_stage_bytes",
                       "mesh_agg_fallbacks", "domain_demotions",
                       "domain_down"}
    # an engaged table's counters surface through the block
    from tests.conftest import mk_loopback_buses

    monkeypatch.setenv("MINIPS_HIER_MESH_DEVS", "1")
    buses = mk_loopback_buses(3)
    try:
        tables = _mk_tables(buses, "hs", "group=2,agg=mesh")
        t0 = tables[0]
        _LockstepCons.clocks = [1, 1, 1]
        t0._hier_contribute(0, 2, np.array([65], np.int64),
                            np.ones((1, 2), np.float32))
        t0._hier_maybe_flush(force=True)
        tr.tables = {"hs": t0}
        st = tr.hybrid_stats()
        assert st["mesh_reduces"] == 1 and st["rows_reduced"] == 1
        assert st["backend_mesh"] == 0         # degenerate: host tier
        assert st["domain_down"] == 0
        assert all(isinstance(v, int) for v in st.values()), st
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------------ slow tier


@pytest.mark.slow
def test_whole_host_failure_drill_demotes_the_domain_as_one(tmp_path):
    """The whole-host drill: seeded SIGKILL of rank 1 — a mesh MEMBER
    of host group {0,1} — mid-run under ``agg=mesh``. The host is ONE
    failure domain: the surviving leader force-flushes, demotes the
    whole group, and re-enters direct push; survivors finish all steps
    and agree BITWISE with zero lost frames; the flight boxes carry
    ``hier_domain_down``."""
    import tempfile

    from minips_tpu import launch

    run_id = str(92_000_000 + os.getpid())
    flight_dir = os.path.join(tempfile.gettempdir(),
                              f"minips-flight-{run_id}")
    ck = str(tmp_path / "ck")
    rc, events = launch.run_local_job_raw(
        3, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_example",
            "--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", "30", "--batch", "64",
            "--checkpoint-dir", ck, "--checkpoint-every", "5"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                   "XLA_FLAGS":
                       "--xla_force_host_platform_device_count=2",
                   "MINIPS_ELASTIC": "1",
                   "MINIPS_HIER": "group=2,agg=mesh",
                   "MINIPS_CHAOS_KILL": "7:rank=1,step=12",
                   "MINIPS_HEARTBEAT": "interval=0.1,timeout=1.0",
                   "MINIPS_RUN_ID": run_id},
        timeout=240.0, kill_on_failure=False)
    dones = {r: ev[-1] for r, ev in enumerate(events)
             if ev and ev[-1].get("event") == "done"}
    assert set(dones) == {0, 2}, (rc, events)
    for d in dones.values():
        assert d["clock"] == 30
        assert d["max_skew_seen"] <= 3           # SSP bound held
        assert d["frames_dropped"] == 0          # zero poisons
        assert d["wire_frames_lost"] == 0        # zero unrecovered
        assert np.isfinite(d["loss_last"])
        assert d["hier_spec"] == "group=2,agg=mesh"
        assert d["hybrid"] is not None
    # rank 0 led the broken domain: it demoted the group AS ONE and
    # its mesh backend had demonstrably engaged before the kill
    h0 = dones[0]["hybrid"]
    assert h0["domain_demotions"] >= 1
    assert h0["domain_down"] == 1
    assert h0["backend_mesh"] == 1               # 2 devices were armed
    assert h0["mesh_reduces"] >= 1
    assert h0["mesh_agg_fallbacks"] == 0
    assert dones[0]["hier"]["fallbacks"] >= 1    # re-entered direct
    # rank 2's singleton group never had a domain to lose
    assert dones[2]["hybrid"]["domain_demotions"] == 0
    # survivors agree BITWISE on the final table
    sums = [d["param_sum"] for d in dones.values()]
    norms = [d["param_norm"] for d in dones.values()]
    assert sums[0] == sums[1] and norms[0] == norms[1], (sums, norms)
    # the post-mortem box carries the domain demotion with its WHY
    path = os.path.join(flight_dir, "flight-rank0.json")
    assert os.path.exists(path), os.listdir(flight_dir)
    doc = json.load(open(path))
    downs = [e for e in doc["events"]
             if e["kind"] == "hier_domain_down"]
    assert downs, sorted({e["kind"] for e in doc["events"]})
    assert downs[0]["args"]["gone"] == [1]
    assert downs[0]["args"]["group"] == [0, 1]
