"""Device-mesh bootstrap — the rebuild of Engine::StartEverything topology.

The reference boots one process per node, each hosting server threads +
worker threads, glued by a global id-mapper and a ZeroMQ mailbox (SURVEY.md
§3.1). On TPU the topology is a ``jax.sharding.Mesh``: every device is both
a "worker" (computes grads on its data shard) and a "server" (owns a
contiguous shard of every table — FlexPS-style colocation becomes literal
SPMD). SimpleIdMapper is replaced by mesh coordinates (SURVEY.md §2
"SimpleIdMapper").

Axes:
- ``data`` — the worker/data-parallel axis; also the server-shard axis
  (parameters are range-partitioned along it, the PS analog of
  weight-update sharding, PAPERS.md arXiv 2004.13336).
- ``model`` — reserved, size 1 by default. The reference has no TP/PP/SP/EP
  (SURVEY.md §2.2) but the mesh must not structurally preclude them
  (SURVEY.md §5.7).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    num_workers: Optional[int] = None,
    *,
    model_size: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``Mesh`` with axes ``(data, model)``.

    ``num_workers`` defaults to all available devices / ``model_size``. This
    is the moral equivalent of the reference's hostfile + worker allocation
    (SURVEY.md §1 L7): the mesh defines who computes and who owns which
    parameter range, with no process bootstrapping needed on a single host
    (multi-host adds ``jax.distributed.initialize`` upstream, see
    minips_tpu/comm/cluster.py).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_workers is None:
        num_workers = len(devs) // model_size
    need = num_workers * model_size
    if need > len(devs):
        raise ValueError(
            f"mesh ({num_workers}x{model_size}) needs {need} devices, "
            f"have {len(devs)}"
        )
    grid = np.array(devs[:need]).reshape(num_workers, model_size)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def local_mesh_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


def padded_size(n: int, shards: int) -> int:
    """Smallest multiple of ``shards`` >= n (range-partition padding)."""
    return shards * math.ceil(max(n, 1) / shards)
