"""Distributed smoke tests — N REAL processes over loopback zmq.

Tiering note: the mid-size smokes (~13-18s each) run in the FAST tier to
keep the slow tier inside the driver's ~560s budget (VERDICT r1 weak #6
discipline); only the longest drills (SSP-vs-BSP wall-clock, W&D
ssp-staleness, kill/resume in test_fault_recovery.py) stay @slow.

The reference's distributed smoke story: run the launch scripts against a
hostfile of localhost entries, N processes, real sockets (SURVEY.md §4).
These tests do exactly that: minips_tpu.launch spawns
apps/ssp_lr_example.py workers that exchange parameter deltas + clocks over
the ControlBus, and we assert the three consistency contracts:

- BSP: lockstep (pre-gate skew <= 1), replicas agree, loss falls.
- SSP(s): a straggler forces gate waits on fast ranks, yet observed skew
  never exceeds s+1 (skew is measured before the gate closes the gap, so
  the admission-time bound s shows up as s+1 pre-gate) and replicas agree.
- ASP: nobody ever waits; still converges on IID shards.

Replica agreement after finalize() is the PS invariant: additive deltas
commute, so every process's merged state matches up to float reorder noise.
"""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

from minips_tpu import launch

APP = "minips_tpu.apps.ssp_lr_example"


def run_job(n: int, extra: list[str], iters: int = 30,
            timeout: float = 240.0, env_extra: dict | None = None
            ) -> list[dict]:
    """Launch n local worker processes, harvest one JSON line per rank
    (the shared spawn/harvest protocol lives in launch.run_local_job)."""
    env_patch = {"MINIPS_FORCE_CPU": "1",
                 "JAX_PLATFORMS": "cpu"}
    env_patch.update(env_extra or {})
    return launch.run_local_job(
        n, [sys.executable, "-m", APP, "--iters", str(iters)] + extra,
        base_port=None, env_extra=env_patch, timeout=timeout)


def assert_replicas_agree(results: list[dict]) -> None:
    sums = [r["param_sum"] for r in results]
    norms = [r["param_norm"] for r in results]
    assert max(sums) - min(sums) < 1e-4, sums
    assert max(norms) - min(norms) < 1e-4, norms


def test_bsp_lockstep_three_processes():
    res = run_job(3, ["--mode", "bsp"])
    for r in res:
        assert r["event"] == "done"
        assert r["loss_last"] < r["loss_first"]
        assert r["max_skew_seen"] <= 1          # lockstep
        assert r["deltas_applied"] == 2 * 30    # every peer's every step
    assert_replicas_agree(res)


def test_ssp_straggler_bounded_staleness():
    s = 2
    res = run_job(3, ["--mode", "ssp", "--staleness", str(s),
                      "--slow-rank", "1", "--slow-ms", "40"])
    for r in res:
        assert r["event"] == "done"
        assert r["max_skew_seen"] <= s + 1      # the SSP contract
    # the straggler makes at least one fast rank hit the gate
    assert sum(r["gate_waits"] for r in res if r["rank"] != 1) > 0
    assert_replicas_agree(res)


@pytest.mark.slow
def test_asp_never_waits():
    res = run_job(3, ["--mode", "asp", "--slow-rank", "2",
                      "--slow-ms", "20"])
    for r in res:
        assert r["event"] == "done"
        assert r["gate_waits"] == 0             # ASP never blocks
        assert r["loss_last"] < r["loss_first"]
    assert_replicas_agree(res)


@pytest.mark.slow
def test_ssp_on_native_mailbox():
    """The full multi-process SSP job over the C++ TCP mailbox instead of
    pyzmq (MINIPS_BUS=native) — same consistency contracts must hold."""
    from minips_tpu.comm.native_bus import NativeControlBus

    if not NativeControlBus.available():
        pytest.skip("native mailbox unavailable")
    s = 2
    res = run_job(3, ["--mode", "ssp", "--staleness", str(s),
                      "--slow-rank", "1", "--slow-ms", "40"],
                  env_extra={"MINIPS_BUS": "native"})
    for r in res:
        assert r["event"] == "done"
        assert r["max_skew_seen"] <= s + 1
    assert_replicas_agree(res)


def test_ssp_mlp_staleness4():
    """BASELINE.json config 2 — 3-layer MLP (MNIST-shaped), SSP s=4 —
    through the same SSPTrainer: skew bounded, replicas agree, loss falls."""
    res = run_job(3, ["--model", "mlp", "--mode", "ssp", "--staleness", "4",
                      "--lr", "0.05", "--slow-rank", "1", "--slow-ms", "30"])
    for r in res:
        assert r["event"] == "done"
        assert r["max_skew_seen"] <= 5       # s+1 pre-gate bound
        assert r["loss_last"] < r["loss_first"]
    assert_replicas_agree(res)


@pytest.mark.slow
def test_ssp_compressed_push_converges_and_agrees():
    """--compress 0.1: top-k sparsified deltas with error feedback ship a
    fraction of the bytes, yet finalize's dense residual flush makes the
    replicas agree exactly and training still converges."""
    res = run_job(3, ["--mode", "ssp", "--staleness", "2",
                      "--compress", "0.1"], iters=40)
    dense_bytes = None
    for r in res:
        assert r["event"] == "done"
        assert r["loss_last"] < r["loss_first"]
        # dense would ship nparam*4 bytes per push, every step
        if dense_bytes is None:
            dense_bytes = 40 * 65 * 4   # iters * dim+1 params * f32
        assert r["bytes_pushed"] < dense_bytes / 2, r["bytes_pushed"]
    assert_replicas_agree(res)


@pytest.mark.slow
def test_two_processes_converge_better_than_start():
    res = run_job(2, ["--mode", "ssp", "--staleness", "1"], iters=50)
    for r in res:
        assert r["loss_last"] < r["loss_first"] - 0.02
    assert_replicas_agree(res)


@pytest.mark.slow
def test_ssp_beats_bsp_under_transient_stalls():
    """The secondary-metric mechanism (BASELINE.json "SSP wall-clock to
    target loss", bench_ssp.py's measurement): with random per-rank
    stalls, BSP pays the union of all stalls, SSP absorbs them in the
    slack window — less wall-clock, same loss, staleness bound held."""
    jitter = ["--jitter-ms", "50", "--jitter-prob", "0.3"]
    walls, finals, skews = {}, {}, {}
    for mode, s in [("bsp", 0), ("ssp", 4)]:
        rs = run_job(3, ["--mode", mode, "--staleness", str(s)] + jitter,
                     iters=60)
        walls[mode] = max(r["wall_s"] for r in rs)
        finals[mode] = max(r["loss_last"] for r in rs)
        skews[mode] = max(r["max_skew_seen"] for r in rs)
    assert walls["ssp"] < walls["bsp"] * 0.92, (walls, skews)
    assert abs(finals["ssp"] - finals["bsp"]) < 0.05, finals
    assert skews["ssp"] <= 5  # s + 1 pre-gate


def test_run_local_job_tolerates_non_json_brace_lines():
    """ADVICE round 1: a log line that starts with '{' but is not JSON
    (e.g. a dict repr) must be skipped, not crash the harvest loop."""
    code = ("print({'pyrepr': 1}); "
            "print('{not json either'); "
            "import json; print(json.dumps({'ok': 1}))")
    res = launch.run_local_job(1, [sys.executable, "-c", code],
                               base_port=None, timeout=60)
    assert res == [{"ok": 1}]

    # but a malformed FINAL brace line must fail loudly, not silently
    # surface an earlier metrics line as the result
    with pytest.raises(RuntimeError, match="final brace line"):
        launch.run_local_job(
            1, [sys.executable, "-c",
                "import json; print(json.dumps({'metrics': 1})); "
                "print({'result': 2})"],
            base_port=None, timeout=60)


def test_spawn_rank_path_selection(tmp_path, monkeypatch):
    """The fork fast path is opt-in by SHAPE, not a mode switch: only
    CPU-pinned ``python -m`` ranks fork from the jax-warm server —
    anything else (TPU-eligible ranks, script paths, explicit opt-out)
    must stay a plain subprocess, because PJRT plugins and fork don't
    mix and non-module argv can't be re-run via runpy."""
    out = (tmp_path / "o.txt").open("w+")
    argv_m = [sys.executable, "-m", "json.tool", "--help"]
    # a dev shell may export the escape hatches this test manipulates —
    # start from a base env without them so each case sets its own
    base_env = {k: v for k, v in os.environ.items()
                if k not in ("MINIPS_FORCE_CPU", "MINIPS_SPAWN")}

    # no MINIPS_FORCE_CPU in the child env => TPU-eligible => subprocess
    p = launch._spawn_rank(argv_m, dict(base_env), out)
    assert not isinstance(p, launch._ForkProc)
    assert p.wait(timeout=60) == 0

    env_cpu = dict(base_env)
    env_cpu["MINIPS_FORCE_CPU"] = "1"
    # script-path argv (not -m) => subprocess even when CPU-pinned
    p = launch._spawn_rank([sys.executable, "-c", "pass"], env_cpu, out)
    assert not isinstance(p, launch._ForkProc)
    assert p.wait(timeout=60) == 0

    # explicit opt-out wins over eligibility
    monkeypatch.setenv("MINIPS_SPAWN", "subprocess")
    p = launch._spawn_rank(argv_m, env_cpu, out)
    assert not isinstance(p, launch._ForkProc)
    assert p.wait(timeout=60) == 0
    monkeypatch.delenv("MINIPS_SPAWN")

    # the eligible shape forks; exit code and output land like a
    # subprocess's would (argparse error => rc 2, message in the file)
    fout = (tmp_path / "f.txt").open("w+")
    p = launch._spawn_rank(
        [sys.executable, "-m", "minips_tpu.launch", "--n", "0"],
        env_cpu, fout)
    assert isinstance(p, launch._ForkProc)
    assert p.wait(timeout=120) == 2  # need --hostfile or --n
    fout.flush()
    fout.seek(0)
    assert "hostfile" in fout.read()


def test_wide_deep_multiproc_ssp_staleness4():
    """VERDICT r1 #3: the flagship sparse workload (W&D embedding tables)
    on the key-range-sharded PS at SSP staleness 4 — row-sparse wire,
    replica agreement after finalize, AUC above chance and improving."""
    slots = 1 << 18  # Criteo-sized enough that batches touch a sliver
    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.wide_deep_example",
            "--exec", "multiproc", "--consistency", "ssp", "--staleness",
            "4", "--num_slots", str(slots), "--num_iters", "40",
            "--batch_size", "256", "--slow-rank", "1", "--slow-ms", "25"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=300.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r  # no silently-lost gradients
        assert r["loss_last"] < r["loss_first"], r
        assert r["auc"] > 0.65, r["auc"]          # improving vs 0.5 chance
        assert r["max_skew_seen"] <= 5            # s + 1
        # embedding tables partitioned: each process holds ~1/3
        assert r["local_bytes"] * 3 <= r["table_bytes"] * 1.01 + 64
        # row-sparse deltas: embedding wire scales with TOUCHED rows
        # (256 samples * 26 fields * ≤2 remote owners * (wide 12B +
        # emb-row 40B) ≈ 0.7 MB/step), never with table size — a delta
        # relay ships slots*(1+8)*4B * 2 peers ≈ 18.9 MB/step
        full_relay = r["clock"] * slots * 9 * 4 * 2
        assert r["sparse_bytes_pushed"] < full_relay / 20, (
            r["sparse_bytes_pushed"], full_relay)
    fps = [r["param_fingerprint"] for r in res]
    assert max(fps) - min(fps) < 1e-4, fps


def test_wide_deep_multiproc_asp_never_waits():
    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.wide_deep_example",
            "--exec", "multiproc", "--consistency", "asp", "--num_slots",
            "16384", "--num_iters", "30", "--batch_size", "256",
            "--slow-rank", "2", "--slow-ms", "20"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=300.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["gate_waits"] == 0       # ASP never blocks
        assert r["loss_last"] < r["loss_first"], r
    fps = [r["param_fingerprint"] for r in res]
    assert max(fps) - min(fps) < 1e-4, fps


@pytest.mark.slow
def test_wide_deep_multiproc_int8_push_wire():
    """The compressed cross-process push wire on the flagship: identical
    seeds make the two runs push the SAME key streams, so the embedding
    table's wire bytes must land at exactly the codec's ratio — per
    remote row, f32 ships 8 (key) + 4*dim and int8 ships 8 + 4 (scale) +
    dim, i.e. 20/40 at dim 8 — while training still converges with a
    live AUC and bitwise replica agreement (quantization happens on the
    PUSH; owner state and the pulls everyone shares stay f32)."""
    def run(comm):
        return launch.run_local_job(
            2, [sys.executable, "-m", "minips_tpu.apps.wide_deep_example",
                "--exec", "multiproc", "--consistency", "ssp",
                "--staleness", "2", "--num_slots", "16384",
                "--num_iters", "30", "--batch_size", "256",
                "--push-comm", comm],
            base_port=None,
            env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
            timeout=300.0)

    f32 = run("float32")
    q8 = run("int8")
    for r in q8:
        assert r["event"] == "done"
        assert r["push_comm"] == "int8"
        assert r["frames_dropped"] == 0, r
        assert r["loss_last"] < r["loss_first"], r
        assert r["auc"] > 0.6, r["auc"]
    fps = [r["param_fingerprint"] for r in q8]
    assert max(fps) - min(fps) < 1e-4, fps
    # exact wire ratio, rank for rank (same key streams): (8+4+8)/(8+32)
    for rf, rq in zip(f32, q8):
        ratio = rq["emb_bytes_pushed"] / rf["emb_bytes_pushed"]
        assert abs(ratio - 0.5) < 0.02, ratio
    # and compressed pushes must not cost convergence at smoke scale
    assert (max(r["loss_last"] for r in q8)
            < max(r["loss_last"] for r in f32) + 0.05)


@pytest.mark.slow
def test_mf_multiproc_asp_partitioned_factors():
    """MF (BASELINE config 3, 'async ASP') on the key-range-sharded PS:
    user/item factor tables partitioned by id range (exact per-key rows,
    no hashing), ASP pulls never gated, replicas agree after finalize,
    holdout RMSE beats the rating scale's trivial spread."""
    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.mf_example",
            "--exec", "multiproc", "--consistency", "asp",
            "--num_iters", "80", "--batch_size", "256"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=300.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["gate_waits"] == 0       # ASP never blocks
        assert r["loss_last"] < r["loss_first"], r
        assert r["rmse"] is not None and r["rmse"] < 1.5, r["rmse"]
        # factor tables partitioned: each process holds ~1/3
        assert r["local_bytes"] * 3 <= r["table_bytes"] * 1.01 + 6 * 9 * 4
    fps = [r["param_fingerprint"] for r in res]
    assert max(fps) - min(fps) < 1e-4, fps


@pytest.mark.slow
def test_word2vec_multiproc_ssp_partitioned_vocab():
    """Word2vec (BASELINE config 5, 'async push') on the sharded PS with
    the vocab range-partitioned; run at SSP s=2 with a straggler to prove
    the same gate bounds skew for the embedding workload too."""
    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.word2vec_example",
            "--exec", "multiproc", "--consistency", "ssp",
            "--staleness", "2", "--num_iters", "50", "--batch_size", "128",
            "--slow-rank", "1", "--slow-ms", "25"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=300.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["loss_last"] < r["loss_first"], r
        assert r["max_skew_seen"] <= 3    # s + 1
        assert r["local_bytes"] * 3 <= r["table_bytes"] * 1.01 + 6 * 64 * 4
    # the straggler actually engaged the gate on at least one fast rank
    assert any(r["gate_waits"] > 0 for r in res), res
    fps = [r["param_fingerprint"] for r in res]
    assert max(fps) - min(fps) < 1e-4, fps
