"""Heat-aware shard rebalancing for the sharded PS (train/sharded_ps.py).

Two halves, deliberately separable:

- :mod:`minips_tpu.balance.heat` — decayed per-key-block touch counters
  kept by every owner on its serve path (bounded memory, vectorized),
  the observability that makes range-partition skew measurable before
  it is fixed;
- :mod:`minips_tpu.balance.rebalancer` — the coordinator that collects
  per-shard heat, computes a new block→owner assignment (greedy
  bin-pack with hysteresis) and drives the epoch-fenced online
  migration through the tables' wire protocol.

Enabled by ``MINIPS_REBALANCE`` (off by default) — knob reference in
docs/api.md, the protocol walkthrough in docs/architecture.md.
"""

from minips_tpu.balance.heat import HeatAccountant
from minips_tpu.balance.rebalancer import (RebalanceConfig, Rebalancer,
                                           plan_assignment)

__all__ = ["HeatAccountant", "RebalanceConfig", "Rebalancer",
           "plan_assignment"]
