"""Host-side step timing for throughput accounting (SURVEY.md §5.1).

The [T1] primary metric is samples/sec/chip (BASELINE.json:2), so timing is a
first-class utility, not an afterthought. ``StepTimer`` excludes the first
``warmup_steps`` (compile-bearing) steps from steady-state rate computation —
under XLA the first invocation traces + compiles (~20-40s cold on TPU) and
would poison a naive average. ``warmup_steps=0`` counts everything from
construction time.
"""

from __future__ import annotations

import threading
import time


class CommTimers:
    """Per-leg wire timing for the overlapped PS pipeline
    (train/sharded_ps.py): pull issue→last-reply latency vs. the time the
    caller actually spent BLOCKED waiting for it, and push send→ack
    latency. The interesting derived number is ``pull_overlap_fraction``
    — the share of pull latency hidden behind other work (1.0 = fully
    prefetched, 0.0 = fully synchronous); it is what the
    ``overlap_on_off_3proc`` bench sweep exists to move.

    Thread-safe: replies and acks land on the bus receive thread while
    the training thread records its blocked time."""

    def __init__(self):
        self._lock = threading.Lock()
        self.pulls = 0
        self.pull_latency_s = 0.0   # issue → last reply ARRIVED
        self.pull_blocked_s = 0.0   # caller actually waiting in wait()
        self.push_acks = 0
        self.push_ack_latency_s = 0.0  # frame send → ack received
        # pull-leg ROW flow (the dedup + row-cache observables): how many
        # rows callers asked for vs how many actually crossed the wire —
        # the gap is dupes collapsed, own-shard rows, and cache hits
        self.pull_rows_requested = 0
        self.pull_rows_wire = 0
        self.cache_hits = 0
        self.cache_lookups = 0

    def record_pull(self, latency_s: float, blocked_s: float) -> None:
        with self._lock:
            self.pulls += 1
            self.pull_latency_s += max(latency_s, 0.0)
            self.pull_blocked_s += max(blocked_s, 0.0)

    def record_pull_rows(self, requested: int, wire: int,
                         hits: int = 0, lookups: int = 0) -> None:
        """Per-issue row accounting: ``requested`` keys asked for,
        ``wire`` unique miss rows actually sent to owners, and the row
        cache's hit/lookup counts for this issue (0/0 when cache-off)."""
        with self._lock:
            self.pull_rows_requested += int(requested)
            self.pull_rows_wire += int(wire)
            self.cache_hits += int(hits)
            self.cache_lookups += int(lookups)

    def record_push_ack(self, latency_s: float) -> None:
        with self._lock:
            self.push_acks += 1
            self.push_ack_latency_s += max(latency_s, 0.0)

    @property
    def pull_overlap_fraction(self) -> float | None:
        """1 − blocked/latency over all pulls; None before any pull.
        Clamped at 0 (scheduling jitter can make blocked ≥ latency)."""
        with self._lock:
            if self.pull_latency_s <= 0.0:
                return None
            return max(0.0, 1.0 - self.pull_blocked_s
                       / self.pull_latency_s)

    def summary(self) -> dict:
        """Flat JSON-able record for metrics/bench lines."""
        with self._lock:
            out = {
                "pulls": self.pulls,
                "pull_latency_ms_mean": round(
                    1e3 * self.pull_latency_s / self.pulls, 4)
                if self.pulls else None,
                "pull_blocked_ms_mean": round(
                    1e3 * self.pull_blocked_s / self.pulls, 4)
                if self.pulls else None,
                "push_acks": self.push_acks,
                "push_ack_ms_mean": round(
                    1e3 * self.push_ack_latency_s / self.push_acks, 4)
                if self.push_acks else None,
                # rows-local vs rows-wire: requested − wire = dupes +
                # own-shard rows + cache hits served without a frame
                "pull_rows_requested": self.pull_rows_requested,
                "pull_rows_wire": self.pull_rows_wire,
                "pull_rows_local": (self.pull_rows_requested
                                    - self.pull_rows_wire),
                "cache_hits": self.cache_hits,
                "cache_lookups": self.cache_lookups,
                "cache_hit_rate": round(
                    self.cache_hits / self.cache_lookups, 4)
                if self.cache_lookups else None,
            }
        frac = self.pull_overlap_fraction
        out["pull_overlap_fraction"] = (round(frac, 4)
                                        if frac is not None else None)
        return out

    @staticmethod
    def aggregate(timers: "list[CommTimers]") -> dict:
        """One summary over several tables' timers (count-weighted)."""
        agg = CommTimers()
        for t in timers:
            with t._lock:
                agg.pulls += t.pulls
                agg.pull_latency_s += t.pull_latency_s
                agg.pull_blocked_s += t.pull_blocked_s
                agg.push_acks += t.push_acks
                agg.push_ack_latency_s += t.push_ack_latency_s
                agg.pull_rows_requested += t.pull_rows_requested
                agg.pull_rows_wire += t.pull_rows_wire
                agg.cache_hits += t.cache_hits
                agg.cache_lookups += t.cache_lookups
        return agg.summary()


class StepTimer:
    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = max(int(warmup_steps), 0)
        self._steps = 0
        self._samples = 0
        self._t_start: float | None = (
            time.monotonic() if self.warmup_steps == 0 else None)
        self._t_last: float | None = None

    def step(self, n_samples: int) -> None:
        now = time.monotonic()
        self._steps += 1
        if self._steps == self.warmup_steps:
            # last warmup step just finished: steady state begins now
            self._t_start = now
            self._samples = 0
        elif self._steps > self.warmup_steps:
            self._samples += n_samples
        self._t_last = now

    @property
    def steady_seconds(self) -> float:
        if self._t_start is None or self._t_last is None:
            return 0.0
        return max(self._t_last - self._t_start, 0.0)

    @property
    def samples_per_sec(self) -> float:
        s = self.steady_seconds
        return self._samples / s if s > 0 else 0.0
