"""Wire framing — the codec layer every bus backend shares.

The seed wire framed every control head as ``json.dumps(head)`` with the
ndarray blob riding a separate multipart frame. On loopback — where every
bench arm in this repo runs — that JSON round-trip IS the dominant cost
of a frame (ROADMAP item 5): text-encoding per-leg int lists (acks,
seqs, svU block tables, clock vectors) and re-parsing them on the
receive thread costs more than the memcpy the frame exists to move.

This module defines the wire format ONCE, for all backends (zmq, native,
shm):

- **Binary head** (default, ``MINIPS_WIRE_FMT=bin``): a fixed
  struct-packed prefix (magic, version, stream flags, sender, seq, kind)
  followed by a compact TLV tail for the payload dict. Homogeneous int
  lists — the hot fields — pack as raw little-endian int64 arrays
  (one C-speed ``struct.pack`` call, no text). ndarray payloads never
  enter the head at all: they ride the blob slot as raw bytes views
  (``memoryview``/``np.frombuffer`` — no base64, no copy).
- **JSON head** (``MINIPS_WIRE_FMT=json``): the seed codec, kept
  selectable for A/B honesty drills and byte-level debugging.

Receivers never need to know the sender's format: :func:`decode_head`
sniffs the first byte (binary frames open with ``MAGIC``; JSON heads
open with ``{``), so a mixed fleet — one rank on the seed codec —
decodes per frame instead of dying on the first foreign head. TLV
additionally carries raw ``bytes`` values (JSON cannot), which the
reliable channel's retransmit wrapper uses to re-ship binary heads
verbatim.

The TLV decode mirrors JSON's semantic quirks on purpose so handlers
see identical objects whichever codec framed the wire: dict keys are
coerced to ``str`` on encode (``json.dumps`` does this silently) and
tuples decode as lists.

Head-key contract: payload keys ride the TLV tail VERBATIM — there is
no fixed key table to extend, which is what lets a protocol layer add
a stamp without a codec version bump. The per-frame config stamp
(``ws``/``nr``/``dm``/``rb``, train/sharded_ps._cfg_header) grew the
tenancy field ``tb`` this way (tenant/registry.py: the owning table's
1-based tenant id; absent = tenancy off, so an off fleet's frames are
byte-identical to pre-tenancy builds and the small-int TLV path makes
the armed stamp cost three bytes).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Optional, Union

__all__ = ["MAGIC", "wire_fmt_from_env", "encode_head", "encode_head_bin",
           "decode_head", "decode_head_bytes", "dup_msg", "rt_wrap"]

MAGIC = 0xB6  # first byte of every binary head; != ord("{") (0x7B)
_VER = 1

# magic u8 | version u8 | flags u8 (1=bs, 2=ds) | sender i32 | seq i64
# | kind_len u16  — then kind utf8, then the TLV payload
_PRE = struct.Struct("<BBBiqH")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1
_QPACK: dict[int, struct.Struct] = {}  # int-list packers, keyed by count


def _qstruct(n: int) -> struct.Struct:
    """Count-keyed ``<I{n}q`` codec for the int64-list fast path,
    shared by encode and decode — struct's own format cache holds only
    100 entries and clears wholesale when full, and ack/seq list
    lengths vary enough to thrash it. Bounded the same way."""
    s = _QPACK.get(n)
    if s is None:
        if len(_QPACK) >= 1024:
            _QPACK.clear()
        s = _QPACK[n] = struct.Struct(f"<I{n}q")
    return s


def wire_fmt_from_env() -> str:
    """Resolve ``$MINIPS_WIRE_FMT`` (``bin`` default, ``json`` = seed)."""
    fmt = os.environ.get("MINIPS_WIRE_FMT", "bin").strip() or "bin"
    if fmt not in ("bin", "json"):
        raise ValueError(f"MINIPS_WIRE_FMT={fmt!r} (expected bin|json)")
    return fmt


# ------------------------------------------------------------------ encode
_pU32, _pI64, _pF64 = _U32.pack, _I64.pack, _F64.pack


def _enc(out: bytearray, v) -> None:
    t = type(v)
    if t is int:             # the common case first (seqs/reqs/clocks)
        if _I64_MIN <= v <= _I64_MAX:
            out += b"i" + _pI64(v)
        else:                # arbitrary precision: decimal text
            b = str(v).encode()
            out += b"n" + _pU32(len(b)) + b
    elif t is str:
        b = v.encode()
        out += b"s" + _pU32(len(b)) + b
    elif t is bool:          # bool is an int subclass, but type() is exact
        out += b"T" if v else b"F"
    elif t is float:
        out += b"f" + _pF64(v)
    elif v is None:
        out += b"Z"
    elif t is dict:
        out += b"d" + _pU32(len(v))
        for k, item in v.items():
            kb = (k if type(k) is str else _json_key(k)).encode()
            out += _pU32(len(kb)) + kb
            _enc(out, item)
    elif t in (list, tuple):
        n = len(v)
        if n and all(type(x) is int and _I64_MIN <= x <= _I64_MAX
                     for x in v):
            # the hot fast path: acks/seqs/clock vectors pack as one
            # raw int64 array — this is where JSON paid per digit
            # (type() not isinstance(): bool must keep its JSON shape)
            out += b"q" + _qstruct(n).pack(n, *v)
        else:
            out += b"l" + _U32.pack(n)
            for item in v:
                _enc(out, item)
    elif t in (bytes, bytearray, memoryview):
        b = bytes(v)
        out += b"b" + _U32.pack(len(b)) + b
    else:
        raise TypeError(
            f"frame payload value of type {t.__name__} is not wire-"
            "encodable (JSON types + bytes only)")


def _json_key(k) -> str:
    """Match ``json.dumps`` key coercion so both codecs deliver the same
    payload shape to handlers."""
    if k is True:
        return "true"
    if k is False:
        return "false"
    if k is None:
        return "null"
    if isinstance(k, (int, float)):
        return json.dumps(k)
    raise TypeError(f"frame payload dict key {k!r} is not wire-encodable")


def encode_head_bin(head: dict) -> bytes:
    flags, seq = 0, 0
    if "bs" in head:
        flags, seq = 1, int(head["bs"])
    elif "ds" in head:
        flags, seq = 2, int(head["ds"])
    kind = str(head.get("kind", "")).encode()
    out = bytearray(_PRE.pack(MAGIC, _VER, flags,
                              int(head.get("sender", -1)), seq,
                              len(kind)))
    out += kind
    _enc(out, head.get("payload", {}))
    return bytes(out)


def encode_head(head: dict, fmt: str = "bin") -> bytes:
    """Encode a control head on the chosen wire format. The head shape
    is fixed by the backends' ``_emit``: kind, sender, payload, and at
    most one of bs/ds."""
    if fmt == "json":
        return json.dumps(head).encode()
    return encode_head_bin(head)


# ------------------------------------------------------------------ decode
def _dec(buf, off: int):
    tag = buf[off:off + 1]
    off += 1
    if tag == b"i":
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == b"s":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return bytes(buf[off:off + n]).decode(), off + n
    if tag == b"q":
        n = _U32.unpack_from(buf, off)[0]
        # the shared cached struct covers count + values; skip the count
        return (list(_qstruct(n).unpack_from(buf, off)[1:]),
                off + 4 + 8 * n)
    if tag == b"d":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            kl = _U32.unpack_from(buf, off)[0]
            off += 4
            k = bytes(buf[off:off + kl]).decode()
            off += kl
            d[k], off = _dec(buf, off)
        return d, off
    if tag == b"l":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec(buf, off)
            items.append(v)
        return items, off
    if tag == b"f":
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"Z":
        return None, off
    if tag == b"b":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return bytes(buf[off:off + n]), off + n
    if tag == b"n":
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        return int(bytes(buf[off:off + n])), off + n
    raise ValueError(f"bad TLV tag {tag!r} at offset {off - 1}")


def decode_head_bytes(raw: Union[bytes, bytearray, memoryview]
                      ) -> Optional[dict]:
    """Decode a BINARY head; None on any structural damage (the caller
    counts it malformed, like torn JSON)."""
    try:
        magic, ver, flags, sender, seq, klen = _PRE.unpack_from(raw, 0)
        if magic != MAGIC or ver != _VER:
            return None
        off = _PRE.size
        kind = bytes(raw[off:off + klen]).decode()
        off += klen
        payload, off = _dec(raw, off)
        if off != len(raw) or not isinstance(payload, dict):
            return None
        head = {"kind": kind, "sender": sender, "payload": payload}
        if flags == 1:
            head["bs"] = seq
        elif flags == 2:
            head["ds"] = seq
        return head
    except (struct.error, ValueError, UnicodeDecodeError, IndexError):
        return None


def decode_head(raw) -> Optional[dict]:
    """Backend-shared head decode, format-sniffed per frame: binary
    heads open with ``MAGIC``, JSON heads with ``{``. ``str`` input
    (a journaled JSON head re-shipped through a retransmit wrapper)
    decodes as JSON. Returns None for malformed frames — the caller
    counts them (``frames_malformed``) instead of raising on the
    receive thread."""
    if isinstance(raw, str):
        try:
            msg = json.loads(raw)
        except json.JSONDecodeError:
            return None
        return msg if isinstance(msg, dict) else None
    if isinstance(raw, memoryview):
        raw = bytes(raw)
    if raw[:1] == b"{":
        try:
            msg = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None
        return msg if isinstance(msg, dict) else None
    return decode_head_bytes(raw)


# --------------------------------------------------------------- utilities
def rt_wrap(msg: Union[bytes, bytearray, memoryview]) -> dict:
    """The reliable channel's ``__rt`` retransmit payload for a
    journaled encoded head: JSON heads ride as text (``"m"``), binary
    heads as raw bytes (``"m2"`` — TLV carries bytes natively, JSON
    cannot). Defined HERE because two layers must agree on its exact
    shape: comm/reliable.py ships it on NACK, and comm/shm_bus.py's
    record-cap pre-check sizes the very same wrapper so a frame that
    fits at first send can never become unretransmittable."""
    msg = bytes(msg) if not isinstance(msg, bytes) else msg
    return {"m": msg.decode()} if msg[:1] == b"{" else {"m2": msg}


def dup_msg(msg: dict) -> dict:
    """Codec-agnostic deep copy of a decoded head — what the chaos
    injector's duplicate op needs (handlers receive the payload dict
    itself and may mutate it, so the dup must not alias). The seed did
    ``json.loads(json.dumps(msg))``, which double-pays the codec on
    every dup AND raises on binary-only values (bytes in a retransmit
    wrapper). This walks the decoded object instead: no re-encode, any
    wire-encodable value."""
    return {k: _dup(v) for k, v in msg.items()}


def _dup(v):
    t = type(v)
    if t is dict:
        return {k: _dup(x) for k, x in v.items()}
    if t is list:
        return [_dup(x) for x in v]
    if t is tuple:
        return [_dup(x) for x in v]  # JSON parity: tuples decode as lists
    if t is bytearray or t is memoryview:
        return bytes(v)
    return v  # str/int/float/bool/None/bytes: immutable
