"""lm_example — decoder-only LM with optional sequence parallelism.

Beyond-parity app (the reference has no attention models, SURVEY.md §2.2):
demonstrates the framework's long-context path end-to-end. Two layouts:

- ``--layout dp``  (default): batch sharded over the mesh ``data`` axis,
  full attention per shard — ordinary data parallelism.
- ``--layout sp``: BATCH REPLICATED, SEQUENCE sharded over the same axis —
  causal ring attention (K/V rotate over ppermute), positional embeddings
  offset per shard. Identical numerics to dp (tests prove grad parity);
  per-device activation memory scales as T/N, so sequences that cannot fit
  one device train anyway.

Usage: python -m minips_tpu.apps.lm_example --num_iters 200 --layout sp
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.apps.common import app_main
from minips_tpu.core.config import Config, TableConfig, TrainConfig
from minips_tpu.data import synthetic
from minips_tpu.data.loader import BatchIterator
from minips_tpu.models import transformer as tfm
from minips_tpu.parallel.mesh import DATA_AXIS, make_mesh
from minips_tpu.tables.dense import DenseTable
from minips_tpu.train.loop import TrainLoop

DEFAULT = Config(
    table=TableConfig(name="lm", kind="dense", updater="adam", lr=3e-3),
    train=TrainConfig(batch_size=32, num_iters=200),
)

MODEL = dict(vocab=256, dim=64, heads=4, depth=2, max_len=1024)


def _flags(parser):
    parser.add_argument("--layout", default="dp", choices=["dp", "sp"],
                        help="dp: batch sharded; sp: sequence sharded "
                             "(ring attention)")
    parser.add_argument("--seq_len", type=int, default=128)


def run(cfg: Config, args, metrics) -> dict:
    seq_len = getattr(args, "seq_len", 128)
    layout = getattr(args, "layout", "dp")
    mesh = make_mesh()
    n_shards = mesh.shape[DATA_AXIS]
    if seq_len % n_shards:
        raise SystemExit(f"--seq_len {seq_len} must divide by the "
                         f"{n_shards}-way mesh")
    if seq_len > MODEL["max_len"]:
        # the model's static check can't see the GLOBAL length on the sp
        # path (each shard only knows its T_local; the shift is traced),
        # so the app validates it here for both layouts
        raise SystemExit(f"--seq_len {seq_len} exceeds the model's "
                         f"max_len {MODEL['max_len']}")

    data = synthetic.lm_sequences(2048, seq_len, MODEL["vocab"],
                                  seed=cfg.train.seed)
    params = tfm.init(jax.random.PRNGKey(cfg.train.seed), **MODEL)
    table = DenseTable(params, mesh, updater=cfg.table.updater,
                       lr=cfg.table.lr, name=cfg.table.name)
    heads = MODEL["heads"]

    if layout == "dp":
        step = table.make_step(
            functools.partial(tfm.grad_fn, heads=heads),
            batch_spec=P(DATA_AXIS))
        batch_sharding = NamedSharding(mesh, P(DATA_AXIS))

        def prep(batch):
            return jax.device_put({"tokens": jnp.asarray(batch["tokens"])},
                                  batch_sharding)
    else:
        T_local = seq_len // n_shards

        def sp_grad(p, b):
            # batch replicated, sequence sharded: inside shard_map each
            # device sees its token slice; ring attention stitches them
            def shard_loss(p_, inp, tgt):
                shift = jax.lax.axis_index(DATA_AXIS) * T_local
                return tfm.loss_sp(p_, inp, tgt, shift, heads=heads,
                                   reduce="local")
            toks = b["tokens"]
            return jax.value_and_grad(shard_loss)(p, toks["inp"], toks["tgt"])

        # make_step all-gathers params per shard and psum_scatters grads —
        # the same PS shape; only the batch specs change (sequence axis)
        step = table.make_step(
            sp_grad,
            batch_spec={"tokens": {"inp": P(None, DATA_AXIS),
                                   "tgt": P(None, DATA_AXIS)}})
        seq_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

        def prep(batch):
            t = jnp.asarray(batch["tokens"])
            return {"tokens": {
                "inp": jax.device_put(t[:, :-1], seq_sharding),
                "tgt": jax.device_put(t[:, 1:], seq_sharding)}}

    batches = BatchIterator(data, cfg.train.batch_size, seed=cfg.train.seed)
    loop = TrainLoop(lambda b: table.step_inplace(step, prep(b)), batches,
                     metrics=metrics, log_every=cfg.train.log_every,
                     batch_size=cfg.train.batch_size)
    losses = loop.run(cfg.train.num_iters)
    metrics.log(final_loss=losses[-1], layout=layout, seq_len=seq_len,
                tokens_per_sec=loop.timer.samples_per_sec * seq_len)
    return {"losses": losses, "table": table, "layout": layout,
            "samples_per_sec": loop.timer.samples_per_sec}


def main():
    return app_main("lm_example", DEFAULT, run, extra_flags=_flags)


if __name__ == "__main__":
    main()
