"""Decoder-only transformer LM — the rebuild's long-context model family.

The reference has no attention models at all (SURVEY.md §2.2: configs are
LR/MLP/MF/W&D/w2v), so this family is beyond parity: it exists to exercise
the framework's first-class long-context path — causal ring attention
(``parallel/ring_attention.py``) with the sequence axis sharded across the
mesh — inside the same PS machinery (DenseTable fused step) every other
model uses.

Functional plain-dict params like the other model files, so the whole LM
lives in one DenseTable. Matmuls run bfloat16 on the MXU with float32
params; pre-LN blocks, learned positional embeddings, GELU MLP, weight-tied
output head.

Two attention modes, numerically identical:
- ``apply(params, tokens)`` — single-program causal attention (any device).
- ``apply_sp(params, tokens_local, shift, axis_name)`` — call under
  ``shard_map`` with tokens sharded along the sequence axis; attention runs
  as a ring over ``axis_name`` and positional embeddings are indexed by the
  shard's global offset ``shift``.
"""

from __future__ import annotations

import jax

import jax.numpy as jnp

from minips_tpu.utils.jaxcompat import axis_size as _axis_size
from minips_tpu.parallel.mesh import DATA_AXIS
from minips_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention_local,
)
from minips_tpu.utils import jaxcompat


def init(key, *, vocab: int = 256, dim: int = 64, heads: int = 4,
         depth: int = 2, max_len: int = 1024, mlp_mult: int = 4,
         kv_heads: int = None, rope: bool = False):
    """``kv_heads < heads`` builds a grouped-query model (1 = MQA): the
    K/V projection emits ``kv_heads`` heads that every group of
    ``heads // kv_heads`` q-heads shares — the projection weights, the
    attention K/V activations, and (under sp) the ring's ppermute wire
    all shrink by the group factor. ``None``/``heads`` keeps the classic
    fused [dim, 3, dim] qkv layout (same param tree as before GQA).

    ``rope=True`` replaces the learned positional table with rotary
    embeddings (:func:`rope_rotate` on Q/K inside every attention call):
    no ``pos_emb`` params, no ``max_len`` sequence cap — the long-context
    positional scheme (``max_len`` is ignored)."""
    if dim % heads:
        raise ValueError(f"dim {dim} not divisible by heads {heads}")
    gqa = kv_heads is not None and kv_heads != heads
    if gqa and (kv_heads < 1 or heads % kv_heads):
        raise ValueError(f"kv_heads {kv_heads} must be >= 1 and divide "
                         f"heads {heads}")
    hd = dim // heads
    if rope and hd % 2:
        raise ValueError(f"rope needs an even head dim (dim/heads = {hd})")
    ks = iter(jax.random.split(key, 2 + depth))
    scale = dim ** -0.5
    params = {
        "tok_emb": jax.random.normal(next(ks), (vocab, dim)) * scale,
        "ln_f": {"g": jnp.ones(dim), "b": jnp.zeros(dim)},
        "blocks": [],
    }
    if not rope:
        params["pos_emb"] = (jax.random.normal(next(ks), (max_len, dim))
                             * scale)
    else:
        next(ks)  # burn the key so rope=True doesn't reshuffle block init
    for _ in range(depth):
        kq, kp, ki, ko, kk = jax.random.split(next(ks), 5)
        blk = {
            "ln1": {"g": jnp.ones(dim), "b": jnp.zeros(dim)},
            "ln2": {"g": jnp.ones(dim), "b": jnp.zeros(dim)},
            "proj": jax.random.normal(kp, (dim, dim)) * scale,
            "mlp_in": jax.random.normal(ki, (dim, mlp_mult * dim)) * scale,
            "mlp_out": jax.random.normal(ko, (mlp_mult * dim, dim))
                       * (mlp_mult * dim) ** -0.5,
        }
        if gqa:
            # split layout: full-width Q, narrow fused KV ([dim, 2, kv
            # width], axis 1 = (k, v)); head dim contiguous in the last
            # axis so TP shards both at head boundaries
            blk["wq"] = jax.random.normal(kq, (dim, dim)) * scale
            blk["wkv"] = (jax.random.normal(kk, (dim, 2, kv_heads * hd))
                          * scale)
        else:
            # one [dim, 3, dim] tensor, axis 1 = (q, k, v); the last dim
            # is the head dim (heads contiguous), so tensor parallelism
            # can shard it at head boundaries
            blk["qkv"] = jax.random.normal(kq, (dim, 3, dim)) * scale
        params["blocks"].append(blk)
    return params


def _ln(x, p):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * p["g"] + p["b"]


def _dropout(x, rate, key):
    """Inverted dropout; identity when rate is 0 or no key is given
    (eval). ``rate`` is static, ``key`` traced."""
    if not rate or key is None:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _block(h, blk, heads, attn_fn, compute_dtype, psum_axis=None,
           ffn_fn=None, dropout=0.0, rng=None):
    """One pre-LN block. With ``psum_axis`` the block runs Megatron-style
    tensor parallel under shard_map: qkv/mlp_in arrive sharded on their
    OUTPUT feature dim (this device computes heads/k heads and hidden/k
    MLP units), proj/mlp_out on their INPUT dim, and the two row-parallel
    matmuls' partial products are psum'd before each residual add —
    activations stay replicated, two collectives per block.

    ``ffn_fn(blk, x_2d [B*T, D]) -> (y_2d, aux)`` replaces the dense MLP
    (the MoE variant); the dense path reports aux 0. Returns (h, aux)."""
    B, T, _ = h.shape
    tp = 1 if psum_axis is None else _axis_size(psum_axis)
    local_heads = heads // tp
    from jax.ad_checkpoint import checkpoint_name
    x = _ln(h, blk["ln1"]).astype(compute_dtype)
    # q/k/v stay in compute_dtype: the flash kernel runs its dots at the
    # input dtype's MXU rate with f32 accumulation, so a bf16 run keeps
    # bf16 VMEM/HBM traffic end-to-end (upcasting here doubled both and
    # forced f32-rate attention matmuls)
    if "wkv" in blk:
        # grouped-query layout: full-width Q, narrow fused KV; the
        # attention impls map q-head h onto kv head h // g themselves
        q = x @ blk["wq"].astype(compute_dtype)
        kv = jnp.einsum("btd,dce->btce", x,
                        blk["wkv"].astype(compute_dtype))
        # same checkpoint names as the fused layout, so every remat
        # policy ("hybrid_qkv" saves the projections) works unchanged
        q = checkpoint_name(q, "qkv")
        kv = checkpoint_name(kv, "qkv")
        hd = q.shape[-1] // local_heads
        local_kv = kv.shape[-1] // hd
        q = q.reshape(B, T, local_heads, hd)
        k = kv[:, :, 0].reshape(B, T, local_kv, hd)
        v = kv[:, :, 1].reshape(B, T, local_kv, hd)
    else:
        qkv = jnp.einsum("btd,dce->btce", x,
                         blk["qkv"].astype(compute_dtype))
        # named so "hybrid_qkv" can save it — with qkv, attn_out and
        # mlp_hidden all resident, backward recomputes only the attention
        # output projection (2 of 24 D^2-units per block)
        qkv = checkpoint_name(qkv, "qkv")
        q, k, v = (qkv[:, :, i] for i in range(3))
        hd = q.shape[-1] // local_heads
        q = q.reshape(B, T, local_heads, hd)
        k = k.reshape(B, T, local_heads, hd)
        v = v.reshape(B, T, local_heads, hd)
    a = attn_fn(q, k, v).reshape(B, T, -1)
    return _block_tail(h, blk, a, compute_dtype, psum_axis, ffn_fn,
                       dropout, rng)


def _block_tail(h, blk, a, compute_dtype, psum_axis=None, ffn_fn=None,
                dropout=0.0, rng=None):
    """Everything after attention — output projection + residual, then
    MLP (or ``ffn_fn``) + residual. ONE implementation shared by the
    training block above and the KV-cached decode block
    (models/decode.py), so the block math cannot drift between them."""
    from jax.ad_checkpoint import checkpoint_name

    B, T, _ = h.shape
    # named for selective remat: remat="attn" saves exactly this tensor,
    # so the backward never re-runs the attention itself (the priciest
    # recompute per byte: flash kernels + T^2 math) while everything else
    # still recomputes
    a = checkpoint_name(a, "attn_out")
    att = (a.astype(compute_dtype)
           @ blk["proj"].astype(compute_dtype)).astype(jnp.float32)
    if psum_axis is not None:
        att = jax.lax.psum(att, psum_axis)
    if dropout and rng is not None:   # GPT-style residual dropout
        att = _dropout(att, dropout, jax.random.fold_in(rng, 0))
    h = h + att
    if ffn_fn is not None:
        D = h.shape[-1]
        y, aux = ffn_fn(blk, _ln(h, blk["ln2"]).reshape(B * T, D))
        return h + y.reshape(B, T, D), aux
    x = _ln(h, blk["ln2"]).astype(compute_dtype)
    z = x @ blk["mlp_in"].astype(compute_dtype)
    # the [B*T, 4D] PRE-gelu tensor is the bulk of a block's activation
    # memory; the "hybrid" policies save it (with attn_out) so backward
    # skips the expensive up-projection recompute while still shedding
    # the dots-policy tensors that blow HBM at batch 32. It must be the
    # pre-activation: gelu's VJP reads its input, so saving gelu(z)
    # would force the up-projection to be recomputed anyway.
    z = checkpoint_name(z, "mlp_hidden")
    x = jax.nn.gelu(z)
    m = (x @ blk["mlp_out"].astype(compute_dtype)).astype(jnp.float32)
    if psum_axis is not None:
        m = jax.lax.psum(m, psum_axis)
    if dropout and rng is not None:
        m = _dropout(m, dropout, jax.random.fold_in(rng, 1))
    return h + m, 0.0


def _forward(params, tokens, pos, heads, attn_fn, compute_dtype,
             psum_axis=None, apply_blocks=None, ffn_fn=None, remat=False,
             head=True, dropout=0.0, rng=None):
    """Returns (logits, total aux loss) — aux is nonzero only for MoE
    ``ffn_fn`` blocks; the plain ``apply*`` wrappers drop it. ``remat``
    wraps each block in ``jax.checkpoint`` so the backward pass recomputes
    block activations instead of storing them — the standard HBM-for-FLOPs
    trade that long-context training needs."""
    if "pos_emb" in params:
        # static check: jax clamps out-of-range indices silently, so an
        # oversized sequence would reuse the last positional embedding row
        # for every tail position instead of erroring
        max_len = params["pos_emb"].shape[0]
        if pos.shape[0] > max_len:
            raise ValueError(f"sequence length {pos.shape[0]} exceeds the "
                             f"model's max_len {max_len}")
        h = params["tok_emb"][tokens] + params["pos_emb"][pos]
    else:
        # rope model: positions enter through the attention rotation
        # (below); no table, no sequence-length cap
        h = params["tok_emb"][tokens]
        if attn_fn is not None:
            attn_fn = _rope_wrap(attn_fn, pos)
    if not 0.0 <= dropout < 1.0:
        raise ValueError(f"dropout rate {dropout} outside [0, 1)")
    if dropout and apply_blocks is not None:
        # the parallel-schedule path replaces the sequential layer loop,
        # so the per-block residual dropout below would be silently
        # skipped — only embedding dropout would apply, and a library
        # caller would under-regularize without noticing (lm_example
        # guards this at the CLI; the library must refuse too, like the
        # adamw-on-tp/pp/ep refusals)
        raise ValueError("dropout > 0 is not supported on parallel-"
                         "schedule (apply_blocks) paths: per-block "
                         "residual dropout lives in the sequential loop")
    aux_total = 0.0
    if dropout and rng is not None:   # embedding dropout (GPT-style)
        h = _dropout(h, dropout, jax.random.fold_in(rng, 2 ** 20))
    if apply_blocks is not None:
        # parallel schedules (e.g. the GPipe pipeline) replace the
        # sequential layer loop but share embedding/head/LN code
        h = apply_blocks(h)
    else:
        block_fn = _block
        if remat:
            # dropout (7) is static config like its neighbours; the rng
            # key (8) is a traced array and replays exactly in recompute
            block_fn = jax.checkpoint(
                _block, static_argnums=(2, 3, 4, 5, 6, 7),
                policy=_remat_policy(remat))
        for i, blk in enumerate(params["blocks"]):
            blk_rng = (jax.random.fold_in(rng, i)
                       if dropout and rng is not None else None)
            h, aux = block_fn(h, blk, heads, attn_fn, compute_dtype,
                              psum_axis, ffn_fn, dropout, blk_rng)
            aux_total = aux_total + aux
    h = _ln(h, params["ln_f"])
    if not head:  # chunked-CE path applies the tied head itself
        return h, aux_total
    # weight-tied head
    logits = (h.astype(compute_dtype)
              @ params["tok_emb"].T.astype(compute_dtype)).astype(jnp.float32)
    return logits, aux_total


def _remat_policy(remat):
    """Rematerialization spectrum for the block checkpoint — the
    FLOPs↔HBM dial (SURVEY brief: jax.checkpoint to trade FLOPs for
    memory):

    - ``True``  — save only block inputs; backward recomputes the whole
      block (max memory savings, +1/3 executed FLOPs).
    - ``"attn"`` — additionally save each block's attention output
      (checkpoint_name above): the backward re-runs the matmuls but never
      the attention itself. Costs one [B, T, D] compute_dtype tensor
      (bf16 in the default mixed-precision run) per block.
    - ``"dots"`` — save every matmul output, recompute only elementwise
      (LN/gelu/softmax): near-zero recompute, the memory win is only the
      elementwise intermediates.
    - ``"hybrid"`` — save attn_out + the [B*T, 4D] pre-gelu mlp_hidden:
      backward recomputes only qkv + the attention output projection
      (~8 of 24 D^2-units per block, ~1.1x total FLOPs) at a fraction
      of dots' residency — for batch sizes where dots spills HBM.
    - ``"hybrid_qkv"`` — hybrid plus the qkv tensor: recompute drops to
      the attention output projection alone (~1.03x) for +3 D-units of
      residency.
    """
    if remat is True:
        return None
    if remat == "attn":
        return jax.checkpoint_policies.save_only_these_names("attn_out")
    if remat == "dots":
        return jax.checkpoint_policies.checkpoint_dots
    if remat == "hybrid":
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "mlp_hidden")
    if remat == "hybrid_qkv":
        return jax.checkpoint_policies.save_only_these_names(
            "attn_out", "mlp_hidden", "qkv")
    raise ValueError(f"unknown remat mode {remat!r} "
                     "(expected True/False, 'attn', 'dots', 'hybrid' "
                     "or 'hybrid_qkv')")


def decay_mask(params):
    """Params-shaped 0/1 pytree for AdamW's decoupled weight decay: decay
    matrices (ndim >= 2 — projections, embeddings), never LayerNorm
    gains/biases (the standard rule). Feed to
    ``DenseTable(updater="adamw", updater_kwargs={"decay_mask": ...})``,
    which ravels it alongside the params."""
    return jax.tree.map(
        lambda x: jnp.full(x.shape, float(jnp.ndim(x) >= 2), x.dtype),
        params)


def rope_rotate(x, pos, theta: float = 10000.0):
    """Rotary position embedding: rotate half-split head-dim pairs of
    ``x`` [B, T, H, hd] by angles ``pos · theta^(-2i/hd)`` (``pos`` [T],
    GLOBAL positions — the sp path passes each shard's offset range, so
    K rows are rotated at their home shard before the ring moves them).
    Angles/trig run in f32; the product drops back to x.dtype so bf16
    runs keep bf16-rate attention dots."""
    half = x.shape[-1] // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * freq[None, :]      # [T, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _rope_wrap(attn_fn, pos):
    """Attention wrapper applying RoPE to Q and K (never V). Works for
    any head layout — GQA's narrow K rotates the same way."""
    return lambda q, k, v: attn_fn(rope_rotate(q, pos),
                                   rope_rotate(k, pos), v)


def _attn_fn(attn_impl: str):
    """Causal attention implementation by name: ``reference`` (full [T, T]
    scores, XLA-fused) or ``flash`` (ops/flash_attention.py — Pallas kernel
    on TPU, exact blockwise scan elsewhere; O(T) memory either way)."""
    if attn_impl == "flash":
        from minips_tpu.ops.flash_attention import flash_attention

        return lambda q, k, v: flash_attention(q, k, v, causal=True)
    if attn_impl != "reference":
        raise ValueError(f"unknown attn_impl {attn_impl!r} "
                         "(expected 'reference' or 'flash')")
    return lambda q, k, v: reference_attention(q, k, v, causal=True)


def apply(params, tokens, *, heads=4, compute_dtype=jnp.bfloat16,
          remat=False, attn_impl="reference", dropout=0.0, rng=None):
    """Logits [B, T, vocab]; plain causal attention in one program.
    ``heads`` is static model structure, not table state — pass the value
    used at ``init``. ``remat=True`` recomputes block activations in the
    backward pass (jax.checkpoint) to cut peak HBM on long sequences.
    ``attn_impl="flash"`` swaps in the fused O(T)-memory attention.
    ``dropout`` (with an ``rng`` key) enables GPT-style embedding +
    residual dropout — train-time only; omit both at eval."""
    T = tokens.shape[1]
    return _forward(params, tokens, jnp.arange(T), heads,
                    _attn_fn(attn_impl), compute_dtype, remat=remat,
                    dropout=dropout, rng=rng)[0]


def apply_sp(params, tokens_local, shift, *, heads=4, axis_name=DATA_AXIS,
             compute_dtype=jnp.bfloat16, remat=False,
             attn_impl="reference"):
    """Sequence-parallel logits for a local token shard [B, T_local].

    Call inside ``shard_map``: ``shift`` is this shard's global sequence
    offset (``axis_index * T_local``); full params, sharded activations —
    sequence parallelism in its pure form. Two SP strategies x two
    attention impls (``attn_impl``):

    - ``"reference"`` / ``"flash"`` — causal RING over ``axis_name``
      (K/V rotate via ppermute; flash = the offset-masked kernel per
      ring step). O(T/N) K/V memory; any head count.
    - ``"a2a"`` / ``"a2a_flash"`` — ALL-TO-ALL re-shard to head groups
      with the full sequence local (parallel/a2a_attention.py, Ulysses
      lineage): two collectives total, attention fully local (a2a_flash
      = the fused kernel at full rate, no ring bookkeeping). Needs
      ``heads`` divisible by the axis size. RoPE rotates before the
      exchange, so positions stay correct.
    """
    T_local = tokens_local.shape[1]
    pos = shift + jnp.arange(T_local)
    if attn_impl == "flash":
        from minips_tpu.ops.flash_attention import (
            ring_flash_attention_local)

        attn = lambda q, k, v: ring_flash_attention_local(  # noqa: E731
            q, k, v, axis_name=axis_name, causal=True)
    elif attn_impl == "reference":
        attn = lambda q, k, v: ring_attention_local(  # noqa: E731
            q, k, v, axis_name=axis_name, causal=True)
    elif attn_impl in ("a2a", "a2a_flash"):
        from minips_tpu.parallel.a2a_attention import a2a_attention_local

        inner = None
        if attn_impl == "a2a_flash":
            from minips_tpu.ops.flash_attention import flash_attention

            inner = flash_attention  # causal/scale threaded by a2a
        attn = lambda q, k, v: a2a_attention_local(  # noqa: E731
            q, k, v, axis_name=axis_name, causal=True, inner=inner)
    else:
        raise ValueError(f"unknown attn_impl {attn_impl!r} (expected "
                         "'reference', 'flash', 'a2a', or 'a2a_flash')")
    return _forward(params, tokens_local, pos, heads, attn,
                    compute_dtype, remat=remat)[0]


def sp_train_wiring(heads, T_local, axis_name=DATA_AXIS,
                    attn_impl="reference"):
    """``(grad_fn, batch_spec)`` for SEQUENCE-parallel training through
    ``DenseTable.make_step``: the batch is ``{"inp", "tgt"}`` of [B, T]
    tokens sharded on the sequence axis; each shard computes its local
    loss at its global shift and ring attention stitches the sequence.
    One wiring shared by ``lm_example --layout sp`` and the multi-host
    lm path (apps/multihost_example.py) so the shift/reduce semantics
    cannot drift between them."""
    from jax.sharding import PartitionSpec as P

    def sp_grad(p, b):
        def shard_loss(p_, inp, tgt):
            shift = jax.lax.axis_index(axis_name) * T_local
            return loss_sp(p_, inp, tgt, shift, heads=heads,
                           reduce="local", attn_impl=attn_impl)
        return jax.value_and_grad(shard_loss)(p, b["inp"], b["tgt"])

    return sp_grad, {"inp": P(None, axis_name), "tgt": P(None, axis_name)}


def apply_tp(params, tokens, *, heads=4, axis_name="model",
             compute_dtype=jnp.bfloat16):
    """Megatron-style tensor-parallel logits — call INSIDE shard_map with
    block weights sharded per ``tp_specs`` (qkv/mlp_in column-parallel,
    proj/mlp_out row-parallel; embeddings/LN replicated). Activations are
    replicated across the ``axis_name`` axis; two psums per block.

    For training, take ``value_and_grad`` OUTSIDE the shard_map (of a loss
    that closes over the shard_map call): shard_map's transpose inserts the
    Megatron conjugate-operator reductions automatically. Raw local grads
    taken inside would mis-reduce the replicated params
    (tests/test_tensor_parallel.py::test_tp_composes_with_dp).
    """
    tp = _axis_size(axis_name)
    if heads % tp:
        raise ValueError(f"heads {heads} not divisible by tensor-parallel "
                         f"size {tp} (head-boundary sharding)")
    blk0 = params["blocks"][0]
    if "wkv" in blk0:
        # params arrive SHARDED here: wkv's local width must still be a
        # whole number of kv heads, else the head-boundary sharding split
        # a kv head across model shards
        hd = params["tok_emb"].shape[1] // heads
        local_w = blk0["wkv"].shape[2]
        if local_w % hd:
            raise ValueError(
                f"GQA kv_heads {local_w * tp // hd} not divisible by "
                f"tensor-parallel size {tp} (each shard needs whole kv "
                f"heads)")
    T = tokens.shape[1]
    return _forward(params, tokens, jnp.arange(T), heads,
                    lambda q, k, v: reference_attention(q, k, v, causal=True),
                    compute_dtype, psum_axis=axis_name)[0]


def tp_specs(params, axis_name="model"):
    """PartitionSpec pytree for ``apply_tp``: shard each block's qkv and
    mlp_in on their output feature dim, proj and mlp_out on their input
    dim; replicate embeddings and layernorms."""
    from jax.sharding import PartitionSpec as P

    def one_block(blk):
        out = {
            "ln1": jax.tree.map(lambda _: P(), blk["ln1"]),
            "ln2": jax.tree.map(lambda _: P(), blk["ln2"]),
            "proj": P(axis_name, None),
            "mlp_in": P(None, axis_name),
            "mlp_out": P(axis_name, None),
        }
        if "wkv" in blk:   # GQA: both projections column-parallel at
            out["wq"] = P(None, axis_name)         # head boundaries
            out["wkv"] = P(None, None, axis_name)
        else:
            out["qkv"] = P(None, None, axis_name)
        return out

    return {
        "tok_emb": P(),
        **({"pos_emb": P()} if "pos_emb" in params else {}),
        "ln_f": jax.tree.map(lambda _: P(), params["ln_f"]),
        "blocks": [one_block(b) for b in params["blocks"]],
    }


def apply_pp(params, tokens, *, heads=4, axis_name="model",
             num_microbatches=4, compute_dtype=jnp.bfloat16):
    """GPipe pipeline-parallel logits — call INSIDE shard_map with
    ``params["blocks"]`` STACKED (parallel/pipeline.stack_layers) and its
    leading depth axis sharded over ``axis_name``; embeddings/LN
    replicated (see ``pp_specs``). The batch splits into
    ``num_microbatches`` that flow through the stages via ppermute.

    Like ``apply_tp``, take grads OUTSIDE the shard_map.
    """
    from minips_tpu.parallel.pipeline import gpipe

    B, T = tokens.shape
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible into "
                         f"{num_microbatches} microbatches")
    blocks_local = params["blocks"]  # leading depth axis, local slice
    attn = lambda q, k, v: reference_attention(  # noqa: E731
        q, k, v, causal=True)
    if "pos_emb" not in params:   # rope: _forward's wrap can't reach the
        attn = _rope_wrap(attn, jnp.arange(T))   # stage closure, wrap here

    def stage_fn(x):
        def one(hc, blk):
            h2, _ = _block(hc, blk, heads, attn, compute_dtype)
            return h2, None
        return jax.lax.scan(one, x, blocks_local)[0]

    def piped_blocks(h):
        h_mb = h.reshape(num_microbatches, B // num_microbatches, T, -1)
        return gpipe(stage_fn, h_mb, axis_name=axis_name).reshape(B, T, -1)

    return _forward(params, tokens, jnp.arange(T), heads, None,
                    compute_dtype, apply_blocks=piped_blocks)[0]


def pp_specs(params_stacked, axis_name="model"):
    """PartitionSpec pytree for ``apply_pp``: shard every stacked block
    leaf on its leading depth axis; replicate everything else."""
    from jax.sharding import PartitionSpec as P

    return {
        "tok_emb": P(),
        **({"pos_emb": P()} if "pos_emb" in params_stacked else {}),
        "ln_f": jax.tree.map(lambda _: P(), params_stacked["ln_f"]),
        "blocks": jax.tree.map(lambda _: P(axis_name),
                               params_stacked["blocks"]),
    }


def init_moe_lm(key, *, vocab: int = 256, dim: int = 64, heads: int = 4,
                depth: int = 2, max_len: int = 1024, num_experts: int = 8,
                expert_hidden: int = 256, kv_heads: int = None,
                rope: bool = False):
    """LM variant whose FFNs are Switch-style MoE layers (parallel/moe.py):
    same attention as ``init`` (incl. grouped-query via ``kv_heads``),
    each block's MLP replaced by router + stacked expert weights. Use with
    ``apply_ep`` under shard_map (experts sharded over the data axis) or
    with moe_apply_dense on one device."""
    from minips_tpu.parallel.moe import init_moe

    k_base, k_moe = jax.random.split(key)
    base = init(k_base, vocab=vocab, dim=dim, heads=heads, depth=depth,
                max_len=max_len, mlp_mult=1, kv_heads=kv_heads, rope=rope)
    ks = jax.random.split(k_moe, depth)
    for i, blk in enumerate(base["blocks"]):
        del blk["mlp_in"], blk["mlp_out"]
        blk["moe"] = init_moe(ks[i], num_experts, dim, expert_hidden)
    return base


def apply_moe_dense(params, tokens, *, heads=4, capacity: int,
                    compute_dtype=jnp.bfloat16, k_top: int = 1):
    """Single-program MoE-LM logits (oracle / one device):
    returns (logits, total aux loss)."""
    from minips_tpu.parallel.moe import moe_apply_dense

    return _forward(
        params, tokens, jnp.arange(tokens.shape[1]), heads,
        lambda q, k, v: reference_attention(q, k, v, causal=True),
        compute_dtype,
        ffn_fn=lambda blk, x: moe_apply_dense(
            blk["moe"], x, capacity=capacity, compute_dtype=compute_dtype,
            k_top=k_top))


def apply_ep(params, tokens_local, *, heads=4, axis_name=DATA_AXIS,
             capacity: int, compute_dtype=jnp.bfloat16, k_top: int = 1):
    """Expert-parallel MoE-LM logits — call INSIDE shard_map with the
    batch sharded over ``axis_name``, attention weights replicated, and
    each block's expert stacks sharded per ``ep_lm_specs``. Attention runs
    data-parallel per shard; every FFN's tokens fan out to the experts by
    all_to_all. Grads OUTSIDE the shard_map, like the other schedules."""
    from minips_tpu.parallel.moe import moe_apply_local

    return _forward(
        params, tokens_local, jnp.arange(tokens_local.shape[1]), heads,
        lambda q, k, v: reference_attention(q, k, v, causal=True),
        compute_dtype,
        ffn_fn=lambda blk, x: moe_apply_local(
            blk["moe"], x, axis_name=axis_name, capacity=capacity,
            compute_dtype=compute_dtype, k_top=k_top))


def ep_lm_specs(params, axis_name=DATA_AXIS):
    """PartitionSpec pytree for ``apply_ep``: expert stacks sharded over
    the axis, everything else replicated."""
    from jax.sharding import PartitionSpec as P

    from minips_tpu.parallel.moe import ep_specs

    def one_block(blk):
        out = {
            "ln1": jax.tree.map(lambda _: P(), blk["ln1"]),
            "ln2": jax.tree.map(lambda _: P(), blk["ln2"]),
            "proj": P(),
            "moe": ep_specs(axis_name),
        }
        # attention projections replicate either layout (fused or GQA)
        for name in ("qkv", "wq", "wkv"):
            if name in blk:
                out[name] = P()
        return out

    return {
        "tok_emb": P(),
        **({"pos_emb": P()} if "pos_emb" in params else {}),
        "ln_f": jax.tree.map(lambda _: P(), params["ln_f"]),
        "blocks": [one_block(b) for b in params["blocks"]],
    }


def nll(logits, targets):
    """Mean next-token negative log-likelihood — the one cross-entropy
    shared by every layout (full/sp/tp/pp)."""
    logp = jax.nn.log_softmax(logits)
    return jnp.mean(
        -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0])


def nll_chunked(h, tok_emb, targets, chunk, compute_dtype=jnp.bfloat16):
    """Tied-head projection + cross-entropy, scanned over sequence chunks
    so the full ``[B, T, vocab]`` f32 logits tensor NEVER exists — in the
    forward (each chunk's logits die inside its scan step) or the backward
    (``jax.checkpoint`` recomputes one chunk's logits to form its
    ``dlogits``/``dh``). At bench shapes (B=64, T=1024, V=16384) that
    tensor is 4.3 GB of f32 each way; chunking trades it for one extra
    per-chunk head matmul in the backward (~vocab·dim of the 6·P budget).
    Numerics: identical reduction tree to :func:`nll` per chunk, summed in
    f32 — oracle-equality tested in tests/test_transformer.py."""
    B, T, D = h.shape
    if T % chunk:
        raise ValueError(f"seq len {T} must divide by head chunk {chunk}")
    n = T // chunk
    hs = jnp.moveaxis(h.reshape(B, n, chunk, D), 1, 0)        # [n,B,c,D]
    ts = jnp.moveaxis(targets.reshape(B, n, chunk), 1, 0)     # [n,B,c]

    @jax.checkpoint
    def chunk_nll_sum(hc, tc):
        logits = (hc.astype(compute_dtype)
                  @ tok_emb.T.astype(compute_dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, tc[..., None], axis=-1).sum()

    def body(acc, xt):
        hc, tc = xt
        return acc + chunk_nll_sum(hc, tc), None

    # under shard_map the fresh carry is axis-invariant but the chunk sums
    # vary with the sharded inputs — pcast keeps the scan carry type fixed
    # (same treatment as DenseTable.make_step's accum fold)
    acc0 = jnp.zeros((), jnp.float32)
    vma = (getattr(jaxcompat.typeof(h), "vma", frozenset())
           | getattr(jaxcompat.typeof(targets), "vma", frozenset()))
    if vma:
        acc0 = jaxcompat.pcast(acc0, tuple(sorted(vma)), to="varying")
    total, _ = jax.lax.scan(body, acc0, (hs, ts))
    return total / (B * T)


def loss(params, batch, *, heads=4, compute_dtype=jnp.bfloat16,
         attn_impl="reference", remat=False, head_chunk=0, dropout=0.0):
    """Next-token cross-entropy; batch = {"tokens": [B, T+1] int32}.
    ``remat=True`` recomputes block activations in the backward pass —
    activation memory stops scaling with depth, the standard trade for
    fitting larger models (SURVEY brief: jax.checkpoint to trade FLOPs
    for HBM). ``head_chunk > 0`` computes the tied head + CE in sequence
    chunks of that size (:func:`nll_chunked`) so the [B, T, vocab] logits
    never materialize. ``dropout > 0`` reads the step's PRNG key from
    ``batch["rng"]`` (the fused step is pure, so randomness must ride the
    batch) and raises if it is absent.

    ``batch["rng"]`` contract: a RAW uint32 key array — ``[2]`` (one key,
    replicated), or ``[W, 2]`` fed through shard_map with ``batch_spec
    P(DATA_AXIS)`` so each worker's shard sees its own ``[1, 2]`` slice
    (distinct masks per worker). New-style typed keys
    (``jax.random.key``) are rejected: a typed ``[W]`` stack would bypass
    the per-worker slice below and silently broadcast one mask."""
    toks = batch["tokens"]
    rng = batch.get("rng")
    if dropout and rng is None:
        raise ValueError('dropout > 0 needs a per-step key in '
                         'batch["rng"] (the fused step is pure)')
    if dropout and jnp.issubdtype(rng.dtype, jax.dtypes.prng_key):
        # only when the rng will actually be consumed: an eval call
        # (dropout=0) reusing a training batch dict must not start
        # rejecting a key it never reads
        raise TypeError('batch["rng"] must be a RAW uint32 key array '
                        '([2] or [W, 2] via jax.random.PRNGKey), not a '
                        'typed jax.random.key array: the per-worker '
                        '[W, 2] slicing below cannot see typed-key '
                        'stacks and would silently reuse one mask')
    if rng is not None and rng.ndim == 2:
        # per-WORKER keys sharded over the data axis (a [W, 2] stack fed
        # with batch_spec P(DATA_AXIS)): each shard sees its [1, 2] slice
        # — distinct dropout masks per worker, not one replicated pattern
        if dropout and rng.shape[-1] != 2:
            raise ValueError(f'batch["rng"] 2-D stack must be [W, 2] raw '
                             f'uint32 keys, got {rng.shape}')
        rng = rng[0]
    if head_chunk:
        T = toks.shape[1] - 1
        h, _ = _forward(params, toks[:, :-1], jnp.arange(T), heads,
                        _attn_fn(attn_impl), compute_dtype, remat=remat,
                        head=False, dropout=dropout, rng=rng)
        return nll_chunked(h, params["tok_emb"], toks[:, 1:], head_chunk,
                           compute_dtype)
    logits = apply(params, toks[:, :-1], heads=heads,
                   compute_dtype=compute_dtype, attn_impl=attn_impl,
                   remat=remat, dropout=dropout, rng=rng)
    return nll(logits, toks[:, 1:])


def grad_fn(params, batch, *, heads=4, attn_impl="reference", remat=False,
            head_chunk=0, dropout=0.0):
    l, g = jax.value_and_grad(
        lambda p, b: loss(p, b, heads=heads, attn_impl=attn_impl,
                          remat=remat, head_chunk=head_chunk,
                          dropout=dropout))(params, batch)
    return l, g


def loss_sp(params, tokens_local, targets_local, shift, *, heads=4,
            axis_name=DATA_AXIS, compute_dtype=jnp.bfloat16,
            reduce="pmean", attn_impl="reference"):
    """Per-shard next-token loss over the shard's tokens.

    ``reduce="pmean"`` returns the global mean loss (standalone use — take
    ``jax.grad`` OUTSIDE the shard_map). ``reduce="local"`` returns the
    shard-local mean: required when differentiating INSIDE shard_map under
    ``DenseTable.make_step``, whose psum_scatter + 1/N already averages the
    per-shard grads — a pmean here would double-scale them by 1/N.
    """
    logits = apply_sp(params, tokens_local, shift, heads=heads,
                      axis_name=axis_name, compute_dtype=compute_dtype,
                      attn_impl=attn_impl)
    local = nll(logits, targets_local)
    if reduce == "local":
        return local
    return jax.lax.pmean(local, axis_name)
