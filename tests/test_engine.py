"""Engine / MLTask / KVClientTable integration on fake devices — the
reference's single-process multi-thread engine tests (SURVEY.md §4)."""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu import Engine, MLTask
from minips_tpu.core.config import TableConfig


def make_engine(n=4, **table_kw):
    e = Engine(num_workers=n).start_everything()
    cfg = TableConfig(name="t", kind="dense", lr=0.5, **table_kw)
    e.create_table(cfg, template={"w": jnp.zeros(8)})
    return e


def test_default_task_uses_engine_workers():
    e = make_engine(3)
    seen = []
    e.run(MLTask(fn=lambda info: seen.append(info.worker_id)))
    assert sorted(seen) == [0, 1, 2]
    e.stop_everything()


def test_udf_error_surfaces_root_cause():
    e = make_engine(2, consistency="bsp")

    def udf(info):
        tbl = info.table("t")
        if info.worker_id == 1:
            raise RuntimeError("worker 1 exploded")
        tbl.pull(); tbl.push({"w": jnp.ones(8)}); tbl.clock()
        tbl.pull(timeout=30.0)  # parked; unblocked by the stop cascade

    with pytest.raises(RuntimeError, match="worker 1 exploded"):
        e.run(MLTask(fn=udf))
    e.stop_everything()


def test_engine_reusable_after_failed_run():
    e = make_engine(2, consistency="bsp")
    with pytest.raises(RuntimeError):
        e.run(MLTask(fn=lambda info: (_ for _ in ()).throw(
            RuntimeError("boom"))))
    done = []
    e.run(MLTask(fn=lambda info: done.append(info.worker_id)))
    assert sorted(done) == [0, 1]
    e.stop_everything()


def test_threaded_lr_converges_bsp():
    rng = np.random.default_rng(0)
    true_w = rng.normal(size=8).astype(np.float32)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (X @ true_w > 0).astype(np.float32)
    e = make_engine(4, consistency="bsp", updater="adagrad")
    losses = {w: [] for w in range(4)}

    def udf(info):
        import jax
        tbl = info.table("t")
        shard = np.array_split(np.arange(len(X)), 4)[info.worker_id]
        xb, yb = jnp.asarray(X[shard]), jnp.asarray(y[shard])

        def loss_grad(params):
            logits = xb @ params["w"]
            loss = jnp.mean(jnp.logaddexp(0.0, logits) - yb * logits)
            return loss

        g = jax.jit(jax.value_and_grad(loss_grad))
        for _ in range(15):
            params = tbl.pull()
            loss, grads = g(params)
            tbl.push({"w": grads["w"] / info.num_workers})
            tbl.clock()
            losses[info.worker_id].append(float(loss))

    e.run(MLTask(fn=udf))
    e.stop_everything()
    for w in range(4):
        assert losses[w][-1] < losses[w][0] * 0.9


def test_sparse_table_via_engine():
    e = Engine(num_workers=2).start_everything()
    e.create_table(TableConfig(name="emb", kind="sparse", num_slots=64,
                               dim=4, lr=1.0, consistency="asp",
                               init_scale=0.0))
    def udf(info):
        tbl = info.table("emb")
        keys = np.array([3, 9]) if info.worker_id == 0 else np.array([9, 17])
        tbl.push(jnp.ones((2, 4)), keys=keys)
        tbl.clock()

    e.run(MLTask(fn=udf))
    tbl = e.tables["emb"]
    rows = np.asarray(tbl.pull(jnp.array([3, 9, 17])))
    e.stop_everything()
    # SGD pushes are additive and ASP is ordering-free: key 9 was pushed by
    # both workers (-lr*2), keys 3/17 once each (-lr*1), modulo hash
    # collisions (none for these keys at 64 slots — checked below).
    slots = np.asarray(tbl.slots_of(jnp.array([3, 9, 17])))
    assert len(set(slots.tolist())) == 3
    np.testing.assert_allclose(rows[1], 2 * rows[0], rtol=1e-6)
    np.testing.assert_allclose(rows[0], rows[2], rtol=1e-6)


def test_mltask_builder_api():
    """Reference builder verbs (SURVEY.md §2 MLTask::SetLambda /
    SetWorkerAlloc) — chainable and honored by Engine.run."""
    eng = make_engine(2, consistency="bsp")
    seen = []
    task = MLTask().set_lambda(
        lambda info: seen.append(info.worker_id)).set_worker_alloc(2)
    eng.run(task)
    eng.stop_everything()
    assert sorted(seen) == [0, 1]


def test_config_json_roundtrip(tmp_path):
    """to_json/from_json and the --config_file path (SURVEY.md §5.6)."""
    import argparse

    from minips_tpu.core.config import (Config, TrainConfig,
                                        add_config_flags, config_from_args)

    cfg = Config(table=TableConfig(name="x", kind="sparse", staleness=3,
                                   updater="adagrad", lr=0.25, dim=7),
                 train=TrainConfig(batch_size=96, num_iters=5),
                 app={"extra": 1})
    assert Config.from_json(cfg.to_json()) == cfg
    # the gflags-style file path: --config_file round-trips through argparse
    path = tmp_path / "cfg.json"
    path.write_text(cfg.to_json())
    parser = argparse.ArgumentParser()
    add_config_flags(parser)
    args = parser.parse_args(["--config_file", str(path)])
    assert config_from_args(args) == cfg
