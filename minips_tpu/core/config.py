"""Config dataclasses — the rebuild of the reference's gflags config system.

The reference configures apps through gflags (``--config_file``, ``--my_id``,
app hyperparameters) plus a plaintext hostfile (SURVEY.md §5.6, §2 "gflags/
glog config+log"). Here each app carries a typed ``Config`` dataclass with an
argparse bridge, so the ``lr_example``-style entrypoints launch with the same
flag surface.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class TableConfig:
    """Declares one parameter table — the rebuild of CreateTable(ModelType,
    StorageType) in the reference Engine (SURVEY.md §1 L4).

    ``kind`` selects dense (VectorStorage analog: a sharded array pytree) or
    sparse (MapStorage analog: fixed-slot hashed embedding — TPUs have no
    dynamic dicts, SURVEY.md §2 "KVTable storage").
    """

    name: str = "table0"
    kind: str = "dense"  # "dense" | "sparse"
    # consistency model: "bsp" | "ssp" | "asp" (SURVEY.md §2 consistency rows)
    consistency: str = "bsp"
    staleness: int = 0  # SSP bound s; north-star s <= 4 (BASELINE.json:4)
    # server-side updater applied on push (SURVEY.md §2 "Updaters");
    # adam_bf16 / adam8 store moments in bf16 / blockwise int8 — the
    # optimizer-state HBM levers (tables/updaters.py)
    updater: str = "sgd"  # sgd | adagrad | adam | adamw | adam_bf16 | adam8
    lr: float = 0.1
    # sparse-only: fixed slot capacity + embedding dim + init scale
    num_slots: int = 1 << 16
    dim: int = 8
    init_scale: float = 0.01
    # ASP: sync period in local steps (local-SGD emulation, SURVEY.md §7.1)
    sync_every: int = 8


@dataclass
class TrainConfig:
    """Per-app training loop knobs (mirrors reference app gflags)."""

    batch_size: int = 256
    num_iters: int = 100
    num_workers: int = 4  # logical workers (mesh data-axis size)
    seed: int = 0
    log_every: int = 10
    metrics_path: Optional[str] = None  # JSONL metrics sink (SURVEY.md §5.5)
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0  # 0 = disabled


@dataclass
class Config:
    """Top-level config: table + train + free-form app params."""

    table: TableConfig = field(default_factory=TableConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    app: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Config":
        raw = json.loads(text)
        return cls(
            table=TableConfig(**raw.get("table", {})),
            train=TrainConfig(**raw.get("train", {})),
            app=raw.get("app", {}),
        )


def add_config_flags(parser: argparse.ArgumentParser) -> None:
    """Register the shared flag surface (the gflags analog)."""
    parser.add_argument("--config_file", type=str, default=None,
                        help="JSON config file (reference: --config_file)")
    parser.add_argument("--consistency", type=str, default=None,
                        choices=["bsp", "ssp", "asp"])
    parser.add_argument("--staleness", type=int, default=None)
    parser.add_argument("--updater", type=str, default=None,
                        choices=["sgd", "adagrad", "adam", "adamw",
                                 "adam_bf16", "adam8"])
    # adamw is dense-table-only (lm_example dp/sp); the sparse/sharded
    # tables refuse it loudly at construction
    parser.add_argument("--lr", type=float, default=None)
    parser.add_argument("--num_slots", type=int, default=None,
                        help="sparse table capacity (power of two)")
    parser.add_argument("--batch_size", type=int, default=None)
    parser.add_argument("--num_iters", type=int, default=None)
    parser.add_argument("--num_workers", type=int, default=None)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--log_every", type=int, default=None)
    parser.add_argument("--metrics_path", type=str, default=None)
    parser.add_argument("--checkpoint_dir", type=str, default=None)
    parser.add_argument("--checkpoint_every", type=int, default=None)


def config_from_args(args: argparse.Namespace,
                     default: Optional[Config] = None) -> Config:
    """Overlay CLI flags onto a default/app config (+ optional JSON file)."""
    cfg = default or Config()
    if getattr(args, "config_file", None):
        with open(args.config_file) as f:
            cfg = Config.from_json(f.read())
    for name in ("consistency", "staleness", "updater", "lr", "num_slots"):
        val = getattr(args, name, None)
        if val is not None:
            setattr(cfg.table, name, val)
    for name in ("batch_size", "num_iters", "num_workers", "seed",
                 "log_every", "metrics_path", "checkpoint_dir",
                 "checkpoint_every"):
        val = getattr(args, name, None)
        if val is not None:
            setattr(cfg.train, name, val)
    return cfg
