"""Multi-window SLO burn-rate accounting over the windowed quantiles —
the layer that turns "p99 looks high" into a signal the fleet can act
on, per tenant, without paging on one bad interval.

The classic burn-rate alert (SRE workbook shape): pick an objective
("99% of reads under 20ms"), measure the fraction of samples violating
it, and divide by the error budget ``1 - q``. A burn of 1.0 means the
budget is being spent exactly at the sustainable rate; 10x means it is
gone in a tenth of the window. One window cannot be both fast and
credible, so the standard fix is TWO: a metric is BURNING only when the
fast window (reacts in seconds) AND the slow window (filters blips)
both exceed the threshold. Both reads come from the PR13 windowed
layer (obs/window.py) over the same log2 histograms everything else
reports — no second recording path, and the log2 quantization is
explicit in the math (the straddling bucket contributes linearly).

Three objectives, each optional (target 0 = not monitored), each keyed
by tenant (tenants are tables — tenant/registry.py; with tenancy off
there is one implicit ``*`` tenant over the fleet signals):

- ``fresh_ms`` — push-visible-at-replica lag (obs/freshness.py)
- ``read_ms``  — serving read latency (``pull_latency`` hists)
- ``shed_rate`` — admission sheds per second (rate, not quantile: the
  burn is observed rate / target rate)

A rising burn edge emits a flight-recorder ``slo_burn`` CHECKPOINT
(obs/flight.py — event + dump, zero pre-arming, so the violation IS the
post-mortem box); a falling edge emits a plain ``slo_clear`` event. The
burning set feeds two consumers: the serving plane's promotion budget
(``replica_boost`` — a burning tenant's tables get ``boost`` extra
replicas while burning, the "replica budgets ride demand" half of
ROADMAP item 4) and the autoscaler's arming pressure
(balance/autoscaler.py ``_slo_pressure``, the rank half).

Spec grammar (``MINIPS_SLO``): ``""``/``"0"`` = off, ``"1"`` = armed
with defaults (no targets — armed-idle), else a k=v comma list::

    fresh_ms=50,read_ms=20,shed_rate=5,fast=2,slow=8,burn=1.0,q=0.99,
    boost=1,pressure=1

Done-line convention (PR5): layer OFF -> ``slo`` block is ``None``;
armed with no targets or no traffic -> zero counters, empty burning set.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from minips_tpu.obs import flight as _flight

__all__ = ["SloConfig", "SloTracker", "maybe_config"]

_DEF_FAST = 2
_DEF_SLOW = 8


def _bounds_us(i: int) -> tuple[float, float]:
    """[lo, hi) of log2 bucket ``i`` in microseconds (obs/hist.py)."""
    if i == 0:
        return 0.0, 1.0
    return float(2 ** (i - 1)), float(2 ** i)


def frac_over_target(counts: list, target_us: float) -> float:
    """Fraction of samples above ``target_us`` given log2 bucket counts.
    Buckets fully above the target count whole; the straddling bucket
    contributes its linear fraction above it (same interpolation the
    quantiles use — honest to the bucket resolution, no better)."""
    total = sum(counts)
    if total == 0:
        return 0.0
    over = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        lo, hi = _bounds_us(i)
        if lo >= target_us:
            over += c
        elif hi > target_us:
            over += c * (hi - target_us) / (hi - lo)
    return over / total


class SloConfig:
    """Parsed ``MINIPS_SLO`` knobs."""

    def __init__(self, *, fresh_ms: float = 0.0, read_ms: float = 0.0,
                 shed_rate: float = 0.0, fast: int = _DEF_FAST,
                 slow: int = _DEF_SLOW, burn: float = 1.0,
                 q: float = 0.99, boost: int = 1, pressure: int = 1):
        # inverted comparisons so NaN fails validation instead of
        # slipping through (NaN < x is False for every x)
        if not (fresh_ms >= 0 and read_ms >= 0 and shed_rate >= 0):
            raise ValueError("MINIPS_SLO: targets must be >= 0 "
                             "(0 = not monitored)")
        if fast < 1:
            raise ValueError("MINIPS_SLO: fast window must be >= 1 roll")
        if slow < fast:
            raise ValueError(
                f"MINIPS_SLO: slow window ({slow}) must be >= fast "
                f"({fast}) — a slow window shorter than the fast one "
                "inverts the blip filter")
        if not (burn > 0):
            raise ValueError("MINIPS_SLO: burn threshold must be > 0")
        if not (0.0 < q < 1.0):
            raise ValueError("MINIPS_SLO: q must be in (0, 1)")
        if boost < 0:
            raise ValueError("MINIPS_SLO: boost must be >= 0 replicas")
        if pressure not in (0, 1):
            raise ValueError("MINIPS_SLO: pressure must be 0 or 1")
        self.fresh_ms = float(fresh_ms)
        self.read_ms = float(read_ms)
        self.shed_rate = float(shed_rate)
        self.fast = int(fast)
        self.slow = int(slow)
        self.burn = float(burn)
        self.q = float(q)
        self.boost = int(boost)
        self.pressure = int(pressure)

    _CASTS = {"fresh_ms": float, "read_ms": float, "shed_rate": float,
              "fast": int, "slow": int, "burn": float, "q": float,
              "boost": int, "pressure": int}

    @classmethod
    def parse(cls, spec: str) -> "Optional[SloConfig]":
        """None = the layer is OFF (``""``/``"0"``); config otherwise."""
        spec = (spec or "").strip()
        if spec in ("", "0"):
            return None
        if spec in ("1", "on", "true"):
            return cls()
        kw: dict = {}
        for item in filter(None, (e.strip() for e in spec.split(","))):
            if "=" not in item:
                raise ValueError(
                    f"MINIPS_SLO: expected k=v, got {item!r}")
            k, _, v = item.partition("=")
            k = k.strip()
            cast = cls._CASTS.get(k)
            if cast is None:
                raise ValueError(f"MINIPS_SLO: unknown knob {k!r}")
            try:
                kw[k] = cast(v)
            except ValueError as e:
                raise ValueError(
                    f"MINIPS_SLO: bad value for {k}: {v!r}") from e
        return cls(**kw)

    def signature(self) -> tuple:
        return (self.fresh_ms, self.read_ms, self.shed_rate, self.fast,
                self.slow, self.burn, self.q, self.boost, self.pressure)


def maybe_config(spec: Optional[str] = None) -> "Optional[SloConfig]":
    """Explicit spec wins, else ``$MINIPS_SLO`` (the shared knob
    convention); None when the layer is off."""
    if spec is None:
        spec = os.environ.get("MINIPS_SLO", "")
    return SloConfig.parse(spec)


# (metric key, config target attr, windowed signal prefix, kind)
_METRICS = (("read", "read_ms", "pull_latency", "hist"),
            ("fresh", "fresh_ms", "freshness", "hist"),
            ("shed", "shed_rate", "shed", "counter"))


class SloTracker:
    """Evaluates the burn state once per windowed roll and serves the
    burning set to the promotion budget and the autoscaler.

    ``tenants`` is the list of tenant/table names to key by (empty ->
    one implicit ``"*"`` tenant over the fleet signals). Per-tenant
    signals (``pull_latency:{name}`` etc., registered by the trainer
    when tenancy is on) are preferred; an unregistered per-tenant name
    falls back to the fleet signal so an SLO on an untagged run still
    evaluates."""

    def __init__(self, cfg: SloConfig, ow, tenants: "list[str]"):
        if ow is None:
            raise ValueError(
                "MINIPS_SLO reads the windowed quantiles — it cannot "
                "run with MINIPS_OBS=0")
        self.cfg = cfg
        self._ow = ow
        self.tenants = list(tenants) or ["*"]
        self._lock = threading.Lock()
        self._state: dict = {}       # (tenant, metric) -> burning bool
        self._last: dict = {}        # (tenant, metric) -> (fast, slow)
        self._budget: dict = {t: 0 for t in self.tenants}
        self.counters = {"checks": 0, "burns": 0, "clears": 0,
                         "boost_ticks": 0}

    # ------------------------------------------------------------- eval
    def _signal(self, prefix: str, tenant: str) -> str:
        if tenant != "*":
            return f"{prefix}:{tenant}"
        return prefix

    def _burn_pair(self, tenant: str, target: float, prefix: str,
                   kind: str) -> "Optional[tuple[float, float]]":
        """(fast_burn, slow_burn) for one (tenant, metric); None when
        the signal is unregistered in the windowed layer."""
        name = self._signal(prefix, tenant)
        if kind == "hist":
            tgt_us = target * 1e3
            budget = max(1.0 - self.cfg.q, 1e-9)
            pair = []
            for k in (self.cfg.fast, self.cfg.slow):
                counts = self._ow.window_counts(name, k)
                if counts is None and tenant != "*":
                    counts = self._ow.window_counts(prefix, k)
                if counts is None:
                    return None
                pair.append(frac_over_target(counts, tgt_us) / budget)
            return pair[0], pair[1]
        # counter: burn = observed events/s over the window / target
        pair = []
        for k in (self.cfg.fast, self.cfg.slow):
            r = self._ow.rate(name, k)
            if r is None and tenant != "*":
                r = self._ow.rate(prefix, k)
            if r is None:
                return None
            pair.append(r / target)
        return pair[0], pair[1]

    def on_roll(self) -> None:
        """Re-evaluate every (tenant, metric) pair; called from the
        tick thread right after ``WindowedMetrics.roll()`` so the fast
        window always includes the interval that just closed."""
        cfg = self.cfg
        edges = []
        with self._lock:
            self.counters["checks"] += 1
            for tenant in self.tenants:
                for metric, attr, prefix, kind in _METRICS:
                    target = getattr(cfg, attr)
                    if target <= 0:
                        continue
                    pair = self._burn_pair(tenant, target, prefix, kind)
                    if pair is None:
                        continue
                    fast_b, slow_b = pair
                    key = (tenant, metric)
                    self._last[key] = (fast_b, slow_b)
                    now_burning = (fast_b >= cfg.burn
                                   and slow_b >= cfg.burn)
                    was = self._state.get(key, False)
                    if now_burning and not was:
                        self.counters["burns"] += 1
                        edges.append(("burn", tenant, metric,
                                      fast_b, slow_b, target))
                    elif was and not now_burning:
                        self.counters["clears"] += 1
                        edges.append(("clear", tenant, metric,
                                      fast_b, slow_b, target))
                    self._state[key] = now_burning
        # flight I/O outside the lock: a checkpoint dumps a file
        for edge, tenant, metric, fast_b, slow_b, target in edges:
            args = {"tenant": tenant, "metric": metric,
                    "fast_burn": round(fast_b, 3),
                    "slow_burn": round(slow_b, 3), "target": target}
            if edge == "burn":
                _flight.checkpoint("slo_burn", args)
            else:
                _flight.record("slo_clear", args)

    # -------------------------------------------------------- consumers
    def burning(self, tenant: str) -> bool:
        with self._lock:
            return any(b for (t, _m), b in self._state.items()
                       if b and t in (tenant, "*"))

    def burning_tenants(self) -> "list[str]":
        with self._lock:
            return sorted({t for (t, _m), b in self._state.items()
                           if b})

    def replica_boost(self, tenant: str) -> int:
        """Extra replicas the promotion budget grants this tenant's
        tables while it burns (serve/plane.py ``_promote_hot``)."""
        if self.cfg.boost <= 0 or not self.burning(tenant):
            return 0
        with self._lock:
            self.counters["boost_ticks"] += 1
        return self.cfg.boost

    def note_budget(self, tenant: str, nrep: int) -> None:
        """Promotion budget actually applied — the artifact's proof
        that the replica budget flexed (max over the run)."""
        with self._lock:
            if nrep > self._budget.get(tenant, 0):
                self._budget[tenant] = int(nrep)

    def pressure_quanta(self) -> int:
        """Burning-tenant count for the autoscaler's arming pressure
        (0 when the ``pressure`` knob is off)."""
        if not self.cfg.pressure:
            return 0
        return len(self.burning_tenants())

    # ------------------------------------------------------------ record
    def record(self) -> dict:
        cfg = self.cfg
        with self._lock:
            per_tenant: dict = {}
            for tenant in self.tenants:
                burning = sorted(m for (t, m), b in self._state.items()
                                 if b and t == tenant)
                tn: dict = {"burning": burning,
                            "max_budget": self._budget.get(tenant, 0)}
                for metric, attr, _p, _k in _METRICS:
                    pair = self._last.get((tenant, metric))
                    if pair is not None:
                        tn[f"{metric}_burn"] = [round(pair[0], 3),
                                                round(pair[1], 3)]
                per_tenant[tenant] = tn
            return {"fast": cfg.fast, "slow": cfg.slow,
                    "burn": cfg.burn, "q": cfg.q, "boost": cfg.boost,
                    "pressure": cfg.pressure,
                    "targets": {"fresh_ms": cfg.fresh_ms,
                                "read_ms": cfg.read_ms,
                                "shed_rate": cfg.shed_rate},
                    **dict(self.counters),
                    "burning": sorted(
                        f"{t}/{m}" for (t, m), b in self._state.items()
                        if b),
                    "tenants": per_tenant}
