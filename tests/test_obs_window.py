"""Windowed metrics + flight recorder (this PR's tentpole).

Unit tier: the MINIPS_OBS spec parser, window rotation and the
delta-sum == recompute property, off-vs-idle conventions, counter
rates/re-baselining, gauges; the flight recorder's bounded typed ring,
atomic + RE-ENTRANT dump (two poison paths firing concurrently — the
satellite-6 regression), env gating, default-dir run-id keying, stale
sweep, and the merge CLI's offset-aligned timeline.

Autoscaler tier: the ROADMAP item 3(b) close — the windowed p99 arms
STRICTLY no later than the cumulative signal under a storm breaking on
long calm history, and DISARMS within one window after the storm ends,
where the cumulative hist provably cannot (it never forgets a storm —
the old behavior, asserted gone from the rbH report).

Drill tier (slow): a seeded 3-proc MINIPS_CHAOS_KILL run with NO
observability env armed leaves per-rank flight dumps in the DEFAULT
directory from which the merge CLI reconstructs the failure sequence
(death verdict → term advance → death plan, with signal values).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from minips_tpu.obs import flight as fl
from minips_tpu.obs.hist import Log2Histogram, summarize_counts
from minips_tpu.obs.window import (ObsWindowConfig, WindowedMetrics,
                                   maybe_build)

APP = "minips_tpu.apps.sharded_ps_example"


# ------------------------------------------------------------ spec parsing
def test_obs_config_parse_defaults_off_and_knobs():
    cfg = ObsWindowConfig.parse("")
    assert (cfg.window, cfg.ring) == (8, 32)
    assert ObsWindowConfig.parse("1").window == 8
    assert ObsWindowConfig.parse("0") is None          # the tax arm
    cfg = ObsWindowConfig.parse("window=4,ring=16")
    assert (cfg.window, cfg.ring) == (4, 16)
    with pytest.raises(ValueError, match="unknown knob"):
        ObsWindowConfig.parse("cap=9")
    with pytest.raises(ValueError, match="k=v"):
        ObsWindowConfig.parse("window")
    with pytest.raises(ValueError, match="ring"):
        ObsWindowConfig.parse("window=16,ring=4")  # ring < window
    assert maybe_build("0") is None
    assert maybe_build("window=2,ring=4").window == 2


# --------------------------------------------------------- window semantics
def test_window_quantile_forgets_a_storm_the_cumulative_hist_cannot():
    """THE carry-forward pin (ROADMAP 3(b)): after a storm ends, the
    windowed p99 returns to calm within `window` rolls; the cumulative
    hist's p99 keeps reporting the storm forever."""
    h = Log2Histogram()
    w = WindowedMetrics(window=3, ring=8)
    w.register_hist("lat", lambda: h.counts)
    for _ in range(50):
        h.record_us(400_000.0)  # the storm: 400ms tails
    w.roll()
    assert w.quantile_ms("lat", 0.99) > 100.0
    for _ in range(3):          # calm: no samples at all
        w.roll()
    assert w.quantile_ms("lat", 0.99) is None  # idle window = calm
    # the OLD signal never forgets — this is exactly why it was replaced
    assert summarize_counts(h.counts)["p99_ms"] > 100.0
    # calm traffic (fast samples) keeps the window honest too
    for _ in range(3):
        for _ in range(50):
            h.record_us(100.0)
        w.roll()
    assert w.quantile_ms("lat", 0.99) < 1.0
    assert summarize_counts(h.counts)["p99_ms"] > 100.0


def test_window_delta_sum_equals_recompute_over_window():
    """Property (seeded): for any sample/roll schedule and any window
    k, the ring's elementwise delta sum equals the difference of the
    cumulative snapshots at the window's edges — the fixed-bucket merge
    argument, applied over time."""
    rng = np.random.default_rng(7)
    h = Log2Histogram()
    w = WindowedMetrics(window=4, ring=16)
    w.register_hist("lat", lambda: h.counts)
    snaps = [list(h.counts)]  # cumulative snapshot at each roll edge
    for _ in range(12):
        for us in rng.integers(1, 10_000_000, rng.integers(0, 40)):
            h.record_us(float(us))
        w.roll()
        snaps.append(list(h.counts))
    for k in (1, 2, 4, 7, 16):
        got = w.window_counts("lat", window=k)
        kk = min(k, len(snaps) - 1)
        want = [a - b for a, b in zip(snaps[-1], snaps[-1 - kk])]
        assert got == want, (k, got, want)


def test_window_rotation_ring_bound_and_clamping():
    h = Log2Histogram()
    w = WindowedMetrics(window=2, ring=3)
    w.register_hist("lat", lambda: h.counts)
    for i in range(10):
        h.record_us(10.0)
        w.roll()
    # ring holds only the last 3 deltas; a wider window clamps to it
    assert sum(w.window_counts("lat", window=100)) == 3
    assert sum(w.window_counts("lat")) == 2  # the default window
    assert w.rolls == 10
    with pytest.raises(ValueError):
        w.window_counts("lat", window=0)
    assert w.window_counts("nope") is None
    assert w.summarize("nope") is None


def test_counter_rate_rebaseline_and_registration_priming():
    c = {"v": 100.0}  # pre-registration history must never be counted
    t = [0.0]
    w = WindowedMetrics(window=4, ring=8, clock=lambda: t[0])
    w.register_counter("shed", lambda: c["v"])
    c["v"] += 10
    t[0] = 1.0
    w.roll()
    assert w.delta_sum("shed") == 10.0
    assert w.rate("shed") == 10.0  # 10 events / 1s span
    # a BACKWARD counter (restarted layer) re-baselines, never negative
    c["v"] = 3.0
    t[0] = 2.0
    w.roll()
    assert w.delta_sum("shed") == 10.0  # 10 + max(3-110, 0)
    c["v"] = 5.0
    t[0] = 3.0
    w.roll()
    assert w.delta_sum("shed") == 12.0  # rebaselined at 3 → +2


def test_gauge_last_and_max():
    g = {"v": 0.0}
    w = WindowedMetrics(window=3, ring=8)
    w.register_gauge("gap_age", lambda: g["v"])
    assert w.gauge("gap_age") is None  # no rolls yet
    for v in (1.0, 5.0, 2.0):
        g["v"] = v
        w.roll()
    assert w.gauge("gap_age") == 2.0
    assert w.gauge("gap_age", agg="max") == 5.0
    assert w.gauge("gap_age", agg="max", window=1) == 2.0


def test_record_follows_off_vs_idle_convention():
    h = Log2Histogram()
    w = WindowedMetrics(window=2, ring=4)
    w.register_hist("lat", lambda: h.counts)
    w.register_counter("shed", lambda: 0.0)
    rec = w.record()
    assert rec["hist"]["lat"] == {"count": 0}  # armed but idle
    assert rec["events"]["shed"] == 0
    h.record_us(500.0)
    w.roll()
    rec = w.record()
    assert rec["hist"]["lat"]["count"] == 1
    assert rec["rolls"] == 1 and rec["window"] == 2


# ------------------------------------------- autoscaler signal A/B drill
def _p99_streams(schedule_ms, window):
    """One latency schedule (list of per-tick sample lists, ms) →
    (windowed p99 stream, cumulative p99 stream) — the two candidate
    autoscaler signals derived from the SAME histogram."""
    h = Log2Histogram()
    w = WindowedMetrics(window=window, ring=window * 2)
    w.register_hist("lat", lambda: h.counts)
    windowed, cumulative = [], []
    for tick in schedule_ms:
        for ms in tick:
            h.record_us(ms * 1e3)
        w.roll()
        windowed.append(w.quantile_ms("lat", 0.99))
        cumulative.append(summarize_counts(h.counts).get("p99_ms"))
    return windowed, cumulative


def _drive_autoscaler(p99_stream, spec):
    """Feed a p99-per-tick stream through a fake-backed Autoscaler
    (the rbH report shape) and return its hot-tick count per tick."""
    from tests.test_control_plane import _mk_autoscaler

    tr, mb, a = _mk_autoscaler(spec)
    hot = []
    for p in p99_stream:
        tr.rebalancer.reports = {
            r: {"total": 10.0, "sv": {"shed": 0.0}, "p99": p}
            for r in (0, 1, 2)}
        a.on_tick()
        hot.append(a.counters["hot_ticks"])
    return hot


def test_windowed_p99_arms_no_later_and_disarms_where_cumulative_cannot():
    """The acceptance A/B: a storm breaking on long calm history ARMS
    the windowed signal strictly no later than the cumulative one
    (fresh deltas vs history-diluted quantile), and after the storm
    ends the windowed signal DISARMS within one window while the
    cumulative hist keeps the autoscaler hot forever."""
    WINDOW = 4
    # 50 calm ticks × 2000 samples: 100k of history — old enough that
    # the window has forgotten all but the last 3 ticks of it, big
    # enough that the cumulative p99 needs several storm ticks before
    # the slow tail crosses its 1% mass
    calm_hist = [[0.1] * 2000 for _ in range(50)]
    storm = [[400.0] * 400 for _ in range(4)]
    calm_after = [[0.1] * 50 for _ in range(12)]
    schedule = calm_hist + storm + calm_after
    windowed, cumulative = _p99_streams(schedule, WINDOW)
    spec = "up_shed=1e9,up_p99_ms=100,up_after=1,down_after=2,cool=0"
    hot_w = _drive_autoscaler(windowed, spec)
    hot_c = _drive_autoscaler(cumulative, spec)

    def arm_tick(hot):
        return next(i for i, hcount in enumerate(hot) if hcount > 0)

    # ARMING: windowed strictly no later (here strictly earlier: the
    # cumulative p99 needs the slow tail to exceed 1% of ALL history)
    assert arm_tick(hot_w) < arm_tick(hot_c)
    assert arm_tick(hot_w) == len(calm_hist)  # the FIRST storm tick
    # DISARMING: within one window of the storm's end the windowed
    # signal reads calm and hot_ticks STOPS growing...
    settle = len(calm_hist) + len(storm) + WINDOW
    assert hot_w[settle:] == [hot_w[settle]] * len(hot_w[settle:])
    # ...while the cumulative signal stays hot EVERY tick to the end of
    # the horizon — the old behavior, now confined to MINIPS_OBS=0
    assert hot_c[-1] == len(hot_c) - arm_tick(hot_c)
    assert windowed[-1] is None or windowed[-1] < 100
    assert cumulative[-1] > 100


def test_send_heat_reports_windowed_p99_not_cumulative():
    """Integration pin on the rbH wire: with the window layer armed the
    report's p99 field is the WINDOWED quantile (None once a storm ages
    out — the disarm evidence), not the cumulative summary."""
    from tests.test_control_plane import _mk_lockstep_pair

    buses, tables, trainers = _mk_lockstep_pair(elastic="1",
                                                autoscale="1")
    try:
        tr0 = trainers[0]
        assert tr0.obs_window is not None  # always-on by default
        rb = tr0.rebalancer
        for _ in range(20):
            tables[0].timers.record_pull(0.4, 0.4)  # 400ms storm
        tr0.obs_window.roll()
        rb._send_heat("t", tables[0])
        rep = rb.heat_reports("t")[0]
        assert rep["p99"] is not None and rep["p99"] > 100.0
        for _ in range(tr0.obs_window.window):
            tr0.obs_window.roll()  # the storm ages out of the window
        rb._send_heat("t", tables[0])
        rep = rb.heat_reports("t")[0]
        # the OLD behavior (cumulative — never forgets) is GONE:
        assert rep["p99"] is None
        assert summarize_counts(
            tables[0].timers.snapshot()["hists"]["pull_latency"]
        )["p99_ms"] > 100.0
    finally:
        for b in buses:
            b.close()


def test_obs_off_env_disables_window_and_keeps_cumulative_signal(
        monkeypatch):
    """MINIPS_OBS=0 (the tax arm): the trainer builds no window layer,
    window_stats reports None (off ≠ idle), and the rbH p99 falls back
    to the cumulative quantile."""
    monkeypatch.setenv("MINIPS_OBS", "0")
    from tests.test_control_plane import _mk_lockstep_pair

    buses, tables, trainers = _mk_lockstep_pair(elastic="1",
                                                autoscale="1")
    try:
        tr0 = trainers[0]
        assert tr0.obs_window is None
        assert tr0.window_stats() is None
        for _ in range(5):
            tables[0].timers.record_pull(0.2, 0.2)
        tr0.rebalancer._send_heat("t", tables[0])
        rep = tr0.rebalancer.heat_reports("t")[0]
        assert rep["p99"] is not None and rep["p99"] > 100.0
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------- flight recorder
@pytest.fixture
def flight_box(tmp_path):
    """A fresh recorder in a tmp dir; restores the global after."""
    fl.reset_for_tests()
    rec = fl.init(0, str(tmp_path / "box"))
    yield rec
    fl.reset_for_tests()


def test_flight_ring_is_bounded_and_drops_oldest(tmp_path):
    fl.reset_for_tests()
    try:
        rec = fl.init(3, str(tmp_path), cap=4)
        for i in range(10):
            rec.ev("e", {"i": i})
        rec.dump()
        doc = json.load(open(rec.out_path))
        assert doc["rank"] == 3 and doc["cap"] == 4
        assert [e["args"]["i"] for e in doc["events"]] == [6, 7, 8, 9]
    finally:
        fl.reset_for_tests()


def test_flight_dump_is_atomic_idempotent_and_carries_window(flight_box):
    rec = flight_box
    rec.ev("hb_death", {"rank": 1})
    rec.snapshot_hook = lambda: {"rolls": 7}
    p1 = rec.dump()
    p2 = rec.dump()  # idempotent: re-dump rewrites whole
    assert p1 == p2 == rec.out_path
    assert not [f for f in os.listdir(os.path.dirname(p1))
                if ".tmp" in f]  # no torn tmp left behind
    doc = json.load(open(p1))
    assert doc["window"] == {"rolls": 7}
    assert doc["events"][0]["kind"] == "hb_death"
    assert doc["reasons"] == []
    # a snapshot hook that BLOWS UP must not lose the box
    rec.snapshot_hook = lambda: 1 / 0
    rec.dump()
    doc = json.load(open(p1))
    assert doc["window"] == {"error": "snapshot_hook failed"}


def test_flight_poison_reentrant_concurrent_paths(flight_box):
    """THE satellite-6 regression: two poison paths firing concurrently
    (gate timeout racing the heartbeat verdict) must both land — the
    dump serializes on its lock, the reasons list is append-only, and
    the file is complete valid JSON after every interleaving."""
    rec = flight_box
    n_threads, n_each = 6, 5
    barrier = threading.Barrier(n_threads)

    def path(i):
        barrier.wait()
        for j in range(n_each):
            rec.poison(f"poison_{i}", {"j": j})

    ths = [threading.Thread(target=path, args=(i,))
           for i in range(n_threads)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    doc = json.load(open(rec.out_path))  # parses = never torn
    kinds = [r["kind"] for r in doc["reasons"]]
    assert len(kinds) == n_threads * n_each
    for i in range(n_threads):
        assert kinds.count(f"poison_{i}") == n_each
    assert rec.dumps == n_threads * n_each


def test_flight_checkpoint_is_not_a_poison(flight_box):
    """Autoscaler actions dump via checkpoint(): recorded + dumped,
    but NOT a reason — healthy scaling must not read as failure on the
    merged timeline (review-round fix)."""
    fl.checkpoint("as_admit", {"shed_rate": 5.0})
    doc = json.load(open(flight_box.out_path))
    assert [e["kind"] for e in doc["events"]] == ["as_admit"]
    assert doc["reasons"] == []
    merged, _ = fl.merge_dumps({0: doc})
    assert merged["flight"][0]["poison"] is False


def test_flight_reasons_list_is_bounded(tmp_path):
    """A poison LOOP must not grow the reasons list without bound —
    past the cap the dropped counter testifies instead."""
    fl.reset_for_tests()
    try:
        rec = fl.init(0, str(tmp_path), cap=16)
        for i in range(rec._MAX_REASONS + 7):
            if len(rec._reasons) < rec._MAX_REASONS:
                rec._reasons.append((0.0, f"p{i}", None))
            else:
                rec.poison(f"p{i}")
        assert len(rec._reasons) == rec._MAX_REASONS
        assert rec.reasons_dropped == 7
        doc = json.load(open(rec.out_path))
        assert doc["reasons_dropped"] == 7
    finally:
        fl.reset_for_tests()


def test_flight_env_gate_and_default_dir(monkeypatch, tmp_path):
    fl.reset_for_tests()
    try:
        monkeypatch.setenv("MINIPS_FLIGHT", "0")
        assert fl.maybe_init(0) is None          # the tax arm
        fl.record("x")                           # no-ops, never raise
        fl.poison("x")
        assert fl.dump_now() is None
        monkeypatch.setenv("MINIPS_FLIGHT",
                           str(tmp_path / "explicit") + ":cap=9")
        rec = fl.maybe_init(1)
        assert rec.cap == 9
        assert rec.out_dir == str(tmp_path / "explicit")
        fl.reset_for_tests()
        monkeypatch.delenv("MINIPS_FLIGHT", raising=False)
        monkeypatch.setenv("MINIPS_RUN_ID", "424242")
        assert fl.default_dir() == os.path.join(
            tempfile.gettempdir(), "minips-flight-424242")
        with pytest.raises(ValueError, match="unknown option"):
            fl._parse_spec("/x:zap=1")
    finally:
        fl.reset_for_tests()


def test_flight_cli_merges_offset_aligned_timeline(tmp_path):
    """Two synthetic rank dumps with asymmetric heartbeat delays merge
    onto one aligned timeline (the NTP two-sample estimate), poisons
    flagged, exit 0 — and exit 1 with nothing to merge."""
    d = tmp_path / "boxes"
    d.mkdir()

    def box(rank, t0, events, reasons, hb):
        json.dump({"rank": rank, "cap": 64,
                   "events": [{"t_us": t, "kind": k} for t, k in events],
                   "reasons": [{"t_us": t, "kind": k}
                               for t, k in reasons],
                   "hb_delays_us": hb},
                  open(d / f"flight-rank{rank}.json", "w"))

    # rank 1's clock runs 1000us ahead: its min delay of rank 0's beats
    # reads 500+1000, rank 0's of rank 1's reads 500-1000 → offset 1000
    box(0, 0, [(100.0, "hb_death")], [(200.0, "term_advance")],
        {"1": -500.0})
    box(1, 0, [(1150.0, "late_event")], [], {"0": 1500.0})
    out = d / "merged.json"
    rc = fl.main([str(d), "-o", str(out)])
    assert rc == 0
    doc = json.load(open(out))
    assert doc["summary"]["clock_offsets_us"] == {"0": 0.0,
                                                  "1": 1000.0}
    kinds = [e["kind"] for e in doc["flight"]]
    assert kinds == ["hb_death", "late_event", "term_advance"]
    assert doc["flight"][2]["poison"] is True
    # aligned: rank 1's 1150us event lands at 150us, between the two
    assert doc["flight"][1]["t_us"] == 150.0
    empty = tmp_path / "empty"
    empty.mkdir()
    assert fl.main([str(empty)]) == 1


def test_flight_merge_surfaces_per_tenant_slo_burns(tmp_path, capsys):
    """The merged summary rolls up slo_burn edges BY TENANT (the burn
    is why the box exists — no grepping the timeline), tolerant of a
    burn event with torn args, and empty when no burns fired."""
    d = tmp_path / "boxes"
    d.mkdir()

    def box(rank, events):
        json.dump({"rank": rank, "cap": 64,
                   "events": [{"t_us": t, "kind": k, "args": a}
                              for t, k, a in events],
                   "reasons": [], "hb_delays_us": {}},
                  open(d / f"flight-rank{rank}.json", "w"))

    box(0, [(100.0, "slo_burn", {"tenant": "inf", "metric": "read"}),
            (300.0, "slo_clear", {"tenant": "inf", "metric": "read"}),
            (400.0, "slo_burn", {"tenant": "inf", "metric": "shed"})])
    box(1, [(150.0, "slo_burn", {"tenant": "trn", "metric": "read"}),
            (500.0, "slo_burn", None)])  # torn args: counted as "?"
    rc = fl.main([str(d)])
    assert rc == 0
    out = capsys.readouterr().out
    summary = json.loads(out.strip().splitlines()[-1])
    assert summary["slo_burns"] == {"inf": 2, "trn": 1, "?": 1}
    assert "SLO burn edges on this timeline" in out
    # clears don't count as burns; a burn-free merge reports {}
    quiet = tmp_path / "quiet"
    quiet.mkdir()
    json.dump({"rank": 0, "cap": 64,
               "events": [{"t_us": 1.0, "kind": "slo_clear",
                           "args": {"tenant": "inf"}}],
               "reasons": [], "hb_delays_us": {}},
              open(quiet / "flight-rank0.json", "w"))
    assert fl.main([str(quiet)]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["slo_burns"] == {}
    assert "SLO burn edges" not in out


def test_flight_sweep_reclaims_dead_runs_only(tmp_path, monkeypatch):
    tmp = tmp_path / "tmp"
    tmp.mkdir()
    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp))
    alive = tmp / f"minips-flight-{os.getpid()}"
    dead = tmp / "minips-flight-99999999"  # beyond pid_max: dead
    named = tmp / "minips-flight-mybox"    # operator's: never touched
    for p in (alive, dead, named):
        p.mkdir()
        (p / "flight-rank0.json").write_text("{}")
    removed = fl.sweep_stale_dirs()
    assert removed == 1
    assert alive.exists() and named.exists() and not dead.exists()


def test_heartbeat_stall_forgiveness_is_counted(monkeypatch):
    """Satellite: a forgiven stall is VISIBLE — the monitor counts it
    and stats() (the wire_record heartbeat block) carries it; before
    this a forgiven stall was indistinguishable from health."""
    from tests.conftest import mk_loopback_buses

    from minips_tpu.comm.heartbeat import HeartbeatMonitor

    monkeypatch.setenv("MINIPS_HEARTBEAT",
                       "interval=0.05,timeout=1.0,stall=2.0")
    buses = mk_loopback_buses(2)
    try:
        fake = [0.0]
        mon = HeartbeatMonitor(buses[0], [0, 1], interval=0.05,
                               timeout=1.0, clock=lambda: fake[0])
        mon._on_beat(1, {})
        fake[0] = 0.5
        mon.check()                      # baseline sweep
        assert mon.stall_forgiven == 0
        fake[0] = 5.5                    # 5s observer coma
        mon.check()                      # forgiven — and COUNTED now
        assert mon.stall_forgiven == 1
        st = mon.stats()
        assert st["stall_s"] == 2.0 and st["stall_forgiven"] == 1
        assert st["dead"] == []
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------------ slow drill
@pytest.mark.slow
def test_chaos_kill_leaves_flight_dumps_with_no_obs_env(tmp_path):
    """THE acceptance drill: a seeded 3-proc SIGKILL of rank 0 (the
    lease holder) with NO observability env armed — MINIPS_TRACE,
    MINIPS_FLIGHT, MINIPS_OBS all explicitly empty — leaves per-rank
    flight dumps in the DEFAULT directory; every survivor's box carries
    the death verdict and the term advance with its signal values, and
    the merge CLI (exit 0) reconstructs verdict → term advance →
    death plan."""
    import subprocess

    from minips_tpu import launch

    run_id = str(90_000_000 + os.getpid())  # synthetic, beyond pid_max
    flight_dir = os.path.join(tempfile.gettempdir(),
                              f"minips-flight-{run_id}")
    ck = str(tmp_path / "ck")
    rc, events = launch.run_local_job_raw(
        3, [sys.executable, "-m", APP, "--model", "sparse", "--mode",
            "ssp", "--staleness", "2", "--iters", "30", "--batch",
            "64", "--checkpoint-dir", ck, "--checkpoint-every", "5"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                   "MINIPS_ELASTIC": "1",
                   "MINIPS_CHAOS_KILL": "7:rank=0,step=12",
                   "MINIPS_HEARTBEAT": "interval=0.1,timeout=1.0",
                   # ZERO pre-arming — the whole point of the box:
                   "MINIPS_TRACE": "", "MINIPS_FLIGHT": "",
                   "MINIPS_OBS": "",
                   # namespace the default dir for this drill only (a
                   # launcher id, not an observability knob)
                   "MINIPS_RUN_ID": run_id},
        timeout=240.0, kill_on_failure=False)
    dones = {r: ev[-1] for r, ev in enumerate(events)
             if ev and ev[-1].get("event") == "done"}
    assert set(dones) == {1, 2}, (rc, events)
    # every SURVIVOR left a box (rank 0 was SIGKILLed: nothing can)
    all_reasons: list[str] = []
    for r in (1, 2):
        path = os.path.join(flight_dir, f"flight-rank{r}.json")
        assert os.path.exists(path), os.listdir(flight_dir)
        doc = json.load(open(path))
        reasons = [e["kind"] for e in doc["reasons"]]
        all_reasons += reasons
        assert "hb_death" in reasons, reasons
        # the final windowed-metrics snapshot rides the dump
        assert doc["window"] is not None
        assert doc["window"]["rolls"] > 0
    # the term ADVANCE decision lands in at least one box — the first
    # rank to convict decides; a survivor whose own verdict lost the
    # race to the successor's beat stamp only OBSERVED the new term
    # (its done line still reads term 1) and legitimately records no
    # decision of its own
    assert "term_advance" in all_reasons, all_reasons
    boxes = {r: json.load(open(os.path.join(
        flight_dir, f"flight-rank{r}.json"))) for r in (1, 2)}
    adv = next(e for doc in boxes.values()
               for e in doc["reasons"] if e["kind"] == "term_advance")
    # the decision's WHY: the ballot inputs at decision time
    assert adv["args"]["term"] == 1
    assert adv["args"]["holder"] == 1
    assert adv["args"]["dead"] == 0
    # the successor (rank 1) also planned the death
    r1_reasons = [e["kind"] for e in boxes[1]["reasons"]]
    assert "death_plan" in r1_reasons, r1_reasons
    plan = next(e for e in boxes[1]["reasons"]
                if e["kind"] == "death_plan")
    assert plan["args"]["rank"] == 0 and plan["args"]["rstep"] >= 0
    # the merge CLI reconstructs the sequence with exit 0, on the
    # MERGED cross-rank timeline (whichever rank decided each step)
    proc = subprocess.run(
        [sys.executable, "-m", "minips_tpu.obs.flight", flight_dir],
        capture_output=True, text=True, timeout=60.0)
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.splitlines()
    summary = json.loads(lines[-1])
    assert sorted(int(r) for r in summary["ranks"]) == [1, 2]
    timeline = "\n".join(lines[:-1])
    assert timeline.index("hb_death") < timeline.index("term_advance") \
        < timeline.index("death_plan")