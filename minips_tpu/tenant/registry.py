"""Tenant registry — one PS fleet serving many models with isolated SLOs.

A TENANT is a table with its own service contract: its own updater and
wire tier, its own staleness bound ``s``, its own admission budget
(``rate``/``burst`` token bucket), and its own replica/hedge budgets.
The fleet-level machinery (heat accounting, migration planning, the
autoscaler's load picture, the serve plane's shed paths) historically
summed every table into one signal — PR 12 documented that a summed
shed counter cannot tell a storming tenant from a hot fleet. This
registry is the naming layer that splits those signals: every frame
head carries the owning table's tenant id (``tb``, next to the
ws/nr/dm/rb config stamp), heat reports are stamped with it, and the
serve plane's admission/shed counters are kept per tenant so an
elastic decision can NAME the tenant that caused it.

Config rides ``MINIPS_TENANT`` (off by default). Entries are split on
``;``: each entry is a tenant — ``name`` or ``name:k=v,k=v`` where the
name is the TABLE name it governs — or a fleet-global knob written
plain ``k=v`` (no ``:``). ``"1"`` arms a single default tenant per
table with no overrides (the armed-idle drill config: bitwise-equal to
off, zero tenant counters). Examples::

    MINIPS_TENANT="1"
    MINIPS_TENANT="trn:rate=0,s=1;inf:rate=500,burst=64,s=2"
    MINIPS_TENANT="trn;inf:rate=500;shared=1"

Per-tenant knobs: ``updater`` (sgd|adagrad|adam), ``wire`` (f32|int8,
the pull wire tier), ``s`` (staleness bound, float or ``inf``),
``block`` (rebalance block rows), ``rate``/``burst`` (admission token
bucket; rate=0 = never shed), ``replicas`` (serve-plane replica
budget), ``hedge`` (hedge budget per window). Global knobs:
``shared`` (0|1 — ONE fleet-wide admission bucket shared by every
tenant instead of per-tenant buckets; the coupling contrast arm the
multi_tenant bench measures against). Unknown knobs, bad values, and
duplicate tenant names raise ValueError naming the offending token.
Knob reference: docs/api.md; protocol and the isolation argument:
docs/architecture.md "Multi-tenant tables".

Tenant ids are 1-based (0 on the wire = tenancy off): named tenants
take spec order; the bare-``"1"`` default takes sorted table-name
order at bind. Every rank must agree — the ``tb`` config stamp in the
frame head poisons a table on divergence exactly like a ws/nr/dm/rb
mismatch would, so a fleet half-armed or armed with reordered specs
fails loudly instead of silently crossing tenants' wires.

Honest limits: tenancy namespaces ACCOUNTING and ADMISSION, not
compute — tenants still share each rank's process, bus, and push
thread, so a tenant burning CPU inside its own admitted budget still
steals cycles (the bench's 10% isolation bound, not 0%). And the
registry governs tables, not requests: one table = one tenant, there
is no finer-grained per-request tenancy.
"""

from __future__ import annotations

import os
import re
from typing import Optional

__all__ = ["TenantSpec", "TenantRegistry", "maybe_registry"]

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

_UPDATERS = ("sgd", "adagrad", "adam")
_WIRES = ("f32", "int8")


def _f_nonneg(v: str, knob: str) -> float:
    try:
        x = float(v)
    except ValueError as e:
        raise ValueError(f"bad value for {knob}: {v!r}") from e
    if not (x >= 0.0):  # refuses nan too
        raise ValueError(f"bad value for {knob}: {v!r} (must be >= 0)")
    return x


def _i_min(v: str, knob: str, lo: int) -> int:
    try:
        x = int(v)
    except ValueError as e:
        raise ValueError(f"bad value for {knob}: {v!r}") from e
    if x < lo:
        raise ValueError(
            f"bad value for {knob}: {v!r} (must be >= {lo})")
    return x


class TenantSpec:
    """One tenant's parsed service contract. Every field except
    ``name`` is Optional — ``None`` means "inherit today's behavior",
    which is what makes the bare default tenant bitwise-idle."""

    def __init__(self, name: str, *,
                 updater: Optional[str] = None,
                 wire: Optional[str] = None,
                 s: Optional[float] = None,
                 block: Optional[int] = None,
                 rate: Optional[float] = None,
                 burst: Optional[int] = None,
                 replicas: Optional[int] = None,
                 hedge: Optional[int] = None):
        self.name = name
        self.tid = 0          # assigned by the registry (1-based)
        self.updater = updater
        self.wire = wire
        self.s = s
        self.block = block
        self.rate = rate
        self.burst = burst
        self.replicas = replicas
        self.hedge = hedge

    _KNOBS = ("updater", "wire", "s", "block", "rate", "burst",
              "replicas", "hedge")

    def overrides(self) -> dict:
        """The non-None knobs, for stats/flight evidence."""
        out = {}
        for k in self._KNOBS:
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kv = ", ".join(f"{k}={v!r}" for k, v in self.overrides().items())
        return f"TenantSpec({self.name!r}, tid={self.tid}{', ' if kv else ''}{kv})"


class TenantRegistry:
    """Parsed ``MINIPS_TENANT``: the tenant set plus fleet-global
    knobs. ``bind(tables)`` (called once from the trainer ctor, before
    any balance/serve layer arms) assigns tenant ids and validates the
    spec against the constructed tables — every rank runs the same
    deterministic assignment, and the wire's ``tb`` stamp enforces
    that they actually did."""

    def __init__(self, tenants: Optional[dict[str, TenantSpec]] = None,
                 *, shared: bool = False):
        # named tenants keep SPEC order (dict insertion order); the
        # default registry (tenants=None) materializes one bare tenant
        # per table in sorted-name order at bind
        self.tenants: dict[str, TenantSpec] = dict(tenants or {})
        self.default = not self.tenants
        self.shared = bool(shared)
        self._bound = False
        for i, sp in enumerate(self.tenants.values()):
            sp.tid = i + 1

    # ------------------------------------------------------------ parse
    @classmethod
    def parse(cls, spec: str) -> "TenantRegistry":
        spec = (spec or "").strip()
        if spec in ("1", "on", "true"):
            return cls()
        tenants: dict[str, TenantSpec] = {}
        shared = False
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if ":" not in entry and "=" in entry:
                # fleet-global knob
                k, v = entry.split("=", 1)
                k, v = k.strip(), v.strip()
                if k == "shared":
                    if v not in ("0", "1"):
                        raise ValueError(
                            f"MINIPS_TENANT: bad value for shared: "
                            f"{v!r} (must be 0 or 1)")
                    shared = v == "1"
                else:
                    raise ValueError(
                        f"MINIPS_TENANT: unknown global knob {k!r}")
                continue
            name, _, body = entry.partition(":")
            name = name.strip()
            if not _NAME_RE.fullmatch(name):
                raise ValueError(
                    f"MINIPS_TENANT: bad tenant name {name!r}")
            if name in tenants:
                raise ValueError(
                    f"MINIPS_TENANT: duplicate tenant {name!r}")
            kw: dict = {}
            for item in body.split(","):
                item = item.strip()
                if not item:
                    continue
                if "=" not in item:
                    raise ValueError(
                        f"MINIPS_TENANT: expected k=v in tenant "
                        f"{name!r}, got {item!r}")
                k, v = item.split("=", 1)
                k, v = k.strip(), v.strip()
                try:
                    if k == "updater":
                        if v not in _UPDATERS:
                            raise ValueError(
                                f"bad value for updater: {v!r}")
                        kw["updater"] = v
                    elif k == "wire":
                        if v == "float32":  # push-knob spelling
                            v = "f32"
                        if v not in _WIRES:
                            raise ValueError(
                                f"bad value for wire: {v!r}")
                        kw["wire"] = v
                    elif k == "s":
                        kw["s"] = _f_nonneg(v, "s")
                    elif k == "block":
                        kw["block"] = _i_min(v, "block", 1)
                    elif k == "rate":
                        kw["rate"] = _f_nonneg(v, "rate")
                    elif k == "burst":
                        kw["burst"] = _i_min(v, "burst", 1)
                    elif k == "replicas":
                        kw["replicas"] = _i_min(v, "replicas", 1)
                    elif k == "hedge":
                        kw["hedge"] = _i_min(v, "hedge", 0)
                    else:
                        raise ValueError(f"unknown knob {k!r}")
                except ValueError as e:
                    raise ValueError(
                        f"MINIPS_TENANT: tenant {name!r}: {e}") from e
            tenants[name] = TenantSpec(name, **kw)
        if not tenants:
            raise ValueError(
                f"MINIPS_TENANT: no tenants in spec {spec!r}")
        return cls(tenants, shared=shared)

    # ------------------------------------------------------------- bind
    def bind(self, tables: dict) -> None:
        """Assign tenant ids over the trainer's table set and validate
        the spec against what was actually constructed. Named mode:
        every table must be named (an unlisted table would silently
        run outside every SLO — refuse instead), and a spec'd
        updater/wire must MATCH the built table (the registry cannot
        rebuild a table; a mismatch means the app ignored
        ``table_kwargs``). Default mode: one bare tenant per table,
        sorted-name order. Idempotent per registry instance."""
        if self._bound:
            return
        if self.default:
            for i, name in enumerate(sorted(tables)):
                sp = TenantSpec(name)
                sp.tid = i + 1
                self.tenants[name] = sp
        else:
            missing = sorted(set(tables) - set(self.tenants))
            if missing:
                raise ValueError(
                    f"MINIPS_TENANT: table {missing[0]!r} has no "
                    f"tenant spec (every table must be named)")
            for name, sp in self.tenants.items():
                t = tables.get(name)
                if t is None:
                    continue  # spec'd tenant whose table this job lacks
                if sp.updater is not None and sp.updater != t.updater:
                    raise ValueError(
                        f"MINIPS_TENANT: tenant {name!r} spec says "
                        f"updater={sp.updater!r} but table was built "
                        f"with {t.updater!r}")
                if sp.wire is not None and sp.wire != t.pull_wire:
                    raise ValueError(
                        f"MINIPS_TENANT: tenant {name!r} spec says "
                        f"wire={sp.wire!r} but table was built with "
                        f"{t.pull_wire!r}")
        self._bound = True

    def spec_for(self, name: str) -> Optional[TenantSpec]:
        return self.tenants.get(name)

    def table_kwargs(self, name: str) -> dict:
        """Ctor overrides an app should splat into ``ShardedTable``
        for this tenant's table — the spec'd updater/wire become the
        build, so ``bind`` has nothing to refuse."""
        sp = self.tenants.get(name)
        if sp is None:
            return {}
        kw: dict = {}
        if sp.updater is not None:
            kw["updater"] = sp.updater
        if sp.wire is not None:
            kw["pull_wire"] = sp.wire
        return kw


def maybe_registry(spec: Optional[str] = None) -> Optional[TenantRegistry]:
    """The trainer-ctor arming rule every MINIPS_* layer shares:
    explicit spec wins, else $MINIPS_TENANT, else off; ``""``/``"0"``
    = off, anything else parses or raises."""
    if spec is None:
        spec = os.environ.get("MINIPS_TENANT", "")
    if spec in ("", "0"):
        return None
    return TenantRegistry.parse(spec)
