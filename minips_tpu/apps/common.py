"""Shared app scaffolding — the gflags `main()` pattern of the reference
apps (SURVEY.md §1 L7, §5.6): parse flags, build config, run, print metrics.
"""

from __future__ import annotations

import argparse

from minips_tpu.core.config import Config, add_config_flags, config_from_args
from minips_tpu.utils.metrics import MetricsLogger


def app_main(name: str, default_cfg: Config, run, extra_flags=None):
    # Dev escape hatch: MINIPS_FORCE_CPU=1 runs on (fake multi-) CPU devices.
    # Must happen before the first backend-touching JAX call; the sandbox's
    # TPU plugin ignores the JAX_PLATFORMS env var, hence config.update.
    import os
    if os.environ.get("MINIPS_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    parser = argparse.ArgumentParser(prog=name)
    add_config_flags(parser)
    parser.add_argument("--exec", dest="exec_mode", default="spmd",
                        choices=["spmd", "threaded"],
                        help="spmd: fused collective step (TPU fast path); "
                             "threaded: per-worker threads with the "
                             "consistency gate (reference semantics)")
    if extra_flags is not None:
        extra_flags(parser)
    args = parser.parse_args()
    cfg = config_from_args(args, default=default_cfg)
    metrics = MetricsLogger(cfg.train.metrics_path, verbose=True)
    result = run(cfg, args, metrics)
    metrics.close()
    return result
