"""Elastic membership — server ranks join and leave a LIVE job.

The reference MiniPs lineage answers a dead server with detect-and-
restart: the whole gang dies and resumes from the last checkpoint
(PARITY.md failure-model rows). This module is the production
alternative the roadmap names (item 3): PR3's reliable delivery plus
PR4's epoch-fenced key-range migration are 80% of online resharding —
the membership state machine here is the remaining 20%, composing them
into a training SERVICE that survives preemptible fleets. Loss of a
rank degrades to latency and reduced capacity, never to a poisoned run.

Armed by ``MINIPS_ELASTIC`` (off by default — armed-but-idle is pinned
bitwise-equal to off by the lockstep drill). The world is launched at a
fixed ADDRESS SPACE of ``num_processes`` bus slots; membership is which
slots are LIVE. Three transitions, all riding the existing machinery:

**Join.** A rank configured standby (``MINIPS_ELASTIC="live=0-2"`` in a
4-slot world makes rank 3 a standby) connects and handshakes like
everyone, but is EXCLUDED from clock gossip (its idle clock must not
gate the fleet) and trains nothing. The coordinator's bootstrap plan —
a normal epoch-fenced migration at step ~0 — moves the standby's home
blocks onto live ranks (the standby ships its freshly-initialized
state via ``rbS``, so seeded init survives). When the standby announces
(``mbJ`` — at its configured join step, or whenever its operator says),
the coordinator admits it at a routing-epoch boundary: ``mbA`` carries
the catch-up clock, the admit plan returns the joiner's home blocks to
it (rows + optimizer state hand off under the existing rbS/rbA/rbF
fence — the SSP bound holds mid-join exactly as it does mid-migration),
the joiner publishes the catch-up clock and THEN its live announce
(``mbL``, same FIFO link — so every rank re-includes it in gossip only
after a current clock is stored; including a clock-0 ghost would wedge
every gate), and trains from there.

**Leave (graceful).** A rank receiving a preemption signal (SIGTERM, a
``mbDr`` control frame, or the drill's ``--drain-at``) stops training,
hard-drains its in-flight pushes, publishes the RETIRED clock sentinel
(gates never wait on it again), and asks the coordinator to plan it out
(``mbQ``, refreshed with its settle state — the coordinator plans only
over a settled leaver, the one real precondition of the fence
protocol). The leave plan is a normal migration: the leaver SHIPS its
owned blocks to survivors and releases fences only after every live
rank's adoption ack — per-link FIFO then guarantees no frame addressed
to the leaver is still in flight when it announces ``mbG`` and exits
clean: rc 0, zero restored state, zero poisons.

**Death (ungraceful).** When the ``HeartbeatMonitor`` declares a rank
dead, every rank immediately excludes it from gossip (the SSP gate
recomputes over the shrunken membership — a corpse cannot hold the
clock hostage) and unjams waits aimed at it (push windows drop their
unacked seqs, counted). The coordinator picks the newest checkpoint
step every rank holds under the current partition
(``ckpt/elastic.find_live_step``) and broadcasts a DEATH plan: the
corpse's owned blocks re-home onto survivors with the plan's ``dead``
extras, and each new owner installs ``ckpt/elastic.load_block_state``
— which reads THROUGH the save-time rebalance overlay — instead of
waiting for an rbS no corpse will send. Restored blocks serve
un-fenced: no stale push can be forwarded from a corpse, so the fence
would protect nothing; the recovery semantics are exactly "that rank's
ranges roll back to the last checkpoint". Workers re-route refused or
orphaned legs via the existing ``psE``/resend machinery; replicas on
the dead rank demote by lease expiry (PR6). A DEAD COORDINATOR is no
longer the SPOF it was: the coordinator role is a lease
(balance/control_plane.py) — on the holder's death verdict every rank
advances the term and the lowest-ranked live rank succeeds
deterministically, re-targets the in-flight ``mbJ``/``mbQ`` retry
loops (they address ``self.coord``, which succession updates), and
issues the old holder's death plan itself; a stale ex-coordinator
returning from a partition is fenced by term on every coordinator
broadcast it attempts. A death the plane CANNOT own — no checkpoint
anywhere, no live rank left to take the lease, a verdict that never
arrives within the grace window — stays exactly as loud as before:
``PeerFailureError``, exit 42, the gang-restart drill.

Spec grammar (``$MINIPS_ELASTIC``)::

    1                        # armed, all ranks live (idle plane)
    live=0-2                 # ranks 0..2 live, the rest standby
    live=0+2,grace=20        # '+'-separated list; death-verdict grace

Knob table: docs/fault_tolerance.md "The membership ladder".
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from minips_tpu.balance.control_plane import (CoordinatorLease,
                                              SuspicionQuorum,
                                              expand_to_domains)
from minips_tpu.consistency.gate import (FencedOutError,
                                         PeerFailureError, publish_clock)
from minips_tpu.obs import flight as _fl
from minips_tpu.obs import tracer as _trc

__all__ = ["MembershipConfig", "Membership", "plan_evacuation",
           "plan_admission"]


def _parse_ranks(val: str) -> set[int]:
    out: set[int] = set()
    for part in filter(None, (p.strip() for p in val.split("+"))):
        lo, dash, hi = part.partition("-")
        if dash:
            out.update(range(int(lo), int(hi) + 1))
        else:
            out.add(int(lo))
    return out


class MembershipConfig:
    """Parsed ``MINIPS_ELASTIC`` knobs (``k=v`` comma list; the bare
    string ``"1"`` arms the plane with every rank live)."""

    def __init__(self, *, live: Optional[set[int]] = None,
                 grace: float = 15.0):
        if grace <= 0:
            raise ValueError("grace must be > 0 seconds")
        self.live = None if live is None else {int(r) for r in live}
        self.grace = float(grace)  # death-verdict wait before poisoning

    @classmethod
    def parse(cls, spec: str) -> "MembershipConfig":
        spec = (spec or "").strip()
        if spec in ("", "1", "on", "true"):
            return cls()
        kw: dict = {}
        for item in filter(None, (e.strip() for e in spec.split(","))):
            if "=" not in item:
                raise ValueError(
                    f"MINIPS_ELASTIC: expected k=v, got {item!r}")
            k, _, v = item.partition("=")
            k = k.strip()
            if k == "live":
                kw["live"] = _parse_ranks(v)
            elif k == "grace":
                try:
                    kw["grace"] = float(v)
                except ValueError as e:
                    raise ValueError(
                        f"MINIPS_ELASTIC: bad value for grace: "
                        f"{v!r}") from e
            else:
                raise ValueError(
                    f"MINIPS_ELASTIC: unknown knob {k!r}")
        return cls(**kw)


def plan_evacuation(router, victims: set[int],
                    targets: list[int]) -> dict[int, int]:
    """New FULL overlay with every block currently owned by a rank in
    ``victims`` re-homed round-robin onto ``targets`` — the leave /
    death / bootstrap planner (pure, deterministic: every rank handed
    the same router state computes the same table). A block whose
    round-robin slot IS its home rank leaves the overlay (home blocks
    must be absent, BlockRouter.apply's invariant)."""
    if not targets:
        raise ValueError("plan_evacuation: no live targets left")
    _ep, ov = router.table()
    owner = router.owner_of_blocks()
    new_ov = {int(b): int(o) for b, o in ov.items()
              if int(o) not in victims}
    vb = sorted(int(b) for v in victims
                for b in np.nonzero(owner == v)[0])
    for i, b in enumerate(vb):
        dst = int(targets[i % len(targets)])
        if dst == router.home_of(b):
            new_ov.pop(b, None)
        else:
            new_ov[b] = dst
    return new_ov


def plan_admission(router, joiner: int, *,
                   reports: Optional[dict] = None,
                   live: Optional[set] = None,
                   threshold: float = 1.0,
                   max_blocks: int = 8) -> dict[int, int]:
    """New FULL overlay admitting ``joiner``: its home blocks return
    home (their interim owners ship state under the normal fence);
    everything else keeps its current assignment.

    HEAT-AWARE PLACEMENT (ROADMAP item 3's remaining headroom, 'one
    planner call away'): with the coordinator's per-rank heat
    ``reports`` (balance/rebalancer.py ``rbH`` payloads, ``live`` =
    the pre-join live set they cover), the admission plan additionally
    runs the PR4 bin-packer (:func:`plan_assignment`) over the
    POST-ADMISSION load picture — the joiner starts at the heat of its
    returning home blocks, interim owners are debited the same — so a
    rank joining a skewed fleet immediately absorbs hot blocks instead
    of idling on its (typically cold, freshly-bootstrapped) home range
    until the ordinary rebalance loop notices. ``threshold`` defaults
    to 1.0 here (not the steady-state hysteresis): an empty joiner IS
    the imbalance, and admission is already a migration — extra moves
    ride the same fence for free. Every rank in ``live`` must have
    reported; otherwise (or with no reports) the plan degrades to
    home-blocks-only, exactly the pre-heat behavior."""
    _ep, ov = router.table()
    new_ov = {int(b): int(o) for b, o in ov.items()
              if router.home_of(int(b)) != joiner and int(o) != joiner}
    if not reports or live is None or not live <= set(reports):
        return new_ov
    home = router.home_of
    joiner_home = {b for b in range(router.num_blocks)
                   if home(b) == joiner}
    ranks = sorted(set(live) | {joiner})
    idx = {r: i for i, r in enumerate(ranks)}
    loads = np.zeros(len(ranks), np.float64)
    candidates: dict[int, tuple[int, float]] = {}
    for r in sorted(set(live)):
        rep = reports[r]
        loads[idx[r]] = float(rep.get("total", 0.0))
        for b, h in zip(rep.get("blocks", ()), rep.get("heat", ())):
            b, h = int(b), float(h)
            if b in joiner_home:
                # this block is returning to the joiner under the
                # admission overlay: credit the joiner, debit the
                # interim owner — the planner sees the POST-join world
                loads[idx[joiner]] += h
                loads[idx[r]] -= h
                continue
            candidates[b] = (idx[r], h)
    from minips_tpu.balance.rebalancer import plan_assignment

    for b, _src, dst in plan_assignment(loads, candidates, threshold,
                                        max_blocks):
        if ranks[dst] == home(b):
            new_ov.pop(b, None)
        else:
            new_ov[b] = ranks[dst]
    return new_ov


class Membership:
    """The membership state machine riding a ShardedPSTrainer — module
    docstring for the protocol. One instance per process; rank 0 holds
    the coordinator LEASE at launch (balance/control_plane.py — on its
    death the lowest-ranked live rank succeeds deterministically)."""

    JOIN_KIND = "mbJ"     # standby -> coordinator: admit me
    ADMIT_KIND = "mbA"    # coordinator broadcast: rank + catch-up clock
    LIVE_KIND = "mbL"     # joiner broadcast: include me (clock published)
    LEAVE_KIND = "mbQ"    # leaver -> coordinator: plan me out (+settle)
    GONE_KIND = "mbG"     # leaver broadcast: fences done, exiting clean
    DEATH_KIND = "mbD"    # coordinator broadcast: verdict (rstep | -1)
    DRAIN_KIND = "mbDr"   # operator -> rank: please drain (the --drain
    #                       control frame; SIGTERM is the other trigger)
    END_KIND = "mbEnd"    # coordinator broadcast at finalize: no more
    #                       admissions — un-admitted standbys exit clean
    #                       instead of timing out against a gone fleet
    HANDOVER_KIND = "mbH"  # holder broadcast: lease transferred (new
    #                        term + holder + the coordinator state the
    #                        successor installs — heat reports, queues,
    #                        autoscaler hysteresis)

    def __init__(self, trainer, cfg: MembershipConfig):
        self.trainer = trainer
        self.cfg = cfg
        self.bus = trainer.bus
        self.rank = int(trainer.bus.my_id)
        self.n = int(trainer.num_processes)
        self.coord = 0
        self.rb = trainer.rebalancer
        if self.rb is None:
            raise RuntimeError(
                "elastic membership needs the rebalancer machinery "
                "(the trainer arms it when MINIPS_ELASTIC is set)")
        all_ranks = set(range(self.n))
        live = all_ranks if cfg.live is None else set(cfg.live) & all_ranks
        if self.coord not in live:
            raise ValueError(
                "MINIPS_ELASTIC: rank 0 (the launch-time coordinator "
                "lease holder) must be in the initial live set")
        # the coordinator lease (control_plane.py): rank 0 holds term 0;
        # a holder death advances the term to the lowest live rank at
        # every rank identically — no election frames
        self.lease = CoordinatorLease(self.coord)
        # autoscaler plumbing (balance/autoscaler.py): with hold_joins
        # armed, announced standbys queue until a grant_join() credit —
        # scale-up becomes a load decision instead of an auto-admit
        self.hold_joins = False
        self._join_credits = 0
        self._lock = threading.Lock()
        self.live: set[int] = set(live)
        self.standby: set[int] = all_ranks - live
        self.dead: set[int] = set()
        self.left: set[int] = set()
        self._unrecoverable: set[int] = set()
        self._death_t: dict[int, float] = {}   # rank -> detection time
        self._verdicts: dict[int, int] = {}    # rank -> rstep (-1 bad)
        self._pending_deaths: list[int] = []   # coordinator queue
        self._pending_joins: list[int] = []    # coordinator queue
        self._leave_reqs: dict[int, dict] = {}  # rank -> latest mbQ
        self._bootstrapped = not self.standby
        self._admit_clk: Optional[int] = None  # my mbA, standby side
        self._drain = False
        self._last_join_tx = 0.0
        self._ckpt_dir: Optional[str] = None
        self.counters = {"joins": 0, "leaves": 0, "deaths": 0,
                         "plans": 0}
        # standbys are OUT of every rank's gossip view from the first
        # frame (their clocks sit at 0 and must gate nobody — the
        # joiner re-enters via include() after its catch-up publish)
        for s in self.standby:
            trainer.gossip.exclude(s)
        # death detection hook: the monitor's sweep thread fires this
        # the moment a peer's silence crosses the timeout
        # split-brain hardening (this PR): death verdicts are
        # CORROBORATED — the monitor's timeout makes a SUSPECT, the
        # suspicion gossips on the heartbeat wire next to the lease
        # stamp, and conviction needs a majority of the live view
        # (control_plane.SuspicionQuorum). A minority island cannot
        # convict the majority, so it cannot mint a term or issue plans.
        self.quorum = SuspicionQuorum(self.rank)
        self._quorum_claimed: set[int] = set()  # verdicts this rank
        #                                         recorded (dedup across
        #                                         sweep + beat threads)
        self._convicted_term: Optional[int] = None  # fleet declared ME dead
        # fail-slow quorum (obs/slowness.py, bound via bind_slowness):
        # a SECOND SuspicionQuorum over the same heartbeat gossip wire
        # — slow ballots ride as ``slw`` next to the death ballot's
        # ``sus``, and a SLOW VERDICT needs the same strict majority.
        # Unlike death, a slow verdict is NOT sticky: it stands only
        # while the quorum stands (slow_view recomputes), so a
        # recovered rank's demotion bias lifts by itself.
        self.slow_quorum = SuspicionQuorum(self.rank)
        self._slow_lock = threading.Lock()
        self._slow_verdicts: set[int] = set()
        self._slow_since: dict[int, int] = {}  # rank -> holder ticks
        self._slow_drained: set[int] = set()   # escalations issued
        self._slowness = None                  # obs.slowness monitor
        self._slow_cfg = None                  # its SlownessConfig
        self._domain_group = 1                 # hybrid-plane domains
        self.counters["slow_verdicts"] = 0
        self.counters["slow_drains"] = 0
        if trainer.monitor is not None:
            trainer.monitor.on_failure = self._on_peer_dead
            trainer.monitor.on_suspect = self._on_suspect
            # lease stamps + my suspicion ballot ride every heartbeat:
            # peers max-merge the term, so a partitioned ex-coordinator
            # learns it lost the lease from the FIRST beat it hears on
            # return (the self fence — control_plane.py module
            # docstring), and ballots reach exactly the ranks a
            # partition still lets us reach
            trainer.monitor.payload_extra = self._beat_payload
            trainer.monitor.on_beat_extra = self._on_lease_beat
        bus = self.bus
        bus.on(self.JOIN_KIND, self._on_join_req)
        bus.on(self.ADMIT_KIND, self._on_admit)
        bus.on(self.LIVE_KIND, self._on_live)
        bus.on(self.LEAVE_KIND, self._on_leave_req)
        bus.on(self.GONE_KIND, self._on_gone)
        bus.on(self.DEATH_KIND, self._on_death_verdict)
        bus.on(self.DRAIN_KIND, self._on_drain)
        self._fleet_done = False
        bus.on(self.END_KIND, self._on_end)
        bus.on(self.HANDOVER_KIND, self._on_handover)

    # ------------------------------------------------------------- plumbing
    def bind_checkpoint(self, checkpoint_dir: Optional[str]) -> None:
        """Point the death path at the shared elastic checkpoint dir
        (the app knows it; the trainer doesn't). Without one, death
        stays the reference's gang-restart failure."""
        self._ckpt_dir = checkpoint_dir or None

    @property
    def i_am_standby(self) -> bool:
        with self._lock:
            return self.rank in self.standby

    def live_view(self) -> set[int]:
        """Snapshot of the live set (the autoscaler's fleet picture)."""
        with self._lock:
            return set(self.live)

    def pending_joins(self) -> int:
        """Announced standbys queued at this (coordinator) rank."""
        with self._lock:
            return len(self._pending_joins)

    def grant_join(self) -> None:
        """Autoscaler hook: release ONE held standby admission — the
        next ``_coord_step`` boundary pops the queue. A no-op credit
        (nothing queued) is consumed by the next announce."""
        with self._lock:
            self._join_credits += 1

    # ---------------------------------------------------------- the lease
    def _retarget(self, succ: int) -> None:
        """Point every coordinator-addressed loop at the new lease
        holder: ``self.coord`` (the mbJ/mbQ retry loops and the
        coordinator-only guards read it live) and the rebalancer's rbH
        destination. Idempotent — verdict, beat-stamp, and plan-stamp
        observation may all land it."""
        self.coord = int(succ)
        self.rb.coord = int(succ)
        tr = _trc.TRACER
        if tr is not None:
            term, holder = self.lease.current()
            tr.instant("membership", "mb_lease",
                       {"term": term, "holder": holder})

    def _beat_payload(self) -> dict:
        """Every outgoing heartbeat: lease stamp + my suspicion ballot
        (empty list = explicit retraction — a voter that calmed down
        must clear its stale ballot at every receiver). With the
        fail-slow plane bound, my SLOW ballot rides next to it as
        ``slw`` — same channel, same retraction semantics; unbound
        fleets ship byte-identical beats to pre-slow ones."""
        out = {**self.lease.stamp(), "sus": self.quorum.my_suspects()}
        if self._slowness is not None:
            out["slw"] = self.slow_quorum.my_suspects()
        return out

    def _on_lease_beat(self, sender: int, payload: dict) -> None:
        """Heartbeat receive hook (monitor thread): max-merge the lease
        stamp. Learning a newer term here is the partition-return self
        fence — an ex-holder stops planning the moment it hears the
        fleet moved on, and every receiver re-targets without waiting
        for its own death verdict. Then bank the sender's suspicion
        ballot and re-check quorum — a verdict completes the moment
        the corroborating vote lands, whichever rank's beat carried
        it."""
        if self.lease.observe(payload):
            self._retarget(self.lease.holder)
        sus = payload.get("sus")
        if sus is not None:
            self.quorum.vote(sender, sus)
            self._check_quorum()
        slw = payload.get("slw")
        if slw is not None:
            self.slow_quorum.vote(sender, slw)
            self._update_slow_verdicts()

    def _on_suspect(self, r: int, suspected: bool) -> None:
        """Monitor sweep hook: MY suspicion of ``r`` began/retracted.
        The ballot updates locally and rides the next beat; quorum is
        re-checked immediately (a 2-rank fleet's solo quorum, or the
        case where peers' votes arrived before mine)."""
        mine = self.quorum.mark_local(r, suspected)
        # suspicion into the black box: the post-mortem sequence reads
        # suspicion -> quorum verdict -> term advance -> death plan
        _fl.record("hb_suspect" if suspected else "hb_unsuspect",
                   {"rank": int(r), "ballot": mine})
        if suspected:
            self._check_quorum()

    def _check_quorum(self) -> None:
        """Convict every suspect a majority of the live view now
        corroborates. Runs on the monitor thread (my sweep) and the
        bus receive thread (a peer's beat) — conviction itself is
        idempotent (``monitor.convict`` fires on_failure once, and
        ``_on_peer_dead`` re-checks under its lock)."""
        mon = self.trainer.monitor
        if mon is None:
            return
        with self._lock:
            live = set(self.live)
            already = self.dead | self.left | self._quorum_claimed
        for r in self.quorum.convictable(live):
            if r in already:
                continue
            with self._lock:
                # claim the verdict: the sweep thread (my vote) and
                # the beat thread (a peer's vote) can both reach
                # convictable at the same instant — exactly one may
                # record the quorum_verdict and convict
                if r in self._quorum_claimed:
                    continue
                self._quorum_claimed.add(r)
            if r == self.rank:
                # peers' gossiped ballots corroborate MY death (the
                # asymmetric half-partition: my outbound is cut, my
                # inbound flows) — I must not convict myself through
                # the PEER-death path (self-exclusion from my own
                # gossip, succession against myself). The majority
                # will convict on its side and its mbD reaches me on
                # the working inbound; the fenced-out path owns it.
                continue
            voters = self.quorum.voters_for(r, live)
            # the QUORUM VERDICT with its why — who corroborated, over
            # which live view — before the conviction cascades into
            # hb_death/term_advance/death_plan
            _fl.record("quorum_verdict",
                       {"rank": int(r), "voters": voters,
                        "live": sorted(live)})
            self.quorum.verdicts += 1
            self.quorum.drop_voter(r)
            mon.convict(r)

    # ---------------------------------------------------- fail-slow quorum
    def bind_slowness(self, sm, cfg) -> None:
        """Wire the fail-slow detector (obs/slowness.py) into the
        gossip/quorum plane: local suspicion transitions update my
        ``slw`` ballot (next beat carries it), and heartbeat STALL
        forgiveness retracts slow ballots exactly like death ballots —
        a coma observer's latency samples are as undateable as its
        timeout verdicts (the false-positive drill pins both)."""
        self._slowness = sm
        self._slow_cfg = cfg
        sm.on_slow = self._on_slow_suspect
        mon = self.trainer.monitor
        if mon is not None and hasattr(mon, "on_stall_forgiven"):
            mon.on_stall_forgiven = sm.retract_all

    def bind_failure_domains(self, group: int) -> None:
        """Arm whole-host failure domains (the hybrid data plane,
        ``MINIPS_HIER agg=mesh``): slow verdicts expand to the
        convicted rank's entire contiguous host group via
        ``control_plane.expand_to_domains`` — a mesh host's ranks
        share one reduce group, so demoting one member without its
        peers would leave the planner shedding load onto ranks whose
        collectives still stall behind the sick one."""
        self._domain_group = max(1, int(group))

    def _on_slow_suspect(self, r: int, suspected: bool) -> None:
        """SlownessMonitor transition (push-driving thread, its roll):
        MY slow ballot changed — gossip rides the next beat; the
        quorum re-checks immediately (a peer's corroborating vote may
        already be banked)."""
        mine = self.slow_quorum.mark_local(r, suspected)
        _fl.record("slow_suspect" if suspected else "slow_unsuspect",
                   {"rank": int(r), "ballot": mine})
        self._update_slow_verdicts()

    def _update_slow_verdicts(self) -> None:
        """Recompute the quorum's CURRENT slow-verdict set — strict
        majority of the live view, exactly :func:`quorum_needed` (a
        single complainer never convicts; a minority island cannot
        demote the majority). Not sticky: a verdict whose
        corroboration fell away CLEARS, and the demotion bias lifts
        with it. Runs on the monitor/beat threads and the roll thread;
        the transition record is deduped under ``_slow_lock``."""
        with self._lock:
            live = set(self.live)
            gone = self.dead | self.left
        cur = {r for r in self.slow_quorum.convictable(live)
               if r not in gone}
        dom_added: set[int] = set()
        if cur and self._domain_group > 1:
            # hybrid-plane failure domains: a verdict against one mesh
            # member implicates its whole host group (live peers only
            # — the dead are the death quorum's problem). Not sticky
            # either: the expansion recomputes from the base set, so a
            # cleared member verdict lifts the whole domain with it
            full = expand_to_domains(cur, self._domain_group, self.n)
            dom_added = {r for r in full
                         if r in live and r not in gone} - cur
            cur |= dom_added
        with self._slow_lock:
            new = cur - self._slow_verdicts
            cleared = self._slow_verdicts - cur
            self._slow_verdicts = cur
            for r in new:
                self.counters["slow_verdicts"] += 1
                self._slow_since.setdefault(r, 0)
            for r in cleared:
                self._slow_since.pop(r, None)
        for r in new:
            if r in dom_added:
                _fl.record("slow_domain_verdict",
                           {"rank": int(r),
                            "group": self._domain_group,
                            "live": sorted(live)})
            else:
                _fl.record("slow_verdict",
                           {"rank": int(r),
                            "voters": self.slow_quorum.voters_for(
                                r, live),
                            "live": sorted(live)})
        for r in cleared:
            _fl.record("slow_cleared", {"rank": int(r)})

    def slow_view(self) -> set[int]:
        """The current quorum-corroborated slow set — read by the
        hedge plane (immediate hedging), the rebalancer's planner
        (demotion bias), and the autoscaler (shed pressure)."""
        with self._slow_lock:
            return set(self._slow_verdicts)

    def slow_demote_bias(self) -> float:
        """The planner's load multiplier for a slow-verdict rank
        (``MINIPS_SLOW demote=``; 0/1 = no bias)."""
        cfg = self._slow_cfg
        return float(cfg.demote) if cfg is not None else 0.0

    def _slow_escalate(self) -> None:
        """The second threshold — drain-not-convict: on the LEASE
        HOLDER, a rank whose slow verdict has stood ``drain_after``
        consecutive boundaries is drained through the PR 8 leave path
        (graceful: blocks ship to survivors under the fence, rc 0 —
        and if the sick rank IS the holder, ``leave()`` hands the
        lease over first). Never shrinks the fleet below 2: with one
        rank left there is nobody to absorb the blocks — the verdict
        then stays a demotion bias only."""
        cfg = self._slow_cfg
        if cfg is None or cfg.drain_after <= 0:
            return
        with self._slow_lock:
            standing = sorted(self._slow_verdicts)
            due = []
            for r in standing:
                if r in self._slow_drained:
                    continue
                self._slow_since[r] = self._slow_since.get(r, 0) + 1
                if self._slow_since[r] >= cfg.drain_after:
                    due.append(r)
        if not due:
            return
        with self._lock:
            live = set(self.live)
        for r in due:
            if r not in live or len(live) < 3:
                # len < 3: draining from a 2-fleet leaves a 1-fleet —
                # and a 2-fleet slow verdict cannot exist anyway (one
                # complainer, quorum 2); belt and braces
                continue
            with self._slow_lock:
                if r in self._slow_drained:
                    continue
                self._slow_drained.add(r)
            self.counters["slow_drains"] += 1
            _fl.checkpoint("slow_drain",
                           {"rank": int(r),
                            "since_ticks": self._slow_since.get(r),
                            "holder": self.rank})
            if r == self.rank:
                # the sick rank is the holder itself: leave() hands the
                # lease (mbH) before draining — lease-handover-aware by
                # construction
                self.begin_drain()
            else:
                self.bus.send(r, self.DRAIN_KIND,
                              {**self.lease.stamp()})

    def slow_stats(self) -> dict:
        with self._slow_lock:
            return {"slow_verdict_ranks": sorted(self._slow_verdicts),
                    "slow_drained": sorted(self._slow_drained),
                    "slow_ballots": self.slow_quorum.stats()["ballots"],
                    "demote_bias": self.slow_demote_bias() or None}

    def fence_frame(self, payload: dict) -> bool:
        """THE receive fence, in one place for every coordinator-
        originated frame (rbP plans, mbA admits, mbD verdicts, mbEnd,
        mbDr): max-merge the stamp's term (re-targeting on a newer
        one), then admit/drop by term. False = stale ex-coordinator
        frame, counted at the lease — the handler must return without
        acting."""
        if self.lease.observe(payload):
            self._retarget(self.lease.holder)
        return self.lease.admit(payload)

    @property
    def busy(self) -> bool:
        """A membership transition is queued or mid-flight — the heat
        planner yields (one planner stream at a time)."""
        with self._lock:
            return bool(self._pending_deaths or self._pending_joins
                        or self._leave_reqs or not self._bootstrapped)

    def membership_epoch(self) -> int:
        """Max routing epoch across tables — the 'versioned membership
        epoch' observability stamp (every transition bumps it)."""
        return max((t.router.epoch
                    for t in self.trainer.tables.values()), default=0)

    def stats(self) -> dict:
        with self._lock:
            out = {"live": sorted(self.live),
                   "standby": sorted(self.standby),
                   "dead": sorted(self.dead),
                   "left": sorted(self.left),
                   "coord": self.coord,
                   "held_joins": len(self._pending_joins)
                   if self.hold_joins else 0,
                   **self.counters}
        out["lease"] = self.lease.stats()
        out["quorum"] = self.quorum.stats()
        out["fenced_out"] = self._convicted_term is not None
        # the successor's ADDRESS derives from the membership table, not
        # the spawn-time env: the bus is a full mesh wired at launch, so
        # succession is a rank-id change (launch.bus_endpoint_of) — the
        # endpoint here is observability, never renegotiation
        from minips_tpu.launch import bus_endpoint_of

        out["coord_endpoint"] = bus_endpoint_of(out["coord"])
        out["epoch"] = self.membership_epoch()
        out["blocks_restored"] = sum(
            t.rb_stats["blocks_restored"]
            for t in self.trainer.tables.values())
        out["pushes_lost_to_dead"] = sum(
            t.rb_stats["pushes_lost_to_dead"]
            for t in self.trainer.tables.values())
        return out

    def _live_targets(self, exclude: set[int] = frozenset()) -> list:
        with self._lock:
            return sorted(self.live - set(exclude))

    # --------------------------------------------------------------- death
    def _on_peer_dead(self, r: int) -> None:
        """Monitor verdict (heartbeat thread): exclude NOW — the gate
        must recompute over the shrunken membership immediately — and
        unjam every wait aimed at the corpse. The plan (or the
        unrecoverable verdict) follows from the coordinator."""
        # the free-vs-planned verdict keys on OWNERSHIP, not membership
        # category: a standby normally owns nothing (bootstrap moved
        # its home range away) — but a PRE-bootstrap standby or a
        # mid-admission joiner does own blocks, and skipping its death
        # plan would strand those ranges on a corpse forever
        owns = any((t.router.owner_of_blocks() == r).any()
                   for t in self.trainer.tables.values())
        free = False
        succeeded = None
        with self._lock:
            if r in self.dead or r in self.left:
                return
            self.dead.add(r)
            self.live.discard(r)
            self.standby.discard(r)
            self._death_t[r] = time.monotonic()
            self.counters["deaths"] += 1
            self._pending_joins = [j for j in self._pending_joins
                                   if j != r]
            # a leaver that died mid-drain must not leave a stale
            # request pinning `busy` (and pausing the heat planner)
            # for the rest of the run
            self._leave_reqs.pop(r, None)
            if r == self.coord:
                # LEASE SUCCESSION (control_plane.py): the verdict plus
                # the membership table give every rank the same answer —
                # term += 1, holder = lowest live rank. The successor
                # plans the old holder's death itself below; only a
                # fleet with NOBODY left to take the lease stays the
                # reference's gang-restart case.
                succ = self.lease.succeed(r, self.live)
                if succ is None:
                    self._unrecoverable.add(r)
                else:
                    self.coord = succ
                    self.rb.coord = succ
                    succeeded = succ
            if r not in self._unrecoverable:
                if not owns:
                    # nothing routed to it, gated nobody: death is free
                    self._verdicts[r] = 0
                    free = True
                elif self.rank == self.coord:
                    self._pending_deaths.append(r)
        with self._lock:
            live_snap = sorted(self.live)
        # the heartbeat DEATH VERDICT is a poison-class event whether or
        # not the plane can own it: record + dump FIRST so every
        # survivor's box opens with the verdict — the post-mortem
        # sequence reads verdict → term advance → death plan
        _fl.poison("hb_death", {"rank": int(r), "owns": bool(owns),
                                "live": live_snap})
        # a corpse's standing suspicion ballot is void — it must not
        # keep corroborating verdicts against ranks it can no longer
        # see. MY vote against the corpse deliberately PERSISTS: every
        # rank reaches its own quorum verdict independently, and the
        # first convictor retracting would starve a slower survivor of
        # the corroborating vote it still needs (its next beat would
        # gossip "sus": [] and RETRACT the vote at every receiver —
        # reproduced: the seeded-kill drills wedged with one survivor
        # convicted and the other forever one vote short). A
        # convicted-dead rank's lingering ballot entry is the settled
        # evidence, not noise.
        self.quorum.drop_voter(r)
        # the corpse's SLOW ballot is void outright (both directions:
        # its votes and any verdict against it — death outranks slow)
        self.slow_quorum.drop_voter(r)
        if self._slowness is not None:
            self._slowness.exclude(r)
        self._update_slow_verdicts()
        if succeeded is not None:
            term, holder = self.lease.current()
            tr = _trc.TRACER
            if tr is not None:
                tr.instant("membership", "mb_lease",
                           {"term": term, "holder": holder})
            # LEASE DECISION into the black box, with its WHY — the
            # ballot inputs every rank advanced on (verdict + live set)
            # — then dump: a term advance is exactly the decision a
            # post-mortem reconstructs ("who took over, from what")
            _fl.poison("term_advance",
                       {"term": term, "holder": holder,
                        "dead": int(r), "live": live_snap})
        if free and self.rank == self.coord:
            # converge laggards whose tables still route to the corpse
            # (mid-adoption views): rstep 0 = free verdict, no plan
            self.bus.publish(self.DEATH_KIND,
                             {"rank": int(r), "rstep": 0,
                              **self.lease.stamp()})
        self.trainer.gossip.exclude(r)
        for t in self.trainer.tables.values():
            t.on_ranks_dead({r})
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("membership", "mb_dead", {"rank": int(r)})

    def _on_death_verdict(self, sender: int, payload: dict) -> None:
        if not self.fence_frame(payload):
            return  # stale ex-coordinator's verdict: fenced by term
        r, rstep = int(payload.get("rank", -1)), int(
            payload.get("rstep", -1))
        if r == self.rank:
            # the fleet convicted ME dead and moved on (a partition
            # outlasted the quorum verdict): record, dump, and let the
            # training thread exit via FencedOutError at its next
            # boundary — continuing would write zombie gradients into
            # ranges the fleet already rolled back
            if self._convicted_term is None:
                self._convicted_term = int(payload.get(
                    "lt", self.lease.current()[0]))
                _fl.poison("fenced_out",
                           {"rank": self.rank, "rstep": rstep,
                            "term": self._convicted_term})
            return
        with self._lock:
            self._verdicts[r] = rstep
            if rstep < 0:
                self._unrecoverable.add(r)

    def refuses_own_death_plan(self, payload: dict) -> bool:
        """Plan receive guard (balance/rebalancer._mk_on_plan): a death
        plan whose ``dead`` extras name THIS rank must not be adopted
        here — adoption would snapshot-and-ship rbS state for blocks
        whose new owners restore from the checkpoint instead (the
        double-apply the heal drill forbids). The convicted rank stops
        participating and exits via the FencedOutError path."""
        dead = payload.get("dead")
        if not dead or self.rank not in {int(d) for d in dead}:
            return False
        if self._convicted_term is None:  # mbD normally precedes (FIFO)
            self._convicted_term = int(payload.get(
                "lt", self.lease.current()[0]))
            _fl.poison("fenced_out",
                       {"rank": self.rank, "via": "death_plan",
                        "term": self._convicted_term})
        return True

    def _raise_if_fenced_out(self) -> None:
        term = self._convicted_term
        if term is None:
            return
        # lame-duck linger: peers may still be NACK-recovering my
        # journaled partition-era frames (the repair loops ride the bus
        # threads, not this one) — one beat of grace keeps the heal's
        # zero-unrecovered-frames contract, then the poison fires
        time.sleep(1.0)
        raise FencedOutError(self.rank, term)

    def fatal_dead(self, dead: set[int]) -> set[int]:
        """The subset of monitor-dead ranks that must still POISON a
        wait. Survivable: a completed leave, a dead standby, a live
        death whose transition is planned or pending within the grace
        window. Fatal: an unrecoverable verdict (no checkpoint / dead
        coordinator), or a verdict that never arrived in time."""
        fatal: set[int] = set()
        now = time.monotonic()
        for r in set(dead):
            with self._lock:
                if r in self._unrecoverable:
                    fatal.add(r)
                    continue
                known = r in self.dead or r in self.left
                has_verdict = r in self._verdicts
                t0 = self._death_t.get(r, now)
            if not known:
                # monitor saw it before our hook did (foreign monitor
                # instance): register and re-judge next check
                self._on_peer_dead(r)
                continue
            if r in self.left or has_verdict:
                continue
            if now - t0 > self.cfg.grace:
                fatal.add(r)  # no verdict came: stop limping, restart
        return fatal

    def block_restorer(self, name: str, extras: dict):
        """The per-table restore closure a death plan's adoption runs
        (train/sharded_ps.adopt_table): block -> checkpoint state read
        through the save-time overlay (ckpt/elastic.load_block_state).
        Returns None when the plan carries no usable step (adoption
        then poisons loudly — a survivable death always carries one)."""
        step = int(extras.get("rstep", -1))
        ckpt = self._ckpt_dir
        if step < 0 or not ckpt:
            return None
        t = self.trainer.tables[name]
        # shared across one adoption's restores: a dead rank's B-block
        # restore must OPEN each shard file once, not B times (rank ->
        # NpzSliceReader — the reader slices block rows instead of
        # materializing whole shards, so the restore stages only what
        # it returns; the reads run under the table's locks)
        npz_cache: dict = {}

        def restore(b: int) -> dict:
            from minips_tpu.ckpt import elastic

            blo, bln = t.router.block_span(b)
            return elastic.load_block_state(
                ckpt, step, name, b, blo, bln, t.router.home_of(b),
                t.part.shard_size, t.router.block_size,
                cache=npz_cache)
        return restore

    # ---------------------------------------------------------------- join
    def _on_join_req(self, sender: int, payload: dict) -> None:
        r = int(payload.get("rank", sender))
        with self._lock:
            if (self.rank == self.coord and r in self.standby
                    and r not in self._pending_joins):
                self._pending_joins.append(r)

    def _on_admit(self, sender: int, payload: dict) -> None:
        if not self.fence_frame(payload):
            return  # a stale ex-coordinator cannot admit anybody
        if int(payload.get("rank", -1)) == self.rank:
            self._admit_clk = int(payload.get("clk", 0))

    def _on_live(self, sender: int, payload: dict) -> None:
        r = int(payload.get("rank", sender))
        with self._lock:
            self.standby.discard(r)
            self.live.add(r)
            if self.rank == self.coord:
                self.counters["joins"] += 1
        # include AFTER its catch-up clock (same link, FIFO: the clock
        # frame precedes this announce) — gossip now gates on it
        self.trainer.gossip.include(r)
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("membership", "mb_live", {"rank": int(r)})

    def standby_loop(self, join_at: Optional[int] = None, *,
                     poll: float = 0.05,
                     timeout: float = 600.0) -> int:
        """The standby rank's whole pre-join life: serve (bus threads),
        adopt plans, announce at ``join_at`` (max live clock observed
        via gossip; None = announce immediately), block until admitted.
        Returns the catch-up clock to train from — or ``-1`` when the
        fleet FINISHED without admitting me (``mbEnd``): the run ended
        calm, which is a clean outcome for a standby, not a failure."""
        deadline = time.monotonic() + timeout
        while True:
            self.rb.adopt_now()  # pre-tick: any thread may adopt
            self._raise_if_fenced_out()
            with self._lock:
                if self._unrecoverable:
                    raise PeerFailureError(set(self._unrecoverable))
            if self._fleet_done:
                return -1
            if self._admit_clk is not None:
                break
            if self._join_due(join_at) \
                    and time.monotonic() - self._last_join_tx > 0.5:
                # repeat until admitted: the announce may race the
                # coordinator's handler registration or simply drop
                self.bus.send(self.coord, self.JOIN_KIND,
                              {"rank": self.rank})
                self._last_join_tx = time.monotonic()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"standby rank {self.rank}: never admitted")
            time.sleep(poll)
        clk = int(self._admit_clk)
        tr = self.trainer
        tr.clock = clk
        tr.gated_clock = clk
        # ORDER IS THE PROTOCOL: catch-up clock first, live announce
        # second, same FIFO link — every rank stores the clock before
        # it re-includes me, so the gate never sees a clock-0 ghost
        publish_clock(tr.gossip, clk, False)
        tr.gossip.include(self.rank)
        with self._lock:
            self.standby.discard(self.rank)
            self.live.add(self.rank)
        self.bus.publish(self.LIVE_KIND, {"rank": self.rank})
        self.rb.adopt_now()  # the admit plan may already be pending
        if _trc.TRACER is not None:
            _trc.TRACER.instant("membership", "mb_join",
                                {"rank": self.rank, "clk": clk})
        return clk

    def _join_due(self, join_at: Optional[int]) -> bool:
        if join_at is None:
            return True
        snap = self.trainer.gossip.snapshot()
        with self._lock:
            live = set(self.live)
        mx = max((max(v) for p, v in snap.items()
                  if v and p in live), default=0)
        return mx >= int(join_at)

    # --------------------------------------------------------------- leave
    def _on_drain(self, sender: int, payload: dict) -> None:
        # fenced like every coordinator frame: a partitioned
        # ex-coordinator's autoscaler must not shrink the fleet it no
        # longer runs (operator mbDr frames are unstamped and pass)
        if not self.fence_frame(payload):
            return
        self.begin_drain()

    def begin_drain(self) -> None:
        """Preemption signal landed (SIGTERM / mbDr / --drain-at): the
        training loop polls ``draining`` and hands over to leave()."""
        self._drain = True

    @property
    def draining(self) -> bool:
        return self._drain

    def _on_leave_req(self, sender: int, payload: dict) -> None:
        if self.rank != self.coord:
            return
        r = int(payload.get("rank", sender))
        with self._lock:
            if r in self.live and r != self.coord:
                self._leave_reqs[r] = dict(payload)

    def _on_gone(self, sender: int, payload: dict) -> None:
        r = int(payload.get("rank", sender))
        with self._lock:
            if r not in self.live and r not in self.standby:
                return
            self.live.discard(r)
            self.standby.discard(r)
            self.left.add(r)
            self._leave_reqs.pop(r, None)
            if self.rank == self.coord:
                self.counters["leaves"] += 1
        # the leaver published RETIRED before mbG; exclusion is the
        # belt-and-braces half (finalize/pull_all live sets, fence acks)
        self.trainer.gossip.exclude(r)
        self.quorum.drop_voter(r)  # a left rank's ballot is void too
        self.slow_quorum.drop_voter(r)
        if self._slowness is not None:
            self._slowness.exclude(r)
        self._update_slow_verdicts()
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("membership", "mb_gone", {"rank": int(r)})

    # ------------------------------------------------------------ handover
    def handover(self) -> int:
        """GRACEFUL LEASE HANDOVER (ROADMAP item 3 headroom (a),
        closed): the holder gives the lease away instead of dying with
        it. Term += 1 (``CoordinatorLease.transfer`` — any in-flight or
        journaled frame of mine is now stale-term and fences at every
        receiver, so handover is partition-proof by the same mechanism
        as succession), then ONE broadcast (``mbH``) carries the new
        ``(term, holder)`` plus the coordinator state succession would
        otherwise re-derive over several boundaries: the transition
        queues (pending joins / join credits / leave requests), the
        stored heat reports, and the autoscaler's hysteresis state —
        so the successor's next autoscale decision equals an
        uninterrupted coordinator's (pinned by test). Returns the
        successor's rank. Only the holder may call (raises
        otherwise); the caller then proceeds to :meth:`leave` — the
        PR8 drain path, which now addresses the NEW coordinator."""
        if self.rank != self.coord:
            raise RuntimeError(
                f"rank {self.rank} does not hold the lease "
                f"(holder: {self.coord}) — nothing to hand over")
        targets = self._live_targets(exclude={self.rank})
        if not targets:
            raise RuntimeError(
                "handover: no live rank left to take the lease — the "
                "last rank drains by just finishing (finalize)")
        succ = targets[0]  # sorted: the lowest live survivor, the same
        #                    pick succession would make
        tr = self.trainer
        with self._lock:
            # snapshot the coordinator queues under the lock: bus-
            # thread handlers (_on_leave_req, _on_join_req) mutate
            # them concurrently with this training-thread drain
            state: dict = {
                "joins": [int(j) for j in self._pending_joins],
                "credits": int(self._join_credits),
                "leave_reqs": {str(r): dict(req)
                               for r, req in self._leave_reqs.items()},
            }
        # heat reports re-gossip every tick anyway; shipping the store
        # means the successor's FIRST boundary sees the same load
        # picture the old holder did, not a cold start
        state["reports"] = {
            name: {str(r): dict(rep)
                   for r, rep in self.rb.heat_reports(name).items()}
            for name in tr.tables}
        a = getattr(tr, "autoscaler", None)
        if a is not None:
            state["autoscale"] = a.export_state()
        term, holder = self.lease.transfer(succ)
        self.bus.publish(self.HANDOVER_KIND,
                         {"rank": int(succ), "state": state,
                          **self.lease.stamp()})
        self._retarget(succ)
        tr2 = _trc.TRACER
        if tr2 is not None:
            tr2.instant("membership", "mb_handover",
                        {"term": term, "holder": holder})
        # a scaling-class DECISION, not a failure: checkpoint() dumps
        # the box with the transfer's why without flagging a poison
        _fl.checkpoint("lease_handover",
                       {"term": term, "holder": holder,
                        "from": self.rank})
        return int(succ)

    def _on_handover(self, sender: int, payload: dict) -> None:
        """Every receiver: observe the new term (fence_frame max-merges
        and re-targets). The NAMED successor additionally installs the
        transferred coordinator state before its next boundary runs
        the queues."""
        if not self.fence_frame(payload):
            return  # a stale ex-holder cannot hand over what it lost
        if int(payload.get("rank", -1)) != self.rank:
            return
        state = payload.get("state") or {}
        with self._lock:
            self._pending_joins = [
                int(j) for j in state.get("joins", ())
                if int(j) in self.standby
                and int(j) not in self._pending_joins] \
                + [j for j in self._pending_joins]
            self._join_credits = max(self._join_credits,
                                     int(state.get("credits", 0)))
            for r_s, req in (state.get("leave_reqs") or {}).items():
                self._leave_reqs.setdefault(int(r_s), dict(req))
        reports = state.get("reports") or {}
        if reports:
            self.rb.install_reports(
                {name: {int(r): dict(rep) for r, rep in by_rank.items()}
                 for name, by_rank in reports.items()})
        a = getattr(self.trainer, "autoscaler", None)
        a_state = state.get("autoscale")
        if a is not None and a_state:
            a.install_state(a_state)
        tr = _trc.TRACER
        if tr is not None:
            term, holder = self.lease.current()
            tr.instant("membership", "mb_handover_installed",
                       {"term": term, "holder": holder})
        _fl.record("lease_handover_installed",
                   {"from": int(sender), "holder": self.rank})

    def leave(self, timeout: float = 60.0) -> None:
        """Graceful exit of THIS rank (after its training loop broke on
        ``draining``): drain pushes, retire my clock, keep serving and
        re-asking the coordinator until every block I own has handed
        off and my fences released, then announce gone. Zero restored
        state anywhere — this is a migration, not a failure. THE LEASE
        HOLDER drains too (this PR): it hands the lease (and the
        coordinator state) to the lowest live survivor first —
        :meth:`handover`, term advances exactly once — then leaves
        like any other rank, addressing the new coordinator. The LAST
        live rank has nobody to hand to or ship blocks at: it drains
        by just finishing — flush, retire, announce gone, rc 0."""
        if self.rank == self.coord:
            if not self._live_targets(exclude={self.rank}):
                # sole survivor: no successor, no evacuation target —
                # the drain degenerates to a clean local quiesce
                for t in self.trainer.tables.values():
                    t.flush_pushes(acks=False)
                    t.residual_flush(reason="fence")
                    t.flush_pushes()
                    t.check_fatal()
                publish_clock(self.trainer.gossip,
                              self.trainer.clock, True)
                with self._lock:
                    self.live.discard(self.rank)
                    self.left.add(self.rank)
                self.bus.publish(self.GONE_KIND, {"rank": self.rank})
                return
            self.handover()
        tr = self.trainer
        self.rb.claim_drive_thread()  # adoption moves to THIS thread
        for t in tr.tables.values():
            # queue drain FIRST (a queued topk push retains fresh
            # residuals as it encodes on the sender thread), THEN the
            # residual flush — a leaver exiting rc 0 with retained
            # residuals would be silently-lost gradient — then the
            # hard ack drain covers the flush frames too
            t.flush_pushes(acks=False)
            t.residual_flush(reason="fence")
            t.flush_pushes()  # hard drain: owners hold all my updates
            t.check_fatal()
        # retire: gates and owner-side admission never wait on me again
        publish_clock(tr.gossip, tr.clock, True)
        deadline = time.monotonic() + timeout
        last_tx = 0.0
        while True:
            self.rb.adopt_now()
            with self._lock:
                if self._unrecoverable:
                    raise PeerFailureError(set(self._unrecoverable))
            for t in tr.tables.values():
                # a partition can eat an rbF after I stop training —
                # once I exit, nobody can ever release that gainer's
                # fence, so keep re-sending until every release is
                # CONFIRMED (rbG) before announcing gone
                t.resend_stale_releases()
            done = all(
                not (t.router.owner_of_blocks() == self.rank).any()
                and t.rebalance_settled()
                and t.releases_confirmed()
                for t in tr.tables.values())
            if done:
                break
            if time.monotonic() - last_tx > 0.25:
                self.bus.send(self.coord, self.LEAVE_KIND, {
                    "rank": self.rank,
                    "eps": {name: t.router.epoch
                            for name, t in tr.tables.items()},
                    "settled": all(t.rebalance_settled()
                                   for t in tr.tables.values())})
                last_tx = time.monotonic()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"drain of rank {self.rank}: blocks never handed "
                    "off (coordinator mute, or fleet fences stuck)")
            time.sleep(0.05)
        with self._lock:
            self.live.discard(self.rank)
            self.left.add(self.rank)
        self.bus.publish(self.GONE_KIND, {"rank": self.rank})
        if _trc.TRACER is not None:
            _trc.TRACER.instant("membership", "mb_leave",
                                {"rank": self.rank})
        # grace: my fences are released (rbF sent), so per-link FIFO
        # says every frame addressed to me has arrived — this sleep
        # only covers the mbG fan-out itself
        time.sleep(0.25)

    # ------------------------------------------------------ the tick hook
    def on_tick(self) -> None:
        """Called from ShardedPSTrainer.tick at the clock boundary,
        BEFORE the rebalancer's adoption point (a plan issued here is
        adopted in the same tick). Every rank: raise on unrecoverable
        deaths (or on having been fenced out). Coordinator: run the
        transition queues."""
        self._raise_if_fenced_out()
        with self._lock:
            if self._unrecoverable:
                raise PeerFailureError(set(self._unrecoverable))
        if self.rank != self.coord:
            return
        # fail-slow escalation before the transition queues: a drain
        # issued here rides the same boundary's queue machinery
        self._slow_escalate()
        self._coord_step()

    def poll(self) -> None:
        """Death transitions from the pull/fence WAIT paths: a
        coordinator blocked on a corpse-owned pull leg would otherwise
        wait for its own next tick to issue the very plan that unblocks
        it. Runs only on the push-driving thread (the adopt_now rule —
        plan issuance adopts locally) and only handles deaths:
        joins/leaves/bootstrap can wait for a real clock boundary."""
        self._raise_if_fenced_out()
        if self.rank != self.coord:
            return
        drive = self.rb._drive_thread
        if drive is not None and drive != threading.get_ident():
            return
        while True:
            with self._lock:
                if not self._pending_deaths:
                    return
                r = self._pending_deaths.pop(0)
            self._issue_death(r)

    def _on_end(self, sender: int, payload: dict) -> None:
        if not self.fence_frame(payload):
            return
        self._fleet_done = True

    def quiesce(self) -> None:
        """Finalize-time: no further transitions (in-flight migrations
        settle through the normal fence path). The COORDINATOR also
        tells any still-waiting standby the fleet is done (``mbEnd``):
        a run can legitimately end with held admissions (the autoscaler
        never saw load), and without this the orphaned standby would
        watch the fleet's heartbeats die one by one and convict the
        whole world instead of exiting clean."""
        with self._lock:
            self._pending_deaths.clear()
            self._pending_joins.clear()
            self._leave_reqs.clear()
            self._bootstrapped = True
            standbys_waiting = bool(self.standby)
        if standbys_waiting and self.rank == self.coord:
            self.bus.publish(self.END_KIND, {**self.lease.stamp()})

    def _next_eps(self) -> dict[str, int]:
        return {name: t.router.epoch + 1
                for name, t in self.trainer.tables.items()}

    def _issue(self, overlays: dict[str, dict],
               extras: Optional[dict] = None) -> None:
        for name, t in self.trainer.tables.items():
            self.rb.issue_plan(name, t.router.epoch + 1,
                               overlays[name], extras=extras)
        with self._lock:
            self.counters["plans"] += 1

    def _coord_step(self) -> None:
        tables = self.trainer.tables
        # -------- bootstrap: standby home ranges onto the live set
        # (a normal migration at the first boundary — standbys are live
        # SERVERS until it lands, so their seeded init ships via rbS)
        with self._lock:
            boot_needed = not self._bootstrapped
            standby = set(self.standby)
        if boot_needed:
            targets = self._live_targets()
            self._issue({name: plan_evacuation(t.router, standby,
                                               targets)
                         for name, t in tables.items()})
            with self._lock:
                self._bootstrapped = True
            return  # one transition per boundary
        # -------- deaths first: a corpse's ranges are unreachable
        with self._lock:
            death = self._pending_deaths.pop(0) \
                if self._pending_deaths else None
        if death is not None:
            self._issue_death(death)
            return
        # -------- leaves: ALL settled leavers at current epochs drain
        # in ONE evacuation plan — a whole-host drain (every rank of a
        # failure domain leaving together) is a single planned
        # redistribution instead of N independent leave transitions,
        # each of which would re-shuffle the previous one's re-homed
        # blocks (still one transition per boundary: one plan)
        with self._lock:
            leavers = [r for r, req in self._leave_reqs.items()
                       if req.get("settled")
                       and all(int(req.get("eps", {}).get(name, -1))
                               == t.router.epoch
                               for name, t in tables.items())]
            for r in leavers:
                del self._leave_reqs[r]
        if leavers:
            targets = self._live_targets(exclude=set(leavers))
            self._issue({name: plan_evacuation(t.router, set(leavers),
                                               targets)
                         for name, t in tables.items()})
            if len(leavers) > 1:
                _fl.record("mb_evacuation",
                           {"ranks": sorted(int(r) for r in leavers),
                            "targets": [int(t) for t in targets]})
            return
        # -------- joins: admit one rank per boundary. With hold_joins
        # (the autoscaler armed) an announced standby WAITS in the queue
        # until a grant_join() credit — scale-up is a load decision
        with self._lock:
            join = None
            if self._pending_joins and (not self.hold_joins
                                        or self._join_credits > 0):
                join = self._pending_joins.pop(0)
                if join not in self.standby:
                    join = None  # died (or already admitted) meanwhile
                elif self.hold_joins:
                    self._join_credits -= 1
        if join is not None:
            # clock first (the joiner trains from it), plans second —
            # both on my one FIFO link, so the joiner sees them in order
            self.bus.publish(self.ADMIT_KIND,
                             {"rank": join, "clk": self.trainer.clock,
                              **self.lease.stamp()})
            # heat-aware placement: the admit plan runs the PR4
            # bin-packer over the coordinator's stored heat reports
            # (rbH flows even in elastic-only mode), so the joiner
            # absorbs hot blocks at admission instead of idling on its
            # cold home range; missing reports degrade to
            # home-blocks-only (plan_admission docstring)
            live = self._live_targets()
            self._issue({name: plan_admission(
                t.router, join, reports=self.rb.heat_reports(name),
                live=set(live), max_blocks=self.rb.cfg.max_blocks)
                for name, t in tables.items()})

    def _issue_death(self, r: int) -> None:
        """The death transition: verdict + plan. Unrecoverable (no
        complete checkpoint, no dir bound) broadcasts ``rstep=-1`` and
        poisons locally — the honest fallback to gang restart."""
        from minips_tpu.ckpt import elastic

        step = None
        if self._ckpt_dir:
            with self._lock:
                # live ranks + the corpse must share the step (their
                # files hold the state); standbys/leavers need not —
                # a never-checkpointed standby's missing dir must not
                # veto recovery of somebody else's death
                required = self.live | {r}
            try:
                step = elastic.find_live_step(
                    self._ckpt_dir, self.trainer.tables, self.n,
                    required=required)
            except Exception:  # noqa: BLE001 - scan failure = no step
                step = None
        if step is None:
            self.bus.publish(self.DEATH_KIND,
                             {"rank": int(r), "rstep": -1,
                              **self.lease.stamp()})
            with self._lock:
                self._verdicts[r] = -1
                self._unrecoverable.add(r)
            _fl.poison("death_plan",
                       {"rank": int(r), "rstep": -1,
                        "why": "no complete checkpoint"})
            return
        targets = self._live_targets()
        extras = {"dead": [int(r)], "rstep": int(step)}
        self.bus.publish(self.DEATH_KIND,
                         {"rank": int(r), "rstep": int(step),
                          **self.lease.stamp()})
        with self._lock:
            self._verdicts[r] = int(step)
        self._issue({name: plan_evacuation(t.router, {r}, targets)
                     for name, t in self.trainer.tables.items()},
                    extras=extras)
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("membership", "mb_death_plan",
                       {"rank": int(r), "rstep": int(step)})
        # the plan the successor issued, with its WHY (the restore step
        # chosen and who received the ranges) — the third line of the
        # post-mortem sequence verdict → term advance → death plan
        _fl.poison("death_plan",
                   {"rank": int(r), "rstep": int(step),
                    "targets": [int(t) for t in targets]})
