"""MovieLens ratings reader + word-level tokenizer and their app wiring —
the remaining real-file paths for the BASELINE workloads."""

from argparse import Namespace

import numpy as np
import pytest

from minips_tpu.core.config import Config, TableConfig, TrainConfig
from minips_tpu.data.movielens import read_ratings
from minips_tpu.data.text import word_tokens
from minips_tpu.utils.metrics import MetricsLogger


def test_read_ratings_all_three_formats(tmp_path):
    rows = [(3, 7, 4.0), (1, 7, 2.5), (3, 9, 5.0)]
    csv = tmp_path / "ratings.csv"
    csv.write_text("userId,movieId,rating,timestamp\n"
                   + "\n".join(f"{u},{i},{r},123" for u, i, r in rows))
    dat = tmp_path / "ratings.dat"
    dat.write_text("\n".join(f"{u}::{i}::{r}::123" for u, i, r in rows))
    udata = tmp_path / "u.data"
    udata.write_text("\n".join(f"{u}\t{i}\t{r}\t123" for u, i, r in rows))
    outs = [read_ratings(str(p)) for p in (csv, dat, udata)]
    for out in outs:
        assert out["num_users"] == 2 and out["num_items"] == 2
        # dense remap: users {1,3}->{0,1}, items {7,9}->{0,1}
        np.testing.assert_array_equal(out["user"], [1, 0, 1])
        np.testing.assert_array_equal(out["item"], [0, 0, 1])
        np.testing.assert_allclose(out["rating"], [4.0, 2.5, 5.0])


def test_read_ratings_rejects_garbage(tmp_path):
    p = tmp_path / "bad"
    p.write_text("header,line,here\n1,2,3\nnot,a,row\n")
    with pytest.raises(ValueError, match="unparseable"):
        read_ratings(str(p))
    (tmp_path / "empty").write_text("")
    with pytest.raises(ValueError, match="no ratings"):
        read_ratings(str(tmp_path / "empty"))


def test_word_tokens_frequency_ranked_and_filtered(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("the the the cat cat sat on on on on a mat\n")
    ids, counts = word_tokens(str(p), vocab_size=3)
    # top-3: on(4) the(3) cat(2); sat/a/mat dropped
    assert list(counts) == [4, 3, 2]
    assert ids.max() == 2 and len(ids) == 9  # 4+3+2 kept tokens
    # id 0 is the most frequent word
    assert (ids == 0).sum() == 4


def test_mf_example_from_ratings_file(tmp_path):
    from minips_tpu.apps import mf_example as app

    rng = np.random.default_rng(0)
    U = rng.normal(scale=0.5, size=(60, 8))
    V = rng.normal(scale=0.5, size=(80, 8))
    u = rng.integers(0, 60, size=6000)
    i = rng.integers(0, 80, size=6000)
    r = np.clip(3.0 + (U[u] * V[i]).sum(-1), 0.5, 5.0)
    p = tmp_path / "ratings.dat"
    p.write_text("\n".join(f"{a + 1}::{b + 1}::{c:.2f}::0"
                           for a, b, c in zip(u, i, r)))
    cfg = Config(
        table=TableConfig(name="factors", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=9),
        train=TrainConfig(batch_size=512, num_iters=200, log_every=500),
    )
    out = app.run(cfg, Namespace(data_file=str(p)),
                  MetricsLogger(None, verbose=False))
    losses = out["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_word2vec_from_text_file(tmp_path):
    from minips_tpu.apps import word2vec_example as app

    # structured corpus: words co-occur within fixed blocks, so skip-gram
    # signal exists
    rng = np.random.default_rng(1)
    blocks = [[f"w{b}_{k}" for k in range(8)] for b in range(30)]
    words = []
    for _ in range(4000):
        blk = blocks[rng.integers(0, 30)]
        words.extend(rng.choice(blk, size=6))
    p = tmp_path / "corpus.txt"
    p.write_text(" ".join(words))
    cfg = Config(
        table=TableConfig(name="emb", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=32,
                          num_slots=1 << 12),
        train=TrainConfig(batch_size=512, num_iters=150, log_every=500),
    )
    out = app.run(cfg, Namespace(data_file=str(p)),
                  MetricsLogger(None, verbose=False))
    losses = out["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < 3.9, losses[-1]  # off the 4.159 plateau

    # semantic check: words from the same co-occurrence block should be
    # closer in embedding space than words from different blocks
    import jax.numpy as jnp
    from collections import Counter

    in_t, _ = out["tables"]
    # rebuild the frequency-ranked word->id map word_tokens used
    ctr = Counter(p.read_text().split())
    ranked = [w for w, _ in sorted(ctr.items(), key=lambda kv: (-kv[1],
                                                                kv[0]))]
    emb = np.asarray(in_t.pull(jnp.arange(len(ranked))))
    emb = emb / (np.linalg.norm(emb, axis=-1, keepdims=True) + 1e-8)
    sims = emb @ emb.T
    blocks_arr = np.asarray([w.split("_")[0] for w in ranked])
    same_mask = (blocks_arr[:, None] == blocks_arr[None, :]) \
        & ~np.eye(len(ranked), dtype=bool)
    diff_mask = ~same_mask & ~np.eye(len(ranked), dtype=bool)
    assert sims[same_mask].mean() > sims[diff_mask].mean() + 0.05, (
        sims[same_mask].mean(), sims[diff_mask].mean())


def test_corrupt_first_dat_row_raises(tmp_path):
    p = tmp_path / "ratings.dat"
    p.write_text("abc::7::4.0::0\n1::2::3.0::0\n")
    with pytest.raises(ValueError, match="unparseable"):
        read_ratings(str(p))


def test_signed_int_images_rejected(tmp_path):
    from minips_tpu.data.mnist import read_mnist, write_idx

    ip, lp = str(tmp_path / "i"), str(tmp_path / "l")
    write_idx(ip, np.zeros((2, 2, 2), np.int32))
    write_idx(lp, np.zeros(2, np.uint8))
    with pytest.raises(ValueError, match="no defined"):
        read_mnist(ip, lp)


def test_mf_holdout_rmse():
    """--eval_frac on MF: held-out RMSE beats the predict-the-mean
    baseline (the data is genuinely low-rank)."""
    from minips_tpu.apps import mf_example as app

    cfg = Config(
        table=TableConfig(name="factors", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=9),
        train=TrainConfig(batch_size=1024, num_iters=1500, log_every=5000),
    )
    out = app.run(cfg, Namespace(eval_frac=0.2),
                  MetricsLogger(None, verbose=False))
    # mean-baseline RMSE = rating std ~0.73; measured ~0.26 at 1500 iters
    assert 0.0 < out["rmse"] < 0.45, out["rmse"]


def test_mf_threaded_honors_eval_frac():
    from minips_tpu.apps import mf_example as app

    cfg = Config(
        table=TableConfig(name="factors", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=9),
        train=TrainConfig(batch_size=512, num_iters=400, num_workers=2,
                          log_every=5000),
    )
    out = app.run(cfg, Namespace(eval_frac=0.2, exec_mode="threaded"),
                  MetricsLogger(None, verbose=False))
    # mean-baseline RMSE ~0.73; measured ~0.52 at 400 iters
    assert 0.0 < out["rmse"] < 0.65, out["rmse"]


def test_word2vec_threaded_async_push():
    """--exec threaded: the reference's literal 'async push' w2v — ASP
    worker threads, per-sample SGNS pushes, loss leaves the plateau."""
    from minips_tpu.apps import word2vec_example as app

    cfg = Config(
        table=TableConfig(name="emb", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=32,
                          num_slots=1 << 12),
        train=TrainConfig(batch_size=512, num_iters=120, num_workers=2,
                          log_every=5000),
    )
    out = app.run(cfg, Namespace(exec_mode="threaded"),
                  MetricsLogger(None, verbose=False))
    losses = out["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < 3.9, losses[-1]


def test_mf_tables_handle_ml1m_shaped_ids(tmp_path):
    """ADVICE round 1 (high): real MovieLens id counts are not powers of
    two (ML-1M: 6040 users x 3706 items) — table sizing must round up and
    the first pull must not trip the power-of-2 assert."""
    from argparse import Namespace as NS

    import jax.numpy as jnp

    from minips_tpu.apps import mf_example as app
    from minips_tpu.parallel.mesh import make_mesh

    cfg = Config(
        table=TableConfig(name="factors", kind="sparse", consistency="asp",
                          updater="sgd", lr=0.05, dim=9),
        train=TrainConfig(batch_size=256, num_iters=5, log_every=500),
    )
    user_t, item_t = app._make_tables(cfg, make_mesh(), users=6040,
                                      items=3706)
    assert user_t.num_slots == 8192 and item_t.num_slots == 4096
    # identity mapping: distinct dense ids -> distinct rows (no collisions)
    assert len(np.unique(np.asarray(
        user_t.slots_of(jnp.arange(6040))))) == 6040
    user_t.pull(jnp.array([6039]))  # the crash reported by the advisor

    # end-to-end on a tiny ML-1M-shaped file (sparse ids near the maxima)
    rng = np.random.default_rng(2)
    u = np.concatenate([rng.integers(0, 6040, size=1500), [6039]])
    i = np.concatenate([rng.integers(0, 3706, size=1500), [3705]])
    r = np.clip(3.0 + rng.normal(scale=0.5, size=u.size), 0.5, 5.0)
    p = tmp_path / "ratings.dat"
    p.write_text("\n".join(f"{a + 1}::{b + 1}::{c:.2f}::0"
                           for a, b, c in zip(u, i, r)))
    out = app.run(cfg, NS(data_file=str(p)),
                  MetricsLogger(None, verbose=False))
    assert np.isfinite(out["losses"]).all()
