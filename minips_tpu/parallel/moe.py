"""Expert parallelism — top-k MoE FFN over a mesh axis (Switch top-1 default,
GShard-style top-2+ via ``k_top``).

Beyond parity (the reference has no expert parallelism, SURVEY.md §2.2).
Completes the framework's parallelism set (dp / sp ring attention / tp /
pp / ep), all expressed the same way: shard_map over named mesh axes with
explicit collectives.

Mechanics (Switch Transformer shape, public recipe): a linear router picks
each token's top-1 expert; tokens are packed into per-expert capacity
slots (earliest-first, overflow dropped — the standard fixed-shape trick,
since TPU programs need static shapes); an ``all_to_all`` ships slots to
the devices that own the experts (``E`` experts sharded over the axis),
each device runs its local experts' FFN on its slots, a second
``all_to_all`` ships results back, and outputs are combined weighted by
the router probability. Gradients flow through both all_to_alls and the
dispatch/combine einsums; the router gets trained through the combine
weights (straight-through on the top-1 choice, as in Switch).

``moe_apply_dense`` is the unsharded oracle: identical numerics (including
capacity drops) computed without collectives, used by tests and usable on
one device.
"""

from __future__ import annotations

import jax

import jax.numpy as jnp
from minips_tpu.utils.jaxcompat import axis_size as _axis_size


def init_moe(key, num_experts: int, dim: int, hidden: int):
    """Router + stacked expert FFN weights ([E, ...] — shard dim 0 for EP)."""
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = dim ** -0.5
    return {
        "router": jax.random.normal(k1, (dim, num_experts)) * scale_in,
        "w_in": jax.random.normal(k2, (num_experts, dim, hidden)) * scale_in,
        "w_out": jax.random.normal(k3, (num_experts, hidden, dim))
                 * hidden ** -0.5,
    }


def _dispatch_combine(x, router_w, num_experts: int, capacity: int,
                      k_top: int = 1):
    """Route [N, D] tokens to their top-``k_top`` experts: returns
    (dispatch [N, E, C] f32 {0,1}, combine [N, E, C] f32 gate-weighted,
    frac [E], mean_p [E]) — the last two are the raw load-balancing
    statistics for ``_aux_loss``.

    ``k_top=1`` is Switch; ``k_top=2`` is the GShard shape. Capacity slots
    are assigned rank-major (every token's primary choice queues before
    any secondary choice), so when capacity binds, primary routes survive
    preferentially. Gates are the raw softmax probabilities of the chosen
    experts (no top-k renormalization) — for k=1 this is exactly Switch's
    straight-through combine weight."""
    N = x.shape[0]
    logits = x @ router_w                              # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate, expert = jax.lax.top_k(probs, k_top)         # [N, k] each
    onehots = jax.nn.one_hot(expert, num_experts)      # [N, k, E]
    # queue position per (token, choice) within its expert, earliest-first
    # across a rank-major flattening: [k*N, E]
    flat = onehots.transpose(1, 0, 2).reshape(k_top * N, num_experts)
    pos = (jnp.cumsum(flat, axis=0) * flat).astype(jnp.int32) - 1
    keep = (pos >= 0) & (pos < capacity)               # -1 = not routed
    slot = jax.nn.one_hot(pos, capacity)               # [kN, E, C]
    disp = (slot * keep[..., None]).reshape(k_top, N, num_experts,
                                            capacity)
    dispatch = jnp.sum(disp, axis=0)                   # [N, E, C]
    combine = jnp.sum(disp * gate.T[:, :, None, None], axis=0)
    # Switch aux load-balancing statistics: fraction of tokens whose
    # PRIMARY route is each expert and mean router prob per expert.
    # Returned raw (not yet combined) so the distributed path can pmean
    # them BEFORE the product — mean-of-products would differ from the
    # global loss.
    frac = jnp.mean(onehots[:, 0], axis=0)
    mean_p = jnp.mean(probs, axis=0)
    return dispatch, combine, frac, mean_p


def _aux_loss(frac, mean_p, num_experts):
    """E * sum_e(frac_e * mean_prob_e) — minimized at uniform routing."""
    return num_experts * jnp.sum(frac * mean_p)


def _expert_ffn(w_in, w_out, x, compute_dtype):
    """x: [E_local, C', D] through each local expert's GELU MLP."""
    h = jax.nn.gelu(jnp.einsum(
        "ecd,edh->ech", x.astype(compute_dtype), w_in.astype(compute_dtype)))
    return jnp.einsum("ech,ehd->ecd", h,
                      w_out.astype(compute_dtype)).astype(jnp.float32)


def moe_apply_dense(params, x, *, capacity: int,
                    compute_dtype=jnp.bfloat16, k_top: int = 1):
    """Unsharded oracle: [N, D] -> ([N, D], aux_loss). Matches the
    distributed path exactly whenever capacity does not bind; when it
    does, drop patterns differ (one global queue per expert here vs one
    queue per (expert, source device) there)."""
    E = params["router"].shape[1]
    dispatch, combine, frac, mean_p = _dispatch_combine(
        x, params["router"], E, capacity, k_top)
    slots = jnp.einsum("nec,nd->ecd", dispatch, x)     # [E, C, D]
    out_slots = _expert_ffn(params["w_in"], params["w_out"], slots,
                            compute_dtype)
    return (jnp.einsum("nec,ecd->nd", combine, out_slots),
            _aux_loss(frac, mean_p, E))


def moe_apply_local(params_local, x_local, *, axis_name: str,
                    capacity: int, compute_dtype=jnp.bfloat16,
                    k_top: int = 1):
    """Expert-parallel MoE — call INSIDE shard_map with tokens sharded
    [N_local, D] over ``axis_name``, router replicated, and w_in/w_out
    sharded on their expert dim (``ep_specs``). ``capacity`` is per-expert
    per-source-device. Returns ([N_local, D], aux_loss pmean'd).

    Like the other parallel schedules, take grads OUTSIDE the shard_map.
    """
    k = _axis_size(axis_name)
    E = params_local["router"].shape[1]
    e_local = params_local["w_in"].shape[0]
    if e_local * k != E:
        raise ValueError(f"router knows {E} experts but {k} devices hold "
                         f"{e_local} each")
    dispatch, combine, frac, mean_p = _dispatch_combine(
        x_local, params_local["router"], E, capacity, k_top)
    slots = jnp.einsum("nec,nd->ecd", dispatch, x_local)   # [E, C, D]
    # ship: expert block e_blk of every device -> device owning those
    # experts; receive my experts' slots from every source device
    slots = slots.reshape(k, e_local, capacity, -1)
    recv = jax.lax.all_to_all(slots, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)  # [k, eL, C, D]
    # fold source-device axis into the slot axis for the local FFN
    mine = recv.transpose(1, 0, 2, 3).reshape(e_local, k * capacity, -1)
    out = _expert_ffn(params_local["w_in"], params_local["w_out"], mine,
                      compute_dtype)
    # ship results back along the inverse route
    out = out.reshape(e_local, k, capacity, -1).transpose(1, 0, 2, 3)
    back = jax.lax.all_to_all(out, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)  # [k, eL, C, D]
    out_slots = back.reshape(E, capacity, -1)
    y = jnp.einsum("nec,ecd->nd", combine, out_slots)
    # global aux loss: average the statistics across shards BEFORE the
    # product so it equals the dense oracle's loss exactly
    aux = _aux_loss(jax.lax.pmean(frac, axis_name),
                    jax.lax.pmean(mean_p, axis_name), E)
    return y, aux


def ep_specs(axis_name: str = "data"):
    """PartitionSpec pytree for ``moe_apply_local``'s params."""
    from jax.sharding import PartitionSpec as P

    return {"router": P(), "w_in": P(axis_name), "w_out": P(axis_name)}
