from minips_tpu.train.ps_step import PSTrainStep  # noqa: F401
from minips_tpu.train.loop import TrainLoop  # noqa: F401
