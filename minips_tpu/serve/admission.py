"""Per-owner pull admission: a token bucket on the wire serve path.

The serving plane's load-shedding decision point (docs/serving.md): an
owner under read storm consumes one token per arriving pull REQUEST
(frames, not rows — the per-frame serve cost is what saturates an
owner's receive thread, and rows already have their own byte
accounting). An empty bucket never silently drops the request: the
caller sheds it to a replica (``svS``) or refuses it with an explicit
retry-after (``svB``) — loss of capacity degrades to latency, never to
silence, the same ladder the reliable layer established for loss of
frames.

The bucket is deliberately the classic shape: ``rate`` tokens/sec
refill, ``burst`` capacity, monotonic-clock lazy refill, one lock
(taken on the bus receive thread only; the critical section is a few
float ops). ``rate=0`` disables admission entirely — the bucket always
admits — so arming the serve plane for replicas alone costs the serve
path one attribute check.

Tenancy (tenant/registry.py): with ``MINIPS_TENANT`` armed each
table's ``TableServeState`` builds its bucket from its TENANT's
``rate``/``burst`` — one bucket per tenant, so tenant A's storm can
never drain the tokens tenant B's requests needed. The registry's
``shared=1`` contrast arm hands every table ONE plane-level instance
of this same class instead (the lock already makes it safe to share
across tables on one receive thread); the multi_tenant bench measures
the coupling that re-introduces.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    """Lazy-refill token bucket; ``now_fn`` is injectable for tests."""

    def __init__(self, rate: float, burst: int, *, now_fn=time.monotonic):
        if rate < 0:
            raise ValueError("rate must be >= 0 (0 = admission off)")
        if burst < 1:
            raise ValueError("burst must be >= 1")
        self.rate = float(rate)
        self.burst = float(burst)
        self._now = now_fn
        self._tokens = self.burst
        self._t_last = now_fn()
        self._lock = threading.Lock()
        self.admitted = 0
        self.denied = 0

    def take(self, n: float = 1.0) -> bool:
        """Consume ``n`` tokens if available; False = shed/refuse."""
        if self.rate <= 0:
            self.admitted += 1  # admission off: everything passes
            return True
        with self._lock:
            now = self._now()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t_last)
                               * self.rate)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                self.admitted += 1
                return True
            self.denied += 1
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"admitted": self.admitted, "denied": self.denied,
                    "tokens": round(self._tokens, 2)}
