"""KV-cached autoregressive decoding for the transformer LM family.

The reference is a training-only parameter server (SURVEY.md §2 — no
attention models at all), so inference is beyond parity: this module
completes the LM family (models/transformer.py) with the serving half —
prefill + single-token decode steps over a static-shape KV cache, driven
by one ``lax.scan`` (TPU-shaped: no dynamic shapes, no host round-trips
per token).

Design:

- The cache is per-block ``{"k", "v"}`` of shape ``[B, max_T, Hk, hd]``
  where ``Hk`` is the model's KV head count — a grouped-query model
  (``init(kv_heads=...)``) shrinks the cache by the group factor, which
  is GQA's raison d'être at serving time.
- ``_cached_block`` is one implementation for BOTH phases: prefill runs
  it with the whole prompt (``T_cur = prompt_len``, causal mask among
  the prompt), decode with ``T_cur = 1``; each call writes its K/V rows
  into the cache at ``pos_off`` via ``dynamic_update_slice`` and attends
  over the full static cache under the mask ``k_pos <= q_pos`` — masked
  (not sliced) attention keeps every shape static for XLA.
- Positions are global: learned ``pos_emb`` rows or RoPE rotation
  (``rope_rotate``), matching training exactly — greedy decode equals
  argmax over ``transformer.apply`` on the growing sequence
  (tests/test_decode.py pins this against the incremental oracle for
  every layout combination).

MoE blocks are not wired (decode-time expert routing has a different
capacity story); ``init_cache`` refuses them loudly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from minips_tpu.models.transformer import _block_tail, _ln, rope_rotate

_NEG_INF = -1e30


def _head_dims(params, heads):
    dim = params["tok_emb"].shape[1]
    hd = dim // heads
    blk0 = params["blocks"][0]
    if "moe" in blk0:
        raise ValueError("decode does not support MoE blocks")
    hk = blk0["wkv"].shape[2] // hd if "wkv" in blk0 else heads
    return hd, hk


def init_cache(params, batch: int, max_len: int, *, heads: int = 4,
               dtype=jnp.bfloat16):
    """Zeroed per-block KV cache ``[B, max_len, Hk, hd]`` (Hk = the
    model's KV head count — a GQA model's cache is heads/kv_heads times
    smaller). ``dtype`` is the cache storage dtype; attention runs its
    softmax in f32 regardless."""
    hd, hk = _head_dims(params, heads)
    if "pos_emb" in params and max_len > params["pos_emb"].shape[0]:
        raise ValueError(
            f"max_len {max_len} exceeds the learned positional table "
            f"({params['pos_emb'].shape[0]} rows); use a rope model for "
            "unbounded decode")
    return [{"k": jnp.zeros((batch, max_len, hk, hd), dtype),
             "v": jnp.zeros((batch, max_len, hk, hd), dtype)}
            for _ in params["blocks"]]


def _cached_block(h, blk, cache, pos_off, heads, rope, compute_dtype):
    """One block over ``T_cur`` new positions starting at ``pos_off`` (a
    traced scalar), reading/writing the static-shape cache. The causal
    mask ``k_pos <= q_pos`` covers both phases: among-prompt causality in
    prefill and everything-before-me in decode. Returns (h', cache')."""
    B, T_cur, D = h.shape
    x = _ln(h, blk["ln1"]).astype(compute_dtype)
    if "wkv" in blk:
        q = x @ blk["wq"].astype(compute_dtype)
        kv = jnp.einsum("btd,dce->btce", x,
                        blk["wkv"].astype(compute_dtype))
        k_new, v_new = kv[:, :, 0], kv[:, :, 1]
    else:
        qkv = jnp.einsum("btd,dce->btce", x,
                         blk["qkv"].astype(compute_dtype))
        q, k_new, v_new = (qkv[:, :, i] for i in range(3))
    hd = D // heads
    hk = k_new.shape[-1] // hd
    g = heads // hk
    q = q.reshape(B, T_cur, heads, hd)
    k_new = k_new.reshape(B, T_cur, hk, hd)
    v_new = v_new.reshape(B, T_cur, hk, hd)
    pos = pos_off + jnp.arange(T_cur)
    if rope:
        q = rope_rotate(q, pos)
        k_new = rope_rotate(k_new, pos)   # rotated rows enter the cache

    ck = jax.lax.dynamic_update_slice(
        cache["k"], k_new.astype(cache["k"].dtype), (0, pos_off, 0, 0))
    cv = jax.lax.dynamic_update_slice(
        cache["v"], v_new.astype(cache["v"].dtype), (0, pos_off, 0, 0))

    # grouped attention over the WHOLE static cache, masked to the live
    # prefix: q [B, T_cur, Hk, g, hd] x cache [B, max_T, Hk, hd]
    max_T = ck.shape[1]
    qg = q.reshape(B, T_cur, hk, g, hd)
    s = jnp.einsum("bqhgd,bkhd->bqkhg", qg,
                   ck.astype(compute_dtype),
                   preferred_element_type=jnp.float32) * (hd ** -0.5)
    k_pos = jnp.arange(max_T)
    keep = k_pos[None, :] <= pos[:, None]            # [T_cur, max_T]
    s = jnp.where(keep[None, :, :, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=2)
    o = jnp.einsum("bqkhg,bkhd->bqhgd", p.astype(compute_dtype),
                   cv.astype(compute_dtype))
    a = o.reshape(B, T_cur, D)
    # shared tail (projection + residual + MLP): decode-time block math
    # is the training block's by construction
    h, _ = _block_tail(h, blk, a, compute_dtype)
    return h, {"k": ck, "v": cv}


def forward_cached(params, tokens, caches, pos_off, *, heads: int = 4,
                   compute_dtype=jnp.bfloat16):
    """Logits for ``tokens`` [B, T_cur] placed at global positions
    ``pos_off .. pos_off+T_cur-1``, attending to everything at or before
    each position through the caches. Returns (logits [B, T_cur, vocab],
    caches')."""
    rope = "pos_emb" not in params
    if not rope and caches[0]["k"].shape[1] > params["pos_emb"].shape[0]:
        # static guard (cache capacity vs table rows): without it a too-
        # long prefill would silently CLAMP both the pos_emb gather and
        # the cache-write start — wrong logits, corrupted cache rows —
        # the same hazard _forward's max_len check covers in training.
        # (pos_off itself is traced and must be kept < cache capacity by
        # the caller; generate's arithmetic guarantees it.)
        raise ValueError(
            f"cache capacity {caches[0]['k'].shape[1]} exceeds the "
            f"learned positional table ({params['pos_emb'].shape[0]} "
            "rows); use a rope model for unbounded decode")
    pos = pos_off + jnp.arange(tokens.shape[1])
    h = params["tok_emb"][tokens]
    if not rope:
        h = h + params["pos_emb"][pos]
    new_caches = []
    for blk, cache in zip(params["blocks"], caches):
        h, cache = _cached_block(h, blk, cache, pos_off, heads, rope,
                                 compute_dtype)
        new_caches.append(cache)
    h = _ln(h, params["ln_f"])
    logits = (h.astype(compute_dtype)
              @ params["tok_emb"].T.astype(compute_dtype))
    return logits.astype(jnp.float32), new_caches


def generate(params, prompt, steps: int, *, heads: int = 4,
             temperature: float = 0.0, key=None,
             compute_dtype=jnp.bfloat16, cache_dtype=jnp.bfloat16):
    """Autoregressive generation: prefill the prompt [B, T_p] in ONE
    forward, then ``steps`` single-token decode steps under ``lax.scan``.
    ``temperature=0`` is greedy (equals argmax over the training-time
    ``apply`` on the growing sequence); otherwise softmax sampling at
    ``temperature`` with per-step keys folded from ``key``.

    Returns ``[B, steps]`` generated tokens. Jit-friendly: wrap in
    ``jax.jit(..., static_argnames=("steps", "heads", "temperature"))``
    or close over the statics.
    """
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    B, T_p = prompt.shape
    max_T = T_p + steps
    caches = init_cache(params, B, max_T, heads=heads, dtype=cache_dtype)

    logits, caches = forward_cached(params, prompt, caches, 0,
                                    heads=heads,
                                    compute_dtype=compute_dtype)
    last = logits[:, -1]

    def pick(lg, i):
        if temperature <= 0.0:
            return jnp.argmax(lg, axis=-1).astype(prompt.dtype)
        kk = jax.random.fold_in(key, i)
        return jax.random.categorical(
            kk, lg / temperature, axis=-1).astype(prompt.dtype)

    def step(carry, i):
        lg, caches = carry
        tok = pick(lg, i)
        lg2, caches = forward_cached(params, tok[:, None], caches,
                                     T_p + i, heads=heads,
                                     compute_dtype=compute_dtype)
        return (lg2[:, -1], caches), tok

    (_, _), toks = jax.lax.scan(step, (last, caches), jnp.arange(steps))
    return toks.T                                    # [B, steps]
