// Native libsvm/Criteo-text parser — the rebuild of the reference's C++
// data-loading layer (SURVEY.md §2 "Data loading": AbstractDataLoader +
// line parsers feeding per-worker sample stores; §2.1 item 6 marks this as
// the one host-side component where native code earns its keep for
// samples/sec targets).
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in the image).
// Two-pass contract over a whole file:
//   pass 1: libsvm_count()  -> rows + max features/row
//   pass 2: libsvm_parse()  -> fills caller-allocated padded arrays
//           y[N], idx[N*W], val[N*W], mask[N*W]  (row-major, zero padded)
// libsvm_parse_mt() parallelizes pass 2 over line-aligned chunks.
// Parsing is hand-rolled (no iostream/sscanf): one linear scan, no
// allocation per token.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "reader_common.h"

using minips::FileBuf;

namespace {

inline const char* skip_ws(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  return p;
}

// Fast non-locale float parse for "123", "-1", "0.5", "1e-3" style tokens.
inline float parse_float(const char*& p) {
  char* end = nullptr;
  float v = std::strtof(p, &end);
  p = end;
  return v;
}

// int64_t, not long: on LP32 platforms strtol saturates at INT32_MAX (with
// only errno set), which would defeat the int32-overflow guard below —
// strtoll keeps the comparison platform-independent.
inline int64_t parse_long(const char*& p) {
  char* end = nullptr;
  long long v = std::strtoll(p, &end, 10);
  p = end;
  return static_cast<int64_t>(v);
}

// rows + max nnz width over whole lines in [p, endp).
void count_range(const char* p, const char* endp, int64_t* n_rows,
                 int64_t* max_width) {
  int64_t rows = 0, maxw = 0;
  while (p < endp) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    p = skip_ws(p);
    if (p < line_end) {
      ++rows;
      int64_t w = 0;
      for (const char* q = p; q < line_end; ++q)
        if (*q == ':') ++w;
      if (w > maxw) maxw = w;
    }
    p = line_end + 1;
  }
  *n_rows = rows;
  *max_width = maxw;
}

// rows only — the cheap (memchr + whitespace) pass the MT offset
// computation needs; no per-byte ':' tokenization.
int64_t count_rows_only(const char* p, const char* endp) {
  int64_t rows = 0;
  while (p < endp) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    if (skip_ws(p) < line_end) ++rows;
    p = line_end + 1;
  }
  return rows;
}

// Parse whole lines in [p, endp) into row-0-based outputs; reports rows
// written and whether any label was negative (the {-1,1} convention —
// normalization is a global post-pass, it cannot run per chunk).
// ``strict``: a malformed line (non-numeric label, feat token without
// ':', empty value) sets *malformed instead of silently fabricating a
// zero row — the Python parser raises on such lines, and the block
// ingestion path must be exactly as loud (test_data.py parity).
int64_t parse_range(const char* p, const char* endp, int64_t max_rows,
                    int64_t width, float* y, int32_t* idx, float* val,
                    float* mask, bool* saw_negative_label,
                    bool strict = false, bool* malformed = nullptr) {
  int64_t r = 0;
  while (p < endp && r < max_rows) {
    const char* line_end = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(endp - p)));
    if (!line_end) line_end = endp;
    p = skip_ws(p);
    if (p < line_end) {
      const char* lp = p;
      float label = parse_float(p);
      if (strict && p == lp) { *malformed = true; return r; }
      if (label < 0.0f) *saw_negative_label = true;
      y[r] = label;
      int64_t c = 0;
      // strict keeps scanning past the width cap (stores nothing there):
      // the Python parser tokenizes the WHOLE line before truncating, so
      // garbage after the cap must be malformed on both paths
      while (p < line_end && (c < width || strict)) {
        p = skip_ws(p);
        if (p >= line_end || *p == '\n') break;
        const char* fp = p;
        int64_t feature = parse_long(p);
        if (*p != ':') {  // malformed token: stop this row
          if (strict) { *malformed = true; return r; }
          break;
        }
        if (strict && (p == fp || feature > INT32_MAX
                       || feature < INT32_MIN)) {
          // the Python oracle raises OverflowError on indices that don't
          // fit int32; silently wrapping would scatter to wrong features
          *malformed = true;
          return r;
        }
        ++p;
        // the value must start HERE, on this line: strtof skips ALL
        // leading whitespace including '\n', so an empty value at
        // end-of-line would silently steal the next line's label
        if (strict && (p >= line_end || *p == ' ' || *p == '\t'
                       || *p == '\r' || *p == '\n')) {
          *malformed = true;
          return r;
        }
        const char* vp = p;
        float v = parse_float(p);
        if (strict && p == vp) { *malformed = true; return r; }
        if (c < width) {
          int64_t off = r * width + c;
          idx[off] = static_cast<int32_t>(feature);
          val[off] = v;
          mask[off] = 1.0f;
        }
        ++c;
      }
      ++r;
    }
    p = line_end + 1;
  }
  return r;
}

}  // namespace

extern "C" {

int libsvm_parse_mt(const char* path, int64_t n_rows, int64_t width,
                    float* y, int32_t* idx, float* val, float* mask,
                    int n_threads);

// Returns 0 on success; fills n_rows and max_width (max nnz on any row).
int libsvm_count(const char* path, int64_t* n_rows, int64_t* max_width) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  count_range(fb.data, fb.data + fb.size, n_rows, max_width);
  return 0;
}

// Fills y[N], idx[N*W], val[N*W], mask[N*W]; width W truncates longer rows.
// Labels in {-1,1} are normalized to {0,1}; other labels pass through.
int libsvm_parse(const char* path, int64_t n_rows, int64_t width,
                 float* y, int32_t* idx, float* val, float* mask) {
  return libsvm_parse_mt(path, n_rows, width, y, idx, val, mask, 1);
}

// In-memory variants for block/streaming ingestion (the criteo reader has
// the same pair): parse a chunk of whole lines already in a buffer — the
// distributed block path reads its assigned byte range once and parses it
// natively instead of through the 6x-slower Python line parser. Label
// normalization is per-chunk, exactly like the Python block parser
// (data/libsvm.py parse_libsvm_lines).
int libsvm_count_mem(const char* data, int64_t len, int64_t* n_rows) {
  if (len < 0) return 1;
  // rows-only: callers bring their own fixed width, so the per-byte ':'
  // tokenization of count_range would be a wasted pass per block
  *n_rows = count_rows_only(data, data + len);
  return 0;
}

// rc 3 = malformed line — strict like the Python block parser, which
// raises; the block ingestion path must never train on fabricated rows.
// CONTRACT: idx/val/mask must arrive ZERO-INITIALIZED (np.zeros at the
// ctypes caller) — sparse rows only write their nnz slots, and a memset
// here would re-dirty pages calloc left copy-on-write-zero, wasting
// bandwidth on the hot per-block path.
int libsvm_parse_mem(const char* data, int64_t len, int64_t max_rows,
                     int64_t width, float* y, int32_t* idx, float* val,
                     float* mask, int64_t* rows_done) {
  if (len < 0) return 1;
  bool saw_neg = false;
  bool malformed = false;
  *rows_done = parse_range(data, data + len, max_rows, width, y, idx, val,
                           mask, &saw_neg, true, &malformed);
  if (malformed) return 3;
  if (saw_neg)  // {-1,1} -> {0,1}, per chunk like the Python block parser
    for (int64_t i = 0; i < *rows_done; ++i)
      y[i] = y[i] > 0.0f ? 1.0f : 0.0f;
  return 0;
}

// Multi-threaded variant: line-aligned chunks, parallel counting pass for
// row offsets, parallel parse into disjoint slices, then the global
// {-1,1} -> {0,1} label fixup.
int libsvm_parse_mt(const char* path, int64_t n_rows, int64_t width,
                    float* y, int32_t* idx, float* val, float* mask,
                    int n_threads) {
  FileBuf fb(path);
  if (!fb.ok) return 1;
  std::memset(idx, 0, sizeof(int32_t) * static_cast<size_t>(n_rows * width));
  std::memset(val, 0, sizeof(float) * static_cast<size_t>(n_rows * width));
  std::memset(mask, 0, sizeof(float) * static_cast<size_t>(n_rows * width));
  int T = minips::clamp_threads(n_threads);
  if (T == 1) {  // true single scan: no offset pass needed
    bool saw_neg = false;
    int64_t done = parse_range(fb.data, fb.data + fb.size, n_rows, width,
                               y, idx, val, mask, &saw_neg);
    if (saw_neg)
      for (int64_t i = 0; i < n_rows; ++i) y[i] = y[i] > 0.0f ? 1.0f : 0.0f;
    return done == n_rows ? 0 : 2;
  }
  std::vector<const char*> b = minips::line_chunks(fb.data, fb.size, T);
  std::vector<int64_t> counts(static_cast<size_t>(T), 0);
  minips::parallel_for(T, [&](int i) {
    counts[static_cast<size_t>(i)] = count_rows_only(b[i], b[i + 1]);
  });
  std::vector<int64_t> offs(static_cast<size_t>(T) + 1, 0);
  for (int i = 0; i < T; ++i)
    offs[static_cast<size_t>(i) + 1] =
        offs[static_cast<size_t>(i)] + counts[static_cast<size_t>(i)];
  if (offs[static_cast<size_t>(T)] != n_rows) return 2;
  std::vector<char> neg(static_cast<size_t>(T), 0);
  std::vector<int64_t> done(static_cast<size_t>(T), 0);
  minips::parallel_for(T, [&](int i) {
    bool saw_neg = false;
    int64_t off = offs[static_cast<size_t>(i)];
    done[static_cast<size_t>(i)] = parse_range(
        b[i], b[i + 1], counts[static_cast<size_t>(i)], width, y + off,
        idx + off * width, val + off * width, mask + off * width, &saw_neg);
    neg[static_cast<size_t>(i)] = saw_neg ? 1 : 0;
  });
  bool saw_negative_label = false;
  for (int i = 0; i < T; ++i) {
    if (done[static_cast<size_t>(i)] != counts[static_cast<size_t>(i)])
      return 2;
    if (neg[static_cast<size_t>(i)]) saw_negative_label = true;
  }
  if (saw_negative_label) {  // {-1,1} -> {0,1} (a9a convention)
    for (int64_t i = 0; i < n_rows; ++i) y[i] = y[i] > 0.0f ? 1.0f : 0.0f;
  }
  return 0;
}

}  // extern "C"
