"""Orbax checkpoint backend — the ecosystem-standard alternative.

The native ``ckpt.checkpoint.Checkpointer`` (atomic npz-per-table dirs)
rebuilds the reference's Dump/Load semantics with zero dependencies; this
module offers the same interface on top of ``orbax.checkpoint`` for users
who want the JAX-ecosystem format instead: TensorStore/OCDBT storage,
orbax's own async machinery and retention, and multi-host coordination on
real pods (every process participates in one save — exactly what
``jax.distributed`` jobs expect; SURVEY.md §5.4's "orbax-style async
checkpoint" made literal).

Same surface as the native backend (save / wait / restore / list_steps),
same content (each table's ``state_dict()`` + controller clocks), so the
two are drop-in interchangeable:

    ck = make_checkpointer(path, tables, backend="orbax")  # or "native"
"""

from __future__ import annotations

import os
from typing import Any, Optional


def _jsonable(node):
    """Clock state: numpy scalars/arrays -> plain ints/lists for JsonSave."""
    import numpy as np

    if isinstance(node, dict):
        return {k: _jsonable(v) for k, v in node.items()}
    if isinstance(node, (list, tuple)):
        return [_jsonable(v) for v in node]
    if isinstance(node, np.ndarray):
        return node.tolist()
    if isinstance(node, np.generic):
        return node.item()
    return node


class OrbaxCheckpointer:
    def __init__(self, directory: str, tables: dict[str, Any],
                 controllers: Optional[dict[str, Any]] = None,
                 *, keep: int = 3, async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.tables = tables
        self.controllers = controllers or {}
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep if keep > 0 else None,
                enable_async_checkpointing=async_save,
            ),
            # registering the handlers up front makes item_metadata()
            # usable before any save/restore — restore() prunes its
            # template against the saved tree (legacy checkpoints)
            item_handlers={"tables": ocp.StandardCheckpointHandler(),
                           "clocks": ocp.JsonCheckpointHandler()},
        )

    # ------------------------------------------------------------------ save
    def save(self, step: int) -> str:
        # tables are array pytrees (StandardSave/TensorStore); controller
        # clock state carries strings/ints, which Standard rejects — it
        # rides the JSON item of one composite checkpoint
        tables = {n: t.state_dict() for n, t in self.tables.items()}
        clocks = _jsonable({n: c.state_dict()
                            for n, c in self.controllers.items()})
        self._mgr.save(step, args=self._ocp.args.Composite(
            tables=self._ocp.args.StandardSave(tables),
            clocks=self._ocp.args.JsonSave(clocks)))
        return str(step)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    # --------------------------------------------------------------- restore
    def list_steps(self) -> list[int]:
        return sorted(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None) -> int:
        steps = self.list_steps()
        if not steps:
            raise FileNotFoundError(
                f"no checkpoints under {self._mgr.directory}")
        step = steps[-1] if step is None else step
        # restore against the live tables' state as the abstract target:
        # orbax then knows every leaf's shape/dtype (and, on a pod, its
        # sharding) instead of guessing the topology — restoring without a
        # target is the documented-unsafe path
        template = {n: t.state_dict() for n, t in self.tables.items()}
        # prune template entries the checkpoint does not carry (e.g. the
        # sparse 'layout' record added after a checkpoint was written):
        # StandardRestore errors on template keys absent from storage, and
        # load_state_dict owns the is-this-tolerable decision instead
        try:
            saved = self._mgr.item_metadata(step).tables
            template = {n: {k: v for k, v in td.items() if k in saved[n]}
                        for n, td in template.items()}
        except (KeyError, TypeError, AttributeError):
            pass  # metadata unavailable → restore with the full template
        state = self._mgr.restore(step, args=self._ocp.args.Composite(
            tables=self._ocp.args.StandardRestore(template),
            clocks=self._ocp.args.JsonRestore()))
        for name, t in self.tables.items():
            t.load_state_dict(state["tables"][name])
        for name, c in self.controllers.items():
            if name in (state["clocks"] or {}):
                c.load_state_dict(state["clocks"][name])
        return int(step)

    def close(self) -> None:
        self._mgr.close()


def make_checkpointer(directory: str, tables: dict[str, Any],
                      controllers: Optional[dict[str, Any]] = None,
                      *, keep: int = 3, async_save: bool = False,
                      backend: Optional[str] = None):
    """Factory: ``backend`` = "native" (npz dirs, default) or "orbax";
    default from ``$MINIPS_CKPT_BACKEND``."""
    backend = backend or os.environ.get("MINIPS_CKPT_BACKEND", "native")
    if backend == "orbax":
        return OrbaxCheckpointer(directory, tables, controllers,
                                 keep=keep, async_save=async_save)
    if backend != "native":
        raise ValueError(f"unknown checkpoint backend {backend!r} "
                         "(expected 'native' or 'orbax')")
    from minips_tpu.ckpt.checkpoint import Checkpointer

    return Checkpointer(directory, tables, controllers, keep=keep,
                        async_save=async_save)
