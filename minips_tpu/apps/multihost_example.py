"""Multi-host SPMD data plane — the real-pod story, smoke-sized.

The reference actually runs N processes on N nodes glued by the mailbox
(SURVEY.md §1 L7, §3.1); the rebuild's equivalent for the SPMD data plane
is ``jax.distributed.initialize`` + ONE global mesh spanning every
process's devices (SURVEY.md §2.3 "DCN"): the same fused
pull→grad→push→update step (tables/dense.py) compiles unchanged, XLA
routes its collectives across the process boundary (ICI intra-host, DCN
inter-host; Gloo on the CPU loopback smoke), and batches are fed
per-process via ``make_array_from_process_local_data`` — each host
contributes the rows it loaded.

Run under the launcher (which exports MINIPS_COORDINATOR + ranks):

    python -m minips_tpu.launch --n 2 --base-port 59XX -- \
        python -m minips_tpu.apps.multihost_example --iters 30

Each rank prints ONE JSON line (smoke protocol): losses, process/device
counts, a post-training parameter fingerprint (process-allgathered, so
ranks can be compared for SPMD agreement), and the result of a
globally-sharded orbax checkpoint save→restore drill in which every
process writes/reads only its addressable shards (SURVEY.md §5.4).

Single-process (no launcher) the exact same code runs on the local
devices — that run is the loss-parity oracle for the 2-process smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from contextlib import contextmanager


class _Watchdog:
    """Fast failure detection for multi-host SPMD jobs (SURVEY §5.3): a
    peer death leaves the survivors BLOCKED inside a collective — the
    host thread cannot poll anything — so detection rides the
    HeartbeatMonitor's own thread via its ``on_failure`` callback (2s
    timeout over the launcher's control bus): print the structured
    peer_failure event and exit 42, the same protocol as the sharded-PS
    apps. Recovery is the all-or-nothing relaunch + checkpoint restore
    the reference uses (SURVEY §3.5, §7.4.5); jax's own coordination
    service is the ~100s backstop for deaths in the disarm→barrier
    window."""

    def __init__(self, rank: int):
        from minips_tpu.comm.heartbeat import HeartbeatMonitor
        from minips_tpu.launch import init_from_env

        _, n, self.bus = init_from_env()
        self.monitor = None
        self._armed = True
        if self.bus is None:
            return

        def on_dead(peer: int) -> None:
            if self._armed:
                print(json.dumps({"rank": rank, "event": "peer_failure",
                                  "dead": [peer]}), flush=True)
                os._exit(42)

        self.monitor = HeartbeatMonitor(
            self.bus, peer_ids=list(range(n)), interval=0.2,
            timeout=2.0, on_failure=on_dead).start()

    def disarm(self) -> None:
        """Call once training is complete, BEFORE the final barrier: a
        peer closing its bus after finishing must not read as a death."""
        self._armed = False

    def absorb_collective_failure(self, exc: BaseException) -> None:
        """A dead peer does NOT always leave survivors blocked: on the
        Gloo loopback transport the broken TCP pair surfaces INSTANTLY
        as a JaxRuntimeError in whoever touches the collective's output
        — faster than the heartbeat timeout, so the structured
        peer_failure protocol would lose the race to a raw traceback.
        Hold the rank here long enough for the monitor to confirm and
        NAME the corpse (its on_failure callback prints peer_failure
        and exits 42); if no peer is confirmed dead the error was not a
        death — re-raise it."""
        if self.monitor is not None and self._armed:
            deadline = time.monotonic() + 3 * self.monitor.timeout + 2.0
            while time.monotonic() < deadline:
                self.monitor.check()  # on_failure → print + exit 42
                time.sleep(0.1)
        raise exc

    @contextmanager
    def absorbing(self):
        """Run a training loop under the instant-Gloo-error →
        peer_failure translation (one spelling for every runner — see
        absorb_collective_failure)."""
        import jax

        try:
            yield
        except jax.errors.JaxRuntimeError as e:
            self.absorb_collective_failure(e)

    def close(self) -> None:
        self.disarm()
        if self.monitor is not None:
            self.monitor.stop()
        if self.bus is not None:
            self.bus.close()


def _finish(rc: int) -> int:
    """Clean-exit join point: coordinated jax.distributed disconnect
    (cluster.shutdown) AFTER the result line is printed — without it the
    coordinator rank's exit races the followers' error-polling threads
    and a finished follower can be fatally terminated into rc!=0."""
    from minips_tpu.comm import cluster

    cluster.shutdown()
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--mode", default="fused",
                    choices=["fused", "bsp", "ssp", "asp"],
                    help="fused: the one-global-mesh BSP data plane "
                         "(implicit-barrier collectives, the default); "
                         "bsp/ssp/asp: CollectiveSSP (train/ssp_spmd.py) "
                         "— per-process local fused steps under the "
                         "host-side staleness gate, cross-process sync "
                         "as an XLA collective (SURVEY §7.4.1)")
    ap.add_argument("--staleness", type=int, default=4,
                    help="SSP bound s for --mode ssp (bsp pins 0, "
                         "asp pins inf)")
    ap.add_argument("--sync-every", type=int, default=1,
                    help="collective merge every k local steps "
                         "(CollectiveSSP modes)")
    ap.add_argument("--sync-comm", default="float32",
                    choices=["float32", "bfloat16", "int8"],
                    help="CollectiveSSP modes: wire format of the delta "
                         "merge — bfloat16/int8 compress the all-reduce "
                         "with an error-feedback residual (on a pod this "
                         "is DCN bandwidth); lr/lm models only")
    ap.add_argument("--opt-sync", default="local",
                    choices=["local", "avg"],
                    help="CollectiveSSP modes, stateful updaters: "
                         "'local' keeps each process's moments (drift "
                         "documented in docs/consistency.md); 'avg' "
                         "psum-averages float moments alongside the "
                         "param deltas at every merge")
    ap.add_argument("--slow-rank", type=int, default=-1)
    ap.add_argument("--slow-ms", type=int, default=0,
                    help="straggler injection: sleep this long before "
                         "each of --slow-rank's local steps")
    ap.add_argument("--jitter-ms", type=float, default=0.0,
                    help="TRANSIENT stall injection on every rank "
                         "(rank-seeded): sleep this long before a step "
                         "with --jitter-prob — the regime where SSP's "
                         "slack window beats BSP's stall union "
                         "(bench_ssp --collective)")
    ap.add_argument("--jitter-prob", type=float, default=0.0)
    ap.add_argument("--oracle-hosts", type=int, default=0,
                    help="single-process: SIMULATE this many hosts "
                         "sequentially (disjoint submeshes, same merge "
                         "schedule) — the bitwise loss oracle for the "
                         "real N-process CollectiveSSP run")
    ap.add_argument("--model", default="lr", choices=["lr", "wd", "lm"],
                    help="lr: DenseTable LR (checkpoint drill supported); "
                         "wd: the flagship DeepFM fused step — hashed "
                         "SparseTables + deep tower over the GLOBAL mesh, "
                         "collectives crossing the process boundary; "
                         "lm: ring-attention SEQUENCE parallelism over "
                         "the global mesh — each host feeds its sequence "
                         "slice, the K/V ring ppermutes cross the "
                         "process boundary (long-context x multi-host)")
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--sp-attn", default="reference",
                    choices=["reference", "flash", "a2a", "a2a_flash"],
                    help="lm model: the sequence-parallel strategy over "
                         "the GLOBAL mesh — ring (reference/flash, K/V "
                         "ppermute hops cross the process boundary) or "
                         "all-to-all (a2a/a2a_flash: the head/sequence "
                         "exchange crosses it instead; needs heads "
                         "divisible by the global device count — the "
                         "model auto-widens to that head count)")
    ap.add_argument("--num-slots", type=int, default=1 << 14)
    ap.add_argument("--batch", type=int, default=64,
                    help="GLOBAL batch size (split across processes)")
    ap.add_argument("--dim", type=int, default=None,
                    help="lr: feature dim (default 16); wd: embedding "
                         "dim (default 8)")
    ap.add_argument("--lr", type=float, default=None,
                    help="default: 0.3 (lr model) / 0.05 (wd)")
    ap.add_argument("--updater", default="adagrad",
                    choices=["sgd", "adagrad", "adam", "adam_bf16",
                             "adam8"])  # dense-table paths (fused +
    # CollectiveSSP) take the low-precision states too; the sharded-PS
    # apps keep their numpy-twin trio and refuse these loudly
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="shared dir for the globally-sharded orbax "
                         "save→restore drill (skipped when absent)")
    ap.add_argument("--save-at", type=int, default=0,
                    help="iteration AFTER which to save (0 = at the end)")
    ap.add_argument("--restore-from", type=int, default=0,
                    help="restore the step-N checkpoint before training "
                         "(the relaunch leg of the recovery drill)")
    ap.add_argument("--kill-at", type=int, default=0)
    ap.add_argument("--kill-rank", type=int, default=-1)
    args = ap.parse_args(argv)
    if args.dim is None:  # per-model default: lr feature dim / wd emb dim
        args.dim = 16 if args.model == "lr" else 8
    if args.lr is None:
        args.lr = 0.3 if args.model == "lr" else 0.05
    if args.save_at > args.iters:
        ap.error(f"--save-at {args.save_at} exceeds --iters {args.iters}: "
                 "the restore drill would read a checkpoint never saved")
    if args.restore_from >= args.iters:
        ap.error(f"--restore-from {args.restore_from} must be < --iters "
                 f"{args.iters} (nothing left to train)")
    if args.opt_sync != "local" and args.mode == "fused":
        ap.error("--opt-sync is a CollectiveSSP-mode flag; the fused "
                 "global-mesh path has ONE optimizer state (nothing to "
                 "reconcile)")
    if args.sync_comm != "float32" and args.mode == "fused":
        ap.error("--sync-comm compresses the CollectiveSSP delta merge; "
                 "the fused path's wire format is make_step(comm=...)")

    # CPU smoke path: fake local devices BEFORE any backend-touching call
    # (the sandbox TPU plugin ignores JAX_PLATFORMS env, hence
    # config.update — same bootstrap as tests/conftest.py)
    local_devs = int(os.environ.get("MINIPS_MH_LOCAL_DEVICES", "0"))
    if local_devs:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={local_devs}")
    import jax

    if os.environ.get("MINIPS_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    from minips_tpu.comm import cluster

    multi = cluster.initialize()
    rank = jax.process_index()
    nprocs = jax.process_count()
    watchdog = _Watchdog(rank)

    import numpy as np

    from minips_tpu.models import lr as lr_model
    from minips_tpu.parallel.mesh import make_mesh
    from minips_tpu.tables.dense import DenseTable

    mesh = make_mesh(len(jax.devices()))  # ONE mesh over every process
    B, D = args.batch, args.dim
    if B % nprocs:
        raise SystemExit(f"--batch {B} must divide by {nprocs} processes")
    per = B // nprocs
    # every rank generates the identical GLOBAL batch stream and feeds its
    # own row slice — so an n-process run and the single-process oracle
    # train on the same data and must produce the same losses (the smoke's
    # parity assertion)
    rng = np.random.default_rng(args.seed)

    if args.mode != "fused":
        # the staleness axis covers the flagship workloads, not just LR:
        # lr = dense CollectiveSSP (+ the bitwise oracle), wd = row-sparse
        # CollectiveSSPPS over the DeepFM tables, lm = dense CollectiveSSP
        # over the transformer (per-process DP islands)
        if args.model == "lr":
            from minips_tpu.train.ssp_spmd import run_ssp_spmd

            return _finish(run_ssp_spmd(args, rank, nprocs, multi, watchdog))
        if args.oracle_hosts:
            raise SystemExit("--oracle-hosts is the lr model's bitwise "
                             "oracle; wd/lm assert replica agreement "
                             "via fingerprints instead")
        if args.checkpoint_dir or args.save_at or args.restore_from \
                or args.kill_at:
            # refuse-loudly convention: the checkpoint/kill recovery
            # drill lives on the lr CollectiveSSP path (and the fused
            # path); silently ignoring the flags here would complete a
            # run with no snapshot and crash the restore leg later
            raise SystemExit("--checkpoint-dir/--save-at/--restore-from/"
                             "--kill-at are not wired for the wd/lm "
                             "CollectiveSSP paths; use --model lr for "
                             "the collective-SSP recovery drill")
        from minips_tpu.train.cssp_ps import run_lm_cssp, run_wd_cssp

        if args.model == "wd":
            return _finish(run_wd_cssp(args, rank, nprocs, multi, watchdog))
        return _finish(run_lm_cssp(args, rank, nprocs, multi, watchdog))
    if args.model == "wd":
        return _finish(_run_wd(args, mesh, rank, nprocs, per, multi,
                               rng, watchdog))
    if args.model == "lm":
        return _finish(_run_lm_sp(args, mesh, rank, nprocs, multi,
                               watchdog))

    dt = DenseTable(lr_model.init(args.dim), mesh, updater=args.updater,
                    lr=args.lr)
    step = dt.make_step(lr_model.grad_fn_dense)
    w_true = rng.normal(size=D)

    def next_global():
        x = rng.normal(size=(B, D)).astype(np.float32)
        y = (x @ w_true > 0).astype(np.float32)
        return x, y

    ckpt_fp = None
    save_at = args.save_at or args.iters
    ckptr = None
    if args.checkpoint_dir:
        import orbax.checkpoint as ocp

        # synchronous Checkpointer: its primary-host dir creation +
        # barrier protocol is what coordinates a multi-process save (the
        # async StandardCheckpointer races per-process signaling threads
        # on the shared tmp dir in this orbax version)
        ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
        ocp_args = ocp.args

    start = 0
    if args.restore_from:  # relaunch leg of the recovery drill
        if ckptr is None:
            raise SystemExit("--restore-from needs --checkpoint-dir")
        restored = ckptr.restore(
            os.path.join(args.checkpoint_dir, f"step{args.restore_from}"),
            args=ocp_args.StandardRestore(dt.global_arrays()))
        dt.params = restored["params"]
        dt.opt_state = restored["opt_state"]
        start = args.restore_from
        # replay the shared batch stream up to the restore point so the
        # resumed run continues the SAME data sequence (TrainLoop's
        # fast-forward semantics, here at the multihost smoke's scale)
        for _ in range(start):
            next_global()

    losses = []
    t0 = time.monotonic()
    with watchdog.absorbing():
        for i in range(start, args.iters):
            if args.kill_at and rank == args.kill_rank \
                    and i == args.kill_at:
                os._exit(137)
            x, y = next_global()
            batch = cluster.global_batch(
                mesh, {"x": x[rank * per:(rank + 1) * per],
                       "y": y[rank * per:(rank + 1) * per]})
            losses.append(float(dt.step_inplace(step, batch)))
            if ckptr is not None and i + 1 == save_at:
                # coordinated multi-host save: every process writes ONLY
                # its addressable shards of the live sharded arrays
                # (TensorStore under orbax) — no host gather, no full
                # copy anywhere
                ckptr.save(
                    os.path.join(args.checkpoint_dir, f"step{i + 1}"),
                    args=ocp_args.StandardSave(dt.global_arrays()),
                    force=True)
                ckpt_fp = float(cluster.host_copy(dt.params).sum())

    # fingerprint + checkpoint roundtrip are collectives too — same
    # death translation as the training loop
    with watchdog.absorbing():
        # SPMD agreement fingerprint (allgathered => comparable across
        # ranks)
        fp = float(cluster.host_copy(dt.params).sum())

        ckpt_ok = None
        if ckptr is not None and ckpt_fp is not None:
            # restore into a FRESH table (same template/shardings) and
            # check it reproduces the state that was saved — the
            # recovery path of SURVEY.md §3.5 with globally-sharded state
            dt2 = DenseTable(lr_model.init(args.dim), mesh,
                             updater=args.updater, lr=args.lr)
            restored = ckptr.restore(
                os.path.join(args.checkpoint_dir, f"step{save_at}"),
                args=ocp_args.StandardRestore(dt2.global_arrays()))
            dt2.params = restored["params"]
            dt2.opt_state = restored["opt_state"]
            ckpt_ok = bool(abs(float(cluster.host_copy(dt2.params).sum())
                               - ckpt_fp) < 1e-5)
        if ckptr is not None:
            ckptr.close()

    watchdog.disarm()  # peers closing their buses after finishing is fine
    cluster.barrier("multihost_done")  # reference Engine::Barrier
    print(json.dumps({
        "rank": rank, "event": "done",
        "wall_s": round(time.monotonic() - t0, 4),
        "multi": multi,
        "process_count": nprocs,
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "loss_first": losses[0], "loss_last": losses[-1],
        "losses": [round(x, 8) for x in losses],
        "param_fingerprint": fp,
        "ckpt_roundtrip_ok": ckpt_ok,
        "resumed_from": start,
    }), flush=True)
    watchdog.close()
    return _finish(0)


def _run_lm_sp(args, mesh, rank, nprocs, multi, watchdog):
    """Long-context x multi-host: the transformer LM with ring-attention
    SEQUENCE parallelism over the global multi-process mesh. The sequence
    axis is sharded across every device of every process, each host feeds
    only its own sequence slice, and the ring's K/V ppermute hops cross
    the process boundary — the 'ring attention ... scales to multi-host'
    requirement made literal (SURVEY brief; parallel/ring_attention.py).
    Deterministic data (same stream everywhere) so ranks must agree and a
    1-process run with the same global devices is an exact oracle."""
    import time

    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from minips_tpu.comm import cluster
    from minips_tpu.models import transformer as tfm
    from minips_tpu.parallel.mesh import DATA_AXIS
    from minips_tpu.tables.dense import DenseTable

    t0 = time.monotonic()
    n_shards = len(jax.devices())
    T = args.seq_len
    if T % n_shards:
        raise SystemExit(f"--seq-len {T} must divide by the {n_shards}-"
                         "device global mesh")
    heads = 2
    if args.sp_attn in ("a2a", "a2a_flash"):
        # all-to-all shards HEAD groups over the global mesh: widen to
        # one head per device
        heads = n_shards
    # dim must divide by heads AND keep head_dim >= 4; a plain
    # max(32, 4*heads) breaks divisibility for device counts that don't
    # divide 32 (e.g. a 2x3 mesh -> heads 6)
    model = dict(vocab=64, dim=heads * max(4, -(-32 // heads)),
                 heads=heads, depth=2, max_len=T)
    params = tfm.init(jax.random.PRNGKey(args.seed), **model)
    dt = DenseTable(params, mesh, updater=args.updater, lr=args.lr,
                    name="lm_sp")
    T_local = T // n_shards
    sp_grad, sp_spec = tfm.sp_train_wiring(model["heads"], T_local,
                                           attn_impl=args.sp_attn)
    step = dt.make_step(sp_grad, batch_spec=sp_spec)
    seq_spec = P(None, DATA_AXIS)
    B = args.batch
    rng = np.random.default_rng(args.seed)
    # my PROCESS's sequence span (devices within split it further)
    dev_per_proc = n_shards // nprocs
    lo = rank * dev_per_proc * T_local
    hi = lo + dev_per_proc * T_local
    losses = []
    with watchdog.absorbing():
        for i in range(args.iters):
            toks = rng.integers(0, model["vocab"], size=(B, T + 1))
            batch = cluster.global_batch(
                mesh,
                {"inp": toks[:, :-1][:, lo:hi].astype(np.int32),
                 "tgt": toks[:, 1:][:, lo:hi].astype(np.int32)},
                spec=seq_spec)
            losses.append(float(dt.step_inplace(step, batch)))

    with watchdog.absorbing():  # the fingerprint allgather too
        fp = float(cluster.host_copy(dt.params).sum())
    watchdog.disarm()
    cluster.barrier("multihost_lm_done")
    print(json.dumps({
        "rank": rank, "event": "done", "model": "lm",
        "wall_s": round(time.monotonic() - t0, 4),
        "multi": multi,
        "process_count": nprocs,
        "global_devices": n_shards,
        "local_devices": len(jax.local_devices()),
        "seq_len": T, "seq_local": hi - lo,
        "sp_attn": args.sp_attn, "heads": heads,
        "loss_first": losses[0], "loss_last": losses[-1],
        "losses": [round(x, 8) for x in losses],
        "param_fingerprint": fp,
        "ckpt_roundtrip_ok": None,
    }), flush=True)
    watchdog.close()
    return 0


def _run_wd(args, mesh, rank, nprocs, per, multi, rng, watchdog):
    """Flagship DeepFM over the global multi-process mesh: hashed
    SparseTables (wide + field embeddings) and the dense deep tower,
    one fused PSTrainStep whose gathers/scatters and grad collectives
    cross the process boundary — the sparse-embedding-PS-on-a-pod story
    (BASELINE.json config 4) on real processes. Traffic stays batch-sized
    by the same GSPMD shardings tests/test_sharded_traffic.py pins."""
    import time

    import jax
    import numpy as np

    from minips_tpu.apps.wide_deep_example import build
    from minips_tpu.comm import cluster
    from minips_tpu.core.config import Config, TableConfig, TrainConfig
    from minips_tpu.data import synthetic

    t0 = time.monotonic()
    cfg = Config(
        table=TableConfig(name="ctr", kind="sparse", updater=args.updater,
                          lr=args.lr, dim=args.dim,
                          num_slots=args.num_slots),
        train=TrainConfig(batch_size=args.batch, num_iters=args.iters),
    )
    ps, (wide_t, emb_t, deep_t) = build(cfg, use_fm=True, mesh=mesh,
                                        seed=args.seed)
    # ONE dataset (one ground truth), identical on every rank; batches are
    # sampled from it with a shared stream and each rank feeds its slice
    data = synthetic.criteo_like(8192, seed=args.seed)
    losses = []
    with watchdog.absorbing():
        for i in range(args.iters):
            sel = rng.integers(0, data["y"].shape[0], size=args.batch)
            lo, hi = rank * per, (rank + 1) * per
            batch = cluster.global_batch(
                mesh, {k: v[sel][lo:hi] for k, v in data.items()})
            losses.append(float(ps(batch)))

    with watchdog.absorbing():  # the fingerprint allgathers too
        fp = float(cluster.host_copy(emb_t.emb).sum()) \
            + float(cluster.host_copy(deep_t.params).sum())
    watchdog.disarm()
    cluster.barrier("multihost_wd_done")
    import json
    print(json.dumps({
        "rank": rank, "event": "done", "model": "wd",
        "wall_s": round(time.monotonic() - t0, 4),
        "multi": multi,
        "process_count": nprocs,
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "loss_first": losses[0], "loss_last": losses[-1],
        "losses": [round(x, 8) for x in losses],
        "param_fingerprint": fp,
        "ckpt_roundtrip_ok": None,
        "emb_slots": int(args.num_slots),
    }), flush=True)
    watchdog.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
