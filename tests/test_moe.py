"""Expert-parallel MoE (Switch-style top-1) vs the dense oracle.

Beyond parity (reference has no EP, SURVEY.md §2.2): tokens sharded over
the data axis, experts sharded over the same axis, two all_to_alls per
layer. With non-binding capacity the distributed output must equal the
oracle token-for-token; grads (router included) must match too."""

import jax

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from minips_tpu.utils.jaxcompat import shard_map
from minips_tpu.parallel.mesh import make_mesh
from minips_tpu.parallel.moe import (
    ep_specs,
    init_moe,
    moe_apply_dense,
    moe_apply_local,
)

E, D, HID = 8, 16, 32
F32 = dict(compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return init_moe(jax.random.PRNGKey(0), E, D, HID)


def _x(N, seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (N, D), jnp.float32)


def _ep_apply(mesh, params, x, capacity):
    f = shard_map(
        lambda p, x_: moe_apply_local(p, x_, axis_name="data",
                                      capacity=capacity, **F32),
        mesh=mesh, in_specs=(ep_specs("data"), P("data")),
        out_specs=(P("data"), P()))
    return f(params, x)


def test_ep_matches_dense_oracle(mesh8, params):
    x = _x(64)
    # capacity 64 can never bind (each source device has only 8 tokens)
    y_ep, aux_ep = _ep_apply(mesh8, params, x, capacity=64)
    y_dense, aux_dense = moe_apply_dense(params, x, capacity=1024, **F32)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_dense),
                               rtol=1e-4, atol=1e-5)
    # aux loss: dense computes over all tokens; ep pmeans per-device stats.
    # frac/mean_p are means over equal-sized shards, so they agree.
    assert abs(float(aux_ep) - float(aux_dense)) < 1e-5


def test_ep_grads_match_dense(mesh8, params):
    x = _x(64, seed=1)
    tgt = _x(64, seed=2)

    def loss_ep(p):
        def shard_fn(p_, x_, t_):
            y, aux = moe_apply_local(p_, x_, axis_name="data",
                                     capacity=64, **F32)
            return (jax.lax.pmean(jnp.mean((y - t_) ** 2), "data")
                    + 0.01 * aux)
        return shard_map(
            shard_fn, mesh=mesh8,
            in_specs=(ep_specs("data"), P("data"), P("data")),
            out_specs=P())(p, x, tgt)

    def loss_dense(p):
        y, aux = moe_apply_dense(p, x, capacity=1024, **F32)
        return jnp.mean((y - tgt) ** 2) + 0.01 * aux

    l_e, g_e = jax.value_and_grad(loss_ep)(params)
    l_d, g_d = jax.value_and_grad(loss_dense)(params)
    assert abs(float(l_e) - float(l_d)) < 1e-5
    fe, _ = jax.flatten_util.ravel_pytree(g_e)
    fd, _ = jax.flatten_util.ravel_pytree(g_d)
    np.testing.assert_allclose(np.asarray(fe), np.asarray(fd),
                               rtol=2e-4, atol=1e-5)


def test_capacity_drops_tokens(params):
    """With capacity 1, each expert processes at most one token; dropped
    tokens output zero (standard Switch behavior)."""
    x = _x(32, seed=3)
    y, _ = moe_apply_dense(params, x, capacity=1, **F32)
    y_full, _ = moe_apply_dense(params, x, capacity=1024, **F32)
    norms = np.linalg.norm(np.asarray(y), axis=-1)
    assert (norms < 1e-9).sum() >= 32 - E        # most tokens dropped
    # surviving tokens match the uncapped output
    alive = norms > 1e-9
    np.testing.assert_allclose(np.asarray(y)[alive],
                               np.asarray(y_full)[alive],
                               rtol=1e-5, atol=1e-6)


def test_router_trains_toward_balance(mesh8, params):
    """Minimizing the aux loss pushes routing toward uniform expert use."""
    import optax

    x = _x(256, seed=4)
    p = jax.tree.map(jnp.copy, params)
    tx = optax.adam(5e-2)
    opt = tx.init(p)

    def loss(p_):
        _, aux = moe_apply_dense(p_, x, capacity=1024, **F32)
        return aux

    for _ in range(30):
        g = jax.grad(loss)(p)
        updates, opt = tx.update(g, opt, p)
        p = optax.apply_updates(p, updates)
    assert float(loss(p)) < float(loss(params))


def test_expert_count_mismatch_raises(mesh8, params):
    # 16 experts stacked (shards cleanly 8-way, 2 per device) but the
    # router still claims 8 -> moe_apply_local's own guard must fire
    bad = dict(params,
               w_in=jnp.concatenate([params["w_in"]] * 2),
               w_out=jnp.concatenate([params["w_out"]] * 2))
    with pytest.raises(ValueError, match="devices hold"):
        _ep_apply(mesh8, bad, _x(64), capacity=8)


class TestMoELM:
    """MoE transformer (dp attention + ep FFN over the same axis)."""

    CFG = dict(vocab=23, dim=16, heads=2, depth=2, max_len=32,
               num_experts=8, expert_hidden=32)

    @pytest.fixture(scope="class")
    def lm_params(self):
        from minips_tpu.models import transformer as tfm
        return tfm.init_moe_lm(jax.random.PRNGKey(1), **self.CFG)

    def _toks(self, B, T, seed=0):
        rng = jax.random.PRNGKey(seed)
        return jax.random.randint(rng, (B, T), 0, self.CFG["vocab"])

    @pytest.mark.slow  # fast tier: test_ep_matches_dense_oracle
    def test_ep_lm_matches_dense(self, mesh8, lm_params):
        from minips_tpu.models import transformer as tfm

        toks = self._toks(8, 12)
        want, aux_want = tfm.apply_moe_dense(
            lm_params, toks, heads=2, capacity=2048, **F32)
        f = shard_map(
            lambda p, t: tfm.apply_ep(p, t, heads=2, capacity=256, **F32),
            mesh=mesh8,
            in_specs=(tfm.ep_lm_specs(lm_params), P("data")),
            out_specs=(P("data"), P()))
        got, aux_got = f(lm_params, toks)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        assert abs(float(aux_got) - float(aux_want)) < 1e-5

    @pytest.mark.slow  # fast tier: test_ep_grads_match_dense
    def test_ep_lm_trains(self, mesh8, lm_params):
        """value_and_grad outside the shard_map; loss decreases."""
        import optax
        from minips_tpu.models import transformer as tfm

        toks = self._toks(8, 13, seed=2)

        def loss(p):
            def shard_fn(p_, t_):
                logits, aux = tfm.apply_ep(p_, t_[:, :-1], heads=2,
                                           capacity=256, **F32)
                return jax.lax.pmean(
                    tfm.nll(logits, t_[:, 1:]), "data") + 0.01 * aux
            return shard_map(
                shard_fn, mesh=mesh8,
                in_specs=(tfm.ep_lm_specs(lm_params), P("data")),
                out_specs=P())(p, toks)

        tx = optax.adam(1e-2)
        p = jax.tree.map(jnp.copy, lm_params)
        opt = tx.init(p)

        @jax.jit
        def step(p, opt):
            l, g = jax.value_and_grad(loss)(p)
            updates, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, updates), opt, l

        first = None
        for _ in range(15):
            p, opt, l = step(p, opt)
            if first is None:
                first = float(l)
        assert float(loss(p)) < first


class TestTopK:
    """GShard-style top-2 routing (k_top) on the same dispatch machinery."""

    def test_top2_matches_direct_sum_when_capacity_ample(self, params):
        """With no drops, top-2 output == sum over each token's two best
        experts of gate_e * FFN_e(token), computed directly."""
        x = _x(32, seed=3)
        y, _ = moe_apply_dense(params, x, capacity=64, k_top=2, **F32)

        probs = jax.nn.softmax(x @ params["router"], axis=-1)
        gate, idx = jax.lax.top_k(probs, 2)
        expected = jnp.zeros_like(x)
        for n in range(x.shape[0]):
            for r in range(2):
                e = int(idx[n, r])
                h = jax.nn.gelu(x[n] @ params["w_in"][e])
                expected = expected.at[n].add(
                    float(gate[n, r]) * (h @ params["w_out"][e]))
        np.testing.assert_allclose(y, expected, atol=1e-4, rtol=1e-4)

    def test_top2_ep_matches_dense(self, mesh8, params):
        x = _x(64, seed=4)
        yd, auxd = moe_apply_dense(params, x, capacity=64, k_top=2, **F32)
        f = shard_map(
            lambda p, x_: moe_apply_local(p, x_, axis_name="data",
                                          capacity=64, k_top=2, **F32),
            mesh=mesh8, in_specs=(ep_specs("data"), P("data")),
            out_specs=(P("data"), P()))
        ye, auxe = f(params, x)
        np.testing.assert_allclose(ye, yd, atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(auxe, auxd, atol=1e-5, rtol=1e-5)

    def test_top2_primary_survives_capacity_pressure(self, params):
        """Rank-major slot assignment: when capacity binds, every kept
        secondary route has a queue position after ALL kept primaries of
        its expert — no token loses its primary to another's secondary."""
        from minips_tpu.parallel.moe import _dispatch_combine

        x = _x(48, seed=5)
        cap = 3  # far below 48/8: heavy pressure
        dispatch, _, _, _ = _dispatch_combine(
            x, params["router"], E, cap, k_top=2)
        probs = jax.nn.softmax(x @ params["router"], axis=-1)
        _, idx = jax.lax.top_k(probs, 2)
        routed = dispatch.sum(axis=(1, 2))  # 0..2 kept routes per token
        # every expert's slots fill with primaries first: count primaries
        # kept vs total primaries per expert
        for e in range(E):
            primaries = [n for n in range(48) if int(idx[n, 0]) == e]
            kept_primary = sum(
                float(dispatch[n, e].sum()) > 0 for n in primaries)
            # the first min(cap, #primaries) primaries must all be kept
            assert kept_primary == min(cap, len(primaries))

    def test_top1_equals_legacy_switch(self, params):
        """k_top=1 is bit-for-bit the original Switch path."""
        x = _x(40, seed=6)
        y1, a1 = moe_apply_dense(params, x, capacity=8, **F32)
        y2, a2 = moe_apply_dense(params, x, capacity=8, k_top=1, **F32)
        np.testing.assert_array_equal(y1, y2)
        np.testing.assert_array_equal(a1, a2)


@pytest.mark.slow  # app-level ep sweep; library ep parity stays fast
def test_lm_example_ep_layout_trains(mesh8):
    """The ep layout trains the MoE-LM end-to-end from the app surface
    (experts sharded over the 8-device mesh, top-2 routing)."""
    import argparse

    from minips_tpu.apps import lm_example as app
    from minips_tpu.core.config import Config, TableConfig, TrainConfig
    from minips_tpu.utils.metrics import MetricsLogger

    cfg = Config(
        table=TableConfig(name="lm", kind="dense", updater="adam", lr=3e-3),
        train=TrainConfig(batch_size=16, num_iters=10, log_every=100),
    )
    args = argparse.Namespace(layout="ep", seq_len=32, experts=8, k_top=2,
                              capacity=0, tp=2, microbatches=2)
    out = app.run(cfg, args, MetricsLogger(None, verbose=False))
    losses = out["losses"]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
