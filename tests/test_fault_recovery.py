"""Fault injection + recovery — the reference's failure-handling milestone.

The reference lineage: heartbeats through the mailbox, a master that
detects a dead node, and restart-from-checkpoint (SURVEY.md §2 "Heartbeat /
failure detection", §3.5, §5.3). The drill here is the real thing, not a
mock: N processes over loopback, one killed abruptly (``os._exit`` — no
close, no flush) mid-run; survivors' SSP gate stalls on the corpse's clock,
the HeartbeatMonitor times it out, the gate turns the stall into a
PeerFailureError (exit 42, the "I detected a failure" code); the driver
then relaunches the full job with ``--resume`` and everyone restores the
latest checkpoint and finishes — all-or-nothing restart at fixed size,
exactly the reference's recovery semantics (SURVEY.md §7.4.5).
"""

from __future__ import annotations

import os
import sys

import pytest

from minips_tpu import launch

APP = "minips_tpu.apps.ssp_lr_example"
SHARDED_APP = "minips_tpu.apps.sharded_ps_example"


def _run(n: int, extra: list[str], timeout: float = 240.0,
         kill_on_failure: bool = False, app: str = APP):
    """Launch n workers of ``app``; return (rc, per-rank JSON events).
    kill_on_failure=False: survivors must detect the death THEMSELVES via
    heartbeat — the launcher must not mercy-kill them first."""
    return launch.run_local_job_raw(
        n, [sys.executable, "-m", app] + extra, base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=timeout, kill_on_failure=kill_on_failure)


def test_kill_detect_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    base = ["--iters", "40", "--mode", "ssp", "--staleness", "2",
            "--checkpoint-dir", ckpt, "--checkpoint-every", "10"]

    # --- phase 1: rank 2 dies abruptly at step 15 -----------------------
    rc, events = _run(3, base + ["--kill-at", "15", "--kill-rank", "2"])
    assert rc != 0  # the job failed, as it must
    survivors = [ev[-1] for r, ev in enumerate(events) if r != 2 and ev]
    assert len(survivors) == 2, events
    for ev in survivors:
        assert ev["event"] == "peer_failure", events
        assert 2 in ev["dead"]
    # a checkpoint exists from before the crash
    steps = [d for d in os.listdir(ckpt) if d.startswith("step_")]
    assert steps, "no checkpoint written before the kill"

    # --- phase 2: relaunch everyone with --resume ------------------------
    rc, events = _run(3, base + ["--resume"])
    assert rc == 0, events
    dones = [ev[-1] for ev in events]
    for d in dones:
        assert d["event"] == "done", events
        assert d["clock"] == 40  # resumed at 10, finished the run
        assert d["max_skew_seen"] <= 3
    sums = [d["param_sum"] for d in dones]
    norms = [d["param_norm"] for d in dones]
    assert max(sums) - min(sums) < 1e-4
    assert max(norms) - min(norms) < 1e-4


@pytest.mark.slow
def test_clean_job_leaves_no_failure_events(tmp_path):
    """Control: same config, no kill — everyone reports done, nobody
    reports peer_failure, and checkpoints accumulate."""
    ckpt = str(tmp_path / "ckpt")
    rc, events = _run(3, ["--iters", "20", "--mode", "bsp",
                          "--checkpoint-dir", ckpt,
                          "--checkpoint-every", "10"])
    assert rc == 0, events
    for ev in events:
        assert ev[-1]["event"] == "done"
        assert all(e["event"] != "peer_failure" for e in ev)
    assert len([d for d in os.listdir(ckpt) if d.startswith("step_")]) == 2


@pytest.mark.slow
def test_sharded_ps_kill_detect_resume(tmp_path):
    """The SAME drill on the key-range-sharded PS: every rank dumps ITS
    OWN shard (per-rank checkpoint dirs); on relaunch the ranks negotiate
    the newest step all of them hold, restore their shards there, and
    finish with replica agreement — the reference's per-server Dump/Load
    recovery (SURVEY.md §3.5) on the round-2 server topology."""
    ckpt = str(tmp_path / "spck")
    base = ["--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", "40", "--batch", "128",
            "--checkpoint-dir", ckpt, "--checkpoint-every", "5"]

    # --- phase 1: rank 2 dies at step 12 (checkpoints exist at 5, 10) ---
    rc, events = _run(3, base + ["--kill-at", "12", "--kill-rank", "2"],
                      app=SHARDED_APP)
    assert rc != 0
    survivors = [ev[-1] for r, ev in enumerate(events) if r != 2 and ev]
    assert len(survivors) == 2, events
    for ev in survivors:
        assert ev["event"] == "peer_failure", events
        assert 2 in ev["dead"]
    for r in range(3):
        steps = os.listdir(os.path.join(ckpt, f"rank{r}"))
        assert "step_0000000010" in steps, (r, steps)

    # --- phase 2: relaunch; negotiate the common step; resume ------------
    rc, events = _run(3, base, app=SHARDED_APP)
    assert rc == 0, events
    dones = [ev[-1] for ev in events]
    for d in dones:
        assert d["event"] == "done", events
        assert d["resumed_from"] == 10, d
        assert d["clock"] == 40
        assert d["max_skew_seen"] <= 3
    sums = [d["param_sum"] for d in dones]
    assert max(sums) - min(sums) < 1e-5, sums


@pytest.mark.slow
def test_wide_deep_multiproc_kill_detect_resume(tmp_path):
    """The recovery protocol on the FLAGSHIP sparse workload: partitioned
    wide/emb embedding tables + dense-range deep tower all restore from
    rank-scoped shard checkpoints; survivors detect the corpse, the
    relaunch negotiates the common step, and the finished run agrees
    across replicas with a better-than-chance AUC."""
    ckpt = str(tmp_path / "wdck")
    base = ["--exec", "multiproc", "--consistency", "ssp",
            "--staleness", "2", "--num_slots", "16384",
            "--num_iters", "30", "--batch_size", "256",
            "--checkpoint_dir", ckpt, "--checkpoint_every", "5"]
    app = "minips_tpu.apps.wide_deep_example"

    rc, events = _run(3, base + ["--kill-at", "12", "--kill-rank", "2"],
                      app=app)
    assert rc != 0
    survivors = [ev[-1] for r, ev in enumerate(events) if r != 2 and ev]
    assert len(survivors) == 2 and all(
        ev["event"] == "peer_failure" and 2 in ev["dead"]
        for ev in survivors), events

    rc, events = _run(3, base, app=app)
    assert rc == 0, events
    dones = [ev[-1] for ev in events]
    for d in dones:
        assert d["event"] == "done", events
        assert d["resumed_from"] == 10, d
        assert d["clock"] == 30
        assert d["auc"] is None or d["auc"] > 0.6
    fps = [d["param_fingerprint"] for d in dones]
    assert max(fps) - min(fps) < 1e-4, fps


def test_mf_multiproc_kill_detect_resume(tmp_path):
    """The negotiated shard resume on MF's exact-per-id factor tables
    (word2vec's in/out tables are structurally identical — two pure
    ShardedTables + the trainer clock — so this drill covers that shape
    once for both apps)."""
    ckpt = str(tmp_path / "mfck")
    base = ["--exec", "multiproc", "--consistency", "ssp",
            "--staleness", "2", "--num_iters", "30", "--batch_size", "256",
            "--checkpoint_dir", ckpt, "--checkpoint_every", "5"]
    app = "minips_tpu.apps.mf_example"

    rc, events = _run(3, base + ["--kill-at", "12", "--kill-rank", "1"],
                      app=app)
    assert rc != 0
    survivors = [ev[-1] for r, ev in enumerate(events) if r != 1 and ev]
    assert len(survivors) == 2 and all(
        ev["event"] == "peer_failure" and 1 in ev["dead"]
        for ev in survivors), events

    rc, events = _run(3, base, app=app)
    assert rc == 0, events
    dones = [ev[-1] for ev in events]
    for d in dones:
        assert d["event"] == "done", events
        assert d["resumed_from"] == 10, d
        assert d["clock"] == 30
        assert d["rmse"] is not None and d["rmse"] < 1.5
    fps = [d["param_fingerprint"] for d in dones]
    assert max(fps) - min(fps) < 1e-4, fps
