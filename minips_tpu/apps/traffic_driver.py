"""Open-loop traffic driver — serving load that arrives whether or not
the fleet keeps up, measured from SCHEDULED arrival time.

Every serving drill before this one was CLOSED-LOOP: N worker threads
pull, think, pull again. A closed loop self-throttles — when the fleet
slows down the workers slow down WITH it, so offered load collapses to
match capacity and the recorded "latency" is service time only. That
under-reports the tail exactly when it matters (coordinated omission:
the requests that WOULD have queued were never issued). Production
traffic does not think; it arrives on its own schedule.

This driver replays a FIXED, fully precomputed arrival schedule against
``pull_serving`` and records, per request, the time from its scheduled
arrival to completion — queueing delay included, whether the request
queued in the kernel, the bus, or this driver's own dispatcher backlog.
The schedule is deterministic given the spec (arrivals by integrating
the rate curve, user draws from one seeded zipf stream), so two runs of
the same spec offer bit-identical load.

The rate curve models a recsys day in seconds: a base rate, an optional
diurnal ramp (raised-cosine between 1x and ``ramp``x over ``period``
seconds), and an optional flash crowd (``crowd=<at>+<dur>x<mult>``: at
second ``at``, for ``dur`` seconds, multiply by ``mult``). Users are
drawn zipf(``alpha``) over a ``users``-sized population (the "million
user" knob); each request reads that user's ``batch`` pseudo-random
embedding rows (a Knuth-hash fan-out, so hot users pin hot row sets).

Spec grammar (``MINIPS_TRAFFIC``): ``""``/``"0"`` = off, ``"1"`` =
defaults, else a k=v comma list::

    rate=500,users=1000000,alpha=1.1,batch=8,conc=4,ramp=2,period=10,
    crowd=4+2x8,seed=7

``rate=0`` is ARMED-IDLE: the schedule is empty, the dispatchers start
and issue nothing — bitwise-equal to off by construction (the
TRAFFIC-IDLE drill pins it).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

import numpy as np

from minips_tpu.obs.hist import Log2Histogram, summarize_counts

__all__ = ["TrafficConfig", "TrafficDriver", "maybe_config"]

_KNUTH = 2654435761  # multiplicative-hash user->rows fan-out
_MAX_ARRIVALS = 2_000_000  # schedule memory guard (~16MB of float64s)


class TrafficConfig:
    """Parsed ``MINIPS_TRAFFIC`` knobs."""

    def __init__(self, *, rate: float = 200.0, users: int = 1_000_000,
                 alpha: float = 1.1, batch: int = 8, conc: int = 4,
                 ramp: float = 1.0, period: float = 10.0,
                 crowd_at: float = 0.0, crowd_for: float = 0.0,
                 crowd_x: float = 1.0, seed: int = 0):
        # inverted comparisons so NaN fails validation instead of
        # slipping through (NaN < x is False for every x)
        if not (rate >= 0):
            raise ValueError("MINIPS_TRAFFIC: rate must be >= 0 req/s "
                             "(0 = armed-idle)")
        if users < 1:
            raise ValueError("MINIPS_TRAFFIC: users must be >= 1")
        if not (alpha > 1.0):
            raise ValueError(
                "MINIPS_TRAFFIC: alpha must be > 1 (zipf exponent)")
        if batch < 1:
            raise ValueError("MINIPS_TRAFFIC: batch must be >= 1 rows")
        if conc < 1:
            raise ValueError(
                "MINIPS_TRAFFIC: conc must be >= 1 dispatchers")
        if not (ramp >= 1.0):
            raise ValueError(
                "MINIPS_TRAFFIC: ramp is a peak multiplier, must be "
                ">= 1 (1 = flat)")
        if not (period > 0):
            raise ValueError("MINIPS_TRAFFIC: period must be > 0 s")
        if not (crowd_at >= 0 and crowd_for >= 0):
            raise ValueError(
                "MINIPS_TRAFFIC: crowd at/duration must be >= 0 s")
        if not (crowd_x >= 1.0):
            raise ValueError(
                "MINIPS_TRAFFIC: crowd multiplier must be >= 1")
        self.rate = float(rate)
        self.users = int(users)
        self.alpha = float(alpha)
        self.batch = int(batch)
        self.conc = int(conc)
        self.ramp = float(ramp)
        self.period = float(period)
        self.crowd_at = float(crowd_at)
        self.crowd_for = float(crowd_for)
        self.crowd_x = float(crowd_x)
        self.seed = int(seed)

    _CASTS = {"rate": float, "users": int, "alpha": float,
              "batch": int, "conc": int, "ramp": float,
              "period": float, "seed": int}

    @classmethod
    def parse(cls, spec: str) -> "Optional[TrafficConfig]":
        """None = the layer is OFF (``""``/``"0"``); config otherwise."""
        spec = (spec or "").strip()
        if spec in ("", "0"):
            return None
        if spec in ("1", "on", "true"):
            return cls()
        kw: dict = {}
        for item in filter(None, (e.strip() for e in spec.split(","))):
            if "=" not in item:
                raise ValueError(
                    f"MINIPS_TRAFFIC: expected k=v, got {item!r}")
            k, _, v = item.partition("=")
            k = k.strip()
            if k == "crowd":
                kw.update(cls._parse_crowd(v.strip()))
                continue
            cast = cls._CASTS.get(k)
            if cast is None:
                raise ValueError(
                    f"MINIPS_TRAFFIC: unknown knob {k!r}")
            try:
                kw[k] = cast(v)
            except ValueError as e:
                raise ValueError(
                    f"MINIPS_TRAFFIC: bad value for {k}: {v!r}") from e
        return cls(**kw)

    @staticmethod
    def _parse_crowd(v: str) -> dict:
        """``<at>+<dur>x<mult>`` -> crowd_at/crowd_for/crowd_x."""
        at_s, plus, rest = v.partition("+")
        dur_s, x, mult_s = rest.partition("x")
        if not plus or not x:
            raise ValueError(
                f"MINIPS_TRAFFIC: crowd wants <at>+<dur>x<mult> "
                f"(e.g. 4+2x8), got {v!r}")
        try:
            return {"crowd_at": float(at_s), "crowd_for": float(dur_s),
                    "crowd_x": float(mult_s)}
        except ValueError as e:
            raise ValueError(
                f"MINIPS_TRAFFIC: bad crowd value {v!r}") from e

    def signature(self) -> tuple:
        return (self.rate, self.users, self.alpha, self.batch,
                self.conc, self.ramp, self.period, self.crowd_at,
                self.crowd_for, self.crowd_x, self.seed)

    # ------------------------------------------------------- rate curve
    def rate_at(self, t: float) -> float:
        """Offered req/s at second ``t`` of the run (deterministic)."""
        r = self.rate
        if self.ramp > 1.0:
            phase = 0.5 * (1.0 - np.cos(2.0 * np.pi * t / self.period))
            r *= 1.0 + (self.ramp - 1.0) * phase
        if self.crowd_for > 0 and \
                self.crowd_at <= t < self.crowd_at + self.crowd_for:
            r *= self.crowd_x
        return r


def maybe_config(spec: Optional[str] = None
                 ) -> "Optional[TrafficConfig]":
    """Explicit spec wins, else ``$MINIPS_TRAFFIC``; None when off."""
    if spec is None:
        spec = os.environ.get("MINIPS_TRAFFIC", "")
    return TrafficConfig.parse(spec)


class TrafficDriver:
    """Replays one precomputed schedule against a pull callable.

    ``pull_fn(keys)`` is ``table.pull_serving`` (or any compatible
    read); ``rows`` bounds the key space. The schedule covers
    ``duration_s`` seconds; :meth:`start` launches ``conc`` dispatcher
    threads that sleep until each arrival's scheduled time and issue it
    — a dispatcher that falls behind issues immediately, and the
    recorded latency (completion minus SCHEDULED arrival) keeps the
    queueing delay either way."""

    def __init__(self, cfg: TrafficConfig,
                 pull_fn: Callable, rows: int, duration_s: float):
        if rows < 1:
            raise ValueError("traffic driver needs rows >= 1")
        self.cfg = cfg
        self._pull = pull_fn
        self._rows = int(rows)
        self.duration_s = float(duration_s)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._next = 0
        self._threads: list = []
        self._t0: Optional[float] = None
        self.hist_sched = Log2Histogram()  # scheduled-arrival -> done
        self.hist_svc = Log2Histogram()    # issue -> done (service)
        self.counters = {"requests": 0, "rows": 0, "errors": 0,
                         "late_issues": 0}
        self._first_error: Optional[str] = None
        self._build_schedule()

    # ---------------------------------------------------------- schedule
    def _build_schedule(self) -> None:
        cfg = self.cfg
        arrivals = []
        t = 0.0
        while t < self.duration_s:
            r = cfg.rate_at(t)
            if r <= 0:
                break
            t += 1.0 / r
            if t >= self.duration_s:
                break
            arrivals.append(t)
            if len(arrivals) > _MAX_ARRIVALS:
                raise ValueError(
                    "MINIPS_TRAFFIC: schedule exceeds "
                    f"{_MAX_ARRIVALS} arrivals — lower rate/duration")
        self.arrivals = np.asarray(arrivals, dtype=np.float64)
        n = len(self.arrivals)
        rng = np.random.default_rng(cfg.seed)
        z = rng.zipf(cfg.alpha, size=n) if n else \
            np.zeros(0, dtype=np.int64)
        self._users = ((z.astype(np.int64) - 1) % cfg.users)

    def _keys_for(self, i: int) -> np.ndarray:
        u = int(self._users[i])
        j = np.arange(self.cfg.batch, dtype=np.int64)
        return (u * _KNUTH + j * 40503) % self._rows

    # -------------------------------------------------------- dispatch
    def _worker(self) -> None:
        n = len(self.arrivals)
        while not self._stop.is_set():
            with self._lock:
                i = self._next
                if i >= n:
                    return
                self._next = i + 1
            ta = self._t0 + float(self.arrivals[i])
            delay = ta - time.perf_counter()
            if delay > 0:
                if self._stop.wait(delay):
                    return
            else:
                with self._lock:
                    self.counters["late_issues"] += 1
            keys = self._keys_for(i)
            t1 = time.perf_counter()
            try:
                self._pull(keys)
            except Exception as e:  # noqa: BLE001 — driver survives
                with self._lock:
                    self.counters["errors"] += 1
                    if self._first_error is None:
                        self._first_error = repr(e)[:200]
                continue
            t2 = time.perf_counter()
            self.hist_sched.record_s(t2 - ta)
            self.hist_svc.record_s(t2 - t1)
            with self._lock:
                self.counters["requests"] += 1
                self.counters["rows"] += self.cfg.batch

    def start(self) -> None:
        self._t0 = time.perf_counter()
        for k in range(self.cfg.conc):
            th = threading.Thread(target=self._worker,
                                  name=f"traffic-{k}", daemon=True)
            th.start()
            self._threads.append(th)

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=30.0)

    # ------------------------------------------------------------ record
    def record(self) -> dict:
        with self._lock:
            ctr = dict(self.counters)
            issued = ctr["requests"] + ctr["errors"]
        return {"open_loop": 1, "rate": self.cfg.rate,
                "users": self.cfg.users, "alpha": self.cfg.alpha,
                "batch": self.cfg.batch, "conc": self.cfg.conc,
                "ramp": self.cfg.ramp, "crowd_x": self.cfg.crowd_x,
                "seed": self.cfg.seed,
                "scheduled": int(len(self.arrivals)),
                "unissued": int(len(self.arrivals)) - issued,
                # the honest number: scheduled arrival -> completion
                "sched_ms": summarize_counts(self.hist_sched.snapshot()),
                # service time alone, for the closed-vs-open comparison
                "svc_ms": summarize_counts(self.hist_svc.snapshot()),
                **ctr,
                "first_error": self._first_error}
