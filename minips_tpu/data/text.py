"""Byte-level text loader for the LM family.

The sandbox has no network, so there is no tokenizer download path — any
local text/binary file becomes LM training data at the byte level
(vocab 256), the honest equivalent of the reference's "read the local
shard" loaders (SURVEY.md §2 "Data loading"). Windows are sampled with a
stride so a small file still yields many distinct sequences.
"""

from __future__ import annotations

import numpy as np


def read_bytes(path: str) -> np.ndarray:
    """File -> uint8 token stream."""
    with open(path, "rb") as f:
        return np.frombuffer(f.read(), dtype=np.uint8)


def byte_windows(tokens: np.ndarray, seq_len: int, *,
                 max_windows: int | None = None,
                 stride: int | None = None) -> dict:
    """Token stream -> {"tokens": [n, seq_len+1] int32} next-token windows.

    ``stride`` defaults to seq_len // 2 (half-overlapping windows); the
    stream must hold at least one full window.
    """
    need = seq_len + 1
    if len(tokens) < need:
        raise ValueError(f"need at least {need} tokens, file has "
                         f"{len(tokens)}")
    stride = stride or max(seq_len // 2, 1)
    starts = np.arange(0, len(tokens) - need + 1, stride)
    if max_windows is not None:
        starts = starts[:max_windows]
    idx = starts[:, None] + np.arange(need)[None, :]
    return {"tokens": tokens[idx].astype(np.int32)}


def read_lm_file(path: str, seq_len: int, *,
                 max_windows: int | None = None) -> dict:
    """Convenience: file path -> LM windows dict."""
    return byte_windows(read_bytes(path), seq_len, max_windows=max_windows)


def word_tokens(path: str, vocab_size: int = 10_000,
                min_count: int = 1) -> tuple[np.ndarray, np.ndarray]:
    """Whitespace-tokenize a text file into word ids for word2vec.

    Classic w2v preprocessing (the reference's enwiki pipeline shape):
    keep the ``vocab_size`` most frequent words with count >= min_count,
    DROP out-of-vocab tokens from the stream (w2v convention — an UNK
    bucket would dominate the unigram table), and return
    ``(ids [n] int32, counts [vocab] int64)`` where id ordering is by
    descending frequency (id 0 = most frequent; ties broken
    lexicographically for determinism). ``counts`` feeds UnigramSampler
    directly.

    Two streaming line passes (count, then map) so memory stays near the
    KEPT token stream, not several times the corpus size — this is the
    enwiki-scale path."""
    from collections import Counter

    counter: Counter = Counter()
    with open(path, "r", errors="replace") as f:
        for line in f:
            counter.update(line.split())
    if not counter:
        raise ValueError(f"{path}: no tokens")
    ranked = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
    kept = [(w, c) for w, c in ranked[:vocab_size] if c >= min_count]
    if not kept:
        raise ValueError(f"{path}: vocab filter dropped every token")
    word_to_id = {w: i for i, (w, _) in enumerate(kept)}
    chunks = []
    with open(path, "r", errors="replace") as f:
        for line in f:
            mapped = [word_to_id[w] for w in line.split()
                      if w in word_to_id]
            if mapped:
                chunks.append(np.asarray(mapped, np.int32))
    ids = np.concatenate(chunks)
    return ids, np.asarray([c for _, c in kept], np.int64)
