"""The sparse top-k + error-feedback push wire (ISSUE 10 tentpole).

Codec units (top-k mass selection, blockwise 4/8-bit quantization),
ResidualStore semantics (fold/retain/age, overflow never drops mass),
wire integration (decode at the owner, gated-off path byte-identical to
the seed frames), the staleness-bounded age flush, the EXACT residual
flush across a rebalance epoch fence (bitwise vs an uncompressed
oracle), and the convergence drills: lr + mlp training through the
compressed wire pins the loss trajectory to the dense wire within
tolerance — the SparCML claim this whole subsystem rides on.
"""

import os
import threading

import numpy as np
import pytest

from minips_tpu.ops.quantized_comm import (HOST_BLOCK,
                                           blockwise_stream_bytes,
                                           dequantize_blockwise,
                                           quantize_blockwise, topk_rows)
from minips_tpu.train.sharded_ps import (ResidualStore, ShardedPSTrainer,
                                         ShardedTable)


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


# ------------------------------------------------------------ codec units
def test_topk_rows_selects_mass_not_touch_set():
    g = np.zeros((10, 4), np.float32)
    g[3] = 100.0  # one row carries ~all the mass
    g[7] = 0.01
    sel = topk_rows(g, mass=0.9, frac_cap=0.5)
    assert sel.tolist() == [3]
    # flat mass: selection runs into the cap
    flat = np.ones((10, 4), np.float32)
    sel = topk_rows(flat, mass=0.99, frac_cap=0.5)
    assert sel.size == 5
    assert np.array_equal(sel, np.sort(sel))  # sorted, deterministic


def test_topk_rows_edge_cases():
    assert topk_rows(np.empty((0, 4), np.float32)).size == 0
    # all-zero gradient still selects one row (a frame must ship)
    assert topk_rows(np.zeros((5, 4), np.float32)).size == 1
    # mass=1.0 selects everything up to the cap
    g = np.random.default_rng(0).normal(size=(8, 2)).astype(np.float32)
    assert topk_rows(g, mass=1.0, frac_cap=1.0).size == 8


@pytest.mark.parametrize("bits,tol", [(8, 1 / 127), (4, 1 / 7)])
def test_blockwise_roundtrip_error_bounded(bits, tol):
    rng = np.random.default_rng(1)
    g = rng.normal(size=(37, 8)).astype(np.float32)  # ragged last block
    codes, scales = quantize_blockwise(g, bits, block=64)
    back = dequantize_blockwise(codes, scales, 37, 8, bits, block=64)
    # nearest rounding: error <= scale/2 per element, scale = absmax/L
    grid = np.concatenate([g.reshape(-1),
                           np.zeros(64 * 5 - 37 * 8, np.float32)]
                          ).reshape(-1, 64)
    bound = (np.abs(grid).max(axis=1) * tol / 2 + 1e-7)[:, None]
    err = np.abs((back - g).reshape(-1))
    assert (err.reshape(-1) <= np.repeat(bound, 64)[: 37 * 8]).all()
    cb, sb = blockwise_stream_bytes(37, 8, bits, 64)
    assert codes.nbytes == cb and scales.nbytes == sb


def test_blockwise_exact_on_integer_grid():
    """Integer values whose block absmax equals the code range quantize
    EXACTLY (scale 1.0) — the grid the bitwise fence oracle rides."""
    rng = np.random.default_rng(2)
    g = rng.integers(-7, 8, size=(16, 8)).astype(np.float32)
    g.reshape(-1, 8)[:, 0] = 7.0  # every block's absmax = 7
    codes, scales = quantize_blockwise(g, 4, block=8)
    assert (scales == 1.0).all()
    back = dequantize_blockwise(codes, scales, 16, 8, 4, block=8)
    np.testing.assert_array_equal(back, g)
    # stochastic rounding is a no-op on exactly-representable values
    codes2, _ = quantize_blockwise(g, 4, block=8,
                                   rng=np.random.default_rng(3))
    np.testing.assert_array_equal(codes2, codes)


def test_blockwise_stochastic_rounding_is_unbiased():
    g = np.full((4, 8), 0.3, np.float32)
    g[:, 0] = 7.0  # scale 1.0 at 4 bits, block 8
    draws = [float(dequantize_blockwise(
        *quantize_blockwise(g, 4, block=8,
                            rng=np.random.default_rng(s)),
        4, 8, 4, block=8)[:, 1:].mean()) for s in range(300)]
    # 300 seeds x 28 positions: sigma of the grand mean ~ 0.005
    assert abs(float(np.mean(draws)) - 0.3) < 0.02


def test_blockwise_4bit_packs_two_codes_per_byte():
    g = np.ones((4, 8), np.float32)
    codes8, _ = quantize_blockwise(g, 8)
    codes4, _ = quantize_blockwise(g, 4)
    assert codes8.nbytes == 32 and codes4.nbytes == 16


# ------------------------------------------------------ residual store
def test_residual_store_fold_retain_birth_min():
    rs = ResidualStore(2)
    k = np.array([3, 7], np.int64)
    rows = np.ones((2, 2), np.float32)
    ov = rs.retain(k, rows, np.array([5, 9], np.int64))
    assert ov[0].size == 0
    g = np.full((3, 2), 0.5, np.float32)
    births = rs.fold(np.array([3, 4, 7], np.int64), g)
    # stored residuals joined the gradient; absent key untouched
    np.testing.assert_array_equal(g[0], [1.5, 1.5])
    np.testing.assert_array_equal(g[1], [0.5, 0.5])
    assert births.tolist()[0] == 5 and births.tolist()[2] == 9
    assert births[1] == ResidualStore.INF
    assert len(rs) == 0  # fold releases the entries


def test_residual_store_take_aged_and_all():
    rs = ResidualStore(1)
    rs.retain(np.array([1, 2, 3], np.int64),
              np.ones((3, 1), np.float32),
              np.array([0, 5, 10], np.int64))
    k, r = rs.take(5)  # aged: birth <= 5
    assert k.tolist() == [1, 2] and len(rs) == 1
    k, r = rs.take()
    assert k.tolist() == [3] and len(rs) == 0


def test_residual_store_zero_rows_and_overflow():
    rs = ResidualStore(1, cap_bytes=1)  # cap_rows floors at 1024
    z = np.zeros((2, 1), np.float32)
    rs.retain(np.array([1, 2], np.int64), z, np.zeros(2, np.int64))
    assert len(rs) == 0  # nothing to repay: not stored
    n = rs.cap_rows + 5
    keys = np.arange(n, dtype=np.int64)
    ovk, ovr = rs.retain(keys, np.ones((n, 1), np.float32),
                         np.zeros(n, np.int64))
    # overflow RETURNED (caller ships it dense), never dropped
    assert ovk.size == 5 and rs.stats()["flushed_overflow"] == 5
    assert len(rs) == rs.cap_rows


# ------------------------------------------------------ wire validation
def test_push_comm_validation_and_env_resolution(monkeypatch):
    with pytest.raises(ValueError, match="push_comm"):
        ShardedTable("t", 16, 2, None, 0, 1, push_comm="int4")
    with pytest.raises(ValueError, match="push_dedup"):
        ShardedTable("t", 16, 2, None, 0, 1, push_comm="topk8",
                     push_dedup=False)
    monkeypatch.setenv("MINIPS_PUSH_COMM", "topk4")
    t = ShardedTable("t", 16, 2, None, 0, 1)
    assert t.push_comm == "topk4" and t._ef is not None
    # explicit wins over env; empty env means default
    t2 = ShardedTable("t", 16, 2, None, 0, 1, push_comm="float32")
    assert t2.push_comm == "float32" and t2._ef is None
    monkeypatch.setenv("MINIPS_PUSH_COMM", "")
    t3 = ShardedTable("t", 16, 2, None, 0, 1)
    assert t3.push_comm == "float32"


def test_gated_off_f32_frames_are_seed_bytes():
    """The bitwise A/B half of the acceptance: with push_comm left at
    the default the wire frames are BYTE-IDENTICAL to the seed layout
    (int64 keys + f32 rows, head {"n", "comm"} + epoch/config stamps)
    — the compressed pipeline must be invisible when gated off."""
    sent = []

    class _Bus:
        def on(self, *_a):
            pass

        def send(self, dest, kind, head, blob=None):
            sent.append((dest, kind, head, bytes(blob)))

    t = ShardedTable("t", 64, 2, _Bus(), 0, 2, updater="sgd", lr=0.1)
    assert t._ef is None
    keys = np.array([40, 33, 47], np.int64)  # rank 1's range
    g = np.random.default_rng(0).normal(size=(3, 2)).astype(np.float32)
    t.push(keys, g)
    (dest, kind, head, blob), = sent
    assert (dest, kind) == (1, "psP:t")
    assert head == {"n": 3, "comm": "float32", "ws": 2, "nr": 64,
                    "dm": 2, "rb": 0}
    uniq = np.sort(keys)
    order = np.argsort(keys, kind="stable")
    assert blob == uniq.tobytes() + g[order].tobytes() or \
        blob == keys.tobytes() + g.tobytes()


def test_topk_push_decodes_at_owner_within_tolerance():
    """One compressed push: the owner's rows move by the DECODED top-k
    mass; the pusher's residual holds exactly the remainder."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd",
                      lr=1.0, push_comm="topk8", topk_mass=0.5,
                      topk_cap=0.5, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd",
                      lr=1.0, push_comm="topk8", pull_timeout=10.0)
    try:
        keys = np.arange(32, 40, dtype=np.int64)  # rank 1's shard
        g = np.ones((8, 2), np.float32)
        g[0] = 100.0  # the mass row
        t0.push(keys, g)
        import time
        deadline = time.monotonic() + 5
        while not t1._w[:8].any() and time.monotonic() < deadline:
            time.sleep(0.01)
        # the mass row landed (quantized), the tail is in the residual
        assert abs(float(t1._w[0, 0]) + 100.0) < 1.0
        ef = t0.ef_stats()
        assert ef["retained_rows"] >= 7
        assert len(t0._ef) >= 7
        # the flush delivers the remainder exactly (f32 fence flush)
        t0.residual_flush()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                not (np.abs(t1._w[:8] + (g * 1.0)) < 0.5).all():
            time.sleep(0.01)
        np.testing.assert_allclose(t1._w[:8], -g, atol=0.5)
        assert len(t0._ef) == 0
    finally:
        for b in buses:
            b.close()


def test_fold_repays_quantization_error():
    """Two pushes of the same keys: the second fold brings the first
    push's quantization error back into the gradient, so the owner's
    total converges on the exact sum — E2E error feedback."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd",
                      lr=1.0, push_comm="topk8", topk_mass=1.0,
                      topk_cap=1.0, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd",
                      lr=1.0, push_comm="topk8", pull_timeout=10.0)
    try:
        rng = np.random.default_rng(7)
        keys = np.arange(32, 48, dtype=np.int64)
        total = np.zeros((16, 2), np.float32)
        for _ in range(20):
            g = rng.normal(size=(16, 2)).astype(np.float32)
            total += g
            t0.push(keys, g)
        t0.residual_flush()  # exact tail
        import time
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if np.allclose(t1._w[:16], -total, atol=1e-2):
                break
            time.sleep(0.02)
        np.testing.assert_allclose(t1._w[:16], -total, atol=1e-2)
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------- staleness age flush
def test_age_flush_bounds_residual_life_under_ssp():
    """SSP(1): a residual born at clock c must be on the wire by the
    boundary where clock - s reaches c — the RowCache stamp rule
    mirrored onto the write path. ASP never age-flushes."""
    t = ShardedTable("t", 64, 2, None, 0, 1, updater="sgd",
                     push_comm="topk8")

    class _Cons:
        clock = 0
        staleness = 1

    t._cons = _Cons()
    t._ef.retain(np.array([1], np.int64), np.ones((1, 2), np.float32),
                 np.array([0], np.int64))
    _Cons.clock = 0
    assert t.residual_flush(aged_only=True) == 0  # bound not reached
    _Cons.clock = 1
    assert t.residual_flush(aged_only=True) == 1  # birth <= 1 - 1
    assert len(t._ef) == 0
    # ASP: no bound, no age flush ever
    _Cons.staleness = float("inf")
    t._ef.retain(np.array([2], np.int64), np.ones((1, 2), np.float32),
                 np.array([0], np.int64))
    _Cons.clock = 99
    assert t.residual_flush(aged_only=True) == 0
    assert len(t._ef) == 1


def test_aged_flush_rides_the_4bit_stream():
    """The aged flush ships the whole aged set on the topk4 index+code
    stream (unbiased stochastic rounding, error dropped — the int8
    wire's contract), NOT f32: an f32 age flush measurably cost more
    than the int8 wire the tentpole must beat."""
    sent = []

    class _Bus:
        def on(self, *_a):
            pass

        def send(self, dest, kind, head, blob=None):
            sent.append((kind, head))

    t = ShardedTable("t", 64, 2, _Bus(), 0, 2, updater="sgd",
                     push_comm="topk8")

    class _Cons:
        clock = 5
        staleness = 1

    t._cons = _Cons()
    t._ef.retain(np.array([40], np.int64),  # rank 1's range: wire flush
                 np.ones((1, 2), np.float32), np.array([0], np.int64))
    assert t.residual_flush(aged_only=True) == 1
    (kind, head), = sent
    assert kind == "psP:t" and head["comm"] == "topk4"
    assert head["kw"] == 2  # 64-row key space: u16 index stream
    sent.clear()
    # fence flushes stay EXACT f32 (the bitwise oracle contract)
    t._ef.retain(np.array([41], np.int64),
                 np.ones((1, 2), np.float32), np.array([0], np.int64))
    t.residual_flush()
    (kind, head), = sent
    assert head["comm"] == "float32"


# ------------------------------------- the epoch-fence bitwise oracle
def test_residual_flushed_across_rebalance_fence_bitwise():
    """THE acceptance drill: push on an exact-arithmetic grid (integer
    grads, per-block absmax pinned to the 4-bit code range, lr a power
    of two), adopt a rebalance epoch — the fence flush must deliver
    every retained row BEFORE the migration ships, so the assembled
    table is BITWISE equal to an uncompressed oracle."""
    from tests.test_rebalance import _StubRB

    from minips_tpu.balance.rebalancer import RebalanceConfig

    buses = _mk_buses(2)
    mk = lambda r, bus: ShardedTable(  # noqa: E731
        "t", 64, 2, bus, r, 2, updater="sgd", lr=0.125,
        push_comm="topk4", topk_mass=0.5, topk_cap=0.25, topk_block=8,
        pull_timeout=10.0)
    t0, t1 = mk(0, buses[0]), mk(1, buses[1])
    rb = _StubRB()
    rb.tables = [t0, t1]
    cfg = RebalanceConfig.parse("block=4")
    for t in (t0, t1):
        t.attach_rebalancer(rb, cfg)
    oracle = ShardedTable("o", 64, 2, None, 0, 1, updater="sgd",
                          lr=0.125)
    try:
        rng = np.random.default_rng(11)
        keys = np.arange(32, 48, dtype=np.int64)  # rank 1's shard
        g = rng.integers(-7, 8, size=(16, 2)).astype(np.float32)
        g.reshape(-1, 8)[:, 0] = 7.0  # every codec block absmax = 7:
        # the 4-bit stream is EXACT, so selected rows ship whole and
        # retained rows are whole-row exact — nothing is split
        t0.push(keys, g)
        oracle.push(keys, g)
        assert len(t0._ef) > 0  # unselected mass retained
        import time
        time.sleep(0.3)  # let the compressed frame land at t1
        # the epoch fence: block 8 (keys 32..35) migrates 1 -> 0; t0's
        # adoption flushes its WHOLE residual store (f32, old table,
        # ahead of its rbA) before anything ships
        t0.adopt_table(1, {8: 0})
        t1.adopt_table(1, {8: 0})
        deadline = time.monotonic() + 10
        while not (t0.rebalance_settled() and t1.rebalance_settled()):
            assert time.monotonic() < deadline, "migration never settled"
            time.sleep(0.01)
        assert len(t0._ef) == 0  # provably flushed at the fence
        assert t0.ef_stats()["flushed_fence"] > 0
        got = np.empty((64, 2), np.float32)
        got[:32] = t0._w[:32]
        got[32:36] = t0._xtra[8]["w"]  # the migrated block
        got[36:] = t1._w[4:]
        want = oracle.pull_all()
        np.testing.assert_array_equal(got, want)  # BITWISE
    finally:
        for b in buses:
            b.close()


# ---------------------------------------------------- convergence drills
def _train_lr(push_comm, iters=30, staleness=1):
    """2-rank threads-as-nodes logistic regression through the sharded
    PS (dim-1 rows, the lr-example shape): returns the loss curve."""
    buses = _mk_buses(2)
    dim_feat = 32
    tables = [ShardedTable("w", dim_feat, 1, buses[i], i, 2,
                           updater="sgd", lr=0.5, push_comm=push_comm,
                           pull_timeout=20.0)
              for i in range(2)]
    trainers = [ShardedPSTrainer({"w": tables[i]}, buses[i], 2,
                                 staleness=staleness, gate_timeout=30.0)
                for i in range(2)]
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=dim_feat)
    X = rng.normal(size=(256, dim_feat)).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    losses = [[], []]
    errs: list = []

    def worker(r):
        try:
            Xr, yr = X[r::2], y[r::2]
            keys = np.arange(dim_feat, dtype=np.int64)
            for i in range(iters):
                w = tables[r].pull(keys).reshape(-1)
                logits = Xr @ w
                p = 1.0 / (1.0 + np.exp(-logits))
                loss = float(np.mean(
                    np.maximum(logits, 0) - logits * yr
                    + np.log1p(np.exp(-np.abs(logits)))))
                g = (Xr.T @ (p - yr) / len(yr) / 2).astype(np.float32)
                tables[r].push(keys, g.reshape(-1, 1))
                trainers[r].tick()
                losses[r].append(loss)
            trainers[r].finalize(timeout=20.0)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    try:
        ths = [threading.Thread(target=worker, args=(r,))
               for r in (0, 1)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=120.0)
        assert not errs, errs
        ef = trainers[0].ef_stats()
        if push_comm.startswith("topk"):
            assert ef is not None and ef["resident_rows"] == 0
        return np.mean(losses, axis=0)
    finally:
        for b in buses:
            b.close()


def test_lr_convergence_topk8_tracks_dense_wire():
    """The convergence acceptance: lr training through topk8 + error
    feedback pins the loss trajectory within tolerance of the dense
    wire — withheld mass is repaid, never lost."""
    dense = _train_lr("float32")
    topk = _train_lr("topk8")
    assert topk[-1] < 0.35, topk[-1]  # well below log(2) chance
    assert abs(topk[-1] - dense[-1]) < 0.08, (topk[-1], dense[-1])
    # the whole tail tracks, not just the endpoint
    assert float(np.mean(np.abs(topk[-5:] - dense[-5:]))) < 0.1


def test_mlp_convergence_topk8_tracks_dense_wire():
    """The mlp flavor: embedding rows (dim 8) trained through a numpy
    2-layer MLP head, compressed vs dense wire — the wide-row regime
    where blockwise scales and the index stream actually pay."""
    def run(push_comm, iters=40):
        buses = _mk_buses(2)
        rows, dim, hid = 32, 8, 16
        tables = [ShardedTable("e", rows, dim, buses[i], i, 2,
                               updater="sgd", lr=0.3, init_scale=0.5,
                               seed=9, push_comm=push_comm,
                               pull_timeout=20.0)
                  for i in range(2)]
        trainers = [ShardedPSTrainer({"e": tables[i]}, buses[i], 2,
                                     staleness=1, gate_timeout=30.0)
                    for i in range(2)]
        rng = np.random.default_rng(5)
        W1 = rng.normal(scale=0.5, size=(dim, hid)).astype(np.float32)
        W2 = rng.normal(scale=0.5, size=hid).astype(np.float32)
        ids = rng.integers(0, rows, size=256)
        y = (ids % 2).astype(np.float32)  # learnable per-row labels
        losses = [[], []]
        errs: list = []

        def worker(r):
            try:
                idr, yr = ids[r::2], y[r::2]
                for i in range(iters):
                    e = tables[r].pull(idr)
                    h = np.maximum(e @ W1, 0)
                    logits = h @ W2
                    p = 1 / (1 + np.exp(-logits))
                    loss = float(np.mean(
                        np.maximum(logits, 0) - logits * yr
                        + np.log1p(np.exp(-np.abs(logits)))))
                    dl = (p - yr) / len(yr) / 2
                    dh = np.outer(dl, W2) * (h > 0)
                    ge = (dh @ W1.T).astype(np.float32)
                    tables[r].push(idr, ge)
                    trainers[r].tick()
                    losses[r].append(loss)
                trainers[r].finalize(timeout=20.0)
            except Exception as ex:  # noqa: BLE001 - surfaced below
                errs.append(ex)

        try:
            ths = [threading.Thread(target=worker, args=(r,))
                   for r in (0, 1)]
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=120.0)
            assert not errs, errs
            return np.mean(losses, axis=0)
        finally:
            for b in buses:
                b.close()

    dense = run("float32")
    topk = run("topk8")
    assert topk[-1] < dense[0], (topk[-1], dense[0])  # it learned
    assert abs(topk[-1] - dense[-1]) < 0.1, (topk[-1], dense[-1])


# -------------------------------------------------- serve-plane codec
def test_serve_delta_rides_blockwise_codec():
    """The serving plane's grant/delta refreshes ride the same
    blockwise codec when the table runs a compressed push wire —
    replicas get the byte win too."""
    from minips_tpu.serve.plane import ServeConfig, TableServeState

    t = ShardedTable("t", 64, 8, None, 0, 1, push_comm="topk8",
                     topk_block=16)
    sv = TableServeState(t, None, ServeConfig())
    wire, blk = sv._serve_wire()
    assert (wire, blk) == ("blk8", 16)
    rows = np.random.default_rng(0).normal(size=(6, 8)
                                           ).astype(np.float32)
    tag, payload = sv._encode_rows(rows)
    assert tag == "blk8"
    assert len(payload) == sv._row_seg_bytes("blk8", 16, 6)
    back = sv._decode_rows("blk8", 16, 6, payload)
    np.testing.assert_allclose(back, rows, atol=np.abs(rows).max() / 64)
    # int8 < blockwise on bytes: the win the refresh stream inherits
    t2 = ShardedTable("t2", 64, 8, None, 0, 1, pull_wire="int8")
    sv2 = TableServeState(t2, None, ServeConfig())
    assert sv._row_seg_bytes("blk8", 16, 6) \
        < sv2._row_seg_bytes("int8", 0, 6)
    # f32 tables keep the seed wire
    t3 = ShardedTable("t3", 64, 8, None, 0, 1)
    sv3 = TableServeState(t3, None, ServeConfig())
    assert sv3._serve_wire() == ("f32", 0)


# --------------------------------------------------- elastic drain flush
@pytest.mark.slow
def test_drain_flushes_residuals_before_leaving(tmp_path):
    """The elastic half of the acceptance: a graceful drain on the
    compressed wire ships every retained residual before mbG — the
    leaver exits rc 0 with ZERO resident rows and survivors agree."""
    import sys

    from minips_tpu import launch

    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_example",
            "--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", "30", "--batch", "64", "--push-comm", "topk8",
            "--drain-at", "12", "--drain-rank", "2",
            "--checkpoint-dir", str(tmp_path / "ck"),
            "--checkpoint-every", "5"],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                   "MINIPS_ELASTIC": "1", "MINIPS_PUSH_COMM": ""},
        timeout=200.0)
    assert res[2]["event"] == "drained"
    for r in res:
        ef = r.get("ef")
        assert ef is not None and ef["resident_rows"] == 0, (
            r["rank"], ef)
        assert r.get("wire_frames_lost", 0) == 0
    dones = res[:2]
    assert dones[0]["param_sum"] == dones[1]["param_sum"]


def test_ef_counters_ride_wire_record():
    """The done-line `ef` block: None on an exact wire, counters when
    the compressed wire is armed (off vs idle, the PR5 convention)."""
    from minips_tpu.utils.metrics import wire_record

    class _Tr:
        bytes_pushed = bytes_pulled = frames_dropped = 0
        wire_frames_lost = wire_frames_malformed = 0

        def comm_timing(self):
            return {}

        def hist_stats(self):
            return {}

        def cache_stats(self):
            return None

        def ef_stats(self):
            return {"resident_rows": 0, "folded_rows": 3}

        def reliable_stats(self):
            return None

        def chaos_stats(self):
            return None

        def serve_stats(self):
            return {}

        def rebalance_stats(self):
            return None

    rec = wire_record(_Tr())
    assert rec["ef"] == {"resident_rows": 0, "folded_rows": 3}


def test_finalize_flushes_residuals_of_queued_async_pushes():
    """Regression (review finding): finalize() must drain the async
    queue BEFORE the residual flush — a queued topk push encodes on
    the sender thread and RETAINS fresh residuals, so the old
    flush-then-drain order stranded exactly the mass the flush exists
    to ship (resident_rows > 0 on exit, silent gradient loss)."""
    buses = _mk_buses(2)
    tables = [ShardedTable("t", 64, 2, buses[i], i, 2, updater="sgd",
                           lr=1.0, push_comm="topk8", topk_mass=0.5,
                           topk_cap=0.5, async_push=True,
                           pull_timeout=15.0)
              for i in range(2)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], 2,
                                 staleness=float("inf"))
                for i in range(2)]
    errs: list = []
    finals: list = [None, None]

    def worker(r):
        try:
            rng = np.random.default_rng(3 + r)
            other = np.arange(32, 48) if r == 0 else np.arange(0, 16)
            for _ in range(4):
                tables[r].push(other.astype(np.int64),
                               rng.normal(size=(16, 2)
                                          ).astype(np.float32))
            # the LAST push sits queued when finalize starts: its
            # encode (and retain) happens inside finalize's drain
            trainers[r].finalize(timeout=20.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append(e)

    try:
        ths = [threading.Thread(target=worker, args=(r,))
               for r in (0, 1)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=60.0)
        assert not errs, errs
        for r in (0, 1):
            ef = tables[r].ef_stats()
            assert ef["resident_rows"] == 0, (r, ef)
            assert ef["retained_rows"] > 0  # the drill exercised EF
        np.testing.assert_array_equal(finals[0], finals[1])
    finally:
        for b in buses:
            b.close()


# --------------------------------------- delta-encoded index streams
def test_topk_push_ships_delta_keys_on_hot_runs_and_applies_exactly():
    """A near-contiguous hot set rides the sorted-run delta stream
    ('dw' head, ~1 B/key vs the u16 plain width), and the receiver
    decodes it to exactly the keys the plain wire would have carried —
    the applied state matches an uncompressed-key oracle push
    bitwise (same codes, same keys, only the index codec differs)."""
    buses = _mk_buses(2)
    try:
        t0 = ShardedTable("t", 4096, 2, buses[0], 0, 2, updater="sgd",
                          push_comm="topk8", topk_mass=1.0,
                          topk_cap=1.0, pull_timeout=10.0)
        t1 = ShardedTable("t", 4096, 2, buses[1], 1, 2, updater="sgd",
                          push_comm="topk8", topk_mass=1.0,
                          topk_cap=1.0, pull_timeout=10.0)
        sent_heads = []
        orig_send = buses[0].send

        def spy(dest, kind, head, blob=None):
            if kind.startswith("psP:"):
                sent_heads.append(dict(head))
            return orig_send(dest, kind, head, blob=blob)

        buses[0].send = spy
        # rank 1's shard starts at 2048: a contiguous hot run there
        keys = np.arange(3000, 3128, dtype=np.int64)
        g = np.random.default_rng(2).normal(size=(128, 2)
                                            ).astype(np.float32)
        t0.push(keys, g)
        import time

        deadline = time.monotonic() + 5.0
        while t1.serve["push_rows"] < 128 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        (head,) = sent_heads
        assert head["comm"] == "topk8"
        assert head.get("dw") == 1 and "kw" not in head  # delta stream
        # 128 contiguous keys: 8B base + 127 gap bytes, vs 256B at u16
        # — the stream cost is visible in bytes_pushed
        from minips_tpu.ops.quantized_comm import (blockwise_stream_bytes,
                                                   delta_stream_bytes)

        cb, sb = blockwise_stream_bytes(128, 2, 8, t0.topk_block)
        assert t0.bytes_pushed == delta_stream_bytes(128, 1) + cb + sb
        # and the applied rows landed under exactly those keys
        offs = keys - t1.shard_lo
        assert (np.abs(t1._w[offs]) > 0).any()
        untouched = np.setdiff1d(np.arange(t1.part.shard_size), offs)
        assert (t1._w[untouched] == 0).all()
    finally:
        for b in buses:
            b.close()


def test_scattered_keys_fall_back_to_plain_width():
    """Keys whose gaps exceed the break-even point keep the plain
    narrowest-width stream ('kw' head) — the codec choice is per
    frame, cheapest wins."""
    sent = []

    class _Bus:
        def on(self, *_a):
            pass

        def send(self, dest, kind, head, blob=None):
            sent.append(dict(head))

    # 64Ki-row key space: plain width u16; two keys 40000 apart need
    # dw=2 plus the 8-byte base — plain (4 B) wins
    t = ShardedTable("t", 1 << 16, 2, _Bus(), 0, 2, updater="sgd",
                     push_comm="topk8", topk_mass=1.0, topk_cap=1.0)
    t.push(np.array([40000, 65000], np.int64),
           np.ones((2, 2), np.float32))
    (head,) = sent
    assert head.get("kw") == 2 and "dw" not in head
