"""Checkpoint/recovery: disk roundtrip of tables + clocks (SURVEY.md §5.4)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.ckpt.checkpoint import Checkpointer, _flatten, _unflatten
from minips_tpu.consistency import SSP
from minips_tpu.tables.dense import DenseTable
from minips_tpu.tables.sparse import SparseTable


def test_flatten_unflatten_roundtrip():
    tree = {"a": {"b": np.arange(3)}, "c": [np.ones(2), {"d": np.zeros(1)}],
            "e": None}
    back = _unflatten({k: v for k, v in _flatten(tree).items()})
    assert back["e"] is None
    np.testing.assert_array_equal(back["a"]["b"], np.arange(3))
    np.testing.assert_array_equal(back["c"][0], np.ones(2))
    np.testing.assert_array_equal(back["c"][1]["d"], np.zeros(1))


def _trained_tables(mesh, updater="adam"):
    dense = DenseTable({"w": jnp.zeros(8)}, mesh, updater=updater, lr=0.1)
    sparse = SparseTable(64, 4, mesh, updater="adagrad", lr=0.1, seed=7)
    for _ in range(3):
        dense.push({"w": jnp.arange(8.0)})
        sparse.push(jnp.array([1, 2, 3]), jnp.ones((3, 4)))
    return dense, sparse


def test_disk_roundtrip_resumes_identically(mesh8, tmp_path):
    """After restore, further identical pushes must produce identical state
    (i.e. optimizer state incl. adam moments/adagrad accum survived)."""
    d1, s1 = _trained_tables(mesh8)
    ck = Checkpointer(str(tmp_path), {"d": d1, "s": s1})
    ck.save(step=3)

    d2, s2 = _trained_tables(mesh8)  # fresh tables, same shapes
    # diverge d2 so restore provably overwrites
    d2.push({"w": jnp.ones(8) * 100})
    ck2 = Checkpointer(str(tmp_path), {"d": d2, "s": s2})
    assert ck2.restore() == 3

    for t in (d1, d2):
        t.push({"w": jnp.arange(8.0)})
    s1.push(jnp.array([2, 3]), jnp.ones((2, 4)))
    s2.push(jnp.array([2, 3]), jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(d2.params), np.asarray(d1.params),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s2.emb), np.asarray(s1.emb),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s2.accum), np.asarray(s1.accum),
                               rtol=1e-6)


def test_updater_mismatch_rejected(mesh8, tmp_path):
    d1, _ = _trained_tables(mesh8, updater="adam")
    Checkpointer(str(tmp_path), {"d": d1}).save(step=1)
    d_sgd = DenseTable({"w": jnp.zeros(8)}, mesh8, updater="sgd", lr=0.1)
    with pytest.raises(ValueError, match="leaf count mismatch"):
        Checkpointer(str(tmp_path), {"d": d_sgd}).restore()


def test_controller_clocks_roundtrip(mesh8, tmp_path):
    d, s = _trained_tables(mesh8)
    c = SSP(4, staleness=2)
    c.clock(0); c.clock(0); c.clock(1)
    Checkpointer(str(tmp_path), {"d": d}, {"t": c}).save(step=9)
    c2 = SSP(4, staleness=2)
    ck = Checkpointer(str(tmp_path), {"d": d}, {"t": c2})
    assert ck.restore() == 9
    assert c2.tracker.snapshot() == [2, 1, 0, 0]


def test_gc_keeps_newest(mesh8, tmp_path):
    d, _ = _trained_tables(mesh8)
    ck = Checkpointer(str(tmp_path), {"d": d}, keep=2)
    for s in (1, 2, 3, 4):
        ck.save(step=s)
    assert ck.list_steps() == [3, 4]


def test_async_save(mesh8, tmp_path):
    d, s = _trained_tables(mesh8)
    ck = Checkpointer(str(tmp_path), {"d": d, "s": s}, async_save=True)
    ck.save(step=5)
    ck.wait()
    assert ck.list_steps() == [5]
    ck2 = Checkpointer(str(tmp_path), {"d": d, "s": s})
    assert ck2.restore() == 5


def test_partial_tmp_dir_ignored(mesh8, tmp_path):
    """A crash mid-save (leftover .tmp dir) must not break restore."""
    d, _ = _trained_tables(mesh8)
    ck = Checkpointer(str(tmp_path), {"d": d})
    ck.save(step=1)
    os.makedirs(str(tmp_path / "step_0000000002.tmp"))
    assert ck.list_steps() == [1]
    assert ck.restore() == 1


def test_restore_walks_back_past_torn_checkpoint(mesh8, tmp_path, capfd):
    """Fail-slow PR satellite: a TORN newest checkpoint — truncated
    npz, corrupt manifest, or a missing table file — is skipped with a
    loud warning and ``restore()`` walks back to the newest VALID step
    instead of crashing the relaunch. The live tables stay untouched
    by the failed candidate (validate-before-apply)."""
    d, s = _trained_tables(mesh8)
    ck = Checkpointer(str(tmp_path), {"d": d, "s": s})
    ck.save(step=1)
    ck.save(step=2)
    ck.save(step=3)
    # tear step 3: truncate its npz mid-file (the crash-mid-write shape
    # the atomic rename cannot protect against — e.g. disk-full after
    # publish, or bit rot)
    p3 = tmp_path / "step_0000000003" / "d.npz"
    raw = p3.read_bytes()
    p3.write_bytes(raw[: len(raw) // 2])
    d2, s2 = _trained_tables(mesh8)
    ck2 = Checkpointer(str(tmp_path), {"d": d2, "s": s2})
    assert ck2.restore() == 2
    err = capfd.readouterr().err
    assert "skipping torn checkpoint" in err and "step_3" in err
    # an EXPLICIT step keeps strict semantics: asking for the torn one
    # raises instead of silently substituting an older step
    with pytest.raises(Exception):
        ck2.restore(step=3)
    # corrupt manifest on the next-newest: walk back twice
    (tmp_path / "step_0000000002" / "manifest.json").write_text("{tor")
    d3, s3 = _trained_tables(mesh8)
    assert Checkpointer(str(tmp_path), {"d": d3, "s": s3}).restore() == 1
    # a missing table file is a torn checkpoint too
    os.remove(str(tmp_path / "step_0000000001" / "d.npz"))
    d4, s4 = _trained_tables(mesh8)
    with pytest.raises(FileNotFoundError, match="every candidate"):
        Checkpointer(str(tmp_path), {"d": d4, "s": s4}).restore()


def test_sgd_roundtrip_leafless_opt_state(mesh8, tmp_path):
    """sgd's opt state has zero leaves (EmptyStates), so no 'opt_state' key
    lands in the npz at all — restore must tolerate the absent key."""
    d1 = DenseTable({"w": jnp.zeros(8)}, mesh8, updater="sgd", lr=0.1)
    d1.push({"w": jnp.ones(8)})
    Checkpointer(str(tmp_path), {"d": d1}).save(step=1)
    d2 = DenseTable({"w": jnp.zeros(8)}, mesh8, updater="sgd", lr=0.1)
    assert Checkpointer(str(tmp_path), {"d": d2}).restore() == 1
    np.testing.assert_allclose(np.asarray(d2.params), np.asarray(d1.params),
                               rtol=1e-6)


class TestOrbaxBackend:
    """Same contract as the native backend, through orbax.checkpoint."""

    @pytest.fixture(autouse=True)
    def _require_orbax(self):
        pytest.importorskip("orbax.checkpoint")

    def test_roundtrip_resumes_identically(self, mesh8, tmp_path):
        from minips_tpu.ckpt.orbax_backend import make_checkpointer

        d1, s1 = _trained_tables(mesh8)
        ck = make_checkpointer(str(tmp_path), {"d": d1, "s": s1},
                               backend="orbax")
        ck.save(step=3)
        ck.wait()

        d2, s2 = _trained_tables(mesh8)
        d2.push({"w": jnp.ones(8) * 100})      # diverge; restore overwrites
        ck2 = make_checkpointer(str(tmp_path), {"d": d2, "s": s2},
                                backend="orbax")
        assert ck2.restore() == 3
        for t in (d1, d2):
            t.push({"w": jnp.arange(8.0)})
        np.testing.assert_allclose(np.asarray(d2.params),
                                   np.asarray(d1.params), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(s2.emb), np.asarray(s1.emb),
                                   rtol=1e-6)
        ck.close()
        ck2.close()

    def test_keep_and_list_steps(self, mesh8, tmp_path):
        from minips_tpu.ckpt.orbax_backend import make_checkpointer

        d, s = _trained_tables(mesh8)
        ck = make_checkpointer(str(tmp_path), {"d": d}, keep=2,
                               backend="orbax")
        for step in (1, 2, 3):
            ck.save(step=step)
        ck.wait()
        assert ck.list_steps() == [2, 3]
        ck.close()

    def test_clocks_roundtrip(self, mesh8, tmp_path):
        from minips_tpu.ckpt.orbax_backend import make_checkpointer

        d, _ = _trained_tables(mesh8)
        ctl = SSP(staleness=2, num_workers=3)
        for w in range(3):
            ctl.clock(w)
        ctl.clock(0)
        ck = make_checkpointer(str(tmp_path), {"d": d},
                               {"ssp": ctl}, backend="orbax")
        ck.save(step=5)
        ck.wait()
        ctl2 = SSP(staleness=2, num_workers=3)
        ck2 = make_checkpointer(str(tmp_path), {"d": d},
                                {"ssp": ctl2}, backend="orbax")
        assert ck2.restore() == 5
        assert ctl2.state_dict() == ctl.state_dict()
        ck.close()
        ck2.close()

    def test_factory_default_is_native(self, mesh8, tmp_path, monkeypatch):
        from minips_tpu.ckpt.checkpoint import Checkpointer
        from minips_tpu.ckpt.orbax_backend import make_checkpointer

        monkeypatch.delenv("MINIPS_CKPT_BACKEND", raising=False)
        d, _ = _trained_tables(mesh8)
        ck = make_checkpointer(str(tmp_path), {"d": d})
        assert isinstance(ck, Checkpointer)
        with pytest.raises(ValueError, match="unknown checkpoint backend"):
            make_checkpointer(str(tmp_path), {"d": d}, backend="bogus")


def test_resume_replays_exact_data_stream(mesh8, tmp_path):
    """Interrupt-at-k + resume must reproduce the uninterrupted run's final
    params EXACTLY: TrainLoop fast-forwards the BatchIterator to the global
    step, so the resumed run consumes the same batches in the same order."""
    from minips_tpu.data.loader import BatchIterator
    from minips_tpu.models import lr as lr_model
    from minips_tpu.train.loop import TrainLoop

    rng = np.random.default_rng(0)
    data = {"x": rng.normal(size=(256, 16)).astype(np.float32),
            "y": rng.integers(0, 2, size=256).astype(np.float32)}

    def make():
        t = DenseTable(lr_model.init(16), mesh8, updater="adagrad", lr=0.3)
        s = t.make_step(lr_model.grad_fn_dense)
        return t, (lambda b: t.step_inplace(
            s, {k: jnp.asarray(v) for k, v in b.items()}))

    t1, f1 = make()  # uninterrupted: 10 steps
    TrainLoop(f1, BatchIterator(data, 32, seed=3), log_every=0).run(10)

    t2, f2 = make()  # interrupted at 6...
    ck = Checkpointer(str(tmp_path), {"w": t2})
    TrainLoop(f2, BatchIterator(data, 32, seed=3), checkpointer=ck,
              checkpoint_every=6, log_every=0).run(6)
    t3, f3 = make()  # ...resumed for the remaining 4
    start = Checkpointer(str(tmp_path), {"w": t3}).restore()
    assert start == 6
    TrainLoop(f3, BatchIterator(data, 32, seed=3), step_offset=start,
              log_every=0).run(10 - start)

    np.testing.assert_array_equal(np.asarray(t3.params),
                                  np.asarray(t1.params))


def test_orbax_restores_checkpoint_predating_layout_record(mesh8, tmp_path):
    """A pre-'layout' orbax checkpoint (hashed table) must still restore:
    the template is pruned to the saved keys so StandardRestore never sees
    the missing entry (code-review round 2 regression)."""
    pytest.importorskip("orbax.checkpoint")
    from minips_tpu.ckpt.orbax_backend import make_checkpointer
    from minips_tpu.tables.sparse import SparseTable

    s1 = SparseTable(64, 2, mesh8, updater="sgd", lr=0.5)
    s1.push(jnp.array([3]), jnp.ones((1, 2)))
    ck = make_checkpointer(str(tmp_path), {"s": s1}, backend="orbax")
    # simulate a legacy checkpoint: drop 'layout' from what gets saved
    orig = s1.state_dict

    def legacy_state_dict():
        st = orig()
        st.pop("layout")
        return st

    s1.state_dict = legacy_state_dict
    ck.save(step=1)
    ck.wait()
    ck.close()

    s2 = SparseTable(64, 2, mesh8, updater="sgd", lr=0.5, init_scale=0.0)
    ck2 = make_checkpointer(str(tmp_path), {"s": s2}, backend="orbax")
    assert ck2.restore() == 1  # hashed table: legacy tolerance
    np.testing.assert_allclose(np.asarray(s2.emb), np.asarray(s1.emb))
    ck2.close()

    # an identity table must still REFUSE the layout-less checkpoint
    s3 = SparseTable(64, 2, mesh8, updater="sgd", identity=True)
    ck3 = make_checkpointer(str(tmp_path), {"s": s3}, backend="orbax")
    with pytest.raises(ValueError, match="predates layout"):
        ck3.restore()
    ck3.close()


def test_sparse_layout_mismatch_rejected_but_salt_ignored_on_identity(
        mesh8, tmp_path):
    from minips_tpu.ckpt.checkpoint import Checkpointer
    from minips_tpu.tables.sparse import SparseTable

    t = SparseTable(64, 2, mesh8, identity=True, salt=0)
    Checkpointer(str(tmp_path), {"s": t}).save(step=1)
    # identity path never reads salt → differing salt must restore fine
    t2 = SparseTable(64, 2, mesh8, identity=True, salt=7)
    Checkpointer(str(tmp_path), {"s": t2}).restore()
    # but hashed vs identity is a real layout change → refuse
    t3 = SparseTable(64, 2, mesh8, identity=False)
    with pytest.raises(ValueError, match="layout"):
        Checkpointer(str(tmp_path), {"s": t3}).restore()


def test_legacy_checkpoint_refused_for_nonzero_salt(mesh8, tmp_path):
    from minips_tpu.ckpt.checkpoint import Checkpointer
    from minips_tpu.tables.sparse import SparseTable

    t = SparseTable(64, 2, mesh8, salt=3)
    orig = t.state_dict
    t.state_dict = lambda: {k: v for k, v in orig().items()
                            if k != "layout"}
    Checkpointer(str(tmp_path), {"s": t}).save(step=1)
    t2 = SparseTable(64, 2, mesh8, salt=7)
    with pytest.raises(ValueError, match="predates layout"):
        Checkpointer(str(tmp_path), {"s": t2}).restore()


def test_cross_backend_convert_native_to_orbax_and_back(mesh8, tmp_path):
    """VERDICT r1 #10: native save → orbax restore (via convert) and vice
    versa are lossless, including optimizer state — the two backends stay
    honestly drop-in. Post-restore push parity proves the state is live,
    not just byte-equal."""
    pytest.importorskip("orbax.checkpoint")
    from minips_tpu.ckpt import convert_checkpoint
    from minips_tpu.ckpt.orbax_backend import make_checkpointer

    d1, s1 = _trained_tables(mesh8)
    Checkpointer(str(tmp_path / "nat"), {"d": d1, "s": s1}).save(step=5)

    # native → orbax: migrate through scratch tables, then restore into
    # FRESH tables purely from the orbax copy
    dm, sm = _trained_tables(mesh8)
    assert convert_checkpoint(
        str(tmp_path / "nat"), str(tmp_path / "orb"), {"d": dm, "s": sm},
        src_backend="native", dst_backend="orbax") == 5
    d2, s2 = _trained_tables(mesh8)
    d2.push({"w": jnp.ones(8) * 50})  # diverge; restore must overwrite
    ck = make_checkpointer(str(tmp_path / "orb"), {"d": d2, "s": s2},
                           backend="orbax")
    assert ck.restore() == 5
    ck.close()
    np.testing.assert_allclose(np.asarray(d2.params), np.asarray(d1.params),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s2.emb), np.asarray(s1.emb),
                               rtol=1e-6)

    # orbax → native, restored into fresh tables again
    dn, sn = _trained_tables(mesh8)
    assert convert_checkpoint(
        str(tmp_path / "orb"), str(tmp_path / "nat2"), {"d": dn, "s": sn},
        src_backend="orbax", dst_backend="native") == 5
    d3, s3 = _trained_tables(mesh8)
    Checkpointer(str(tmp_path / "nat2"), {"d": d3, "s": s3}).restore()
    # optimizer state survived BOTH hops: identical further pushes give
    # identical state (adam moments / adagrad accumulators intact)
    for d, s in ((d1, s1), (d3, s3)):
        d.push({"w": jnp.arange(8.0)})
        s.push(jnp.array([2, 3]), jnp.ones((2, 4)))
    np.testing.assert_allclose(np.asarray(d3.params), np.asarray(d1.params),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s3.emb), np.asarray(s1.emb),
                               rtol=1e-6)


def test_prune_above_deletes_newer_steps(tmp_path):
    """prune_above removes dead-incarnation checkpoints so a later resume
    negotiation can never land on a mixed-incarnation step."""
    from minips_tpu.ckpt.checkpoint import Checkpointer

    class T:
        def __init__(self):
            self.v = np.zeros(4, np.float32)

        def state_dict(self):
            return {"v": self.v}

        def load_state_dict(self, s):
            self.v = s["v"]

    ck = Checkpointer(str(tmp_path), {"t": T()}, keep=0)
    for s in (5, 10, 15, 20):
        ck.save(s)
    assert ck.list_steps() == [5, 10, 15, 20]
    assert ck.prune_above(10) == [15, 20]
    assert ck.list_steps() == [5, 10]
    assert ck.prune_above(10) == []  # idempotent
    ck.restore(10)  # the kept step still restores
