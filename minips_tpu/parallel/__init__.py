from minips_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    local_mesh_size,
)
from minips_tpu.parallel.partition import RangePartitioner  # noqa: F401
