"""Structured JSONL metrics — rebuild of the reference's glog loss printing.

The reference logs per-iteration loss via glog (SURVEY.md §5.5). Here metrics
are structured JSONL records carrying the [T1] primary metric
(samples/sec/chip) plus SSP's key observable, min/max clock skew
(SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import IO, Any, Optional


class MetricsLogger:
    """Append-only JSONL metrics sink; also mirrors to stderr when verbose.

    Thread-safe: the sharded-PS stack logs from the bus receive thread
    (drop notes, failure events) while the training thread logs step
    records — an unguarded ``write`` + ``flush`` pair can interleave two
    records into one torn JSONL line, which downstream scrapers then
    drop silently. One lock around the whole emit keeps every line
    atomic (``print`` to stderr included: the mirrored stream is
    scraped by the launcher harvest too)."""

    def __init__(self, path: Optional[str] = None, verbose: bool = True):
        self._fh: Optional[IO[str]] = open(path, "a") if path else None
        self._verbose = verbose
        self._t0 = time.monotonic()
        self._lock = threading.Lock()

    def log(self, **record: Any) -> dict:
        record.setdefault("t", round(time.monotonic() - self._t0, 6))
        line = json.dumps(record, sort_keys=True)
        with self._lock:
            if self._fh is not None:
                self._fh.write(line + "\n")
                self._fh.flush()
            if self._verbose:
                print(line, file=sys.stderr)
        return record

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def wire_record(trainer) -> dict:
    """One JSON-able record of a sharded-PS trainer's wire health: bytes
    both directions, loss/drop accounting, and the per-leg timing
    (utils/timing.CommTimers) the overlapped pipeline exposes, nested
    under ``"timing"`` — the done-line shape the apps splat into their
    result line (and the bench worker mirrors with per-window deltas),
    so sweep tooling scrapes one layout."""
    return {
        "bytes_pushed": trainer.bytes_pushed,
        "bytes_pulled": trainer.bytes_pulled,
        "frames_dropped": trainer.frames_dropped,
        "wire_frames_lost": trainer.wire_frames_lost,
        # torn/undecodable frames, counted instead of silently swallowed
        # (comm/bus.py dispatch_message) — nonzero means a stale run's
        # tail or real wire corruption, next to the loss counter on
        # purpose: both are wire-health signals the done line must carry
        "wire_frames_malformed": trainer.wire_frames_malformed,
        "timing": trainer.comm_timing(),
        # log2 latency histograms (obs/hist.py) as p50/p95/p99 blocks:
        # ALWAYS a dict (the layer is always on); a quantity that saw
        # no samples reports {"count": 0} — "idle", distinct from the
        # None an OFF layer (cache/reliable/chaos/rebalance) reports
        "hist": trainer.hist_stats(),
        # WINDOWED metrics (obs/window.py): quantiles/rates over the
        # last K clock boundaries, next to the cumulative hist block —
        # None when the layer is off (MINIPS_OBS=0, the tax arm), idle
        # quantities {"count": 0} as above (getattr: the bench worker's
        # standalone record has no trainer behind it)
        "window": getattr(trainer, "window_stats", lambda: None)(),
        # heartbeat liveness-layer counters (comm/heartbeat.py): the
        # stall= forgiveness window's hits — a forgiven stall must be
        # visible, an operator can't tell forgiveness from health
        # otherwise. None when no monitor rides this trainer.
        "heartbeat": getattr(trainer, "heartbeat_stats",
                             lambda: None)(),
        # row-cache counters (train/sharded_ps.RowCache): None when every
        # table runs cache-off, so scrapers can tell "off" from "cold"
        "cache": trainer.cache_stats(),
        # error-feedback residual counters (compressed push wire,
        # train/sharded_ps.ResidualStore): None when every table runs
        # an exact push wire — fold/retain/flush accounting is the
        # evidence no gradient mass is stranded
        "ef": getattr(trainer, "ef_stats", lambda: None)(),
        # fail-slow plane (serve/hedge.py + obs/slowness.py): hedged
        # pull-leg counters (fired/won/lost/no_holder/denied) and the
        # detection state (suspects, per-peer windowed p99s, slow
        # verdicts when the quorum is armed) — None when the
        # respective knob is off, zeros/empty when armed-but-idle
        "hedge": getattr(trainer, "hedge_stats", lambda: None)(),
        "slowness": getattr(trainer, "slowness_stats",
                            lambda: None)(),
        # hierarchical push tree (balance/hier.py): per-level byte/
        # frame split (l1 intra-group, l2 the cross-group leader leg),
        # aggregation + election/fallback counters — None when
        # MINIPS_HIER is off, zero counters when armed-idle (group=1)
        "hier": getattr(trainer, "hier_stats", lambda: None)(),
        # hybrid data plane (MINIPS_HIER agg=mesh): the leader's
        # in-host device-reduce counters — None when hier is off or
        # the host f64 backend is configured, ALL-ZERO when armed-idle
        # (group=1 never flushes); all-numeric by contract (the
        # schema test pins it)
        "hybrid": getattr(trainer, "hybrid_stats", lambda: None)(),
        # retransmission-protocol + fault-injection counters: None when
        # the respective layer is off ('off' vs 'clean' distinguishable)
        "reliable": trainer.reliable_stats(),
        "chaos": trainer.chaos_stats(),
        # per-owner serve-load counters (ALWAYS on): requests/rows this
        # process served as an owner — max/mean across ranks is the
        # partition-imbalance observable the heat-aware rebalancer acts
        # on, measurable even with the rebalancer off. Its "replica"
        # sub-block carries the read-mostly serving plane's counters
        # (replica-served/shed/lease-refused/stale-reads + the SLO
        # check): None when the plane is OFF, zero counters when armed
        # but idle — the same off-vs-idle convention as the hist block
        "serve": trainer.serve_stats(),
        # rebalancer counters (balance/): None when the subsystem is
        # off (distinguishable from an armed-but-idle run)
        "rebalance": trainer.rebalance_stats(),
        # planned collective redistribution (balance/redistribute.py):
        # round/slice/dup/abort counters and the measured per-round
        # peak staging bytes the RESHARD-MEM gate reads — None when
        # MINIPS_RESHARD is off, zero counters when armed but idle
        "reshard": getattr(trainer, "reshard_stats", lambda: None)(),
        # elastic membership plane (balance/membership.py): None when
        # MINIPS_ELASTIC is off; armed runs carry the live/standby/
        # dead/left sets and transition counters (getattr: the bench
        # worker's standalone record has no trainer behind it)
        "membership": getattr(trainer, "membership_stats",
                              lambda: None)(),
        # closed-loop autoscaler (balance/autoscaler.py): None when
        # MINIPS_AUTOSCALE is off; armed runs carry admit/drain counts,
        # hysteresis streaks, and the pre/post-admit shed rates the
        # CTRL-SCALE tripwire gates
        "autoscale": getattr(trainer, "autoscale_stats",
                             lambda: None)(),
        # multi-tenant tables (tenant/registry.py): per-tenant SLO
        # evidence — tenant id, spec'd overrides, and the deny
        # counters the serve plane attributed to each tenant's own
        # budget (shed/throttle/stale_reads/hedge_denied). None when
        # MINIPS_TENANT is off, zero counters when armed but idle —
        # the TENANT-IDLE gate pins the zeros
        "tenant": getattr(trainer, "tenant_stats", lambda: None)(),
        # push-visible-at-replica freshness (obs/freshness.py): per-
        # tenant visibility-lag p50/p99 + owner stamp counters, next to
        # the read p99 above — None when the serving plane is OFF
        # (there are no replicas to be visible at), {"count": 0} lag
        # summaries + zero counters when armed but idle
        "freshness": getattr(trainer, "freshness_stats",
                             lambda: None)(),
        # SLO burn-rate accounting (obs/slo.py): fast/slow-window burn
        # ratios per tenant, burn/clear edge counts (each burn edge is
        # a flight-recorder checkpoint), and the promotion-budget
        # flex proof (boost_ticks, per-tenant max_budget) — None when
        # MINIPS_SLO is off, zero counters when armed but idle
        "slo": getattr(trainer, "slo_stats", lambda: None)(),
    }
