"""In-mesh collective data plane for the sharded PS (``MINIPS_MESH=1``).

The third data plane next to the zmq/native/shm HOST wire (comm/bus.py):
instead of routing per-owner key slices over sockets or rings, the whole
gang lives on one device mesh and exchanges owner-split rows with XLA
collectives — the retrieval target's endgame (SNIPPETS.md header,
ROADMAP item 1) and the bridge between the host-wire PS and the
fused-SPMD numbers (bench r02's ~915k samples/sec/chip vs the wire
path's control-plane rates):

- **server state is pjit-sharded**: each table's rows AND its updater
  state (adagrad accumulator, adam moments/steps) live as device arrays
  range-sharded across the mesh's ``shard`` axis
  (``NamedSharding(mesh, P("shard"))``) — the updater step itself runs
  sharded per "Automatic Cross-Replica Sharding of Weight Update in
  Data-Parallel Training" (PAPERS.md): no replicated optimizer math, no
  host round-trip on the hot path;
- **push ≡ reduce-scatter**: each logical rank's dense row-space
  contribution rides a ``shard_map``-level ``psum_scatter`` that sums
  across ranks and leaves every device exactly its owned row range;
- **pull ≡ all-gather**: the updated owner shards reassemble on every
  device with one ``all_gather`` fused into the same XLA program;
- **BSP/SSP gate the collective, not wire frames**: the plane keeps a
  DEVICE-SIDE clock vector (one entry per logical rank); pull admission
  is the shared ``consistency.gate.admits`` predicate evaluated against
  ``min`` of that vector — the same clk−s bound as the owner-side park
  on the wire planes, and under BSP the apply wave is the barrier;
- **optional quantized tier** (``comm="blk8"``): the reduce leg runs
  ``ops.quantized_comm.quantized_psum_scatter`` — quantize to blockwise
  absmax int8 codes, exchange, dequantize-ACCUMULATE in f32
  (EQuARX-style), sharing the blockwise codec with the PR9 compressed
  host wire so there is one compression story with two transports.

Semantics vs the wire planes (the consistency contract survives the
transport swap):

- Pushes DEPOSIT into a per-rank dense row-space buffer (duplicate keys
  coalesced exactly like the wire's client-side dedup: per-dim f64
  bincount, rounded once to f32 — bitwise the frame the wire would
  ship). An APPLY WAVE — one jitted program: reduce-scatter, sharded
  updater, all-gather — fires when every live rank has a deposit, when
  a depositing rank pulls (read-your-own-writes), and at every
  ``tick``/``finalize`` (so a rank's step-k pushes are in the shared
  state BEFORE its clock reads k — the wire's per-link-FIFO staleness
  argument, enforced by program order instead of frame order).
- BSP + sgd is BITWISE-equal to the zmq wire path (the
  ``run_bsp_lockstep`` drill pins it): a wave with one push per rank
  applies ``w -= lr * Σ_r g_r`` where cross-rank zeros are exact, i.e.
  exactly the per-push server apply.
- Stateful updaters apply ONE step per wave to each touched row (adam
  stays lazy via a reduced touch mask): when two ranks hit the same row
  in one wave the gradients sum before the update — gradient
  aggregation semantics, vs the wire's update-per-frame. Same
  fixed-point family, documented divergence (docs/architecture.md).

Development and tier-1 run on CPU via the repo's established
``--xla_force_host_platform_device_count`` pattern (tests/conftest.py);
real meshes swap the device list, nothing else.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from minips_tpu.consistency.gate import RETIRED_CLOCK, admits
from minips_tpu.obs import flight as _fl
from minips_tpu.obs import window as _ow
from minips_tpu.obs.hist import Log2Histogram, summarize_counts

MESH_AXIS = "shard"
VALID_MESH_COMM = ("float32", "blk8")
# BSP tick-flush grace: how long a ticking rank lets the eager full
# wave fire before solo-flushing its own deposits (see
# MeshPlane._flush_rank_locked) — generous vs a step, invisible vs the
# gate timeout
_BSP_FLUSH_GRACE = 0.05

__all__ = ["MeshPlane", "MeshRank", "MeshTable", "MeshAggregator",
           "resolve_plane", "resolve_deposit", "MESH_AXIS",
           "VALID_MESH_COMM"]


def resolve_plane(plane: Optional[str]) -> str:
    """The data-plane selection rule every entrypoint shares (same
    explicit-wins-over-env convention as ``make_bus``): an explicit
    ``plane`` wins, else ``MINIPS_MESH`` (any value but ''/'0') selects
    the in-mesh collective plane, else the host wire."""
    if plane:
        if plane not in ("wire", "mesh"):
            raise ValueError(f"plane must be 'wire' or 'mesh', "
                             f"got {plane!r}")
        return plane
    env = os.environ.get("MINIPS_MESH", "").strip()
    return "mesh" if env not in ("", "0") else "wire"


def resolve_deposit(deposit: Optional[str] = None) -> str:
    """Deposit-buffer selection, same explicit-wins-over-env rule:
    ``dense`` stages pushes in the pre-stacked ``[n, padded, dim]``
    host buffers; ``sparse`` stages COO (keys, rows) streams and
    densifies ON DEVICE with a segment-sum scatter inside the wave —
    an embedding-table-sized key space with a small touched set stops
    materializing host buffers that scale with ``num_rows``.
    ``MINIPS_MESH_SPARSE`` (any value but ''/'0') selects sparse."""
    if deposit:
        if deposit not in ("dense", "sparse"):
            raise ValueError(f"mesh deposit must be 'dense' or "
                             f"'sparse', got {deposit!r}")
        return deposit
    env = os.environ.get("MINIPS_MESH_SPARSE", "").strip()
    return "sparse" if env not in ("", "0") else "dense"


def _padded(rows: int, shards: int) -> int:
    return shards * (-(-max(rows, 1) // shards))


class MeshTable:
    """One pjit-sharded KVTable + updater state on the plane's mesh,
    with per-logical-rank deposit buffers. All mutation runs under the
    plane lock; rank-facing entrypoints take the rank explicitly (the
    :class:`MeshRank` handle binds it)."""

    def __init__(self, plane: "MeshPlane", name: str, num_rows: int,
                 dim: int, *, updater: str = "sgd", lr: float = 0.05,
                 adagrad_init: float = 0.1, eps: Optional[float] = None,
                 beta1: float = 0.9, beta2: float = 0.999):
        if updater not in ("sgd", "adagrad", "adam"):
            raise ValueError(
                "mesh-plane updater must be 'sgd', 'adagrad' or 'adam'")
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        self.plane = plane
        self.name = name
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.updater = updater
        self.lr = float(lr)
        # same defaults as the wire table (train/sharded_ps.py), which
        # themselves match the ops/sparse_update.py oracles
        self.eps = float((1e-8 if updater == "adam" else 1e-10)
                         if eps is None else eps)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        n = plane.num_ranks
        self.padded = _padded(self.num_rows, n)
        self.shard_rows = self.padded // n
        self._row_sh = NamedSharding(plane.mesh, P(MESH_AXIS))
        # the rank axis of the stacked deposits shards the same way: each
        # device holds exactly its own logical rank's contribution —
        # data-parallel layout in, range-sharded state out
        self._stack_sh = NamedSharding(plane.mesh, P(MESH_AXIS))
        z = jnp.zeros((self.padded, self.dim), jnp.float32)
        self._w = jax.device_put(z, self._row_sh)
        self._acc = (jax.device_put(
            jnp.full((self.padded, self.dim), float(adagrad_init),
                     jnp.float32), self._row_sh)
            if updater == "adagrad" else None)
        if updater == "adam":
            self._m = jax.device_put(z, self._row_sh)
            self._v = jax.device_put(z, self._row_sh)
            self._steps = jax.device_put(
                jnp.zeros(self.padded, jnp.int32), self._row_sh)
        else:
            self._m = self._v = self._steps = None
        self.deposit = plane.deposit
        if self.deposit == "sparse":
            # sparse device waves: deposits stage as per-rank COO
            # (keys, rows) streams and densify ON DEVICE with a
            # segment-sum scatter inside the wave — host staging
            # scales with the TOUCHED set, not ``num_rows`` (the
            # embedding-table shape PR 11 carried as headroom)
            self._gbuf = None
            self._tstack = None
            self._ckeys: Optional[list] = [[] for _ in range(n)]
            self._cvals: Optional[list] = [[] for _ in range(n)]
            self.peak_deposit_bytes = 0
        else:
            # per-rank host deposit buffers, PRE-STACKED: the wave's
            # input is this one [n, padded, dim] array (each rank
            # deposits into its row — clean ranks contribute exact
            # zeros), so a wave pays one device_put and zero stacking
            # copies
            self._gbuf = np.zeros((n, self.padded, self.dim), np.float32)
            self._tstack = (np.zeros((n, self.padded), np.float32)
                            if updater == "adam" else None)
            self._ckeys = self._cvals = None
            self.peak_deposit_bytes = self._gbuf.nbytes + (
                self._tstack.nbytes if self._tstack is not None else 0)
        self._dirty = [False] * n
        # the replicated pull mirror: the wave's fused all-gather output,
        # host-resident (and read-only: pull_all serves VIEWS — the
        # mirror is REPLACED per wave, never mutated, so an outstanding
        # view stays a valid snapshot) so reads between waves are plain
        # numpy indexing
        self._mirror = np.zeros((self.padded, self.dim), np.float32)
        self._mirror.setflags(write=False)
        self.waves = 0
        self.rows_pushed = 0
        self.rows_pulled = 0
        # collective traffic accounting (the MESH analog of wire bytes):
        # what the reduce-scatter + all-gather move per wave, summed over
        # ranks — ring cost (n-1)/n of the buffer each way, codes+scales
        # for the blk8 tier (blockwise_stream_bytes is the shared bill)
        self.collective_bytes = 0
        # blk8 error feedback (plane.mesh_ef): each device's quantization
        # residual from the reduce leg — input minus what a2a_reduce
        # actually shipped — retained host-side and folded into the next
        # wave's contribution, with an exact-f32 repayment wave at
        # finalize: the wire ResidualStore's fold/flush contract
        # (train/sharded_ps.py) on the collective transport. Born as a
        # DEVICE array (stack-sharded zeros): between waves it is the
        # wave's own device output, and a host-side [n, padded, dim]
        # zeros block would charge sparse mode a dense host buffer it
        # exists to avoid
        self._rbuf = (jax.device_put(
            jnp.zeros((n, self.padded, self.dim), jnp.float32),
            self._stack_sh) if plane.mesh_ef else None)
        self._fence_fn = None  # exact repayment program, built lazily
        self.ef_waves = 0        # waves that folded + re-captured resid
        self.ef_fence_waves = 0  # exact repayment waves (finalize)
        self.sparse_waves = 0    # waves that densified on device
        self._wave_fns: dict = {}  # sparse: one program per L bucket
        self._wave_len = 8         # grow-only L (compile-thrash guard)
        self._wave_fn = (self._build_wave_fn()
                         if self.deposit == "dense" else None)

    # ------------------------------------------------------------ wave
    def _build_wave_fn(self, *, exact: bool = False,
                       sparse_len: Optional[int] = None):
        """One jitted XLA program per table — THE collective data plane:
        reduce-scatter the stacked rank deposits (push), run the updater
        on the owner shard (sharded server math — no replicated
        optimizer state), all-gather the new rows (pull). The signature
        varies by updater so only real state is donated; the updater
        math mirrors the wire table's numpy updaters op for op
        (sharded_ps._update_block/_adam_rows).

        ``sparse_len=L`` swaps the dense ``[n, padded, dim]`` deposit
        input for COO streams (``[n, L]`` keys + ``[n, L, dim]`` rows,
        sentinel key = ``padded`` → dropped): each device densifies ITS
        rank's stream with a segment-sum scatter before the identical
        reduce leg — one cached program per power-of-two L bucket."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from minips_tpu.ops.quantized_comm import (
            quantized_psum_scatter, quantized_psum_scatter_ef)
        from minips_tpu.utils import jaxcompat

        dim = self.dim
        lr = np.float32(self.lr)
        eps = np.float32(self.eps)
        b1 = np.float32(self.beta1)
        b2 = np.float32(self.beta2)
        one_m_b1 = np.float32(1) - b1
        one_m_b2 = np.float32(1) - b2
        comm = "float32" if exact else self.plane.comm
        block = self.plane.block
        # EF only rides the lossy leg: the exact (fence) program ships
        # f32 and must NOT re-capture a residual — it repays one
        ef = bool(self.plane.mesh_ef and comm == "blk8")
        upd = self.updater
        S = P(MESH_AXIS)

        def _reduce(g_mine):
            # g_mine [padded, dim]: my rank's full-row-space contribution;
            # the reduce-scatter leaves me the summed rows I own. Second
            # return is this device's compression residual (EF mode) —
            # what the quantizer did NOT ship, folded into the next wave
            if comm == "float32":
                return jax.lax.psum_scatter(
                    g_mine, MESH_AXIS, scatter_dimension=0,
                    tiled=True), None
            if ef:
                red, resid = quantized_psum_scatter_ef(
                    g_mine.reshape(-1), MESH_AXIS, comm="int8",
                    block=block)
                return red.reshape(-1, dim), resid.reshape(g_mine.shape)
            red = quantized_psum_scatter(
                g_mine.reshape(-1), MESH_AXIS, comm="int8", block=block)
            return red.reshape(-1, dim), None

        def _out(full, resid):
            # resid rides out stacked over the shard axis ([1,...] per
            # device -> [n,...]); non-EF programs keep the bare-full
            # output shape so their jitted artifacts are untouched
            return (full, resid[None]) if ef else full

        if upd == "sgd":
            def body(w, g_stack):
                g, resid = _reduce(g_stack[0])
                w = w - lr * g
                full = jax.lax.all_gather(w, MESH_AXIS, axis=0,
                                          tiled=True)
                return (w,), _out(full, resid)
            n_state = 1
        elif upd == "adagrad":
            def body(w, acc, g_stack):
                g, resid = _reduce(g_stack[0])
                acc = acc + g * g
                w = w - lr * g / (jnp.sqrt(acc) + eps)
                full = jax.lax.all_gather(w, MESH_AXIS, axis=0,
                                          tiled=True)
                return (w, acc), _out(full, resid)
            n_state = 2
        else:
            def body(w, m, v, steps, g_stack, t_stack):
                # lazy adam: the touch-mask reduce keeps untouched rows'
                # moments and step counters frozen, matching the wire's
                # per-key server semantics (sharded_ps._adam_rows)
                g, resid = _reduce(g_stack[0])
                t = jax.lax.psum_scatter(
                    t_stack[0], MESH_AXIS, scatter_dimension=0,
                    tiled=True)
                mask = t > 0
                mcol = mask[:, None]
                steps = steps + mask.astype(jnp.int32)
                m = jnp.where(mcol, b1 * m + one_m_b1 * g, m)
                v = jnp.where(mcol, b2 * v + one_m_b2 * (g * g), v)
                tf = steps.astype(jnp.float32)[:, None]
                bc1 = np.float32(1) - b1 ** tf
                bc2 = np.float32(1) - b2 ** tf
                w = jnp.where(
                    mcol,
                    w - lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps), w)
                full = jax.lax.all_gather(w, MESH_AXIS, axis=0,
                                          tiled=True)
                if ef:
                    # rows NO rank touched this wave skip the update
                    # entirely — shipped mass for them is discarded by
                    # the where, so the residual keeps the FULL input
                    # (nothing landed), not input - sent; without this
                    # a residual-only row would leak its mass
                    mask_full = jax.lax.all_gather(
                        mask, MESH_AXIS, axis=0, tiled=True)
                    resid = jnp.where(mask_full[:, None], resid,
                                      g_stack[0])
                return (w, m, v, steps), _out(full, resid)
            n_state = 4

        if ef:
            # the retained residual stays a DEVICE array between waves
            # (r_stack, last input): folding on device instead of a
            # host-side _gbuf + _rbuf add keeps the wave's hot path
            # free of a full-buffer device->host->device round trip
            # per wave — the residual only ever crosses to the host
            # for the one-time fence and the stats probe
            inner = body
            if upd == "adam":
                def body(w, m, v, steps, g_stack, t_stack, r_stack):
                    return inner(w, m, v, steps, g_stack + r_stack,
                                 t_stack)
            elif upd == "adagrad":
                def body(w, acc, g_stack, r_stack):
                    return inner(w, acc, g_stack + r_stack)
            else:
                def body(w, g_stack, r_stack):
                    return inner(w, g_stack + r_stack)

        if sparse_len is not None:
            # COO front end: densify my rank's staged stream on device
            # (scatter-add; the sentinel key == padded is out of range
            # and mode="drop" discards it), then run the identical
            # dense body — adam's touch mask is the scatter of ones
            # over the same keys, so semantics are byte-for-byte the
            # dense path's
            padded = self.padded

            def _densify(k, v):
                return jnp.zeros((padded, dim), jnp.float32
                                 ).at[k].add(v, mode="drop")

            def _touch(k):
                return jnp.zeros((padded,), jnp.float32
                                 ).at[k].add(1.0, mode="drop")

            dense_body = body
            if upd == "adam":
                def body(w, m, v, steps, k_stack, v_stack, *rest):
                    g = _densify(k_stack[0], v_stack[0])
                    t = _touch(k_stack[0])
                    return dense_body(w, m, v, steps, g[None], t[None],
                                      *rest)
            elif upd == "adagrad":
                def body(w, acc, k_stack, v_stack, *rest):
                    g = _densify(k_stack[0], v_stack[0])
                    return dense_body(w, acc, g[None], *rest)
            else:
                def body(w, k_stack, v_stack, *rest):
                    g = _densify(k_stack[0], v_stack[0])
                    return dense_body(w, g[None], *rest)
            n_in = n_state + 2 + (1 if ef else 0)
        else:
            n_in = (n_state + (2 if upd == "adam" else 1)
                    + (1 if ef else 0))
        # check_vma/check_rep off: the all-gathered output is replicated
        # by construction, but older checkers cannot infer it through
        # the quantized a2a path
        mapped = jaxcompat.shard_map(
            body, mesh=self.plane.mesh, in_specs=(S,) * n_in,
            out_specs=((S,) * n_state, ((P(), S) if ef else P())),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=tuple(range(n_state)))

    def _deposit(self, rank: int, keys: np.ndarray,
                 grads: np.ndarray) -> None:
        """Coalesce duplicates via THE shared client-side dedup kernel
        (sharded_ps.sum_duplicate_keys — the bitwise-parity drill
        depends on both planes summing identically), then accumulate
        into the rank's buffer."""
        from minips_tpu.train.sharded_ps import sum_duplicate_keys

        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32).reshape(keys.size, self.dim)
        if keys.size and (keys.min() < 0 or keys.max() >= self.num_rows):
            raise ValueError("push keys outside the table's key space")
        uniq, summed, _ = sum_duplicate_keys(keys, grads, self.dim)
        if self._ckeys is not None:
            # sparse: stage the deduped COO slice; cross-deposit
            # duplicates coalesce on device (two-term f32 adds are
            # commutative, so the wave equals the dense accumulate)
            self._ckeys[rank].append(np.asarray(uniq, np.int64))
            self._cvals[rank].append(
                np.ascontiguousarray(summed, np.float32))
        else:
            np.add.at(self._gbuf[rank], uniq, summed)
            if self._tstack is not None:
                self._tstack[rank][uniq] = 1.0
        self._dirty[rank] = True
        self.rows_pushed += keys.size

    def _deposit_dense(self, rank: int, grad: np.ndarray) -> None:
        grad = np.asarray(grad, np.float32).reshape(-1, self.dim)
        if grad.shape[0] != self.num_rows:
            raise ValueError(
                f"push_dense expects [{self.num_rows}, {self.dim}]")
        if self._ckeys is not None:
            # a dense push touches every row — COO staging degrades to
            # the full key list (dense workloads should run deposit=
            # dense; the sparse plane stays correct, not clever)
            self._ckeys[rank].append(
                np.arange(self.num_rows, dtype=np.int64))
            self._cvals[rank].append(
                np.ascontiguousarray(grad, np.float32))
        else:
            self._gbuf[rank, : self.num_rows] += grad
            if self._tstack is not None:
                self._tstack[rank, : self.num_rows] = 1.0
        self._dirty[rank] = True
        self.rows_pushed += self.num_rows

    def _wave_locked(self, *, fence: bool = False) -> None:
        """One apply wave: ship the pre-stacked deposits (clean ranks
        contribute exact zeros), reduce-scatter + sharded update +
        all-gather in one jitted program, refresh the pull mirror, zero
        the dirty rows. EF mode folds the retained residual into the
        input and re-captures the wave's new residual; ``fence=True``
        swaps in the exact-f32 program (built lazily — the repayment
        wave at finalize, after which the residual is zero by
        construction). Caller holds the plane lock."""
        import jax

        if self._ckeys is not None and not fence:
            self._wave_sparse_locked()
            return
        t_wave0 = time.monotonic()
        n = self.plane.num_ranks
        ef = self._rbuf is not None
        g_in = self._gbuf
        if ef and fence:
            # the exact program has no r_stack input — fold the
            # residual on the host for this one-time repayment wave.
            # Sparse mode densifies any still-staged COO here too (the
            # fence is the one wave that MUST see a dense input — the
            # honest limit the architecture doc states): at finalize
            # the per-rank flushes already drained the stages, so this
            # is normally residual-only
            if self._ckeys is not None:
                g_in = np.zeros((n, self.padded, self.dim), np.float32)
                for r in range(n):
                    for k, v in zip(self._ckeys[r], self._cvals[r]):
                        np.add.at(g_in[r], k, v)
                g_in += np.asarray(self._rbuf)
            else:
                g_in = self._gbuf + np.asarray(self._rbuf)
        t_in = self._tstack
        fn = self._wave_fn
        extra = ()
        if ef and not fence:
            # residual rides as a device-resident input (a no-op put
            # when it is last wave's output, already stack-sharded)
            extra = (jax.device_put(self._rbuf, self._stack_sh),)
        if fence:
            if self._fence_fn is None:
                self._fence_fn = self._build_wave_fn(exact=True)
            fn = self._fence_fn
            if ef and self.updater == "adam":
                # the fence repays residual as a real (exact) push:
                # residual-only rows must pass the lazy-adam touch mask,
                # exactly like the wire's f32 residual fence arrives as
                # a normal push frame and advances server state
                mass = (np.abs(g_in).sum(axis=-1) > 0
                        ).astype(np.float32)
                t_in = (mass if t_in is None
                        else np.maximum(t_in, mass))
        g_stack = jax.device_put(g_in, self._stack_sh)
        if self.updater == "sgd":
            (self._w,), out = fn(self._w, g_stack, *extra)
        elif self.updater == "adagrad":
            (self._w, self._acc), out = fn(self._w, self._acc,
                                           g_stack, *extra)
        else:
            t_stack = jax.device_put(t_in, self._stack_sh)
            (self._w, self._m, self._v, self._steps), out = \
                fn(self._w, self._m, self._v, self._steps,
                   g_stack, t_stack, *extra)
        if ef and not fence:
            full, resid = out
            self._rbuf = resid  # stays on device until fence/stats
            self.ef_waves += 1
        else:
            full = out
            if ef:
                # repaid: reset to device-born zeros (explicit shape —
                # sparse mode has no _gbuf to zeros_like)
                import jax.numpy as jnp
                self._rbuf = jax.device_put(
                    jnp.zeros((n, self.padded, self.dim), jnp.float32),
                    self._stack_sh)
                self.ef_fence_waves += 1
        mirror = np.asarray(full)
        mirror.setflags(write=False)
        self._mirror = mirror
        for r in range(self.plane.num_ranks):
            if self._dirty[r]:
                if self._gbuf is not None:
                    self._gbuf[r].fill(0.0)
                    if self._tstack is not None:
                        self._tstack[r].fill(0.0)
                else:
                    self._ckeys[r].clear()
                    self._cvals[r].clear()
                self._dirty[r] = False
        self.waves += 1
        self.collective_bytes += self._wave_bytes()
        # the step-phase observable: one wave = one collective program
        # dispatch; its duration hist feeds the plane's windowed layer
        self.plane.hist_wave.record_s(time.monotonic() - t_wave0)

    def _wave_sparse_locked(self) -> None:
        """Sparse apply wave: pack each rank's staged COO stream into
        ``[n, L]`` keys + ``[n, L, dim]`` rows (pad slots carry the
        sentinel key ``padded`` — out of range, dropped by the device
        scatter's ``mode="drop"``), densify ON DEVICE with a
        segment-sum scatter, then run the identical reduce/update/
        gather body. ``L`` rounds up to a power of two so recompiles
        stay O(log max-touched); peak host bytes are the staged slices
        plus these stacks — they scale with the TOUCHED set, never
        ``num_rows``. Caller holds the plane lock."""
        import jax

        t_wave0 = time.monotonic()
        n = self.plane.num_ranks
        ef = self._rbuf is not None
        counts = [sum(k.size for k in self._ckeys[r]) for r in range(n)]
        need = max(max(counts), 1)
        # MONOTONIC stack length: grow-only, so a touched-set count
        # that oscillates across waves reuses ONE compiled program
        # instead of ping-ponging between L buckets (each bucket is a
        # fresh XLA compile — worth 10-100ms, easily dwarfing the wave)
        L = self._wave_len
        while L < need:
            L *= 2
        self._wave_len = L
        k_stack = np.full((n, L), self.padded, np.int32)
        v_stack = np.zeros((n, L, self.dim), np.float32)
        for r in range(n):
            o = 0
            for k, v in zip(self._ckeys[r], self._cvals[r]):
                k_stack[r, o:o + k.size] = k
                v_stack[r, o:o + k.size] = v
                o += k.size
        staged = sum(k.nbytes + v.nbytes
                     for r in range(n)
                     for k, v in zip(self._ckeys[r], self._cvals[r]))
        self.peak_deposit_bytes = max(
            self.peak_deposit_bytes,
            staged + k_stack.nbytes + v_stack.nbytes)
        fn = self._wave_fns.get(L)
        if fn is None:
            fn = self._wave_fns[L] = self._build_wave_fn(sparse_len=L)
        ks = jax.device_put(k_stack, self._stack_sh)
        vs = jax.device_put(v_stack, self._stack_sh)
        extra = ()
        if ef:
            extra = (jax.device_put(self._rbuf, self._stack_sh),)
        if self.updater == "sgd":
            (self._w,), out = fn(self._w, ks, vs, *extra)
        elif self.updater == "adagrad":
            (self._w, self._acc), out = fn(self._w, self._acc,
                                           ks, vs, *extra)
        else:
            (self._w, self._m, self._v, self._steps), out = \
                fn(self._w, self._m, self._v, self._steps,
                   ks, vs, *extra)
        if ef:
            full, resid = out
            self._rbuf = resid
            self.ef_waves += 1
        else:
            full = out
        mirror = np.asarray(full)
        mirror.setflags(write=False)
        self._mirror = mirror
        for r in range(n):
            if self._dirty[r]:
                self._ckeys[r].clear()
                self._cvals[r].clear()
                self._dirty[r] = False
        self.waves += 1
        self.sparse_waves += 1
        self.collective_bytes += self._wave_bytes()
        self.plane.hist_wave.record_s(time.monotonic() - t_wave0)

    def _wave_bytes(self) -> int:
        """Collective bytes one wave moves, summed over ranks: ring
        reduce-scatter + ring all-gather each move (n-1)/n of the buffer
        per rank; the blk8 reduce leg ships codes + blockwise scales
        (the shared ``blockwise_stream_bytes`` bill) instead of f32."""
        from minips_tpu.ops.quantized_comm import blockwise_stream_bytes

        n = self.plane.num_ranks
        full = self.padded * self.dim * 4
        gather = (n - 1) * full  # (n-1)/n per rank, n ranks
        if self.plane.comm == "blk8":
            code, scale = blockwise_stream_bytes(
                self.padded, self.dim, 8, self.plane.block)
            reduce = (n - 1) * (code + scale)
        else:
            reduce = (n - 1) * full
        return reduce + gather

    # ------------------------------------------------------- rank-facing
    def push(self, rank: int, keys: np.ndarray,
             grads: np.ndarray) -> None:
        plane = self.plane
        with plane._cond:
            self._deposit(rank, keys, grads)
            plane._maybe_wave_locked(self)

    def push_dense(self, rank: int, grad: np.ndarray) -> None:
        plane = self.plane
        with plane._cond:
            self._deposit_dense(rank, grad)
            plane._maybe_wave_locked(self)

    def pull(self, rank: int, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64)
        if keys.size and (keys.min() < 0
                          or keys.max() >= self.num_rows):
            # same contract as the wire plane (a misrouted pull is
            # refused, never served): without this a padding row or a
            # numpy-wrapped negative index would silently read zeros
            raise ValueError("pull keys outside the table's key space")
        plane = self.plane
        with plane._cond:
            plane._admit_locked(rank)
            if self._dirty[rank]:  # read-your-own-writes: flush first
                self._wave_locked()
            self.rows_pulled += keys.size
            return self._mirror[keys].copy()

    def pull_all(self, rank: int) -> np.ndarray:
        """Full-table read: a READ-ONLY view of the current pull mirror
        (waves REPLACE the mirror, never mutate it, so the view is a
        stable snapshot — and the full-table hot path pays zero copy,
        exactly the all-gather-once-per-wave story)."""
        plane = self.plane
        with plane._cond:
            plane._admit_locked(rank)
            if self._dirty[rank]:
                self._wave_locked()
            self.rows_pulled += self.num_rows
            return self._mirror[: self.num_rows]

    def load_dense(self, w: np.ndarray) -> None:
        """Install a full [num_rows, dim] weight table (drill/checkpoint
        seeding) — re-sharded onto the mesh, mirror refreshed."""
        import jax
        import jax.numpy as jnp

        w = np.asarray(w, np.float32).reshape(self.num_rows, self.dim)
        padded = np.zeros((self.padded, self.dim), np.float32)
        padded[: self.num_rows] = w
        with self.plane._cond:
            self._w = jax.device_put(jnp.asarray(padded), self._row_sh)
            padded.setflags(write=False)
            self._mirror = padded

    def shard_slice(self, rank: int) -> np.ndarray:
        """Rank ``rank``'s owner rows of the CURRENT table (mirror read)
        — the per-rank final-state view the lockstep drill compares
        against the wire tables' local shards."""
        with self.plane._cond:
            lo = rank * self.shard_rows
            hi = min(lo + self.shard_rows, self.num_rows)
            return self._mirror[lo:hi].copy()

    def ef_stats(self) -> Optional[dict]:
        """blk8 error-feedback accounting — None when EF is off (the
        off-vs-idle convention every wire stats block keeps); resident
        rows are the residual mass currently awaiting its next fold."""
        if self._rbuf is None:
            return None
        return {
            "folded_waves": int(self.ef_waves),
            "fence_waves": int(self.ef_fence_waves),
            "resident_rows": int(
                (np.abs(self._rbuf).sum(axis=-1) > 0).sum()),
        }

    def local_bytes(self) -> int:
        """Device bytes of table + updater state PER SHARD — the same
        ~1/N claim as the wire table's local_bytes."""
        n = self.shard_rows * self.dim * 4
        if self._acc is not None:
            n += self.shard_rows * self.dim * 4
        if self._m is not None:
            n += 2 * self.shard_rows * self.dim * 4 + self.shard_rows * 4
        return n


class MeshRank:
    """A logical rank's handle on the plane: the per-rank API surface
    the wire path spreads across (ShardedTable, ShardedPSTrainer)."""

    def __init__(self, plane: "MeshPlane", rank: int):
        self.plane = plane
        self.rank = rank
        self.tables = _RankTables(plane, rank)

    @property
    def clock(self) -> int:
        return int(self.plane._clk_host[self.rank])

    @property
    def staleness(self) -> float:
        return self.plane.staleness

    def tick(self, *, wait: bool = True) -> None:
        self.plane.tick(self.rank, wait=wait)

    def finalize(self, timeout: float = 30.0) -> None:
        self.plane.finalize(self.rank, timeout=timeout)


class _RankTables:
    def __init__(self, plane, rank):
        self._plane, self._rank = plane, rank

    def __getitem__(self, name: str) -> "_BoundTable":
        return _BoundTable(self._plane.tables[name], self._rank)

    def __iter__(self):
        return iter(self._plane.tables)


class _BoundTable:
    """MeshTable with the rank argument bound — pull/push read like the
    wire ShardedTable's client surface."""

    def __init__(self, table: MeshTable, rank: int):
        self._t, self._r = table, rank

    def __getattr__(self, item):
        return getattr(self._t, item)

    def pull(self, keys):
        return self._t.pull(self._r, keys)

    def pull_all(self):
        return self._t.pull_all(self._r)

    def push(self, keys, grads):
        self._t.push(self._r, keys, grads)

    def push_dense(self, grad):
        self._t.push_dense(self._r, grad)


class MeshPlane:
    """The gang: one process, ``num_ranks`` logical ranks mapped onto
    ``num_ranks`` mesh devices, tables sharded across all of them.

    Construction order: ``MeshPlane(...)`` → ``add_table(...)`` per
    table → ``rank(r)`` handles for the worker threads. BSP/SSP comes
    from ``staleness`` exactly like the wire trainer's; the gate is the
    shared ``admits`` predicate over the plane's device-side clock
    vector."""

    def __init__(self, num_ranks: int, *, staleness: float = 0.0,
                 comm: str = "float32", block: Optional[int] = None,
                 deposit: Optional[str] = None, devices=None,
                 gate_timeout: float = 60.0):
        if comm not in VALID_MESH_COMM:
            raise ValueError(f"mesh comm must be one of "
                             f"{VALID_MESH_COMM}, got {comm!r}")
        if staleness < 0:
            raise ValueError("staleness must be >= 0")
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        from minips_tpu.ops.quantized_comm import HOST_BLOCK

        devs = list(devices) if devices is not None else list(jax.devices())
        if len(devs) < num_ranks:
            raise ValueError(
                f"mesh plane needs {num_ranks} devices, have "
                f"{len(devs)} — set "
                f"--xla_force_host_platform_device_count on CPU")
        self.num_ranks = int(num_ranks)
        self.staleness = float(staleness)
        self.comm = comm
        # the quantized tier defaults to the HOST wire's block size:
        # one codec (blockwise absmax), two transports
        self.block = int(HOST_BLOCK if block is None else block)
        # deposit buffer shape: dense pre-stacked host buffers vs COO
        # staging + on-device segment-sum densify (sparse device waves)
        self.deposit = resolve_deposit(deposit)
        # error feedback on the blk8 reduce leg (default ON): each
        # device retains its quantization residual and folds it into
        # the next wave — unbiased in the limit, exact repayment at
        # finalize. MINIPS_MESH_EF=0 is the kill switch (A/B arm);
        # float32 ships exactly, nothing to feed back
        self.mesh_ef = (comm == "blk8"
                        and os.environ.get("MINIPS_MESH_EF",
                                           "1").strip() != "0")
        self.gate_timeout = float(gate_timeout)
        self.mesh = Mesh(np.array(devs[: self.num_ranks]), (MESH_AXIS,))
        self._rep_sh = NamedSharding(self.mesh, P())
        self.tables: dict[str, MeshTable] = {}
        self._cond = threading.Condition(threading.RLock())
        # the device-side clock vector: pull admission and the SSP gate
        # evaluate min() of THIS array (gate.admits, the one predicate)
        # int32 on device (x64 is off repo-wide); RETIRED_CLOCK = 2^30
        # fits with headroom
        self._clk_dev = jax.device_put(
            jnp.zeros(self.num_ranks, jnp.int32), self._rep_sh)
        self._clk_host = np.zeros(self.num_ranks, np.int64)
        self._retired = np.zeros(self.num_ranks, bool)
        self.gate_waits = 0
        self.max_skew_seen = 0
        # ---- observability: always-on step-PHASE histograms (apply-
        # wave duration, tick-gate blocked time) + the windowed layer
        # over them — the mesh plane's analog of the wire trainer's
        # hist/window blocks; MINIPS_OBS=0 disables the window only
        # (the tax arm), the hists are as free as the wire's
        self.hist_wave = Log2Histogram()
        self.hist_gate = Log2Histogram()
        self.obs_window = _ow.maybe_build()
        if self.obs_window is not None:
            self.obs_window.register_hist(
                "wave", lambda: self.hist_wave.snapshot())
            self.obs_window.register_hist(
                "gate", lambda: self.hist_gate.snapshot())
            self.obs_window.register_counter(
                "waves", lambda: sum(t.waves
                                     for t in self.tables.values()))
            self.obs_window.register_counter(
                "collective_bytes",
                lambda: sum(t.collective_bytes
                            for t in self.tables.values()))

    # ------------------------------------------------------------- setup
    def add_table(self, name: str, num_rows: int, dim: int,
                  **kwargs) -> MeshTable:
        if name in self.tables:
            raise ValueError(f"table {name!r} already exists")
        t = MeshTable(self, name, num_rows, dim, **kwargs)
        self.tables[name] = t
        return t

    def rank(self, r: int) -> MeshRank:
        if not 0 <= r < self.num_ranks:
            raise ValueError(f"rank {r} out of range")
        return MeshRank(self, r)

    # -------------------------------------------------------- gang logic
    def _global_min(self) -> int:
        """min of the clock vector — the freshness certificate the
        admission predicate runs on (the mesh analog of
        ClockGossip.global_min). Reads the host mirror: it is updated
        in lockstep with the device vector under the plane lock
        (bitwise the same values), and the gate wait loops poll this
        every iteration — a jitted device reduction per poll would put
        dispatch churn on the admission hot path for no information.
        Once the poll passes, admission CERTIFIES against the device
        vector (:meth:`_device_min` — one dispatch per admission, not
        per poll), so the predicate's final word is device state."""
        return int(self._clk_host.min())

    def _device_min(self) -> int:
        """min of the DEVICE-side clock vector — the authoritative
        replicated copy every clock write updates under the plane
        lock; the admission certificate reads THIS."""
        return int(self._clk_dev.min())

    def clocks(self) -> np.ndarray:
        """Host copy of the device-side clock vector (tests/obs)."""
        return np.asarray(self._clk_dev)

    def _maybe_wave_locked(self, table: MeshTable) -> None:
        """Fire the apply wave eagerly once every live rank deposited —
        the full wave is the natural BSP barrier and keeps the state
        fresh without waiting for the tick boundary."""
        live = [r for r in range(self.num_ranks) if not self._retired[r]]
        if live and all(table._dirty[r] for r in live):
            table._wave_locked()
            self._cond.notify_all()

    def _admit_locked(self, rank: int) -> bool:
        """Pull admission: wait until ``admits(min(clock_vec), clk, s)``
        — the owner-side park rule. The host mirror screens each poll;
        the admission that actually serves is certified against the
        DEVICE clock vector."""
        clk = int(self._clk_host[rank])
        if not admits(self._global_min(), clk, self.staleness):
            self.gate_waits += 1
            deadline = time.monotonic() + self.gate_timeout
            while not admits(self._global_min(), clk, self.staleness):
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        f"mesh plane gate timed out at clock {clk} "
                        f"(global_min={self._global_min()}, "
                        f"staleness={self.staleness})")
                self._cond.wait(timeout=min(0.2, left))
        if not admits(self._device_min(), clk, self.staleness):
            # cannot happen while mirror and device update under one
            # lock — but the predicate's final word is device state,
            # so a torn update surfaces as a loud refusal, not a
            # silently-early read
            raise RuntimeError(
                "mesh clock mirror ahead of the device vector "
                f"({self._clk_host.tolist()} vs {self.clocks().tolist()})")
        return True

    def _flush_rank_locked(self, rank: int) -> None:
        """Flush rank ``rank``'s deposits ahead of a clock advance.
        Under BSP every live rank deposits every step, so a solo flush
        here would triple the wave count (one per rank's tick instead
        of one full wave per step — measured 2-3x off the fused bench):
        give the eager full wave a short grace to fire first (peers'
        pushes run while we cond-wait), then flush whatever is left —
        correctness (pushes before clock) never depends on the grace."""
        if not any(t._dirty[rank] for t in self.tables.values()):
            return
        if self.staleness == 0:
            deadline = time.monotonic() + _BSP_FLUSH_GRACE
            while any(t._dirty[rank] for t in self.tables.values()):
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=min(0.01, left))
        for t in self.tables.values():
            if t._dirty[rank]:
                t._wave_locked()
                self._cond.notify_all()

    def tick(self, rank: int, *, wait: bool = True) -> None:
        """Clock boundary: flush the rank's deposits (an apply wave —
        its step-k pushes enter the shared state BEFORE the clock reads
        k), advance the device-side clock vector, then gate
        (BSP/SSP/ASP rule) unless ``wait=False`` (single-threaded
        drivers gate at pull admission instead)."""
        poison_args = None
        try:
            with self._cond:
                self._flush_rank_locked(rank)
                new = int(self._clk_host[rank]) + 1
                self._clk_host[rank] = new
                self._clk_dev = self._clk_dev.at[rank].set(new)
                self._cond.notify_all()
                if rank == 0 and self.obs_window is not None:
                    # one roll per full clock (rank 0's boundary): the
                    # plane's windowed intervals track steps like the
                    # wire trainer's tick-time roll
                    self.obs_window.roll()
                # skew is recorded in EVERY mode (ASP and wait=False
                # included) — the observable must not go vacuous just
                # because the gate does not block
                self.max_skew_seen = max(self.max_skew_seen,
                                         new - self._global_min())
                if not wait or self.staleness == float("inf"):
                    return
                threshold = new - int(self.staleness)
                t_gate0 = time.monotonic()
                if self._global_min() < threshold:
                    self.gate_waits += 1
                deadline = time.monotonic() + self.gate_timeout
                try:
                    while self._global_min() < threshold:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            poison_args = {
                                "rank": rank, "clock": new,
                                "global_min": self._global_min(),
                                "staleness": self.staleness}
                            raise TimeoutError(
                                f"mesh plane gate timed out at clock "
                                f"{new} "
                                f"(global_min={self._global_min()}, "
                                f"staleness={self.staleness})")
                        self._cond.wait(timeout=min(0.2, left))
                finally:
                    self.hist_gate.record_s(time.monotonic() - t_gate0)
                if self._device_min() < threshold:  # certify: device
                    raise RuntimeError(
                        "mesh clock mirror ahead of the device vector "
                        f"({self._clk_host.tolist()} vs "
                        f"{self.clocks().tolist()})")
        except TimeoutError:
            # the dump is file I/O: it must not run under the plane
            # lock (every other rank's tick would block behind it —
            # the same outside-the-lock rule comm/reliable.py keeps)
            if poison_args is not None:
                _fl.poison("mesh_gate_deadline", poison_args)
            raise

    def finalize(self, rank: int, timeout: float = 30.0) -> None:
        """Flush, retire (the shared RETIRED_CLOCK sentinel so nobody
        gates on a finished rank), and barrier until every rank
        finalized — after which pull/pull_all return identical rows for
        every rank (there is only ONE state; the barrier guarantees it
        contains everyone's mass)."""
        poison_args = None
        try:
            with self._cond:
                for t in self.tables.values():
                    if t._dirty[rank]:
                        t._wave_locked()
                self._retired[rank] = True
                self._clk_host[rank] = RETIRED_CLOCK
                self._clk_dev = self._clk_dev.at[rank].set(
                    RETIRED_CLOCK)
                if self._retired.all():
                    # LAST rank out repays the blk8 EF residual with one
                    # exact-f32 fence wave per table that still holds
                    # mass — nobody deposits after this point, and the
                    # finalize barrier below means every rank returns
                    # AFTER the repayment refreshed the mirror: no
                    # gradient mass is stranded in the residual at exit
                    # (the wire ResidualStore's fence contract)
                    for t in self.tables.values():
                        if (t._rbuf is not None
                                and np.any(t._rbuf)):
                            t._wave_locked(fence=True)
                self._cond.notify_all()
                deadline = time.monotonic() + timeout
                while not self._retired.all():
                    left = deadline - time.monotonic()
                    if left <= 0:
                        missing = [r for r in range(self.num_ranks)
                                   if not self._retired[r]]
                        poison_args = {"rank": rank,
                                       "missing": missing}
                        raise TimeoutError(
                            f"mesh finalize: ranks {missing} never "
                            "retired")
                    self._cond.wait(timeout=min(0.2, left))
        except TimeoutError:
            if poison_args is not None:  # dump OUTSIDE the plane lock
                _fl.poison("mesh_finalize_deadline", poison_args)
            raise

    def stats(self) -> dict:
        return {
            "plane": "mesh",
            "comm": self.comm,
            "block": self.block if self.comm == "blk8" else None,
            "deposit": self.deposit,
            "ranks": self.num_ranks,
            "devices": len(self.mesh.devices.ravel()),
            "waves": {n: t.waves for n, t in self.tables.items()},
            # peak host bytes the deposit stage held (dense: the fixed
            # pre-stacked buffers; sparse: the high-water COO staging)
            "peak_deposit_bytes": {n: t.peak_deposit_bytes
                                   for n, t in self.tables.items()},
            "sparse_waves": sum(t.sparse_waves
                                for t in self.tables.values()),
            "collective_bytes": sum(t.collective_bytes
                                    for t in self.tables.values()),
            # blk8 reduce-leg error feedback: None when off
            # (float32 plane or MINIPS_MESH_EF=0), per-table
            # fold/fence/resident accounting when armed
            "ef": ({n: t.ef_stats()
                    for n, t in self.tables.items()}
                   if self.mesh_ef else None),
            "gate_waits": self.gate_waits,
            # step-phase hists + windowed layer, the wire trainer's
            # hist/window done-line convention ({"count": 0} idle,
            # None = window layer off)
            "hist": {"wave_ms": summarize_counts(
                         self.hist_wave.snapshot()),
                     "gate_ms": summarize_counts(
                         self.hist_gate.snapshot())},
            "window": (self.obs_window.record()
                       if self.obs_window is not None else None),
        }


class MeshAggregator:
    """The hier leader's in-host reduce backend (``MINIPS_HIER``
    ``agg=mesh``): member contributions deposit as per-slot COO
    streams, and ONE device program — segment-sum densify per slot,
    then a reduce-scatter over the mesh axis (blk8 quantized tier with
    error-feedback residual out, or exact f32) — produces the
    aggregate the leader ships cross-host. This swaps PR 16's
    host-side per-owner f64 dedup loop for XLA collectives while the
    CROSS-host leg (one topk8/topk4 ``psH`` frame per owner) is
    untouched: the reduce-scatter never leaves the host's mesh, so
    cross-host bytes are identical by construction.

    Degenerate meshes (fewer than 2 usable devices, or
    ``MINIPS_HIER_MESH_DEVS=1``) reduce on the host via THE shared
    dedup kernel in the exact deposit order the f64 path uses —
    bitwise-equal to ``agg=host`` (the stamp-folding test pins it).
    The ``reduce()`` residual return feeds the leader's ResidualStore
    so the unbiased-flush contract holds end-to-end."""

    def __init__(self, num_rows: int, dim: int, *, slots: int,
                 comm: str = "blk8", block: Optional[int] = None,
                 devices=None):
        if comm not in VALID_MESH_COMM:
            raise ValueError(f"aggregator comm must be one of "
                             f"{VALID_MESH_COMM}, got {comm!r}")
        import jax

        from minips_tpu.ops.quantized_comm import HOST_BLOCK

        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.block = int(HOST_BLOCK if block is None else block)
        devs = (list(devices) if devices is not None
                else list(jax.devices()))
        m = min(int(slots), len(devs))
        cap = os.environ.get("MINIPS_HIER_MESH_DEVS", "").strip()
        if cap:
            m = min(m, max(int(cap), 1))
        self.m = max(m, 1)
        # one usable device -> nothing to reduce-scatter ACROSS: the
        # degenerate tier is the host dedup kernel, and it reports
        # comm=float32 because that is what it ships (exactly)
        self.comm = comm if self.m >= 2 else "float32"
        self.reduces = 0
        self.rows_reduced = 0
        self.collective_bytes = 0
        self.peak_stage_bytes = 0
        self._staged: list = [[] for _ in range(self.m)]
        self._order: list = []  # (slot-stream flattening) deposit order
        self._L = 8             # grow-only stack length (see reduce())
        if self.m >= 2:
            from jax.sharding import Mesh
            self.mesh = Mesh(np.array(devs[: self.m]), (MESH_AXIS,))
            self.padded = _padded(self.num_rows, self.m)
            self._fns: dict = {}
        else:
            self.mesh = None
            self.padded = self.num_rows
            self._fns = None

    def _build_reduce_fn(self, L: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from minips_tpu.ops.quantized_comm import \
            quantized_psum_scatter_ef
        from minips_tpu.utils import jaxcompat

        padded, dim = self.padded, self.dim
        comm = "int8" if self.comm == "blk8" else "float32"
        block = self.block
        S = P(MESH_AXIS)

        def body(k_stack, v_stack):
            # densify my slot's COO stream (sentinel key == padded is
            # dropped), then reduce-scatter across slots — the same
            # one-signature EF collective the mesh plane's wave runs:
            # float32 returns exact zeros for the residual, so the
            # caller never branches on the codec
            dense = jnp.zeros((padded, dim), jnp.float32
                              ).at[k_stack[0]].add(v_stack[0],
                                                   mode="drop")
            red, resid = quantized_psum_scatter_ef(
                dense.reshape(-1), MESH_AXIS, comm=comm, block=block)
            return red.reshape(-1, dim), resid.reshape(padded, dim)[None]

        mapped = jaxcompat.shard_map(
            body, mesh=self.mesh, in_specs=(S, S), out_specs=(S, S),
            check_vma=False)
        return jax.jit(mapped)

    def deposit(self, slot: int, keys: np.ndarray,
                grads: np.ndarray) -> None:
        """Stage one member contribution. ``slot`` is the member's
        index within the host group (wrapped onto the mesh)."""
        keys = np.asarray(keys, np.int64)
        grads = np.asarray(grads, np.float32).reshape(keys.size,
                                                      self.dim)
        if keys.size == 0:
            return
        if keys.min() < 0 or keys.max() >= self.num_rows:
            raise ValueError("aggregator keys outside the key space")
        self._staged[slot % self.m].append((keys, grads))
        self._order.append((keys, grads))

    def reduce(self):
        """Run the reduce over everything staged since the last call.

        Returns ``(keys, rows, resid_keys, resid_rows)``: the touched
        keys with their aggregated rows, plus the quantizer's residual
        (what the blk8 exchange did NOT ship) for the leader's
        ResidualStore. Exact tiers return empty residuals."""
        if not self._order:
            return (np.zeros(0, np.int64),
                    np.zeros((0, self.dim), np.float32),
                    np.zeros(0, np.int64),
                    np.zeros((0, self.dim), np.float32))
        from minips_tpu.train.sharded_ps import sum_duplicate_keys

        empty_r = (np.zeros(0, np.int64),
                   np.zeros((0, self.dim), np.float32))
        if self.m < 2:
            # host tier: concat in deposit order, THE shared f64 dedup
            # kernel — bitwise what agg=host would have shipped
            ks = np.concatenate([k for k, _ in self._order])
            gs = np.concatenate([g for _, g in self._order])
            self._staged = [[] for _ in range(self.m)]
            self._order = []
            k, g, _ = sum_duplicate_keys(ks, gs, self.dim)
            if k.size and not np.all(k[1:] >= k[:-1]):
                # the kernel keeps the ORIGINAL pairing when nothing
                # coalesced — reduce() contracts SORTED keys (callers
                # searchsorted into them), so restore the order the
                # dedup branch would have produced
                order = np.argsort(k, kind="stable")
                k, g = k[order], g[order]
            self.reduces += 1
            self.rows_reduced += int(k.size)
            return (k, g) + empty_r
        import jax

        counts = [sum(k.size for k, _ in s) for s in self._staged]
        need = max(max(counts), 1)
        # grow-only L: per-flush contribution counts jitter, and every
        # fresh L bucket is a fresh XLA compile — monotonic growth
        # keeps steady state on ONE compiled program
        L = self._L
        while L < need:
            L *= 2
        self._L = L
        k_stack = np.full((self.m, L), self.padded, np.int32)
        v_stack = np.zeros((self.m, L, self.dim), np.float32)
        for s in range(self.m):
            o = 0
            for k, v in self._staged[s]:
                k_stack[s, o:o + k.size] = k
                v_stack[s, o:o + k.size] = v
                o += k.size
        staged_bytes = sum(k.nbytes + g.nbytes for k, g in self._order)
        self.peak_stage_bytes = max(
            self.peak_stage_bytes,
            staged_bytes + k_stack.nbytes + v_stack.nbytes)
        touched = np.unique(np.concatenate(
            [k for k, _ in self._order]))
        self._staged = [[] for _ in range(self.m)]
        self._order = []
        fn = self._fns.get(L)
        if fn is None:
            fn = self._fns[L] = self._build_reduce_fn(L)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        sh = NamedSharding(self.mesh, P(MESH_AXIS))
        agg, resid = fn(jax.device_put(k_stack, sh),
                        jax.device_put(v_stack, sh))
        agg = np.asarray(agg)          # [padded, dim], owner-reassembled
        rows = agg[touched]
        if self.comm == "blk8":
            resid_total = np.asarray(resid).sum(axis=0)
            rk = np.flatnonzero(
                np.abs(resid_total).sum(axis=1) > 0)
            rk = rk[rk < self.num_rows]
            rrows = resid_total[rk]
            from minips_tpu.ops.quantized_comm import \
                blockwise_stream_bytes
            code, scale = blockwise_stream_bytes(
                self.padded, self.dim, 8, self.block)
            self.collective_bytes += (self.m - 1) * (code + scale)
        else:
            rk, rrows = empty_r
            self.collective_bytes += (
                (self.m - 1) * self.padded * self.dim * 4)
        self.reduces += 1
        self.rows_reduced += int(touched.size)
        return touched, rows, np.asarray(rk, np.int64), rrows

    def stats(self) -> dict:
        return {
            "backend": "mesh" if self.m >= 2 else "host-degenerate",
            "slots": self.m,
            "comm": self.comm,
            "reduces": int(self.reduces),
            "rows_reduced": int(self.rows_reduced),
            "collective_bytes": int(self.collective_bytes),
            "peak_stage_bytes": int(self.peak_stage_bytes),
        }
