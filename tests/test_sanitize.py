"""`make -C cpp sanitize` — the asan/tsan drill for the native components
(SURVEY.md §5.2, VERDICT r1 #8). Skips when the toolchain can't build
sanitized binaries (no compiler, or compiler without ASan/TSan runtimes —
common on slim images)."""

import os
import pathlib
import subprocess
import tempfile

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]


def _sanitizers_available() -> bool:
    """Probe-compile AND RUN a trivial -fsanitize program with the same
    compiler the Makefile will use ($CXX override honored): sandboxes
    without working ptrace/ASLR link sanitized binaries fine but abort
    them at startup, which must read as 'unavailable', not a failure."""
    cxx = os.environ.get("CXX", "g++")
    with tempfile.TemporaryDirectory() as td:
        src = pathlib.Path(td) / "probe.cpp"
        exe = pathlib.Path(td) / "probe"
        src.write_text("int main() { return 0; }\n")
        for flag in ("-fsanitize=address", "-fsanitize=thread"):
            try:
                r = subprocess.run(
                    [cxx, flag, "-o", str(exe), str(src)],
                    capture_output=True, timeout=60)
                if r.returncode != 0:
                    return False
                r = subprocess.run(
                    [str(exe)], capture_output=True, timeout=60,
                    env={**os.environ,
                         "ASAN_OPTIONS": "detect_leaks=1"})
            except (OSError, subprocess.TimeoutExpired):
                return False
            if r.returncode != 0:
                return False
    return True


@pytest.mark.slow
def test_native_components_clean_under_sanitizers():
    if not _sanitizers_available():
        pytest.skip("toolchain cannot link ASan/TSan binaries")
    proc = subprocess.run(
        ["make", "-C", "cpp", "sanitize"], capture_output=True, text=True,
        timeout=600, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "asan + tsan clean" in proc.stdout
