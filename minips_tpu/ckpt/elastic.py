"""Elastic resume — reshard rank-local checkpoints across WORLD SIZES.

The reference's recovery is relaunch at the SAME node count + per-server
Dump/Load (SURVEY.md §3.5: "no elastic resize, same as the reference's
fixed node set"). minips_tpu keeps that fast path untouched and adds an
elastic one on top: a job checkpointed by N processes can relaunch at
M != N. Each new rank reassembles its M-way row range from the
overlapping row slices of the N old shard files — parameters AND
optimizer state are row-aligned in a ShardedTable (w/acc/m/v per-row,
steps per-row), so ONE slicing rule re-partitions everything, adam
moments included. A grown world (M > N) and a shrunk one (M < N) are the
same math.

Requirements, stated honestly:

- ``checkpoint_dir`` must be a SHARED filesystem: a new rank reads OLD
  ranks' shard files. That is the assumption the reference's HDFS-backed
  dumps already make; per-host local dirs support only same-size resume
  (the existing fast path).
- resharding is only meaningful at the rank-dir layout
  ``<checkpoint_dir>/rank<r>/step_<s>/<table>.npz`` written by
  ``apps.common.shard_checkpointing``; the step chosen is the NEWEST one
  whose holders form a complete old world (rank dirs 0..k-1 all hold
  it) — a partial holder set means that incarnation's save was torn and
  is skipped.

After an elastic restore the caller should re-publish the resharded
state at the same step under its NEW rank dir (``Checkpointer.save``),
so the next crash resumes through the ordinary same-size path.
"""

from __future__ import annotations

import os
import re
import zipfile
from typing import Optional

import numpy as np
from numpy.lib import format as _npfmt


def _rank_dirs(checkpoint_dir: str) -> dict[int, str]:
    out = {}
    try:
        entries = os.listdir(checkpoint_dir)
    except OSError:
        return out
    for d in entries:
        m = re.fullmatch(r"rank(\d+)", d)
        if m and os.path.isdir(os.path.join(checkpoint_dir, d)):
            out[int(m.group(1))] = os.path.join(checkpoint_dir, d)
    return out


def _steps_in(rank_dir: str) -> set[int]:
    out = set()
    try:
        entries = os.listdir(rank_dir)
    except OSError:
        return out
    for d in entries:
        m = re.fullmatch(r"step_(\d+)", d)
        if m and os.path.exists(os.path.join(rank_dir, d, "manifest.json")):
            out.add(int(m.group(1)))
    return out


def _fits_partition(checkpoint_dir: str, step: int, r: int, tables: dict,
                    k: int) -> bool:
    """True iff rank ``r``'s files at ``step`` were saved under a
    ``k``-process partition (lo == r*shard_size(k) and padded rows ==
    shard_size(k) for every ShardedTable)."""
    d = os.path.join(checkpoint_dir, f"rank{r}", f"step_{step:010d}")
    for name, t in tables.items():
        if not hasattr(t, "shard_lo"):
            continue
        sz = -(-t.num_rows // k)  # RangePartitioner.shard_size at k
        if _shard_layout(d, name) != (r * sz, sz):
            return False
    return True


def find_elastic_step(checkpoint_dir: str,
                      tables: dict) -> Optional[tuple[int, int]]:
    """Newest ``(step, old_n)`` such that ranks 0..old_n-1 all hold
    ``step`` saved under a CONSISTENT old_n-process partition. None if no
    complete old world exists (fresh start).

    The partition-fit check matters because one step NUMBER can carry
    mixed layouts: an earlier elastic resume re-publishes the resharded
    state at the same step under the new world's rank dirs, while ranks
    beyond the new world still hold the old world's files. Candidate
    world sizes are tried largest-first so the most complete consistent
    layout wins."""
    dirs = _rank_dirs(checkpoint_dir)
    if not dirs:
        return None
    holders: dict[int, set[int]] = {}
    for r, d in dirs.items():
        for s in _steps_in(d):
            holders.setdefault(s, set()).add(r)
    for s in sorted(holders, reverse=True):
        ranks = holders[s]
        for k in range(len(ranks), 0, -1):
            if not set(range(k)) <= ranks:
                continue
            if all(_fits_partition(checkpoint_dir, s, r, tables, k)
                   for r in range(k)):
                return s, k
    return None


def _shard_layout(step_dir: str,
                  name: str) -> Optional[tuple[int, int]]:
    """(lo, padded row count) recorded in one table's shard file, or
    None when the file is absent/unreadable — the ONE place both layout
    checks read, so the negotiation filter and the elastic scan cannot
    drift apart on what 'fits' means."""
    path = os.path.join(step_dir, f"{name}.npz")
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            return int(z["lo"]), int(z["w"].shape[0])
    except (OSError, KeyError, ValueError):
        return None


def step_matches_layout(rank_dir: str, step: int, tables: dict) -> bool:
    """True iff ``step`` in ``rank_dir`` was saved under the CALLER'S
    partition — same shard origin (``lo``) and same padded shard row
    count for every ShardedTable. A surviving rank relaunched into a
    DIFFERENT world size still holds its old-world steps; offering those
    to the resume negotiation would either crash the restore (shape/lo
    mismatch) or, worse, silently restore the wrong rows. Steps that
    fail this filter stay on disk — they are exactly what the elastic
    path reshards from."""
    d = os.path.join(rank_dir, f"step_{step:010d}")
    for name, t in tables.items():
        if not hasattr(t, "shard_lo"):
            continue
        if _shard_layout(d, name) != (t.shard_lo, t.part.shard_size):
            return False
    return True


def _load_table_npz(checkpoint_dir: str, step: int, old_rank: int,
                    name: str) -> dict[str, np.ndarray]:
    path = os.path.join(checkpoint_dir, f"rank{old_rank}",
                        f"step_{step:010d}", f"{name}.npz")
    with np.load(path) as z:
        return dict(z.items())


def _shard_path(checkpoint_dir: str, step: int, rank: int,
                name: str) -> str:
    return os.path.join(checkpoint_dir, f"rank{rank}",
                        f"step_{step:010d}", f"{name}.npz")


class NpzSliceReader:
    """Row-range reads out of ONE ``np.savez`` shard file without
    materializing whole arrays — the cap-bounded staging primitive the
    planned-redistribution restore paths stream through.

    ``np.savez`` stores members uncompressed (ZIP_STORED), so a
    member's ``.npy`` payload is a flat seekable byte range: after
    parsing the npy header once, rows ``[a, b)`` of a C-contiguous
    row-aligned leaf are ``(b-a) * row_bytes`` bytes at a computed
    offset. Fortran-order or exotically-versioned members fall back to
    a whole-member read (none exist in minitpups checkpoints today —
    the fallback is the honest escape hatch, not a fast path)."""

    def __init__(self, path: str):
        self.path = path
        self._zf = zipfile.ZipFile(path, "r")
        self._members = {n[:-4]: n for n in self._zf.namelist()
                         if n.endswith(".npy")}
        self._hdr: dict[str, tuple] = {}

    def keys(self):
        return self._members.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._members

    def _header(self, key: str) -> tuple:
        """(shape, dtype, data_offset | None) — offset None means
        'stream-unsliceable, use a whole read' (fortran order or an
        npy version this parser does not know)."""
        if key not in self._hdr:
            with self._zf.open(self._members[key]) as fp:
                ver = _npfmt.read_magic(fp)
                if ver == (1, 0):
                    shape, fortran, dt = \
                        _npfmt.read_array_header_1_0(fp)
                elif ver == (2, 0):
                    shape, fortran, dt = \
                        _npfmt.read_array_header_2_0(fp)
                else:
                    shape, fortran, dt = None, True, None
                if fortran or shape is None:
                    arr = self.read(key)
                    self._hdr[key] = (arr.shape, arr.dtype, None)
                else:
                    self._hdr[key] = (shape, dt, fp.tell())
        return self._hdr[key]

    def shape(self, key: str) -> tuple:
        return tuple(self._header(key)[0])

    def dtype(self, key: str):
        return self._header(key)[1]

    def read(self, key: str) -> np.ndarray:
        """Whole-member read (meta scalars, passthrough leaves, the
        fallback path)."""
        with self._zf.open(self._members[key]) as fp:
            return _npfmt.read_array(fp, allow_pickle=False)

    def read_rows(self, key: str, a: int, b: int) -> np.ndarray:
        """Rows ``[a, b)`` of a row-aligned leaf, staged as exactly
        ``(b-a) * row_bytes`` bytes — never the whole array."""
        shape, dt, off = self._header(key)
        if b <= a:
            return np.zeros((0,) + tuple(shape[1:]),
                            dt if dt is not None else np.float32)
        if off is None:  # fallback: unsliceable member layout
            return np.array(self.read(key)[a:b])
        row = int(dt.itemsize * np.prod(shape[1:], dtype=np.int64)) \
            if len(shape) > 1 else int(dt.itemsize)
        with self._zf.open(self._members[key]) as fp:
            fp.seek(off + a * row)
            buf = fp.read((b - a) * row)
        if len(buf) != (b - a) * row:
            raise ValueError(
                f"{self.path}: short read of {key!r} rows [{a},{b}) — "
                "truncated shard file")
        return np.frombuffer(buf, dt).reshape(
            (b - a,) + tuple(shape[1:])).copy()

    def close(self) -> None:
        self._zf.close()

    def __enter__(self) -> "NpzSliceReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # cache-held readers close on collection
        try:
            self._zf.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


_META_KEYS = ("lo", "ep", "ovb", "ovo", "rb_block")


def saved_overlay(state: dict) -> tuple[int, int, dict[int, int]]:
    """``(epoch, block_size, {block: owner})`` recorded in one shard's
    flat state dict — empty when the step was saved unrebalanced. Every
    rank records the SAME routing table at a settled save boundary, so
    any one shard file is authoritative for the fleet's overlay."""
    ep = int(np.asarray(state.get("ep", 0)))
    if not ep:
        return 0, 0, {}
    blk = int(np.asarray(state.get("rb_block", 0)))
    ov = {int(b): int(o) for b, o in
          zip(np.asarray(state.get("ovb", np.zeros(0))).tolist(),
              np.asarray(state.get("ovo", np.zeros(0))).tolist())}
    return ep, blk, ov


def _block_span(old_sz: int, block_size: int, b: int) -> tuple[int, int]:
    """Global ``(lo, length)`` of block ``b`` under an ``old_sz``-row
    partition cut into ``block_size``-key blocks — the on-disk twin of
    ``BlockRouter.block_span`` (blocks are cut per shard, the last block
    of a shard possibly short)."""
    bps = -(-old_sz // block_size)
    shard, loc = divmod(int(b), bps)
    lo = shard * old_sz + loc * block_size
    return lo, min(block_size, old_sz - loc * block_size)


def reshard_table_state(checkpoint_dir: str, step: int, old_n: int,
                        name: str, num_rows: int, new_lo: int,
                        new_shard_size: int, *,
                        cap_bytes: Optional[int] = None,
                        stats: Optional[dict] = None
                        ) -> dict[str, np.ndarray]:
    """Assemble the state dict for the new shard ``[new_lo, new_lo +
    new_shard_size)`` of table ``name`` from the ``old_n`` old shard
    files at ``step``.

    Slicing rule: any leaf whose leading dimension equals the OLD
    shard_size is row-aligned (w, acc, m, v, steps — shards are PADDED to
    shard_size, so only the rows inside ``num_rows`` are real); ``lo`` is
    replaced by the new shard origin; any other leaf must be identical
    across old shards (there are none today — the assert is the tripwire
    for a future leaf this rule cannot place).

    A REBALANCED checkpoint (saved routing epoch > 0) reshards through
    its overlay instead of refusing: the home-slab slices land first
    (dead copies of moved-out blocks included), then every overlay
    block's live state — held in its save-time owner's ``xtra`` section
    — overwrites its span, optimizer leaves alike. The result is the
    FLATTENED table at the new partition: rows live where the base range
    map says, no overlay survives the resize (the restored fleet starts
    at routing epoch 0, consistent because every rank reshards from the
    same files).

    STREAMING (planned redistribution's mover (c)): old shard files are
    read through :class:`NpzSliceReader` in row chunks of at most
    ``cap_bytes`` (default 64 MiB, the MINIPS_RESHARD cap default) —
    peak transient staging is CAP-bounded, never source-shard- or
    block-bounded, which is what lets a 1/N-memory rank reshard a table
    bigger than its RAM budget. ``stats`` (optional dict out-param)
    records the measured ``peak_stage_bytes`` and ``chunks`` — the
    RESHARD-MEM gate reads the measurement, it does not trust the
    promise."""
    cap = 64 << 20 if cap_bytes is None else max(1, int(cap_bytes))
    peak = chunks = 0
    readers: dict[int, NpzSliceReader] = {}

    def _rd(rank: int) -> NpzSliceReader:
        if rank not in readers:
            readers[rank] = NpzSliceReader(
                _shard_path(checkpoint_dir, step, rank, name))
        return readers[rank]

    try:
        probe = _rd(0)
        meta = {k: probe.read(k) for k in _META_KEYS if k in probe}
        saved_ep, saved_blk, saved_ov = saved_overlay(meta)
        if saved_ep and saved_blk <= 0:
            raise ValueError(
                f"elastic reshard: step {step} of table {name!r} "
                f"records a rebalanced routing table (epoch {saved_ep}) "
                "without its block granularity — torn save, overlay "
                "blocks cannot be placed")
        old_sz = -(-num_rows // old_n)  # RangePartitioner.shard_size
        new_hi = min(new_lo + new_shard_size, num_rows)
        row_keys = sorted(
            k for k in probe.keys()
            if k not in _META_KEYS and "/" not in k
            and len(probe.shape(k)) >= 1 and probe.shape(k)[0] == old_sz)
        if new_hi <= new_lo:
            # a grown world's last shard can lie ENTIRELY in padding
            # (shard_lo >= num_rows): there are no rows to assemble, but
            # the live table still expects every leaf at full shard
            # shape — use old rank 0's leaves as the shape/dtype
            # template, zero-filled. Overlay metadata and xtra subtrees
            # never ride a resharded state: the resize flattens the
            # routing table.
            out = {"lo": np.asarray(new_lo)}
            for key in sorted(probe.keys()):
                if key in _META_KEYS or "/" in key:
                    continue
                if key in row_keys:
                    out[key] = np.zeros(
                        (new_shard_size,) + probe.shape(key)[1:],
                        probe.dtype(key))
                else:
                    out[key] = probe.read(key)
            return out
        # preallocate the DESTINATION arrays once (they are the final
        # storage, not staging) — streamed chunks land in place, the
        # last shard's padding stays zero exactly like __init__ pads
        out: dict[str, np.ndarray] = {"lo": np.asarray(new_lo)}
        for key in row_keys:
            out[key] = np.zeros(
                (new_shard_size,) + probe.shape(key)[1:],
                probe.dtype(key))
        passthrough: dict[str, np.ndarray] = {}
        for o in range(old_n):
            lo_o = o * old_sz
            hi_o = min(lo_o + old_sz, num_rows)
            a, b = max(lo_o, new_lo), min(hi_o, new_hi)
            if a >= b:
                continue
            r = _rd(o)
            for key in sorted(r.keys()):
                if key in _META_KEYS or "/" in key:
                    continue  # routing metadata / xtra: overlay pass
                shape = r.shape(key)
                if len(shape) >= 1 and shape[0] == old_sz:
                    row_b = max(1, int(r.dtype(key).itemsize
                                       * np.prod(shape[1:],
                                                 dtype=np.int64)))
                    step_rows = max(1, cap // row_b)
                    for ca in range(a, b, step_rows):
                        cb = min(ca + step_rows, b)
                        rows = r.read_rows(key, ca - lo_o, cb - lo_o)
                        out[key][ca - new_lo:cb - new_lo] = rows
                        peak = max(peak, int(rows.nbytes))
                        chunks += 1
                        del rows
                else:
                    arr = r.read(key)
                    prev = passthrough.get(key)
                    # a hard refusal, not an assert: resharding a leaf
                    # that is neither row-aligned nor shard-invariant
                    # would silently pick one shard's copy — and
                    # `python -O` strips asserts, so the tripwire must
                    # be a real raise
                    if prev is not None \
                            and not np.array_equal(prev, arr):
                        raise ValueError(
                            f"elastic reshard: leaf {name}.{key} is "
                            "neither row-aligned nor identical across "
                            "old shards")
                    passthrough[key] = arr
        out.update(passthrough)
        if saved_ep:
            # overlay pass: every moved block's LIVE rows sit in its
            # save-time owner's xtra section; the home-slab slice
            # placed above is a dead copy. Overwrite the intersection
            # of each overlay block's span with my new range, every
            # row-aligned leaf alike (optimizer state migrates with
            # its rows) — streamed in the same cap-bounded chunks.
            for blk_id, owner in sorted(saved_ov.items()):
                blo, bln = _block_span(old_sz, saved_blk, blk_id)
                a, b = max(blo, new_lo), min(blo + bln, new_hi)
                if a >= b:
                    continue
                r = _rd(int(owner))
                prefix = f"xtra/{blk_id}/"
                xs = sorted(k[len(prefix):] for k in r.keys()
                            if k.startswith(prefix))
                if not set(row_keys) <= set(xs):
                    # EVERY row-aligned leaf must come from the live
                    # copy: a subset (say w without m) would silently
                    # mix live params with a dead home copy's
                    # optimizer state
                    raise ValueError(
                        f"elastic reshard: step {step} of table "
                        f"{name!r} maps block {blk_id} to rank "
                        f"{owner}, but that rank's shard file lacks "
                        f"{sorted(set(row_keys) - set(xs))} for it — "
                        "torn rebalanced save")
                for key in xs:
                    if key not in out:
                        continue
                    member = prefix + key
                    shape = r.shape(member)
                    row_b = max(1, int(r.dtype(member).itemsize
                                       * np.prod(shape[1:],
                                                 dtype=np.int64)))
                    step_rows = max(1, cap // row_b)
                    for ca in range(a, b, step_rows):
                        cb = min(ca + step_rows, b)
                        rows = r.read_rows(member, ca - blo, cb - blo)
                        out[key][ca - new_lo:cb - new_lo] = rows
                        peak = max(peak, int(rows.nbytes))
                        chunks += 1
                        del rows
        return out
    finally:
        if stats is not None:
            stats["peak_stage_bytes"] = max(
                stats.get("peak_stage_bytes", 0), peak)
            stats["chunks"] = stats.get("chunks", 0) + chunks
        for r in readers.values():
            r.close()


def find_live_step(checkpoint_dir: str, tables: dict, n: int,
                   required=None) -> Optional[int]:
    """Newest step that every rank in ``required`` (default: all of
    ``0..n-1``) holds under the CALLER'S ``n``-way partition (overlays
    allowed — the slab layout is what the fit check reads). The
    elastic-membership death path restores a dead rank's blocks from
    this step, passing ``required = live ∪ {corpse}``: one
    coordinator-chosen step keeps every survivor's restore consistent,
    and a never-checkpointed STANDBY's missing rank dir must not veto
    recovery (it owns nothing a checkpoint could hold — its home range
    was evacuated into live ranks' files at bootstrap). Ranks in
    ``required`` that never created a dir are skipped for the same
    reason; no dirs at all means no recovery."""
    dirs = _rank_dirs(checkpoint_dir)
    need = sorted((set(range(n)) if required is None
                   else {int(r) for r in required}) & set(dirs))
    if not need:
        return None
    common: Optional[set[int]] = None
    for r in need:
        steps = _steps_in(dirs[r])
        common = steps if common is None else common & steps
    for s in sorted(common or (), reverse=True):
        if all(_fits_partition(checkpoint_dir, s, r, tables, n)
               for r in need):
            return s
    return None


def load_block_state(checkpoint_dir: str, step: int, name: str,
                     block: int, blo: int, bln: int, home_rank: int,
                     shard_size: int, block_size: int,
                     cache: Optional[dict] = None
                     ) -> dict[str, np.ndarray]:
    """State of ONE key block at ``step``, read through the save-time
    routing table — the elastic-membership death path's restore unit
    (a dead rank's blocks reassemble onto survivors from exactly what
    the checkpoint holds, wherever the overlay had parked them).

    ``blo``/``bln``/``home_rank`` are the block's LIVE geometry
    (``BlockRouter.block_span``/``home_of``); the saved block size must
    match the live router's, else block ids name different key ranges
    and the restore would be silently torn — refused loudly instead.
    ``cache`` (rank -> open :class:`NpzSliceReader`, caller-held across
    one adoption) keeps a dead rank's B-block restore from re-opening
    the same shard files B times — and because the reader SLICES rows
    instead of materializing whole shards, a B-block restore stages
    only the blocks it returns, never a full old shard (the planned-
    redistribution memory contract, satellite of the same PR)."""

    def _rd(rank: int) -> NpzSliceReader:
        if cache is None:
            return NpzSliceReader(
                _shard_path(checkpoint_dir, step, rank, name))
        if rank not in cache:
            cache[rank] = NpzSliceReader(
                _shard_path(checkpoint_dir, step, rank, name))
        return cache[rank]

    # the routing metadata is identical in every shard file, so read it
    # from the home rank when possible and fall back to ANY holder: the
    # home rank may be a corpse that never checkpointed (an admitted-
    # then-killed joiner), whose blocks' live state sits in other
    # ranks' files per the overlay
    meta = None
    for rank in [home_rank] + sorted(set(_rank_dirs(checkpoint_dir))
                                     - {home_rank}):
        try:
            r = _rd(rank)
            meta = {k: r.read(k) for k in _META_KEYS if k in r}
            break
        except (OSError, ValueError, KeyError):
            continue
    if meta is None:
        raise ValueError(
            f"elastic restore: no readable shard file at step {step} "
            f"of table {name!r} — nothing to restore block {block} "
            "from")
    saved_ep, saved_blk, saved_ov = saved_overlay(meta)
    if saved_ep and saved_blk != block_size:
        raise ValueError(
            f"elastic restore: step {step} of table {name!r} was saved "
            f"at block granularity {saved_blk}, live router runs "
            f"{block_size} — block ids are incomparable")
    owner = saved_ov.get(int(block), home_rank)
    if owner == home_rank:
        try:
            home = _rd(home_rank)
        except (OSError, ValueError, KeyError) as e:
            # the state lived only on the (dir-less) home rank: gone
            raise ValueError(
                f"elastic restore: step {step} of table {name!r} holds "
                f"no file for rank {home_rank}, the save-time owner of "
                f"block {block}") from e
        lo_local = blo - home_rank * shard_size
        st = {}
        for key in sorted(home.keys()):
            if key in _META_KEYS or "/" in key:
                continue
            shape = home.shape(key)
            if len(shape) >= 1 and shape[0] == shard_size:
                st[key] = home.read_rows(key, lo_local, lo_local + bln)
    else:
        state = _rd(int(owner))
        prefix = f"xtra/{block}/"
        st = {k[len(prefix):]: state.read(k) for k in state.keys()
              if k.startswith(prefix)}
    if st.get("w") is None or st["w"].shape[0] != bln:
        raise ValueError(
            f"elastic restore: step {step} of table {name!r} holds no "
            f"usable state for block {block} "
            f"(expected {bln} rows at rank "
            f"{owner})")
    return st


def read_saved_clock(checkpoint_dir: str, step: int,
                     name: str = "trainer") -> int:
    """The clock stamped into rank 0's trainer snapshot at ``step`` — at
    a save boundary every rank stamps the same value (save_hook runs at
    clock == i+1), so one representative suffices."""
    state = _load_table_npz(checkpoint_dir, step, 0, name)
    return int(state["clock"])
