"""Profiling hooks (SURVEY.md §5.1): trace window produces an artifact;
annotations accumulate host time."""

from __future__ import annotations

import os
import time

import jax.numpy as jnp

from minips_tpu.utils.profiling import Annotation, StepWindowProfiler


def test_step_window_profiler_writes_trace(tmp_path):
    d = str(tmp_path / "trace")
    p = StepWindowProfiler(d, start=2, stop=4)
    for i in range(6):
        p.on_step(i)
        jnp.sum(jnp.ones(16)).block_until_ready()
    p.close()
    # jax writes plugins/profile/<run>/ under the log dir
    found = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert found, "no trace artifacts written"


def test_window_closed_even_if_run_ends_early(tmp_path):
    p = StepWindowProfiler(str(tmp_path / "t2"), start=0, stop=100)
    p.on_step(0)
    p.close()  # must not raise / leak an open trace
    p.close()  # idempotent


def test_annotation_accumulates():
    Annotation.totals.clear()
    with Annotation("phase_x"):
        time.sleep(0.01)
    with Annotation("phase_x"):
        time.sleep(0.01)
    assert Annotation.totals["phase_x"] >= 0.02
