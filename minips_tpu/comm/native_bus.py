"""NativeControlBus — ctypes binding for the C++ TCP mailbox.

The reference's Mailbox is native C++ (ZeroMQ ROUTER/DEALER + per-thread
``ThreadsafeQueue`` inboxes + a Sender actor; SURVEY.md L0/L1, §2.3). This
is the rebuild's native-runtime equivalent for the surviving control plane:
``cpp/mailbox.cpp`` implements the transport (raw TCP full mesh, framed
messages, a C++ ThreadsafeQueue inbox, reader actors per connection, a
Sender actor draining an outgoing queue), and this module is the thin
Python skin exposing the exact ``ControlBus`` interface so ``ClockGossip``,
``HeartbeatMonitor``, ``BlockMaster`` etc. run unchanged on either backend.

Select with ``make_bus(..., backend="native")`` or ``MINIPS_BUS=native``.
Like the native data readers, the library builds lazily on first use and
callers degrade to the zmq backend when no compiler is available.
"""

from __future__ import annotations

import ctypes
import threading
from typing import Callable, Optional

from minips_tpu.comm.bus import deliver_frame, stop_bus_layers
from minips_tpu.comm.framing import encode_head, wire_fmt_from_env
from minips_tpu.utils.native_lib import load_native_lib


def _declare(lib: ctypes.CDLL) -> None:
    lib.mailbox_create.argtypes = [ctypes.c_int]
    lib.mailbox_create.restype = ctypes.c_void_p
    lib.mailbox_port.argtypes = [ctypes.c_void_p]
    lib.mailbox_port.restype = ctypes.c_int
    lib.mailbox_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int, ctypes.c_int]
    lib.mailbox_connect.restype = ctypes.c_int
    lib.mailbox_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64]
    lib.mailbox_publish.restype = None
    lib.mailbox_send.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64]
    lib.mailbox_send.restype = None
    lib.mailbox_recv.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64)]
    lib.mailbox_recv.restype = ctypes.c_int
    lib.mailbox_free_buf.argtypes = [ctypes.c_void_p]
    lib.mailbox_free_buf.restype = None
    lib.mailbox_close.argtypes = [ctypes.c_void_p]
    lib.mailbox_close.restype = None
    lib.mailbox_outbox_depth.argtypes = [ctypes.c_void_p]
    lib.mailbox_outbox_depth.restype = ctypes.c_int64
    lib.mailbox_dropped.argtypes = [ctypes.c_void_p]
    lib.mailbox_dropped.restype = ctypes.c_int64
    lib.mailbox_set_outbox_cap.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mailbox_set_outbox_cap.restype = None
    lib.mailbox_interrupt.argtypes = [ctypes.c_void_p]
    lib.mailbox_interrupt.restype = None


def _load() -> Optional[ctypes.CDLL]:
    return load_native_lib("libminips_comm.so", _declare)


def _parse_addr(addr: str) -> tuple[str, int]:
    """``tcp://host:port`` → (IPv4, port); hostnames (``localhost``,
    hostfile names) resolve here so the C side only sees literals."""
    import socket

    hostport = addr.split("//", 1)[-1]
    host, port = hostport.rsplit(":", 1)
    if host in ("*", "0.0.0.0", ""):
        return "0.0.0.0", int(port)
    try:
        socket.inet_aton(host)
    except OSError:
        host = socket.gethostbyname(host)
    return host, int(port)


class NativeControlBus:
    """Same interface as ``ControlBus`` (on/start/publish/handshake/close),
    backed by the C++ mailbox instead of pyzmq. Fan-out happens over the
    full mesh of outgoing TCP connections made in ``start()``."""

    def __init__(self, my_addr: str, peer_addrs: list[str], my_id: int = 0,
                 connect_timeout: float = 15.0,
                 wire_fmt: Optional[str] = None):
        lib = _load()
        if lib is None:
            raise RuntimeError("native mailbox library unavailable")
        from minips_tpu.comm.bus import FrameLossTracker

        self.my_id = my_id
        self.wire_fmt = wire_fmt or wire_fmt_from_env()
        self.bytes_sent = 0
        self.loss = FrameLossTracker()
        self._n_world = len(peer_addrs) + 1
        self._bseq = 0                       # broadcast-stream seq
        self._dseq = [0] * self._n_world     # per-dest directed seq
        self._lib = lib
        _, port = _parse_addr(my_addr)
        self._h = lib.mailbox_create(port)
        if not self._h:
            raise OSError(f"mailbox_create: cannot bind {my_addr}")
        self._peer_addrs = [_parse_addr(a) for a in peer_addrs]
        self._connect_timeout = connect_timeout
        self._handlers: dict[str, Callable[[int, dict], None]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # TWO locks, two concerns:
        # - _seq_lock holds across stamp AND the C enqueue, so wire order
        #   equals seq order even with concurrent publishers (a stamped-
        #   then-preempted frame enqueued late would read as phantom
        #   wire loss at every receiver).
        # - _life (condition) tracks handle liveness + in-flight C calls:
        #   close() interrupts pending bounded pushes, waits the count to
        #   zero, then frees the handle — no use-after-free, and depth/
        #   drop observability never queues behind a 30s backpressure
        #   stall (it takes only _life).
        self._seq_lock = threading.Lock()
        self._h_lock = threading.Lock()
        self._life = threading.Condition(self._h_lock)
        self._inflight = 0

    @staticmethod
    def available() -> bool:
        return _load() is not None

    @property
    def port(self) -> int:
        return self._lib.mailbox_port(self._h)

    def on(self, kind: str, handler: Callable[[int, dict], None]) -> None:
        self._handlers[kind] = handler

    def start(self) -> "NativeControlBus":
        # Outgoing connects retry in C until the peer's listener is up
        # (processes boot in arbitrary order, SURVEY.md §3.1).
        for host, port in self._peer_addrs:
            rc = self._lib.mailbox_connect(
                self._h, host.encode(), port,
                int(self._connect_timeout * 1000))
            if rc != 0:
                raise TimeoutError(
                    f"native bus: cannot reach peer {host}:{port}")
        self._thread = threading.Thread(target=self._recv_loop, daemon=True)
        self._thread.start()
        return self

    # Receive-side protocol caps (cpp/mailbox.cpp kMaxMsg/kMaxBlob). An
    # oversized frame would be written in full here but poison the peer's
    # reader thread there — the link dies silently. Reject at the source.
    MAX_MSG = 16 << 20
    MAX_BLOB = 1 << 30

    def publish(self, kind: str, payload: dict,
                blob: Optional[bytes] = None) -> None:
        """Enqueues onto the C++ Sender actor's bounded queue: nonblocking
        until the outbox holds its cap (default 8192 frames), then applies
        producer BACKPRESSURE — blocks up to 30s, after which the frame is
        counted in ``send_drops`` (never silently lost). A publish after
        close() is a silent no-op (matches zmq's at-worst-an-error
        behavior rather than a use-after-free)."""
        self._emit(-1, kind, payload, blob)

    def send(self, dest: int, kind: str, payload: dict,
             blob: Optional[bytes] = None) -> None:
        """Directed delivery to peer rank ``dest`` over its one TCP link.
        Assumes ``peer_addrs`` was built in ascending-rank order minus my
        own entry (what launch.init_from_env produces) so the connect-order
        index is recoverable from the rank."""
        if dest == self.my_id:
            raise ValueError("directed send to self (serve locally instead)")
        idx = dest if dest < self.my_id else dest - 1
        if not 0 <= idx < len(self._peer_addrs):
            raise ValueError(f"dest rank {dest} out of range")
        self._emit(idx, kind, payload, blob, dest_rank=dest)

    def _emit(self, peer_index: int, kind: str, payload: dict,
              blob: Optional[bytes], dest_rank: int = -1) -> None:
        # size caps validated BEFORE seq stamping: a raise after an
        # increment would leave a permanent stream gap the receiver's
        # loss tracker reads as a wire drop
        if blob is not None and len(blob) > self.MAX_BLOB:
            raise ValueError(f"blob {len(blob)}B exceeds the "
                             f"{self.MAX_BLOB}B protocol cap")
        head = {"kind": kind, "sender": self.my_id, "payload": payload}
        probe = encode_head(head, self.wire_fmt)
        # a stamped header adds <= ~24B (JSON '"bs": <int64>'; the
        # binary prefix carries the seq field either way)
        if len(probe) + 24 > self.MAX_MSG:
            raise ValueError(f"control frame {len(probe)}B exceeds the "
                             f"{self.MAX_MSG}B protocol cap")
        with self._seq_lock:
            with self._life:
                if self._closed:
                    return
                self._inflight += 1
            # seq stamping mirrors the zmq backend (FrameLossTracker):
            # TCP never drops post-connect, so established-stream loss
            # here means a torn link's tail. Stamp AND enqueue under
            # _seq_lock: wire order must equal seq order across threads
            # (a reordered pair would count as phantom loss forever).
            if not kind.startswith("__"):
                if peer_index < 0:
                    head["bs"] = self._bseq
                    self._bseq += 1
                else:
                    head["ds"] = self._dseq[dest_rank]
                    self._dseq[dest_rank] += 1
            msg = encode_head(head, self.wire_fmt)
            rel = getattr(self, "reliable", None)
            if rel is not None and ("bs" in head or "ds" in head):
                # under _seq_lock like the zmq backend: journal order
                # must equal wire order for NACK lookups to be sound
                rel.journal_stamped(
                    "b" if "bs" in head else "d",
                    -1 if "bs" in head else dest_rank,
                    head.get("bs", head.get("ds")), msg, blob)
            data = None if blob is None else bytes(blob)
            blen = -1 if blob is None else len(blob)
            try:
                # may BLOCK under backpressure (bounded outbox); close()
                # unblocks it via mailbox_interrupt without needing
                # _seq_lock, and the in-flight count keeps the handle
                # alive until this call returns
                if peer_index < 0:
                    self._lib.mailbox_publish(self._h, msg, len(msg),
                                              data, blen)
                else:
                    self._lib.mailbox_send(self._h, peer_index, msg,
                                           len(msg), data, blen)
            finally:
                with self._life:
                    self._inflight -= 1
                    self.bytes_sent += len(msg) + (blen if blen > 0 else 0)
                    if self._closed and self._inflight == 0:
                        self._life.notify_all()

    # ---------------------------------------------- queue observability
    def out_queue_depth(self) -> int:
        """Frames waiting on the C++ Sender actor (real depth — the zmq
        backend cannot observe its library-internal queues)."""
        with self._h_lock:
            return 0 if self._closed else int(
                self._lib.mailbox_outbox_depth(self._h))

    @property
    def send_drops(self) -> int:
        """Producer-side drops: bounded-outbox pushes that timed out
        (30s of a full queue). Zero in any healthy job."""
        with self._h_lock:
            return 0 if self._closed else int(
                self._lib.mailbox_dropped(self._h))

    def set_outbox_cap(self, cap: int) -> None:
        with self._h_lock:
            if not self._closed:
                self._lib.mailbox_set_outbox_cap(self._h, int(cap))

    @property
    def frames_lost(self) -> int:
        return self.loss.lost

    @property
    def frames_malformed(self) -> int:
        return self.loss.malformed

    def _recv_loop(self) -> None:
        msg_p = ctypes.c_char_p()
        msg_len = ctypes.c_int64()
        blob_p = ctypes.POINTER(ctypes.c_uint8)()
        blob_len = ctypes.c_int64()
        while not self._stop.is_set():
            got = self._lib.mailbox_recv(
                self._h, 50, ctypes.byref(msg_p), ctypes.byref(msg_len),
                ctypes.byref(blob_p), ctypes.byref(blob_len))
            if not got:
                continue
            try:
                raw = ctypes.string_at(msg_p, msg_len.value)
                blob = (ctypes.string_at(blob_p, blob_len.value)
                        if blob_len.value >= 0 and blob_p else None)
            finally:
                self._lib.mailbox_free_buf(msg_p)
                if blob_p:
                    self._lib.mailbox_free_buf(blob_p)
                blob_p = ctypes.POINTER(ctypes.c_uint8)()
            deliver_frame(self, raw, blob)

    def handshake(self, num_processes: int, timeout: float = 15.0) -> None:
        """TCP never drops post-connect, but a peer may publish before OUR
        connect to it finished accepting — same rendezvous as zmq."""
        from minips_tpu.comm.bus import run_handshake

        run_handshake(self, num_processes, timeout)

    def close(self) -> None:
        stop_bus_layers(self)  # chaos scheduler + reliable repair thread
        with self._life:
            if self._closed:
                return
            self._closed = True
            # wake any publisher blocked in bounded-push backpressure
            # (its frame counts as dropped — teardown is an error path),
            # then wait in-flight C calls out before freeing the handle
            self._lib.mailbox_interrupt(self._h)
            if not self._life.wait_for(lambda: self._inflight == 0,
                                       timeout=35.0):
                return  # a wedged C call: leak the handle, never free it live
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                # A handler is wedged past the grace period. mailbox_close
                # would free the C++ object under the recv thread's feet
                # (use-after-free → segfault); leaking the handle is the
                # safe failure mode.
                return
        self._lib.mailbox_close(self._h)

    def __enter__(self) -> "NativeControlBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
