"""Synthetic dataset generators shaped like the reference's workloads.

The sandbox has no network, so a9a/RCV1/MNIST/MovieLens/Criteo/enwiki
cannot be downloaded; these generators produce statistically-similar data
with the same schemas (BASELINE.json:6-12 configs) so every app trains and
every benchmark measures the same compute/communication shape as the real
dataset would. Real datasets drop in via the same loaders (libsvm/CSV).
"""

from __future__ import annotations

import numpy as np


def zipf_popularity(num_keys: int, alpha: float) -> np.ndarray:
    """Normalized zipf(``alpha``) popularity over ``num_keys`` ranks —
    the one definition every skewed-key generator here shares (sparse
    features, Criteo categoricals, token unigrams, the PS bench's hot-row
    traffic) instead of ad-hoc ``1/rank**a`` copies."""
    p = 1.0 / np.arange(1, num_keys + 1, dtype=np.float64) ** alpha
    return p / p.sum()


def make_zipf_sampler(num_keys: int, alpha: float = 1.1, *,
                      spread_seed: int = 0, permute_hot: bool = True):
    """Seeded zipfian KEY sampler: returns ``sample(rng, size) ->
    int64[size]`` drawing keys with zipf(``alpha``) popularity, with the
    rank→key mapping scrambled by a FIXED permutation (``spread_seed``).

    The permutation matters for anything range-sharded (the sharded PS):
    raw zipf puts all the head mass in keys 0..k, i.e. entirely inside
    shard 0 — every hot row would be one owner's local traffic and the
    skew would never exercise the wire. Sharing ``spread_seed`` across
    ranks keeps every process's notion of 'hot rows' identical, like a
    real workload's.

    ``permute_hot=False`` keeps the raw rank→key identity — the
    PATHOLOGICAL case for a static range partition (the whole head on
    one owner), which is exactly what the heat-aware rebalancer exists
    to fix (balance/): the bench's unpermuted-zipf arms measure that
    imbalance instead of hiding it behind the permutation. The
    permuted default stays, but the skewed case is testable."""
    p = zipf_popularity(num_keys, alpha)
    if permute_hot:
        perm = np.random.default_rng(spread_seed).permutation(num_keys)
    else:
        perm = np.arange(num_keys)

    def sample(rng: np.random.Generator, size: int) -> np.ndarray:
        return perm[rng.choice(num_keys, size=size, p=p)].astype(np.int64)

    return sample


def classification_dense(n: int = 4096, dim: int = 123, seed: int = 0):
    """a9a-like dense binary classification: [N, dim] features, {0,1} labels,
    linearly separable-ish with noise."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=dim).astype(np.float32)
    X = rng.normal(size=(n, dim)).astype(np.float32)
    logits = X @ w + rng.normal(scale=0.5, size=n).astype(np.float32)
    return {"x": X, "y": (logits > 0).astype(np.float32)}


def classification_sparse(n: int = 4096, dim: int = 47_236,
                          nnz_per_row: int = 14, seed: int = 0):
    """RCV1-like sparse rows: padded (idx, val, mask) + labels. Feature ids
    zipf-ish so hot keys exist (realistic PS traffic skew)."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=dim).astype(np.float32) / np.sqrt(nnz_per_row)
    pop = zipf_popularity(dim, 0.7)  # zipf-weighted feature popularity
    idx = rng.choice(dim, size=(n, nnz_per_row), p=pop).astype(np.int32)
    val = np.abs(rng.normal(size=(n, nnz_per_row))).astype(np.float32)
    mask = np.ones((n, nnz_per_row), np.float32)
    logits = (w[idx] * val).sum(-1) + rng.normal(scale=0.3, size=n)
    return {"idx": idx, "val": val, "mask": mask,
            "y": (logits > 0).astype(np.float32)}


def mnist_like(n: int = 8192, dim: int = 784, classes: int = 10,
               seed: int = 0):
    """MNIST-shaped: 10 gaussian class blobs in [0,1]^784."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(0.2, 0.8, size=(classes, dim)).astype(np.float32)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    X = np.clip(centers[y] + rng.normal(scale=0.3, size=(n, dim)), 0, 1)
    return {"x": X.astype(np.float32), "y": y}


def movielens_like(n: int = 100_000, users: int = 1024, items: int = 2048,
                   rank: int = 8, seed: int = 0):
    """MovieLens-shaped implicit low-rank ratings in [0.5, 5]."""
    rng = np.random.default_rng(seed)
    U = rng.normal(scale=0.5, size=(users, rank)).astype(np.float32)
    V = rng.normal(scale=0.5, size=(items, rank)).astype(np.float32)
    u = rng.integers(0, users, size=n).astype(np.int32)
    i = rng.integers(0, items, size=n).astype(np.int32)
    r = 3.0 + (U[u] * V[i]).sum(-1) + rng.normal(scale=0.2, size=n)
    return {"user": u, "item": i,
            "rating": np.clip(r, 0.5, 5.0).astype(np.float32)}


def criteo_like(n: int = 8192, num_dense: int = 13, num_cat: int = 26,
                cat_cardinality: int = 100_000, seed: int = 0):
    """Criteo-shaped CTR rows: 13 numeric + 26 categorical (large id space,
    zipf-skewed), binary click label correlated with a hidden linear model."""
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, num_dense)).astype(np.float32)
    pop = zipf_popularity(cat_cardinality, 1.05)
    cats = rng.choice(cat_cardinality, size=(n, num_cat), p=pop).astype(
        np.int64)
    # distinct id spaces per field (like Criteo's per-column vocabularies)
    cats = cats + np.arange(num_cat, dtype=np.int64) * cat_cardinality
    w_dense = rng.normal(size=num_dense).astype(np.float32)
    cat_effect = ((cats % 97) / 97.0 - 0.5).sum(-1).astype(np.float32)
    logits = dense @ w_dense * 0.5 + 0.3 * cat_effect + rng.normal(
        scale=0.5, size=n)
    return {"dense": dense, "cat": cats,
            "y": (logits > 0).astype(np.float32)}


def text_corpus(vocab: int = 10_000, n_tokens: int = 200_000, seed: int = 0):
    """enwiki-shaped token stream: zipf unigram distribution with weak
    bigram structure (neighbors correlated) for skip-gram training."""
    rng = np.random.default_rng(seed)
    p = zipf_popularity(vocab, 1.05)
    tokens = rng.choice(vocab, size=n_tokens, p=p).astype(np.int32)
    # weak local structure: every other token copies a neighbor's topic bucket
    tokens[1::2] = (tokens[::2][: len(tokens[1::2])] + rng.integers(
        0, 50, size=len(tokens[1::2]))) % vocab
    counts = np.bincount(tokens, minlength=vocab)
    return tokens, counts


def skipgram_pairs(tokens: np.ndarray, window: int = 2, seed: int = 0):
    """(center, context) pairs from a token stream."""
    rng = np.random.default_rng(seed)
    centers, contexts = [], []
    offsets = rng.integers(1, window + 1, size=len(tokens))
    for off in range(1, window + 1):
        sel = offsets >= off
        idx = np.nonzero(sel[:-off])[0]
        centers.append(tokens[idx])
        contexts.append(tokens[idx + off])
    c = np.concatenate(centers)
    x = np.concatenate(contexts)
    perm = rng.permutation(len(c))
    return c[perm], x[perm]


def lm_sequences(n: int = 2048, seq_len: int = 128, vocab: int = 256,
                 seed: int = 0, order: int = 3):
    """Long-context LM windows [n, seq_len+1]: an order-k Markov chain over
    the vocab, so next-token loss has real learnable structure (an LM that
    trains drives cross-entropy well below log(vocab))."""
    rng = np.random.default_rng(seed)
    # deterministic transition: context hash -> a small candidate set
    a, b = rng.integers(1, vocab, size=2) | 1
    stream = list(rng.integers(0, vocab, size=order))
    noise = rng.random(n * (seq_len + 1) + order)
    jump = rng.integers(0, vocab, size=len(noise))
    for i in range(n * (seq_len + 1)):
        h = 0
        for t in stream[-order:]:
            h = (h * a + t * b) % vocab
        nxt = h if noise[i] > 0.15 else jump[i]   # 85% predictable
        stream.append(int(nxt))
    toks = np.asarray(stream[order:], dtype=np.int32)
    return {"tokens": toks.reshape(n, seq_len + 1)}
