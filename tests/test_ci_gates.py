"""CI gate contracts: the collect-only gate catches import-time
breakage, and the bench-regression comparator fails on >10% rows/sec
drops or silently-dropped sweep points (never on new points or wire-byte
movement)."""

import json
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "ci"))

from bench_regression import (backend_mismatch, cache_tripwires,  # noqa: E402
                              chaos_tripwires, compare,
                              control_plane_tripwires,
                              elastic_tripwires, main,
                              mesh_tripwires, obs_tripwires,
                              rebalance_tripwires,
                              serve_tripwires, shape_mismatch,
                              tenant_tripwires,
                              throughput_points, trace_tripwires,
                              transport_tripwires)


def _art(points):
    """Artifact with one sweep dict of {name: rows_per_sec_per_process}."""
    return {"metric": "m", "value": 1.0,
            "sweep": {k: {"rows_per_sec_per_process": v,
                          "wire_bytes_per_row_moved": 26.7}
                      for k, v in points.items()}}


def test_throughput_points_flattens_by_path():
    pts = throughput_points(_art({"a": 100.0, "b": 200.0}))
    assert pts == {"sweep/a": 100.0, "sweep/b": 200.0}


def test_within_tolerance_passes():
    prior, new = _art({"a": 100.0}), _art({"a": 91.0})
    assert compare(prior, new, 0.10) == []


def test_regression_beyond_tolerance_fails():
    prior, new = _art({"a": 100.0}), _art({"a": 89.0})
    problems = compare(prior, new, 0.10)
    assert len(problems) == 1 and "REGRESSED" in problems[0]
    assert "sweep/a" in problems[0]


def test_dropped_sweep_point_fails_new_point_passes():
    prior = _art({"a": 100.0})
    new = _art({"b": 50.0})  # 'a' vanished, 'b' is new
    problems = compare(prior, new, 0.10)
    assert len(problems) == 1 and "MISSING" in problems[0]
    # a brand-new point has no prior floor — never a failure by itself
    assert all("sweep/b" not in p for p in problems)


def test_zero_prior_point_cannot_define_a_floor():
    assert compare(_art({"a": 0.0}), _art({"a": 0.0}), 0.10) == []


def test_wire_bytes_are_not_gated():
    prior, new = _art({"a": 100.0}), _art({"a": 100.0})
    new["sweep"]["a"]["wire_bytes_per_row_moved"] = 999.0
    assert compare(prior, new, 0.10) == []


def _cache_art(hit_rates: dict) -> dict:
    """Artifact with a cache_comparison_3proc zipf grid:
    {s-name: on-arm hit rate}."""
    return {"cache_comparison_3proc": {"zipf": {
        s: {"on": {"rows_per_sec_per_process": 1.0,
                   "cache_hit_rate": hr},
            "off": {"rows_per_sec_per_process": 1.0}}
        for s, hr in hit_rates.items()}}}


def test_cache_tripwire_fails_on_zero_zipf_hit_rate_with_slack():
    """The 'cache silently disabled' tripwire: zipf + s >= 1 + cache on
    must show hit-rate > 0 — zero (or missing) means the lever fell off
    even if rows/sec still looks plausible."""
    problems = cache_tripwires(_cache_art({"s1": 0.0, "s2": 0.31}))
    assert len(problems) == 1 and "zipf/s1" in problems[0]
    assert cache_tripwires(_cache_art({"s1": None, "s2": 0.31}))
    assert cache_tripwires(_cache_art({"s2": {}}))  # field absent


def test_cache_tripwire_exempts_bsp_and_healthy_arms():
    # s=0 (BSP) CANNOT hit across clocks — zero is the correct reading
    assert cache_tripwires(_cache_art({"s0": 0.0, "s1": 0.2,
                                       "s2": 0.4})) == []
    # an artifact without the sweep (other benches) is not this gate's
    # business; a DROPPED sweep is the generic MISSING check's
    assert cache_tripwires({"metric": "m"}) == []


def test_cache_sweep_points_count_toward_missing_detection():
    """Every cache_comparison arm carries rows_per_sec_per_process, so
    the generic dropped-point gate covers the sweep with no extra
    wiring — dropping the zipf/s2 'on' arm fails."""
    prior = _cache_art({"s1": 0.2, "s2": 0.4})
    new = _cache_art({"s1": 0.2})
    problems = compare(prior, new, 0.10)
    assert any("MISSING" in p and "s2" in p for p in problems)


def _chaos_art(clean=100.0, d0=95.0, d1=(90.0, True, 0),
               d5=(80.0, True, 0)) -> dict:
    """chaos_resilience_3proc artifact: drop>0 on-arms as (rate,
    completed, unrecovered-frames); off arms carry NO throughput metric
    (their outcome is bimodal by design — the bench strips it)."""
    def arm(rate, completed=True, lost=0, key="rows_per_sec_per_process"):
        return {key: rate, "completed": completed,
                "wire_frames_lost": lost}

    def lossy(rate, completed=True, lost=0):
        # drop>0 on-arms: rate under the gate-invisible key (completion
        # gates, never run-to-run comparisons)
        return arm(rate, completed, lost, key="rows_per_sec_lossy")
    return {"chaos_resilience_3proc": {
        "clean": arm(clean), "drop0_on": arm(d0),
        "drop1_on": lossy(*d1), "drop5_on": lossy(*d5),
        "drop1_off": {"completed": False, "error": "died (expected)"},
        "drop5_off": {"completed": False, "error": "died (expected)"}}}


def test_chaos_tripwire_tax_on_clean_path_fails():
    """The reliable layer may not tax the lossless path: drop-0 chaos
    arm beyond slack of the clean arm fails; within slack passes."""
    assert chaos_tripwires(_chaos_art(clean=100.0, d0=80.0)) == []
    probs = chaos_tripwires(_chaos_art(clean=100.0, d0=70.0))
    assert len(probs) == 1 and "CHAOS-TAX" in probs[0]
    # a missing drop0 arm is a tax failure too, not a silent pass
    art = _chaos_art()
    del art["chaos_resilience_3proc"]["drop0_on"]
    assert any("CHAOS-TAX" in p for p in chaos_tripwires(art))


def test_chaos_tripwire_dead_or_leaky_on_arm_fails():
    """drop>0 with retransmit ON must complete (rows/sec > 0) with zero
    unrecovered frames — a dead or leaky arm means the delivery layer
    quietly stopped converting loss to latency."""
    assert chaos_tripwires(_chaos_art()) == []
    probs = chaos_tripwires(_chaos_art(d1=(0.0, False, 0)))
    assert len(probs) == 1 and "CHAOS-DEAD" in probs[0] \
        and "drop1_on" in probs[0]
    probs = chaos_tripwires(_chaos_art(d5=(80.0, True, 7)))
    assert len(probs) == 1 and "CHAOS-LEAK" in probs[0]
    # the retransmit-OFF twins are EXPECTED to die: never gated
    art = _chaos_art()
    art["chaos_resilience_3proc"]["drop5_off"]["completed"] = False
    assert chaos_tripwires(art) == []


def test_chaos_tripwire_vacuous_without_the_sweep():
    assert chaos_tripwires({"metric": "m"}) == []


def test_chaos_off_arms_never_enter_the_throughput_gate():
    """The retransmit-off arms' outcome is bimodal BY DESIGN (death is
    the expected measurement; survival is luck): they carry no
    rows_per_sec_per_process in either state, so a prior where one
    survived can never make a later honest death read as a 100%
    regression — nor can a dead prior MISSING-fail a surviving new."""
    art = _chaos_art()
    pts = throughput_points(art)
    assert not any(p.endswith(("_off", "drop1_on", "drop5_on"))
                   for p in pts), pts
    # survived off arm: evidence kept under a gate-invisible name
    art["chaos_resilience_3proc"]["drop1_off"] = {
        "completed": True, "rows_per_sec_survived": 123.0}
    assert compare(_chaos_art(), art, 0.10) == []
    assert compare(art, _chaos_art(), 0.10) == []


def _transport_art(zj=100.0, zb=105.0, shm=130.0, bytes_row=4.4,
                   shm_bytes=None, compose_rate=90.0, completed=True,
                   lost=0, dropped=12, rts=10) -> dict:
    """transport_comparison_3proc artifact: three comparable arms plus
    the compose completion arm (rate gate-invisible, like chaos)."""
    def arm(rate, br):
        return {"rows_per_sec_per_process": rate, "completed": True,
                "wire_bytes_per_row_moved": br}
    return {"transport_comparison_3proc": {
        "zmq_json": arm(zj, bytes_row),
        "zmq_bin": arm(zb, bytes_row),
        "shm": arm(shm, shm_bytes if shm_bytes is not None
                   else bytes_row),
        "shm_compose": {"completed": completed,
                        "rows_per_sec_lossy": compose_rate,
                        "wire_frames_lost": lost,
                        "chaos_dropped": dropped,
                        "retransmits_got": rts}}}


def test_transport_tripwire_passes_on_healthy_sweep():
    assert transport_tripwires(_transport_art()) == []
    assert transport_tripwires({"metric": "m"}) == []  # vacuous


def test_transport_win_requires_shm_strictly_above_zmq_json():
    probs = transport_tripwires(_transport_art(zj=100.0, shm=100.0))
    assert any("TRANSPORT-WIN" in p for p in probs)
    probs = transport_tripwires(_transport_art(zj=100.0, shm=95.0))
    assert any("TRANSPORT-WIN" in p for p in probs)
    # a missing shm arm is a WIN failure, not a silent pass
    art = _transport_art()
    del art["transport_comparison_3proc"]["shm"]
    assert any("TRANSPORT-WIN" in p for p in transport_tripwires(art))


def test_transport_win_requires_bytes_per_row_unchanged():
    """Framing moves head bytes, never blob bytes: a bytes/row drift
    between arms means a codec touched payload rows."""
    probs = transport_tripwires(_transport_art(bytes_row=4.4,
                                               shm_bytes=5.0))
    assert any("TRANSPORT-WIN" in p and "bytes/row" in p for p in probs)
    assert transport_tripwires(_transport_art(bytes_row=4.4,
                                              shm_bytes=4.4)) == []


def test_transport_compose_must_complete_clean_and_engaged():
    probs = transport_tripwires(_transport_art(completed=False,
                                               compose_rate=None))
    assert any("TRANSPORT-COMPOSE" in p for p in probs)
    probs = transport_tripwires(_transport_art(lost=3))
    assert any("TRANSPORT-COMPOSE" in p and "unrecovered" in p
               for p in probs)
    # a compose arm whose injector or repair never fired proves nothing
    probs = transport_tripwires(_transport_art(dropped=0))
    assert any("TRANSPORT-COMPOSE" in p for p in probs)
    probs = transport_tripwires(_transport_art(rts=0))
    assert any("TRANSPORT-COMPOSE" in p for p in probs)


def test_transport_compose_arm_never_enters_the_throughput_gate():
    """The compose arm runs under active seeded loss: its rate lives
    under rows_per_sec_lossy, invisible to the run-to-run ±10% gate in
    both directions (same contract as the chaos arms)."""
    pts = throughput_points(_transport_art())
    assert not any(p.endswith("shm_compose") for p in pts), pts
    assert compare(_transport_art(compose_rate=200.0),
                   _transport_art(compose_rate=10.0), 0.10) == []


def _rebal_art(static_imb=2.8, rb_imb=1.4, migrations=3,
               completed=True, lost=0):
    return {"rebalance_3proc": {
        "permuted": {"completed": True,
                     "rows_per_sec_per_process": 100.0},
        "static": {"completed": True, "rows_per_sec_skewed": 40.0,
                   "serve_load_imbalance": static_imb,
                   "wire_frames_lost": 0},
        "rebalance": {"completed": completed,
                      "rows_per_sec_skewed": 60.0,
                      "serve_load_imbalance": rb_imb,
                      "migrations": migrations,
                      "wire_frames_lost": lost},
    }}


def test_rebalance_tripwire_passes_on_healthy_sweep():
    assert rebalance_tripwires(_rebal_art()) == []
    assert rebalance_tripwires({"metric": "m"}) == []  # vacuous


def test_rebalance_tripwire_fails_without_migration_or_improvement():
    probs = rebalance_tripwires(_rebal_art(migrations=0))
    assert any("REBAL-SKEW" in p and "0 migrations" in p for p in probs)
    # imbalance must be STRICTLY below the static arm's
    probs = rebalance_tripwires(_rebal_art(rb_imb=2.8))
    assert any("REBAL-SKEW" in p and "not strictly below" in p
               for p in probs)
    probs = rebalance_tripwires(_rebal_art(rb_imb=None))
    assert any("REBAL-SKEW" in p for p in probs)


def test_rebalance_tripwire_dead_arm_fails():
    probs = rebalance_tripwires(_rebal_art(completed=False))
    assert any("REBAL-DEAD" in p for p in probs)
    probs = rebalance_tripwires(_rebal_art(lost=2))
    assert any("REBAL-DEAD" in p for p in probs)


def test_rebalance_skewed_arms_never_enter_the_throughput_gate():
    """Skewed-arm rows/sec is one hot owner's serve rate (static) or a
    mid-migration transient (rebalance) — both live under the
    gate-invisible rows_per_sec_skewed key, like the chaos arms."""
    pts = throughput_points(_rebal_art())
    assert [p for p in pts] == ["rebalance_3proc/permuted"], pts


def _trace_art(un=100.0, tr=95.0, merge_ok=True, flows=12):
    return {"metric": "m", "trace_overhead_3proc": {
        "untraced": {"rows_per_sec_per_process": un},
        "traced": {"rows_per_sec_per_process": tr,
                   "merge_ok": merge_ok, "flows_linked": flows,
                   "merged_trace": "/tmp/x/merged_trace.json"},
    }}


def test_trace_tripwire_passes_on_healthy_sweep():
    assert trace_tripwires(_trace_art()) == []
    assert trace_tripwires({"metric": "m"}) == []  # vacuous
    # 15% is the line: 85.0 exactly passes, just below fails
    assert trace_tripwires(_trace_art(tr=85.0)) == []


def test_trace_tripwire_tax_beyond_15pct_fails():
    probs = trace_tripwires(_trace_art(tr=80.0))
    assert len(probs) == 1 and "TRACE-TAX" in probs[0]
    # a missing traced rate is a tax failure too, not a silent pass
    art = _trace_art()
    del art["trace_overhead_3proc"]["traced"]["rows_per_sec_per_process"]
    assert any("TRACE-TAX" in p for p in trace_tripwires(art))


def test_trace_tripwire_unmergeable_or_flowless_trace_fails():
    probs = trace_tripwires(_trace_art(merge_ok=False))
    assert any("TRACE-MERGE" in p for p in probs)
    probs = trace_tripwires(_trace_art(flows=0))
    assert any("TRACE-MERGE" in p for p in probs)


def _obs_art(off=100.0, on=95.0, dumps=2, merge_ok=True,
             kill_completed=True, flight_fields=True):
    kill = {"completed": kill_completed, "lease_term": 1}
    if flight_fields:
        kill["flight_dumps"] = dumps
        kill["flight_merge_ok"] = merge_ok
    return {"metric": "m",
            "obs_tax_3proc": {
                "obs_off": {"rows_per_sec_per_process": off},
                "obs_on": {"rows_per_sec_per_process": on}},
            "control_plane_3proc": {"kill": kill}}


def test_obs_tripwire_passes_on_healthy_artifact():
    assert obs_tripwires(_obs_art()) == []
    # vacuous on artifacts without the sweep / flight fields (an older
    # bench's artifact is not judged for gates its code predates)
    assert obs_tripwires({"metric": "m"}) == []
    art = _obs_art(flight_fields=False)
    del art["obs_tax_3proc"]
    assert obs_tripwires(art) == []
    # 15% is the line: 85.0 exactly passes
    assert obs_tripwires(_obs_art(on=85.0)) == []


def test_obs_tripwire_tax_beyond_band_fails():
    probs = obs_tripwires(_obs_art(on=80.0))
    assert len(probs) == 1 and "OBS-TAX" in probs[0]
    # a missing on-arm rate is a tax failure, not a silent pass
    art = _obs_art()
    del art["obs_tax_3proc"]["obs_on"]["rows_per_sec_per_process"]
    assert any("OBS-TAX" in p for p in obs_tripwires(art))
    # and so is a dead/missing OFF arm: the layer can't be priced
    art = _obs_art()
    del art["obs_tax_3proc"]["obs_off"]["rows_per_sec_per_process"]
    assert any("OBS-TAX" in p for p in obs_tripwires(art))


def test_obs_tripwire_flight_dump_gate():
    # fewer dumps than survivors = a black box silently fell off
    probs = obs_tripwires(_obs_art(dumps=1))
    assert any("FLIGHT-DUMP" in p for p in probs)
    probs = obs_tripwires(_obs_art(dumps=0))
    assert any("FLIGHT-DUMP" in p for p in probs)
    # merge CLI failure trips independently of the dump count
    probs = obs_tripwires(_obs_art(merge_ok=False))
    assert any("FLIGHT-DUMP" in p for p in probs)
    # an arm that did not complete is the CTRL-FAILOVER gate's problem,
    # not this one's (its flight fields may be missing or partial)
    assert obs_tripwires(_obs_art(kill_completed=False,
                                  dumps=0, merge_ok=False)) == []


def _storm_art(*, off_reads=2000.0, on_reads=3000.0, off_p50=15.0,
               on_p50=0.1, off_p99=100.0, on_p99=120.0, local=4000,
               wire=500, stale=0, shed_completed=True, shed=30,
               backpressure=5, on_completed=True) -> dict:
    return {"pull_storm_3proc": {
        "off": {"completed": True, "read_rows_per_sec": off_reads,
                "pull_p50_ms": off_p50, "pull_p99_ms": off_p99},
        "on": {"completed": on_completed, "read_rows_per_sec": on_reads,
               "pull_p50_ms": on_p50, "pull_p99_ms": on_p99,
               "replica_local_rows": local, "replica_wire_rows": wire,
               "stale_reads": stale},
        "shed": {"completed": shed_completed, "shed_redirects": shed,
                 "backpressure": backpressure, "stale_reads": 0}}}


def test_serve_tripwire_passes_on_healthy_sweep():
    assert serve_tripwires(_storm_art()) == []
    # absent sweep (other benches): not this gate's business
    assert serve_tripwires({"metric": "m"}) == []


def test_serve_tripwire_slo_fails_on_no_win_or_disengaged_plane():
    # reads below the off arm beyond the drift band fail; a tie (the
    # 'silently off' shape) is the replica-rows check's job, and small
    # drift passes — the off arm is one hot owner's noisy serve rate
    probs = serve_tripwires(_storm_art(on_reads=1700.0))
    assert any("SERVE-SLO" in p and "costing read throughput" in p
               for p in probs)
    assert serve_tripwires(_storm_art(on_reads=1900.0)) == []
    # zero replica-served rows = plane silently disabled
    probs = serve_tripwires(_storm_art(local=0, wire=0))
    assert any("SERVE-SLO" in p and "silently disabled" in p
               for p in probs)
    # median latency regressing fails; p99 has a slack band
    probs = serve_tripwires(_storm_art(on_p50=20.0))
    assert any("SERVE-SLO" in p and "p50" in p for p in probs)
    assert serve_tripwires(_storm_art(on_p99=240.0)) == []  # in band
    probs = serve_tripwires(_storm_art(on_p99=260.0))  # beyond 2.5x
    assert any("SERVE-SLO" in p and "p99" in p for p in probs)
    # a dead arm fails loudly instead of comparing garbage
    probs = serve_tripwires(_storm_art(on_completed=False))
    assert any("SERVE-SLO" in p and "must complete" in p
               for p in probs)


def test_serve_tripwire_stale_reads_fail():
    probs = serve_tripwires(_storm_art(stale=3))
    assert any("SERVE-STALE" in p for p in probs)


def test_serve_tripwire_shed_must_complete_and_fire():
    probs = serve_tripwires(_storm_art(shed_completed=False))
    assert any("SERVE-SHED" in p and "poison" in p for p in probs)
    probs = serve_tripwires(_storm_art(shed=0, backpressure=0))
    assert any("SERVE-SHED" in p and "silently disabled" in p
               for p in probs)
    # either counter alone satisfies the gate
    assert serve_tripwires(_storm_art(shed=0, backpressure=9)) == []


def test_storm_arms_never_enter_the_throughput_gate():
    """Storm rates live under read_rows_per_sec (gate-invisible): the
    off arm is one hot owner's serve rate and must never feed the
    run-to-run ±10% comparison."""
    art = _storm_art()
    assert throughput_points(art) == {}


def test_backend_mismatch_refuses_cross_backend_compare(capsys):
    prior = {"jax_backend": "tpu", "metric": "m"}
    new = {"jax_backend": "cpu", "metric": "m"}
    probs = backend_mismatch(prior, new)
    assert len(probs) == 1 and "BACKEND-MISMATCH" in probs[0]
    # same backend: clean pass
    assert backend_mismatch(new, dict(new)) == []
    # unstamped prior (pre-stamp artifact): warn, don't refuse — the
    # stamp cannot be invented retroactively
    assert backend_mismatch({"metric": "m"}, new) == []
    assert "WARNING" in capsys.readouterr().out
    assert backend_mismatch({"metric": "m"}, {"metric": "m"}) == []
    # the probe-failure sentinel is a MISSING stamp, not a backend: a
    # transient resolver timeout must warn, never hard-fail the gate
    assert backend_mismatch({"jax_backend": "unknown"}, new) == []
    assert "WARNING" in capsys.readouterr().out
    assert backend_mismatch(prior, {"jax_backend": "unknown"}) == []
    assert backend_mismatch({"jax_backend": "unknown"},
                            {"jax_backend": "unknown"}) == []


def test_backend_mismatch_fails_main_end_to_end(tmp_path):
    p, n = tmp_path / "prior.json", tmp_path / "new.json"
    prior = {**_art({"a": 100.0}), "jax_backend": "tpu"}
    new = {**_art({"a": 100.0}), "jax_backend": "cpu"}
    p.write_text(json.dumps(prior))
    n.write_text(json.dumps(new))
    assert main([str(p), str(n)]) == 1
    n.write_text(json.dumps({**new, "jax_backend": "tpu"}))
    assert main([str(p), str(n)]) == 0


def test_main_end_to_end_exit_codes(tmp_path):
    p, n = tmp_path / "prior.json", tmp_path / "new.json"
    p.write_text(json.dumps(_art({"a": 100.0})))
    n.write_text(json.dumps(_art({"a": 95.0})))
    assert main([str(p), str(n)]) == 0
    n.write_text(json.dumps(_art({"a": 50.0})))
    assert main([str(p), str(n)]) == 1


def _elastic_art(kill: dict, join: dict, steady=None) -> dict:
    return {"elastic_membership_3proc": {
        "steady": ({"completed": True} if steady is None else steady),
        "kill": kill, "join": join}}


_GOOD_KILL = {"completed": True, "blocks_restored": 12,
              "wire_frames_lost": 0, "loss_last": 0.69,
              "finals_agree": True}
_GOOD_JOIN = {"completed": True, "joiner_serve_rows": 431,
              "joiner_serve_requests": 17}


def test_elastic_tripwires_pass_on_healthy_arms():
    assert elastic_tripwires(_elastic_art(_GOOD_KILL, _GOOD_JOIN)) == []
    # absent sweep (other benches): vacuous
    assert elastic_tripwires({}) == []


def test_elastic_dead_trips_on_each_failure_mode():
    # survivors died
    probs = elastic_tripwires(_elastic_art(
        {"completed": False, "error": "x"}, _GOOD_JOIN))
    assert len(probs) == 1 and "ELASTIC-DEAD" in probs[0]
    # completed but nothing restored = death path silently disabled
    probs = elastic_tripwires(_elastic_art(
        {**_GOOD_KILL, "blocks_restored": 0}, _GOOD_JOIN))
    assert any("0 ranges restored" in p for p in probs)
    # unrecovered frames leaked through the transition
    probs = elastic_tripwires(_elastic_art(
        {**_GOOD_KILL, "wire_frames_lost": 3}, _GOOD_JOIN))
    assert any("unrecovered" in p for p in probs)
    # non-finite loss / missing loss
    for bad in (float("nan"), float("inf"), None):
        probs = elastic_tripwires(_elastic_art(
            {**_GOOD_KILL, "loss_last": bad}, _GOOD_JOIN))
        assert any("not finite" in p for p in probs), bad
    # survivors diverged
    probs = elastic_tripwires(_elastic_art(
        {**_GOOD_KILL, "finals_agree": False}, _GOOD_JOIN))
    assert any("disagree" in p for p in probs)
    # an armed-idle fleet failing to complete also trips
    probs = elastic_tripwires(_elastic_art(
        _GOOD_KILL, _GOOD_JOIN, steady={"completed": False}))
    assert any("steady" in p for p in probs)


def test_elastic_join_trips_on_dead_or_idle_joiner():
    probs = elastic_tripwires(_elastic_art(
        _GOOD_KILL, {"completed": False, "error": "x"}))
    assert len(probs) == 1 and "ELASTIC-JOIN" in probs[0]
    probs = elastic_tripwires(_elastic_art(
        _GOOD_KILL, {**_GOOD_JOIN, "joiner_serve_rows": 0}))
    assert len(probs) == 1 and "served 0 rows" in probs[0]


def _ctrl_art(kill: dict, storm: dict, steady=None) -> dict:
    return {"control_plane_3proc": {
        "steady": ({"completed": True, "joins": 0, "leaves": 0,
                    "admits": 0, "drains": 0}
                   if steady is None else steady),
        "kill": kill, "storm": storm}}


_GOOD_CTRL_KILL = {"completed": True, "lease_term": 1,
                   "terms_agree": True, "clock_min": 40, "iters": 40,
                   "blocks_restored": 7, "wire_frames_lost": 0,
                   "finals_agree": True}
_GOOD_CTRL_STORM = {"completed": True, "admits": 1, "drains": 1,
                    "shed_rate_pre": 12.5, "shed_rate_post": 3.0}


def test_control_plane_tripwires_pass_on_healthy_arms():
    assert control_plane_tripwires(
        _ctrl_art(_GOOD_CTRL_KILL, _GOOD_CTRL_STORM)) == []
    # absent sweep (other benches): vacuous
    assert control_plane_tripwires({}) == []
    # post == pre is the boundary: at-or-below passes
    assert control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL,
        {**_GOOD_CTRL_STORM, "shed_rate_post": 12.5})) == []


def test_ctrl_failover_trips_on_each_failure_mode():
    # survivors died under the successor
    probs = control_plane_tripwires(_ctrl_art(
        {"completed": False, "error": "x"}, _GOOD_CTRL_STORM))
    assert len(probs) == 1 and "CTRL-FAILOVER" in probs[0]
    # lease never advanced (succession silently disabled)...
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "lease_term": 0}, _GOOD_CTRL_STORM))
    assert any("exactly once" in p for p in probs)
    # ...or advanced twice (flapped), or survivors disagree on the term
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "lease_term": 2}, _GOOD_CTRL_STORM))
    assert any("exactly once" in p for p in probs)
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "terms_agree": False}, _GOOD_CTRL_STORM))
    assert any("exactly once" in p for p in probs)
    # a lost step across the failover
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "clock_min": 38}, _GOOD_CTRL_STORM))
    assert any("steps were lost" in p for p in probs)
    # nothing restored: the successor never planned the old holder out
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "blocks_restored": 0}, _GOOD_CTRL_STORM))
    assert any("death plan" in p for p in probs)
    # leaked loss / torn finals
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "wire_frames_lost": 2}, _GOOD_CTRL_STORM))
    assert any("unrecovered" in p for p in probs)
    probs = control_plane_tripwires(_ctrl_art(
        {**_GOOD_CTRL_KILL, "finals_agree": False}, _GOOD_CTRL_STORM))
    assert any("disagree" in p for p in probs)


def test_ctrl_scale_trips_on_dead_loop_or_unmoved_sheds():
    # the storm arm died
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, {"completed": False, "error": "x"}))
    assert len(probs) == 1 and "CTRL-SCALE" in probs[0]
    # no admit / no drain: the loop never closed
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, {**_GOOD_CTRL_STORM, "admits": 0}))
    assert any("0 autoscaler admits" in p for p in probs)
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, {**_GOOD_CTRL_STORM, "drains": 0}))
    assert any("0 autoscaler drains" in p for p in probs)
    # admit without recorded load, or sheds that never fell
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, {**_GOOD_CTRL_STORM, "shed_rate_pre": None}))
    assert any("without recorded shed load" in p for p in probs)
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, {**_GOOD_CTRL_STORM, "shed_rate_post": 20.0}))
    assert any("did not fall" in p for p in probs)
    # a calm armed fleet that flapped membership
    probs = control_plane_tripwires(_ctrl_art(
        _GOOD_CTRL_KILL, _GOOD_CTRL_STORM,
        steady={"completed": True, "joins": 1, "leaves": 0,
                "admits": 1, "drains": 0}))
    assert any("flapping without load" in p for p in probs)


@pytest.mark.slow
def test_collect_gate_collects_clean():
    """The real gate against the real tree: `pytest --collect-only` must
    exit 0 — the two seed collection errors (missing hypothesis) are the
    regression this pins."""
    proc = subprocess.run(
        ["bash", str(REPO / "ci" / "collect_gate.sh")],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    summary = proc.stdout.strip().splitlines()[-1]
    assert "collected" in summary and "error" not in summary, summary


# ------------------------------------------- wire-compression tripwires
def _wirecomp_art(*, bi=1.3, bt=0.59, completed=True, lost=0,
                  resident=0, lf=0.69, lt=0.69, agree=True,
                  conv_completed=True) -> dict:
    from ci.bench_regression import WIRE_BYTES_FACTOR  # noqa: F401

    return {"wire_compression_3proc": {
        "zipf_rows": 2048,
        "f32": {"completed": True, "rows_per_sec_per_process": 900.0,
                "wire_push_bytes_per_row_moved": 2.6},
        "int8": {"completed": True, "rows_per_sec_per_process": 880.0,
                 "wire_push_bytes_per_row_moved": bi},
        "topk8": {"completed": completed,
                  "rows_per_sec_per_process": 400.0,
                  "wire_push_bytes_per_row_moved": bt,
                  "wire_frames_lost": lost,
                  "ef_resident_rows": resident},
        "topk4": {"completed": True,
                  "rows_per_sec_per_process": 420.0,
                  "wire_push_bytes_per_row_moved": 0.47,
                  "wire_frames_lost": 0, "ef_resident_rows": 0},
        "converge": {
            "f32": {"completed": conv_completed, "loss_last": lf,
                    "finals_agree": True, "ef_resident_rows": 0},
            "topk8": {"completed": conv_completed, "loss_last": lt,
                      "finals_agree": agree, "ef_resident_rows": 0},
        },
    }}


def test_wire_compression_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import wire_compression_tripwires

    assert wire_compression_tripwires(_wirecomp_art()) == []
    assert wire_compression_tripwires({"metric": "m"}) == []  # vacuous


def test_wire_bytes_requires_2x_over_int8():
    from ci.bench_regression import wire_compression_tripwires

    probs = wire_compression_tripwires(_wirecomp_art(bt=0.7))
    assert any("WIRE-BYTES" in p and "2.0x" in p for p in probs)
    # exactly at the factor passes (<=)
    assert wire_compression_tripwires(_wirecomp_art(bt=0.65)) == []
    # a missing arm is a BYTES failure, not a silent pass
    art = _wirecomp_art()
    del art["wire_compression_3proc"]["topk8"]
    probs = wire_compression_tripwires(art)
    assert any("WIRE-BYTES" in p for p in probs)


def test_wire_bytes_fails_on_loss_or_stranded_mass():
    from ci.bench_regression import wire_compression_tripwires

    probs = wire_compression_tripwires(_wirecomp_art(lost=2))
    assert any("unrecovered" in p for p in probs)
    probs = wire_compression_tripwires(_wirecomp_art(resident=5))
    assert any("stranded" in p for p in probs)
    probs = wire_compression_tripwires(_wirecomp_art(completed=False))
    assert any("must complete" in p for p in probs)


def test_wire_converge_gates_loss_and_finals():
    from ci.bench_regression import wire_compression_tripwires

    probs = wire_compression_tripwires(_wirecomp_art(lf=0.3, lt=0.6))
    assert any("WIRE-CONVERGE" in p and "loss" in p for p in probs)
    probs = wire_compression_tripwires(
        _wirecomp_art(lt=float("nan")))
    assert any("WIRE-CONVERGE" in p for p in probs)
    probs = wire_compression_tripwires(_wirecomp_art(agree=False))
    assert any("finals disagree" in p for p in probs)
    probs = wire_compression_tripwires(
        _wirecomp_art(conv_completed=False))
    assert any("must complete" in p for p in probs)


# ------------------------- multi-tenant tripwires (TENANT-ISO/TENANT-IDLE)
def _tenant_art(*, solo_rate=10_000.0, iso_rate=9_800.0,
                iso_inf_denied=31, iso_trn_denied=0,
                sh_trn_denied=28, sh_shared=1,
                stale=0, lost=0, dropped=0,
                solo_completed=True, iso_completed=True,
                sh_completed=True, equal=True, checked=64,
                tids=(1, 1), idle_counters=0) -> dict:
    def arm(rate, trn_denied, inf_denied, shared, completed):
        return {"completed": completed, "shared": shared,
                "trn_rows_per_sec": rate,
                "read_rows_per_sec": 4_000.0,
                "trn_denied": trn_denied, "inf_denied": inf_denied,
                "stale_reads": stale, "wire_frames_lost": lost,
                "frames_dropped": dropped}
    return {"multi_tenant_3proc": {
        "solo": arm(solo_rate, 0, 0, 0, solo_completed),
        "isolated": arm(iso_rate, iso_trn_denied, iso_inf_denied,
                        0, iso_completed),
        "shared": arm(6_500.0, sh_trn_denied, 40, sh_shared,
                      sh_completed),
        "idle": {"equal": equal, "rows_checked": checked,
                 "tenant_tids": list(tids),
                 "tenant_counters": idle_counters}}}


def test_tenant_tripwires_pass_on_healthy_sweep():
    assert tenant_tripwires(_tenant_art()) == []
    # absent sweep (other benches): vacuous
    assert tenant_tripwires({}) == []


def test_tenant_iso_slo_and_attribution():
    # training tenant dragged >10% below its solo arm by the storm
    probs = tenant_tripwires(_tenant_art(iso_rate=8_000.0))
    assert any("TENANT-ISO" in p and "90%" in p for p in probs)
    assert tenant_tripwires(_tenant_art(iso_rate=9_100.0)) == []
    # storm tenant never denied: the admission split silently disarmed
    probs = tenant_tripwires(_tenant_art(iso_inf_denied=0))
    assert any("TENANT-ISO" in p and "vacuous" in p for p in probs)
    # protected tenant charged for the storm's sheds
    probs = tenant_tripwires(_tenant_art(iso_trn_denied=3))
    assert any("TENANT-ISO" in p and "protected" in p for p in probs)


def test_tenant_iso_shared_contrast_must_show_the_coupling():
    probs = tenant_tripwires(_tenant_art(sh_trn_denied=0))
    assert any("TENANT-ISO" in p and "proves nothing" in p
               for p in probs)
    probs = tenant_tripwires(_tenant_art(sh_shared=0))
    assert any("TENANT-ISO" in p and "fleet bucket" in p
               for p in probs)


def test_tenant_iso_safety_counters_gate_every_arm():
    probs = tenant_tripwires(_tenant_art(stale=2))
    assert sum("stale reads" in p for p in probs) == 3  # all arms
    probs = tenant_tripwires(_tenant_art(lost=1))
    assert any("TENANT-ISO" in p and "lose or drop" in p
               for p in probs)
    probs = tenant_tripwires(_tenant_art(dropped=2))
    assert any("lose or drop" in p for p in probs)
    # a dead arm fails loudly instead of comparing garbage
    probs = tenant_tripwires(_tenant_art(iso_completed=False))
    assert any("TENANT-ISO" in p and "every arm must finish" in p
               for p in probs)


def test_tenant_idle_requires_bitwise_equal_with_the_stamp_engaged():
    probs = tenant_tripwires(_tenant_art(equal=False))
    assert any("TENANT-IDLE" in p and "bitwise-equal" in p
               for p in probs)
    probs = tenant_tripwires(_tenant_art(checked=0))
    assert any("TENANT-IDLE" in p for p in probs)
    # equal-but-disarmed (stamp never rode the wire) is not a pass
    probs = tenant_tripwires(_tenant_art(tids=(0, 0)))
    assert any("TENANT-IDLE" in p and "never engaged" in p
               for p in probs)
    probs = tenant_tripwires(_tenant_art(idle_counters=4))
    assert any("TENANT-IDLE" in p and "zero attributed" in p
               for p in probs)


def test_tenant_arms_never_enter_the_throughput_gate():
    """Tenant arms publish trn_rows_per_sec / read_rows_per_sec (gate-
    invisible): the solo-vs-isolated comparison is TENANT-ISO's job,
    never the run-to-run ±10% comparator's."""
    assert throughput_points(_tenant_art()) == {}


# ------------- million-user tripwires (TRAFFIC-FRESH/SHED/IDLE)
def _traffic_art(*, base_completed=True, crowd_completed=True,
                 over_completed=True, base_unissued=0,
                 crowd_unissued=310, errors=0,
                 crowd_sched_p99=950.0, crowd_svc_p99=95.0,
                 fresh_samples=4_000, fresh_p99=180.0,
                 crowd_budget=2, crowd_burns=3,
                 over_inf_denied=220, over_trn_denied=0,
                 flight_burns=2, burn_tenants=("inf",),
                 stale=0, lost=0, dropped=0,
                 equal=True, checked=64, idle_req=0,
                 idle_sched=0) -> dict:
    def arm(completed, budget, burns, inf_denied, trn_denied,
            unissued, sched_p99=95.0, svc_p99=18.0):
        return {"completed": completed, "scheduled": 540,
                "requests": 540 - errors - unissued,
                "errors": errors,
                "unissued": unissued, "late_issues": 12,
                "sched_p99_ms": sched_p99, "svc_p99_ms": svc_p99,
                "freshness_samples": fresh_samples,
                "freshness_p99_ms": fresh_p99,
                "stamped_frames": 300, "slo_burns": burns,
                "slo_clears": 1, "boost_ticks": 40,
                "inf_max_budget": budget,
                "trn_denied": trn_denied, "inf_denied": inf_denied,
                "stale_reads": stale, "trn_rows_per_sec": 7_000.0,
                "conc": 6,
                "wire_frames_lost": lost, "frames_dropped": dropped}
    over = arm(over_completed, 2, 2, over_inf_denied,
               over_trn_denied, 900, sched_p99=2_000.0,
               svc_p99=190.0)
    over.update({"flight_dumps": 3, "flight_slo_burns": flight_burns,
                 "flight_burn_tenants": sorted(burn_tenants)})
    return {"million_user_3proc": {
        "open_loop_base": arm(base_completed, 1, 0, 0, 0,
                              base_unissued),
        "flash_crowd": arm(crowd_completed, crowd_budget,
                           crowd_burns, 0, 0, crowd_unissued,
                           sched_p99=crowd_sched_p99,
                           svc_p99=crowd_svc_p99),
        "overload_shed": over,
        "idle": {"equal": equal, "rows_checked": checked,
                 "traffic_requests": idle_req,
                 "traffic_scheduled": idle_sched}}}


def test_traffic_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import traffic_tripwires

    assert traffic_tripwires(_traffic_art()) == []
    # absent sweep (other benches): vacuous
    assert traffic_tripwires({}) == []


def test_traffic_fresh_latency_not_loss_and_live_samples():
    from ci.bench_regression import traffic_tripwires

    # the BASE rate must be sustainable: leftover schedule on the
    # flat arm means every latency claim rode an unintended overload
    probs = traffic_tripwires(_traffic_art(base_unissued=30))
    assert any("TRAFFIC-FRESH" in p and "ALL issue" in p
               for p in probs)
    # ... but the stop-boundary sliver (one claimed arrival per
    # dispatcher + 1% of the schedule) is teardown, not overload
    assert traffic_tripwires(_traffic_art(base_unissued=7)) == []
    # ... but CROWD backlog is legitimate (bounded conc cannot drain
    # an 8x burst) — the healthy fabricated sweep carries it
    assert _traffic_art()["million_user_3proc"]["flash_crowd"][
        "unissued"] > 0
    probs = traffic_tripwires(_traffic_art(errors=3))
    assert any("must succeed" in p for p in probs)
    # unissued must be ON the record: a sweep that silently drops the
    # counter is coordinated omission wearing a latency costume
    art = _traffic_art()
    del art["million_user_3proc"]["flash_crowd"]["unissued"]
    probs = traffic_tripwires(art)
    assert any("coordinated omission" in p for p in probs)
    # the crowd's queueing delay must be visible in the sched tail
    probs = traffic_tripwires(_traffic_art(crowd_sched_p99=18.0,
                                           crowd_svc_p99=18.0))
    assert any("never outran the fleet" in p for p in probs)
    # freshness must be measured, and at refresh scale, not backlog
    probs = traffic_tripwires(_traffic_art(fresh_samples=0))
    assert any("TRAFFIC-FRESH" in p and "never measured" in p
               for p in probs)
    probs = traffic_tripwires(_traffic_art(fresh_p99=120_000.0))
    assert any("under a minute" in p for p in probs)
    probs = traffic_tripwires(_traffic_art(fresh_p99=None))
    assert any("under a minute" in p for p in probs)


def test_traffic_fresh_budget_flex_proof_and_safety_counters():
    from ci.bench_regression import traffic_tripwires

    # the crowd must provably flex the budget above the configured 1
    probs = traffic_tripwires(_traffic_art(crowd_budget=1))
    assert any("TRAFFIC-FRESH" in p and "flex the promotion budget"
               in p for p in probs)
    probs = traffic_tripwires(_traffic_art(crowd_burns=0))
    assert any("vacuous" in p for p in probs)
    # the crowd may never degrade to staleness or poison
    probs = traffic_tripwires(_traffic_art(stale=2))
    assert sum("stale reads" in p for p in probs) == 3  # all arms
    probs = traffic_tripwires(_traffic_art(lost=1))
    assert any("poison" in p for p in probs)
    probs = traffic_tripwires(_traffic_art(dropped=2))
    assert any("poison" in p for p in probs)
    probs = traffic_tripwires(_traffic_art(crowd_completed=False))
    assert any("every arm must finish" in p for p in probs)


def test_traffic_shed_attribution_and_flight_box():
    from ci.bench_regression import traffic_tripwires

    probs = traffic_tripwires(_traffic_art(over_inf_denied=0))
    assert any("TRAFFIC-SHED" in p and "admission disarmed" in p
               for p in probs)
    probs = traffic_tripwires(_traffic_art(over_trn_denied=5))
    assert any("TRAFFIC-SHED" in p and "training" in p
               for p in probs)
    probs = traffic_tripwires(_traffic_art(flight_burns=0))
    assert any("post-mortem box" in p for p in probs)
    probs = traffic_tripwires(_traffic_art(burn_tenants=("trn",)))
    assert any("does not name the burning tenant" in p
               for p in probs)


def test_traffic_idle_requires_bitwise_and_zero_schedule():
    from ci.bench_regression import traffic_tripwires

    probs = traffic_tripwires(_traffic_art(equal=False))
    assert any("TRAFFIC-IDLE" in p and "bitwise-equal" in p
               for p in probs)
    probs = traffic_tripwires(_traffic_art(checked=0))
    assert any("TRAFFIC-IDLE" in p for p in probs)
    # equal but the armed driver actually issued requests: not idle
    probs = traffic_tripwires(_traffic_art(idle_req=4, idle_sched=4))
    assert any("TRAFFIC-IDLE" in p and "empty schedule" in p
               for p in probs)


def test_traffic_arms_never_enter_the_throughput_gate():
    """Open-loop rates are OFFERED load (trn_rows_per_sec rides a
    gate-invisible key): the latency/freshness gates are TRAFFIC-*'s
    job, never the run-to-run ±10% comparator's."""
    assert throughput_points(_traffic_art()) == {}


# -------------------------------- mesh-plane tripwires (MESH-WIN/BITWISE)
def _mesh_art(wire=250_000.0, mesh=7_000_000.0, blk8=3_900_000.0,
              mesh_completed=True, blk8_completed=True,
              equal=True, checked=64) -> dict:
    return {"mesh_plane_fused": {
        "wire": {"completed": True, "plane": "wire",
                 "rows_per_sec_per_process": wire},
        "mesh": {"completed": mesh_completed, "plane": "mesh",
                 "mesh_comm": "float32",
                 "rows_per_sec_per_process": mesh},
        "mesh_blk8": {"completed": blk8_completed, "plane": "mesh",
                      "mesh_comm": "blk8",
                      "rows_per_sec_per_process": blk8},
        "bitwise": {"equal": equal, "rows_checked": checked}}}


def test_mesh_tripwires_pass_on_healthy_sweep():
    assert mesh_tripwires(_mesh_art()) == []
    # absent sweep (other benches): vacuous
    assert mesh_tripwires({}) == []


def test_mesh_win_requires_mesh_strictly_above_wire():
    probs = mesh_tripwires(_mesh_art(wire=300_000.0, mesh=290_000.0))
    assert any("MESH-WIN" in p and "not strictly above" in p
               for p in probs)
    # a tie is a loss: the collective plane must WIN
    probs = mesh_tripwires(_mesh_art(wire=100.0, mesh=100.0))
    assert any("MESH-WIN" in p for p in probs)
    # an incomplete mesh arm can never pass
    probs = mesh_tripwires(_mesh_art(mesh_completed=False))
    assert any("MESH-WIN" in p for p in probs)
    # the quantized tier must complete (its rate is recorded, not
    # ordered — quantize costs compute on CPU)
    probs = mesh_tripwires(_mesh_art(blk8_completed=False))
    assert any("mesh_blk8" in p for p in probs)
    assert mesh_tripwires(_mesh_art(blk8=10.0)) == []


def test_mesh_bitwise_requires_equal_finals_and_a_real_drill():
    probs = mesh_tripwires(_mesh_art(equal=False))
    assert any("MESH-BITWISE" in p for p in probs)
    # a drill that checked zero rows proved nothing
    probs = mesh_tripwires(_mesh_art(checked=0))
    assert any("MESH-BITWISE" in p for p in probs)


# ------------------- fail-slow tripwires (SLOW-HEDGE/DRAIN/IDLE)
def _fail_slow_art(u_p99=62.0, h_p99=28.0, fired=120, slowed=300,
                   u_completed=True, h_completed=True,
                   d_completed=True, d_clock=40, verdicts=1,
                   blocks_out=3, d_lost=0, d_agree=True,
                   events=("slow_suspect", "slow_verdict",
                           "hedge_fired", "demote"),
                   idle_equal=True, idle_checked=64,
                   idle_fired=0) -> dict:
    want = {"slow_suspect", "slow_verdict", "hedge_fired", "demote"}
    return {"fail_slow_3proc": {
        "iters": 40, "sick_rank": 1, "reader_rank": 0,
        "unmitigated": {"completed": u_completed,
                        "steps_per_sec_slow": 9.0,
                        "reader_p99_ms": u_p99, "slowed": slowed,
                        "hedges_fired": 0, "wire_frames_lost": 0,
                        "finals_agree": True},
        "hedged": {"completed": h_completed,
                   "steps_per_sec_slow": 11.0,
                   "reader_p99_ms": h_p99, "slowed": slowed,
                   "hedges_fired": fired, "hedges_won": fired,
                   "wire_frames_lost": 0, "finals_agree": True},
        "demote": {"completed": d_completed, "clock_min": d_clock,
                   "steps_per_sec_slow": 12.0,
                   "slow_verdicts": verdicts,
                   "sick_blocks_out": blocks_out,
                   "wire_frames_lost": d_lost,
                   "finals_agree": d_agree,
                   "flight_events": sorted(events),
                   "flight_events_ok": want <= set(events)},
        "idle": {"equal": idle_equal, "rows_checked": idle_checked,
                 "hedges_fired": idle_fired}}}


def test_fail_slow_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import fail_slow_tripwires

    assert fail_slow_tripwires(_fail_slow_art()) == []
    assert fail_slow_tripwires({}) == []  # absent sweep: vacuous


def test_slow_hedge_requires_strict_p99_win_and_engagement():
    from ci.bench_regression import fail_slow_tripwires

    probs = fail_slow_tripwires(_fail_slow_art(u_p99=30.0, h_p99=30.0))
    assert any("SLOW-HEDGE" in p and "strictly below" in p
               for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(h_p99=90.0))
    assert any("strictly below" in p for p in probs)
    # zero hedges fired = silently disarmed plane, whatever the p99
    probs = fail_slow_tripwires(_fail_slow_art(fired=0))
    assert any("0 hedges fired" in p for p in probs)
    # the injector must provably engage
    probs = fail_slow_tripwires(_fail_slow_art(slowed=0))
    assert any("never engaged" in p for p in probs)
    # dead arms can never pass
    probs = fail_slow_tripwires(_fail_slow_art(u_completed=False))
    assert any("unmitigated" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(h_completed=False))
    assert any("hedged" in p for p in probs)


def test_slow_drain_requires_verdict_migration_and_story():
    from ci.bench_regression import fail_slow_tripwires

    probs = fail_slow_tripwires(_fail_slow_art(d_completed=False))
    assert any("SLOW-DRAIN" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(d_clock=38))
    assert any("lost steps" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(verdicts=0))
    assert any("0 quorum slow verdicts" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(blocks_out=0))
    assert any("0 blocks migrated" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(d_lost=3))
    assert any("unrecovered" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(d_agree=False))
    assert any("disagree" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(
        events=("slow_suspect", "hedge_fired")))
    assert any("flight boxes missing" in p for p in probs)


def test_slow_idle_requires_bitwise_and_a_real_drill():
    from ci.bench_regression import fail_slow_tripwires

    probs = fail_slow_tripwires(_fail_slow_art(idle_equal=False))
    assert any("SLOW-IDLE" in p for p in probs)
    probs = fail_slow_tripwires(_fail_slow_art(idle_checked=0))
    assert any("SLOW-IDLE" in p for p in probs)
    # bitwise-equal with hedges fired = equal by luck, not by the floor
    probs = fail_slow_tripwires(_fail_slow_art(idle_fired=3))
    assert any("fired on a clean wire" in p for p in probs)


def _hier_art(h_completed=True, f_completed=True, ratio=2.2,
              agg=25, contribs=25, fallbacks=0, h_lost=0,
              h_agree=True, h_loss=0.672, f_loss=0.672,
              bit_equal=True, bit_checked=96, bit_agg=4,
              idle_equal=True, idle_checked=96,
              idle_agg=0) -> dict:
    return {"hier_agg_3proc": {
        "iters": 40, "group": 2, "tree_ranks": [0, 1],
        "owner_rank": 2,
        "hier": {"completed": h_completed, "hier_spec": "group=2",
                 "l2_tx_bytes": 5000, "l2_frames": 44,
                 "agg_frames": agg, "contribs": contribs,
                 "fallbacks": fallbacks, "loss_last": h_loss,
                 "wire_frames_lost": h_lost, "finals_agree": h_agree},
        "flat": {"completed": f_completed,
                 "hier_spec": "group=2,agg=0",
                 "l2_tx_bytes": 11000, "l2_frames": 100,
                 "agg_frames": 0, "contribs": 0, "fallbacks": 0,
                 "loss_last": f_loss, "wire_frames_lost": 0,
                 "finals_agree": True},
        "l2_bytes_ratio": ratio,
        "bitwise": {"equal": bit_equal, "rows_checked": bit_checked,
                    "agg_frames": bit_agg},
        "idle": {"equal": idle_equal, "rows_checked": idle_checked,
                 "agg_frames": idle_agg}}}


def test_hier_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import hier_tripwires

    assert hier_tripwires(_hier_art()) == []
    assert hier_tripwires({}) == []  # absent sweep: vacuous


def test_hier_win_requires_ratio_engagement_and_trajectory():
    from ci.bench_regression import hier_tripwires

    # the byte win is the whole point: below 1.7x (or absent) trips
    probs = hier_tripwires(_hier_art(ratio=1.4))
    assert any("HIER-WIN" in p and "l2_bytes_ratio" in p
               for p in probs)
    probs = hier_tripwires(_hier_art(ratio=None))
    assert any("l2_bytes_ratio" in p for p in probs)
    # a disengaged tree makes any byte win mislabeled flat traffic
    probs = hier_tripwires(_hier_art(agg=0))
    assert any("never engaged" in p for p in probs)
    probs = hier_tripwires(_hier_art(contribs=0))
    assert any("never engaged" in p for p in probs)
    # fallbacks on a clean wire poison the comparison
    probs = hier_tripwires(_hier_art(fallbacks=2))
    assert any("fallbacks on a clean wire" in p for p in probs)
    # trajectory: aggregated EF must not change what the model learns
    probs = hier_tripwires(_hier_art(h_loss=0.80, f_loss=0.67))
    assert any("diverge" in p for p in probs)
    # dead arms, lost frames, disagreeing finals can never pass
    probs = hier_tripwires(_hier_art(h_completed=False))
    assert any("hier_agg_3proc/hier" in p for p in probs)
    probs = hier_tripwires(_hier_art(f_completed=False))
    assert any("hier_agg_3proc/flat" in p for p in probs)
    probs = hier_tripwires(_hier_art(h_lost=2))
    assert any("unrecovered" in p for p in probs)
    probs = hier_tripwires(_hier_art(h_agree=False))
    assert any("disagree" in p for p in probs)


def test_hier_bitwise_and_idle_require_real_drills():
    from ci.bench_regression import hier_tripwires

    probs = hier_tripwires(_hier_art(bit_equal=False))
    assert any("bitwise-equal" in p for p in probs)
    probs = hier_tripwires(_hier_art(bit_checked=0))
    assert any("hier_agg_3proc/bitwise" in p for p in probs)
    # equal with zero aggregate frames = the tree silently disarmed
    probs = hier_tripwires(_hier_art(bit_agg=0))
    assert any("silently disarmed" in p for p in probs)
    probs = hier_tripwires(_hier_art(idle_equal=False))
    assert any("HIER-IDLE" in p for p in probs)
    probs = hier_tripwires(_hier_art(idle_checked=0))
    assert any("HIER-IDLE" in p for p in probs)
    # aggregate frames under group=1 = a pair wrongly entered hier mode
    probs = hier_tripwires(_hier_art(idle_agg=3))
    assert any("under group=1" in p for p in probs)


# ------------- hybrid-plane tripwires (HYBRID-WIN/HYBRID-IDLE)
def _hybrid_art(t_completed=True, h_completed=True, t_rate=1990.0,
                h_rate=2210.0, t_bytes=5_200_000, h_bytes=5_300_000,
                backend=1, reduces=60, fallbacks=0, demotions=0,
                t_reduces=0, h_lost=0, lt_completed=True,
                lh_completed=True, lh_agree=True, t_loss=0.672,
                h_loss=0.672, l_reduces=40, idle_equal=True,
                idle_checked=96, idle_reduces=0, idle_agg=0,
                deg_equal=True, deg_checked=96, deg_reduces=4,
                deg_fallbacks=0) -> dict:
    return {"hybrid_agg_3proc": {
        "group": 2, "tree_ranks": [0, 1], "owner_rank": 2,
        "tree": {"completed": t_completed,
                 "rows_per_sec_per_process": t_rate,
                 "l2_tx_bytes": t_bytes, "agg_frames": 25,
                 "contribs": 25, "fallbacks": 0,
                 "mesh_reduces": t_reduces, "mesh_agg_fallbacks": 0,
                 "domain_demotions": 0, "backend_mesh": 0,
                 "wire_frames_lost": 0},
        "hybrid": {"completed": h_completed,
                   "rows_per_sec_per_process": h_rate,
                   "l2_tx_bytes": h_bytes, "agg_frames": 25,
                   "contribs": 25, "fallbacks": 0,
                   "mesh_reduces": reduces,
                   "mesh_agg_fallbacks": fallbacks,
                   "domain_demotions": demotions,
                   "backend_mesh": backend,
                   "wire_frames_lost": h_lost},
        "loss_tree": {"completed": lt_completed, "loss_last": t_loss,
                      "finals_agree": True, "mesh_reduces": 0},
        "loss_hybrid": {"completed": lh_completed,
                        "loss_last": h_loss, "finals_agree": lh_agree,
                        "mesh_reduces": l_reduces},
        "idle": {"equal": idle_equal, "rows_checked": idle_checked,
                 "mesh_reduces": idle_reduces,
                 "agg_frames": idle_agg},
        "degenerate": {"equal": deg_equal,
                       "rows_checked": deg_checked,
                       "mesh_reduces": deg_reduces,
                       "mesh_agg_fallbacks": deg_fallbacks}}}


def test_hybrid_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import hybrid_tripwires

    assert hybrid_tripwires(_hybrid_art()) == []
    assert hybrid_tripwires({}) == []  # absent sweep: vacuous


def test_hybrid_win_requires_strict_rate_win_on_a_real_mesh():
    from ci.bench_regression import hybrid_tripwires

    # the rate win is the whole point: slower (or tied) hybrid trips
    probs = hybrid_tripwires(_hybrid_art(h_rate=1800.0))
    assert any("HYBRID-WIN" in p and "not strictly above" in p
               for p in probs)
    probs = hybrid_tripwires(_hybrid_art(h_rate=1990.0))
    assert any("not strictly above" in p for p in probs)
    # the mesh backend must provably engage — else mislabeled host-agg
    probs = hybrid_tripwires(_hybrid_art(backend=0))
    assert any("never engaged" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(reduces=0))
    assert any("never engaged" in p for p in probs)
    # fallbacks or demotions on a clean wire poison the comparison
    probs = hybrid_tripwires(_hybrid_art(fallbacks=2))
    assert any("mesh lane is sick" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(demotions=1))
    assert any("mesh lane is sick" in p for p in probs)
    # mesh reduces in the HOST arm = the baseline ran the lever
    probs = hybrid_tripwires(_hybrid_art(t_reduces=3))
    assert any("silently ran the hybrid backend" in p for p in probs)
    # dead arms and lost frames can never pass
    probs = hybrid_tripwires(_hybrid_art(t_completed=False))
    assert any("hybrid_agg_3proc/tree" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(h_completed=False))
    assert any("hybrid_agg_3proc/hybrid" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(h_lost=2))
    assert any("unrecovered" in p for p in probs)


def test_hybrid_win_bounds_cross_host_bytes_and_trajectory():
    from ci.bench_regression import hybrid_tripwires

    # cross-host bytes: > 10% over the tree = the reduce backend
    # touched the wire (10% only absorbs SSP flush-boundary jitter)
    probs = hybrid_tripwires(
        _hybrid_art(t_bytes=5_000_000, h_bytes=6_000_000))
    assert any("> 10%" in p for p in probs)
    assert hybrid_tripwires(
        _hybrid_art(t_bytes=5_000_000, h_bytes=5_400_000)) == []
    # trajectory: the speed must not come from different math
    probs = hybrid_tripwires(_hybrid_art(h_loss=0.80))
    assert any("diverge" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(lt_completed=False))
    assert any("rank-agreeing" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(lh_agree=False))
    assert any("rank-agreeing" in p for p in probs)
    # a trajectory leg that never reduced certifies nothing
    probs = hybrid_tripwires(_hybrid_art(l_reduces=0))
    assert any("never exercised" in p for p in probs)


def test_hybrid_idle_and_degenerate_require_real_drills():
    from ci.bench_regression import hybrid_tripwires

    probs = hybrid_tripwires(_hybrid_art(idle_equal=False))
    assert any("HYBRID-IDLE" in p and "bitwise-equal" in p
               for p in probs)
    probs = hybrid_tripwires(_hybrid_art(idle_checked=0))
    assert any("HYBRID-IDLE" in p for p in probs)
    # reduces or frames under group=1 = a pair wrongly entered hier
    probs = hybrid_tripwires(_hybrid_art(idle_reduces=2))
    assert any("fired under group=1" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(idle_agg=3))
    assert any("fired under group=1" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(deg_equal=False))
    assert any("one-device mesh" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(deg_checked=0))
    assert any("degenerate" in p for p in probs)
    # equal with zero reduces (or with fallbacks) = equal by luck
    probs = hybrid_tripwires(_hybrid_art(deg_reduces=0))
    assert any("silently disarmed" in p for p in probs)
    probs = hybrid_tripwires(_hybrid_art(deg_fallbacks=1))
    assert any("silently disarmed" in p for p in probs)


# ------------- sparse-deposit tripwires (MESH-SPARSE, in mesh grid)
def _mesh_sparse_art(d_completed=True, s_completed=True, ratio=585.0,
                     rows_ratio=1.05, s_waves=36, d_waves=0) -> dict:
    art = _mesh_art()
    art["mesh_plane_fused"]["sparse_deposit"] = {
        "dense": {"completed": d_completed, "deposit": "dense",
                  "peak_deposit_bytes": 4_194_304,
                  "sparse_waves": d_waves,
                  "rows_per_sec_per_process": 13_600.0},
        "sparse": {"completed": s_completed, "deposit": "sparse",
                   "peak_deposit_bytes": 7_168,
                   "sparse_waves": s_waves,
                   "rows_per_sec_per_process": 14_300.0},
        "peak_bytes_ratio": ratio, "rows_ratio": rows_ratio}
    return art


def test_mesh_sparse_passes_healthy_and_is_vacuous_when_absent():
    assert mesh_tripwires(_mesh_sparse_art()) == []
    # an older artifact without the sub-grid (pre-sparse): vacuous —
    # the plain _mesh_art() healthy test above already covers it
    assert mesh_tripwires(_mesh_art()) == []


def test_mesh_sparse_requires_peak_win_rate_floor_and_engagement():
    probs = mesh_tripwires(_mesh_sparse_art(ratio=3.0))
    assert any("MESH-SPARSE" in p and "peak_bytes_ratio" in p
               for p in probs)
    probs = mesh_tripwires(_mesh_sparse_art(ratio=None))
    assert any("peak_bytes_ratio" in p for p in probs)
    probs = mesh_tripwires(_mesh_sparse_art(rows_ratio=0.80))
    assert any("rows_ratio" in p for p in probs)
    # the sparse arm must provably run sparse waves, and the dense
    # baseline must provably NOT
    probs = mesh_tripwires(_mesh_sparse_art(s_waves=0))
    assert any("0 sparse waves" in p for p in probs)
    probs = mesh_tripwires(_mesh_sparse_art(d_waves=2))
    assert any("DENSE" in p for p in probs)
    # dead arms can never pass
    probs = mesh_tripwires(_mesh_sparse_art(d_completed=False))
    assert any("both deposit arms" in p for p in probs)
    probs = mesh_tripwires(_mesh_sparse_art(s_completed=False))
    assert any("both deposit arms" in p for p in probs)


def test_shape_mismatch_refuses_cross_shape_compare(capsys):
    prior = {"device_shape": "cpu:3", "metric": "m"}
    new = {"device_shape": "cpu:8", "metric": "m"}
    probs = shape_mismatch(prior, new)
    assert len(probs) == 1 and "SHAPE-MISMATCH" in probs[0]
    # same shape: clean pass
    assert shape_mismatch(new, dict(new)) == []
    # unstamped prior (pre-stamp artifact): warn, don't refuse
    assert shape_mismatch({"metric": "m"}, new) == []
    assert "WARNING" in capsys.readouterr().out
    # the mesh-arm-failed sentinel is a MISSING stamp, never a shape
    assert shape_mismatch({"device_shape": "unknown"}, new) == []
    assert "WARNING" in capsys.readouterr().out
    assert shape_mismatch({"device_shape": "unknown"},
                          {"device_shape": "unknown"}) == []


def test_shape_mismatch_fails_main_end_to_end(tmp_path):
    p, n = tmp_path / "prior.json", tmp_path / "new.json"
    prior = {**_art({"a": 100.0}), "device_shape": "cpu:3"}
    new = {**_art({"a": 100.0}), "device_shape": "cpu:8"}
    p.write_text(json.dumps(prior))
    n.write_text(json.dumps(new))
    assert main([str(p), str(n)]) == 1
    n.write_text(json.dumps({**new, "device_shape": "cpu:3"}))
    assert main([str(p), str(n)]) == 0


# --------- planned redistribution gates (RESHARD-MEM / RESHARD-SAFE)
def _reshard_art(mem_equal=True, mem_peak=1 << 20, mem_cap=1 << 20,
                 mem_p2p=12 << 20, pl_completed=True,
                 pp_completed=True, pl_drained=True, moved=131,
                 slices=131, rounds=6, peak_pl=4040, peak_pp=21848,
                 pl_lost=0, pl_agree=True, p2p_absent=True,
                 k_completed=True, restored=3, k_lost=0, k_agree=True,
                 part_completed=True, part_slices=131,
                 part_events=("reshard_round",)) -> dict:
    return {"reshard_3proc": {
        "iters": 30, "cap": 4096, "drain_at": 8, "kill_step": 10,
        "drain_planned": {
            "completed": pl_completed, "leaver_drained": pl_drained,
            "blocks_moved": moved, "peak_p2p": peak_pl,
            "wire_frames_lost": pl_lost, "finals_agree": pl_agree,
            "reshard": {"plans": 1, "rounds": rounds,
                        "slices": slices, "dup_slices": 0,
                        "aborts": 0, "peak_planned": peak_pl}},
        "drain_p2p": {
            "completed": pp_completed, "leaver_drained": True,
            "blocks_moved": moved, "peak_p2p": peak_pp,
            "wire_frames_lost": 0, "finals_agree": True,
            "reshard_absent": p2p_absent},
        "kill": {"completed": k_completed,
                 "blocks_restored": restored,
                 "reshard_aborts": 0, "wire_frames_lost": k_lost,
                 "finals_agree": k_agree},
        "part": {
            "completed": part_completed, "leaver_drained": True,
            "blocks_moved": moved, "peak_p2p": peak_pl,
            "wire_frames_lost": 0, "finals_agree": True,
            "reshard": {"plans": 1, "rounds": rounds,
                        "slices": part_slices, "dup_slices": 0,
                        "aborts": 0, "peak_planned": peak_pl},
            "flight_dumps": 3,
            "flight_events": sorted(part_events),
            "flight_events_ok": "reshard_round" in part_events},
        "mem": {"equal": mem_equal, "cap": mem_cap,
                "peak_planned": mem_peak, "peak_p2p": mem_p2p,
                "chunks": 8}}}


def test_reshard_tripwires_pass_on_healthy_sweep():
    from ci.bench_regression import reshard_tripwires

    assert reshard_tripwires(_reshard_art()) == []
    assert reshard_tripwires({}) == []  # absent sweep: vacuous


def test_reshard_mem_requires_measured_caps_both_ways():
    from ci.bench_regression import reshard_tripwires

    # the streaming drill: bitwise, capped, and a baseline above cap
    probs = reshard_tripwires(_reshard_art(mem_equal=False))
    assert any("RESHARD-MEM" in p and "bitwise" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(mem_peak=(1 << 20) + 1))
    assert any("outside (0, cap=" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(mem_peak=0))
    assert any("outside (0, cap=" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(mem_p2p=1 << 19))
    assert any("too small" in p for p in probs)
    # the live wire: planned peak within cap, p2p one-shot above it
    probs = reshard_tripwires(_reshard_art(peak_pl=5000))
    assert any("drain_planned" in p and "did not hold" in p
               for p in probs)
    probs = reshard_tripwires(_reshard_art(peak_pp=4000))
    assert any("drain_p2p" in p and "not above cap" in p
               for p in probs)
    probs = reshard_tripwires(_reshard_art(moved=0))
    assert any("moved nothing" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(slices=0))
    assert any("never shipped a slice round" in p for p in probs)
    # planner leaking into the baseline arm poisons the A/B
    probs = reshard_tripwires(_reshard_art(p2p_absent=False))
    assert any("leaked into the p2p arm" in p for p in probs)


def test_reshard_safe_requires_survival_and_the_story():
    from ci.bench_regression import reshard_tripwires

    for kw in ({"pl_completed": False}, {"pp_completed": False},
               {"part_completed": False}):
        probs = reshard_tripwires(_reshard_art(**kw))
        assert any("RESHARD-SAFE" in p and "completed=" in p
                   for p in probs)
    probs = reshard_tripwires(_reshard_art(pl_drained=False))
    assert any("never reached its drained exit" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(pl_lost=2))
    assert any("unrecovered frames" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(pl_agree=False))
    assert any("disagree" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(k_completed=False))
    assert any("kill" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(restored=0))
    assert any("0 blocks restored" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(k_lost=1))
    assert any("kill" in p and "unrecovered" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(k_agree=False))
    assert any("kill" in p and "disagree" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(part_slices=0))
    assert any("never exercised the planner" in p for p in probs)
    probs = reshard_tripwires(_reshard_art(part_events=()))
    assert any("missing reshard_round" in p for p in probs)
