"""`make -C cpp sanitize` — the asan/tsan drill for the native components
(SURVEY.md §5.2, VERDICT r1 #8). Skips when no compiler is present."""

import shutil
import subprocess

import pytest


@pytest.mark.slow
def test_native_components_clean_under_sanitizers():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    proc = subprocess.run(
        ["make", "-C", "cpp", "sanitize"], capture_output=True, text=True,
        timeout=600, cwd=str(__import__("pathlib").Path(__file__).resolve().parents[1]))
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "asan + tsan clean" in proc.stdout
