"""Dynamic data-block assignment — the HDFS block assigner/coordinator of
the reference lineage (SURVEY.md §1 L5: "HDFS block assigner/coordinator in
the FlexPS lineage"), rebuilt host-side for the TPU framework.

The reference statically shards data per worker only in the simplest apps;
the lineage's coordinator hands out *blocks* dynamically so fast workers
take more blocks (straggler mitigation) and a dead worker's unfinished
blocks can be re-queued (SURVEY.md §5.3 failure handling). That is exactly
what SSP-style asynchrony wants on the data side, so the rebuild keeps it:

- ``split_rows`` / ``split_file_lines`` produce JSON-serializable block
  descriptors (row ranges, or newline-aligned byte ranges of a text file).
- ``LocalBlockAssigner`` — thread-safe queue for single-process Engines
  (threads-as-workers, SURVEY.md §4).
- ``BlockMaster`` / ``BlockClient`` — the multi-process protocol over the
  control bus (comm/bus.py): workers request the next block, the master
  (process 0) assigns; ``done`` acks retire a block, and
  ``BlockMaster.handle_failure(pid)`` re-queues a dead worker's outstanding
  blocks for the survivors.

The bus does not loop a process's own messages back to itself, so the
master's co-located worker passes ``local_master=`` to its client and is
served by direct call — same code path, no sockets.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Iterator, Optional

Block = dict  # JSON-serializable descriptor; "id" is the only required key


def split_rows(n_rows: int, block_size: int) -> list[Block]:
    """Row-range blocks [{"id", "start", "end"}] covering [0, n_rows)."""
    if block_size <= 0:
        raise ValueError("block_size must be positive")
    return [{"id": k, "start": s, "end": min(s + block_size, n_rows)}
            for k, s in enumerate(range(0, n_rows, block_size))]


def split_file_lines(path: str, lines_per_block: int) -> list[Block]:
    """Newline-aligned byte-range blocks of a text file:
    [{"id", "path", "offset", "nbytes", "lines"}]. One scan; no line is ever
    split across blocks (the HDFS-block analog for local/NFS files)."""
    if lines_per_block <= 0:
        raise ValueError("lines_per_block must be positive")
    blocks: list[Block] = []
    start = 0
    lines = 0
    pos = 0
    last_byte = b"\n"
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            base = pos
            at = 0
            while True:
                nl = chunk.find(b"\n", at)
                if nl < 0:
                    break
                lines += 1
                at = nl + 1
                if lines == lines_per_block:
                    end = base + at
                    blocks.append({"id": len(blocks), "path": path,
                                   "offset": start, "nbytes": end - start,
                                   "lines": lines})
                    start, lines = end, 0
            pos += len(chunk)
            last_byte = chunk[-1:]
    if pos > start:  # tail; an unterminated final line still counts as one
        blocks.append({"id": len(blocks), "path": path, "offset": start,
                       "nbytes": pos - start,
                       "lines": lines + (last_byte != b"\n")})
    return blocks


def read_block_bytes(block: Block) -> bytes:
    """Read one ``split_file_lines`` block back as raw bytes (whole lines
    by construction) — feed to a mem parser without a splitlines pass."""
    with open(block["path"], "rb") as f:
        f.seek(block["offset"])
        return f.read(block["nbytes"])


def read_block_lines(block: Block) -> list[bytes]:
    """Read one ``split_file_lines`` block back as its lines."""
    return read_block_bytes(block).splitlines()


def iter_block_batches(client, parse_block, batch_size: int,
                       drop_last: bool = True):
    """Stream fixed-size batches out of dynamically assigned blocks — the
    out-of-core input pipeline for file-backed training (Criteo-1TB scale,
    SURVEY.md §7.4 item 4): ``parse_block(block) -> dict[str, np.ndarray]``
    materializes ONE block at a time; rows left over from a block carry into
    the next, so batch shape stays static for the TPU step regardless of
    block size. ``client`` is a BlockClient (or any iterable of blocks, e.g.
    a plain list for single-worker use)."""
    import numpy as np

    if batch_size <= 0:
        raise ValueError("batch_size must be positive")
    buf: Optional[dict] = None
    for block in client:
        d = parse_block(block)
        buf = d if buf is None else \
            {k: np.concatenate([buf[k], d[k]]) for k in buf}
        n = len(next(iter(buf.values())))
        s = 0
        while n - s >= batch_size:
            yield {k: v[s:s + batch_size] for k, v in buf.items()}
            s += batch_size
        buf = {k: v[s:] for k, v in buf.items()}
    if (not drop_last and buf is not None
            and len(next(iter(buf.values())))):
        yield buf  # ragged tail (eval sweeps; training wants drop_last)


class LocalBlockAssigner:
    """Thread-safe dynamic block queue with per-worker outstanding tracking
    (the in-process coordinator; workers are threads, SURVEY.md §4)."""

    def __init__(self, blocks: list[Block]):
        self._q: deque[Block] = deque(blocks)
        self._outstanding: dict[int, dict[int, Block]] = {}
        self._lock = threading.Lock()

    def next_block(self, worker: int = 0) -> Optional[Block]:
        """Pop the next block for ``worker`` (None when exhausted). The block
        stays outstanding until ``done`` or a ``requeue_worker``."""
        with self._lock:
            if not self._q:
                return None
            b = self._q.popleft()
            self._outstanding.setdefault(worker, {})[b["id"]] = b
            return b

    def done(self, worker: int, block_id: int) -> None:
        with self._lock:
            self._outstanding.get(worker, {}).pop(block_id, None)

    def requeue_worker(self, worker: int) -> int:
        """Return a dead worker's outstanding blocks to the queue (failure
        handling, SURVEY.md §5.3). Returns how many were re-queued."""
        with self._lock:
            stale = self._outstanding.pop(worker, {})
            self._q.extend(stale.values())
            return len(stale)

    @property
    def remaining(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def outstanding_total(self) -> int:
        """Blocks popped but not yet done/re-queued, across all workers."""
        with self._lock:
            return sum(len(v) for v in self._outstanding.values())


class BlockMaster:
    """Bus-side coordinator (runs on one process, conventionally id 0):
    serves ``blk_req`` with ``blk_asn`` and retires blocks on ``blk_done``.

    Assignment is idempotent per (sender, req): a client that never saw the
    reply (lost frame, slow master) retries the SAME req id and gets the
    SAME block back — without this, a timed-out request would strand its
    already-popped block on a live worker forever (never trained, never
    re-queued by ``handle_failure`` because the worker isn't dead).

    Exhaustion is answered with "wait, retry" for up to ``wait_grace``
    seconds while blocks are still outstanding on other workers: a dead
    holder's blocks come back via ``handle_failure`` within the heartbeat
    timeout, and answering None in that window would let survivors exit
    with those blocks stranded. The wait MUST be bounded: a live holder can
    be SSP-gate-blocked precisely because the starved requester stopped
    clocking — an unbounded wait is a three-way deadlock (requester waits
    for a block, holder's gate waits for the requester's clock)."""

    def __init__(self, bus, blocks: list[Block], wait_grace: float = 6.0):
        self.bus = bus
        self.assigner = LocalBlockAssigner(blocks)
        self.wait_grace = wait_grace
        # last (req, block) served per sender; client reqs are sequential,
        # so one entry per sender bounds memory
        self._last: dict[int, tuple] = {}
        self._wait_since: dict[int, float] = {}
        self._lock = threading.Lock()
        bus.on("blk_req", self._on_req)
        bus.on("blk_done", self._on_done)

    _WAIT = object()  # _last marker: this req was answered "retry later"

    def _on_req(self, sender: int, payload: dict) -> None:
        req = payload.get("req")
        with self._lock:
            last = self._last.get(sender)
            if last is not None and last[0] == req:
                block = last[1]  # duplicate request: re-serve, don't re-pop
                # a wait'd req must KEEP answering wait: the client has
                # moved on to a fresh req id, so popping a real block for
                # the stale id would be dropped as stale and stranded
                if block is self._WAIT:
                    self.bus.publish("blk_asn", {"to": sender, "req": req,
                                                 "wait": True})
                    return
            else:
                import time as _time

                block = self.assigner.next_block(sender)
                if (block is None
                        and self.assigner.outstanding_total > 0
                        and (_time.monotonic()
                             - self._wait_since.setdefault(
                                 sender, _time.monotonic()))
                        < self.wait_grace):
                    # queue empty but blocks are still OUT — a dead
                    # worker's come back via handle_failure within the
                    # heartbeat timeout, so retry for wait_grace; past
                    # that the holders are live (they will finish their
                    # own blocks) and the requester must be released to
                    # retire, or a gate-blocked holder deadlocks with it
                    self._last[sender] = (req, self._WAIT)
                    self.bus.publish("blk_asn", {"to": sender, "req": req,
                                                 "wait": True})
                    return
                if block is not None:
                    self._wait_since.pop(sender, None)
                self._last[sender] = (req, block)
        self.bus.publish("blk_asn", {"to": sender, "req": req,
                                     "block": block})

    def _on_done(self, sender: int, payload: dict) -> None:
        self.assigner.done(sender, payload.get("block_id"))

    def handle_failure(self, process_id: int) -> int:
        """Re-queue a dead process's outstanding blocks (wire this to the
        HeartbeatMonitor's on_failure)."""
        return self.assigner.requeue_worker(process_id)


class BlockClient:
    """Worker-side handle: ``next_block()`` asks the master for work;
    iteration drains until the master reports exhaustion."""

    def __init__(self, bus, *, local_master: Optional[BlockMaster] = None,
                 timeout: float = 30.0, retry_every: float = 1.0):
        self.bus = bus
        self.timeout = timeout
        self.retry_every = retry_every
        self._local = local_master
        self._req = 0
        self._waiting: Optional[int] = None
        self._replies: dict[int, Optional[Block]] = {}
        self._cond = threading.Condition()
        if local_master is None:
            bus.on("blk_asn", self._on_asn)

    def _on_asn(self, sender: int, payload: dict) -> None:
        if payload.get("to") != self.bus.my_id:
            return  # assignment addressed to another worker
        with self._cond:
            if payload.get("req") != self._waiting:
                return  # stale reply for an abandoned request: don't leak
            self._replies[payload.get("req")] = payload
            self._cond.notify_all()

    def next_block(self) -> Optional[Block]:
        """Next block, or None when the master's queue is exhausted. The
        request is re-published every ``retry_every`` seconds until answered
        (the master re-serves duplicates idempotently), so a lost frame
        costs latency, not a block."""
        import time

        if self._local is not None:
            # same bounded wait as the master gives remote clients
            deadline = time.monotonic() + self._local.wait_grace
            while True:
                b = self._local.assigner.next_block(self.bus.my_id)
                if (b is None
                        and self._local.assigner.outstanding_total > 0
                        and time.monotonic() < deadline):
                    time.sleep(min(self.retry_every, 0.25))
                    continue
                return b
        deadline = time.monotonic() + self.timeout
        while True:
            with self._cond:
                self._req += 1
                req = self._req
                self._waiting = req
            try:
                reply = None
                while reply is None:
                    self.bus.publish("blk_req", {"req": req})
                    with self._cond:
                        if self._cond.wait_for(
                                lambda: req in self._replies,
                                min(self.retry_every,
                                    max(deadline - time.monotonic(),
                                        0.01))):
                            reply = self._replies.pop(req)
                    if reply is None and time.monotonic() > deadline:
                        raise TimeoutError(
                            f"block request {req} unanswered after "
                            f"{self.timeout}s (master process dead?)")
            finally:
                with self._cond:
                    self._waiting = None
            if not reply.get("wait"):
                return reply.get("block")
            # queue empty but blocks outstanding elsewhere: retry with a
            # FRESH req id (the master served this one) until they either
            # come back (dead-worker re-queue) or all complete
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "block queue drained but blocks still outstanding "
                    f"after {self.timeout}s")
            time.sleep(self.retry_every)

    def done(self, block: Block) -> None:
        if self._local is not None:
            self._local.assigner.done(self.bus.my_id, block["id"])
        else:
            self.bus.publish("blk_done", {"block_id": block["id"]})

    def __iter__(self) -> Iterator[Block]:
        """Drain: yields blocks and acks each one after the loop body ran
        (ack-on-next-yield keeps at most one block outstanding per worker).

        Deliberately NOT ack-on-close: a consumer that stops early —
        whether by ``break`` or because its step raised — reaches the
        generator identically as GeneratorExit, and acking there would
        retire a block a FAILING worker never trained, so handle_failure
        could not re-queue it (silent data loss). Leaving it outstanding
        costs the benign break case at most the master's bounded
        ``wait_grace`` before peers see true exhaustion."""
        while True:
            b = self.next_block()
            if b is None:
                return
            yield b
            self.done(b)
