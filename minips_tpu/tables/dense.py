"""DenseTable — the KVTable + RangeManager + updater collapsed into data.

The reference's dense path is ``VectorStorage<Val>`` on server threads, a
``SimpleRangeManager`` contiguous key partition, and a server-side updater
applied at push (SURVEY.md §2 "KVTable storage", "SimpleRangeManager",
"Updaters"; §3.3 hot loop). TPU-first, all three collapse into one object:

- The table's key space 0..n-1 is a flat parameter vector, padded to ``P``
  and sharded in contiguous ranges across the mesh's ``data`` axis — the
  range partition *is* the ``PartitionSpec``.
- ``pull``  ≡ ``all_gather``  of the owner shards (SURVEY.md §2.3).
- ``push``  ≡ ``psum_scatter`` of worker grads into the owner shard followed
  by the optax updater on that shard — i.e. weight-update sharding
  (PAPERS.md, arXiv 2004.13336), which is exactly the PS server role.
- ``make_step`` fuses pull → grad → push → update into ONE jitted SPMD
  program so XLA overlaps the collectives with compute; this is the hot
  path replacing the reference's zmq round-trips (SURVEY.md §3.3).

Apps see parameters as a pytree: the table ravels any pytree template via
``jax.flatten_util.ravel_pytree``, so "keys" are positions in the raveled
vector — the same world view as the reference's integer key space.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.parallel.mesh import DATA_AXIS, padded_size
from minips_tpu.parallel.partition import RangePartitioner
from minips_tpu.tables.updaters import (Adam8bitState, LearningRate,
                                        make_updater, masked_merge_adam8)
from minips_tpu.utils import jaxcompat

PyTree = Any


def cast_floating(tree: PyTree, dtype) -> PyTree:
    """Cast every floating leaf of ``tree`` to ``dtype`` (ints/bools pass
    through) — the shared mixed-precision downcast used by
    ``DenseTable.make_step`` and ``PSTrainStep`` so both paths keep the
    same contract. ``dtype=None`` is the identity."""
    if dtype is None:
        return tree
    dt = jnp.dtype(dtype)

    def down(x):
        return (x.astype(dt)
                if jnp.issubdtype(jnp.result_type(x), jnp.floating) else x)

    return jax.tree.map(down, tree)


class DenseTable:
    """A dense parameter table sharded across the mesh ``data`` axis."""

    def __init__(
        self,
        template: PyTree,
        mesh: Mesh,
        *,
        name: str = "dense0",
        updater: str = "sgd",
        lr: LearningRate = 0.1,
        grad_reduce: str = "mean",
        tx: Optional[optax.GradientTransformation] = None,
        updater_kwargs: Optional[dict] = None,
    ):
        if grad_reduce not in ("mean", "sum"):
            raise ValueError("grad_reduce must be 'mean' or 'sum'")
        self.name = name
        self.mesh = mesh
        self.grad_reduce = grad_reduce
        self.num_shards = mesh.shape[DATA_AXIS]

        flat, self._unravel = ravel_pytree(template)
        self.num_keys = int(flat.shape[0])
        kw = dict(updater_kwargs or {})
        # adam8's blockwise-quantized moments need whole blocks per shard
        # (one f32 scale per `block` contiguous elements); align the
        # range padding instead of erroring — padding keys are zeros with
        # zero grads, so they quantize to zero codes and never move
        align = int(kw.get("block", 256)) if updater == "adam8" else 1
        self.partitioner = RangePartitioner(self.num_keys, self.num_shards,
                                            align=align)
        self.padded = self.partitioner.padded
        self._shard_shape = (self.padded // self.num_shards,)
        # clip-by-global-norm must see the GLOBAL gradient, but the optax
        # transform runs on one owner shard inside shard_map — intercept
        # and apply it in the fused step with a cross-shard psum instead
        self._clip_norm = float(kw.pop("clip_norm", 0.0) or 0.0)
        if kw.get("decay_mask") is not None:
            # a params-shaped pytree mask (e.g. transformer.decay_mask)
            # travels the same ravel as the params; padding rows never
            # decay (they are zeros and must stay zeros)
            mflat, _ = ravel_pytree(kw["decay_mask"])
            if mflat.shape != flat.shape:
                raise ValueError(
                    f"decay_mask ravels to {mflat.shape}, params to "
                    f"{flat.shape} — the mask must be params-shaped")
            kw["decay_mask"] = (jnp.zeros(self.padded, flat.dtype)
                                .at[: self.num_keys].set(mflat))
        self.tx = tx if tx is not None else make_updater(updater, lr, **kw)

        self._pspec = P(DATA_AXIS)
        self._sharding = NamedSharding(mesh, self._pspec)
        padded_flat = jnp.zeros(self.padded, flat.dtype).at[: self.num_keys].set(flat)
        self.params = jax.device_put(padded_flat, self._sharding)

        opt_state = jax.eval_shape(self.tx.init, self.params)
        a8 = [x for x in jax.tree.leaves(
                  opt_state, is_leaf=lambda l: isinstance(l, Adam8bitState))
              if isinstance(x, Adam8bitState)]
        block = a8[0].mu_q.shape[0] // a8[0].mu_s.shape[0] if a8 else 0
        if block and self._shard_shape[0] % block:
            raise ValueError(
                f"quantized opt state with block={block} does not align "
                f"with shard size {self._shard_shape[0]}: each contiguous "
                "range shard must hold whole blocks (use updater='adam8' "
                "so the table aligns its padding, or pick a block that "
                "divides the shard size)")
        self._opt_specs = self._opt_specs_tree(opt_state)
        opt_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self._opt_specs,
            is_leaf=lambda x: isinstance(x, P))
        # Note: specs describe the *global* opt leaves; inside shard_map
        # sharded leaves have the per-shard shape.
        self.opt_state = jax.jit(
            self.tx.init, out_shardings=opt_shardings
        )(self.params)

    def _opt_specs_tree(self, opt_state) -> PyTree:
        """Spec tree for the opt state: params-length 1-D leaves range-
        shard; an ``Adam8bitState``'s OWN scale fields (``mu_s``/``nu_s``)
        are tagged structurally — by position in that state, never by
        shape inference (ADVICE r4 low: a foreign 1-D leaf that happens
        to length-match padded/block must stay replicated, or shard_map
        would silently hand its transform a slice). Scalars (adam's
        count) and everything else stay replicated. Works for
        updater='adam8' and for a user-supplied quantized tx alike."""
        def leaf_spec(leaf) -> P:
            if getattr(leaf, "ndim", None) == 1 \
                    and leaf.shape[0] == self.padded:
                return P(DATA_AXIS)
            return P()

        def node_spec(x):
            if isinstance(x, Adam8bitState):
                # codes are params-length (leaf rule would shard them
                # anyway); scales are tagged BECAUSE they are this
                # state's scales — contiguous range shards hold whole
                # blocks, so they slice in alignment with the codes
                return Adam8bitState(P(), P(DATA_AXIS), P(DATA_AXIS),
                                     P(DATA_AXIS), P(DATA_AXIS))
            return leaf_spec(x)  # the outer map decomposed other nodes

        return jax.tree.map(
            node_spec, opt_state,
            is_leaf=lambda x: isinstance(x, Adam8bitState))

    # ------------------------------------------------------------------ pull
    def pull(self) -> PyTree:
        """Full parameter pytree (all-gather of the owner shards).

        Reference: ``KVClientTable::Pull/Get`` over all keys (SURVEY.md §2
        "KVClientTable"). Under jit this is an all-gather on ICI; as a host
        call it just reads the (distributed) array.
        """
        return self._unravel(self.params[: self.num_keys])

    def pull_keys(self, keys: np.ndarray) -> jnp.ndarray:
        """Sparse read of a dense table (emulation/API-parity path)."""
        return self.params[jnp.asarray(keys)]

    # ------------------------------------------------------------------ push
    def push(self, grads: PyTree) -> None:
        """Apply a full-pytree gradient through the server-side updater.

        Reference: ``KVClientTable::Push/Add`` → server ``updater->Update``
        (SURVEY.md §3.3). The caller passes the already-reduced gradient
        (the engine's fused path reduces across workers itself).
        """
        gflat, _ = ravel_pytree(grads)
        self._push_flat(jnp.zeros(self.padded, gflat.dtype)
                        .at[: self.num_keys].set(gflat))

    def push_keys(self, keys: np.ndarray, vals: jnp.ndarray) -> None:
        """Sparse additive push into a dense table (emulation path).

        Per-key server semantics (SURVEY.md §3.3 ``updater->Update(keys,
        grads)``): only the pushed keys' parameters and elementwise
        optimizer state move; untouched keys are masked out so stateful
        updaters (adam/momentum) do not drift them. Scalar opt-state
        (e.g. adam's step count) still advances once per push.
        """
        keys = jnp.asarray(keys)
        flat = jnp.zeros(self.padded, self.params.dtype).at[keys].add(vals)
        mask = jnp.zeros(self.padded, self.params.dtype).at[keys].set(1.0)
        self.params, self.opt_state = self._jit_apply_masked(
            self.params, self.opt_state, flat, mask)

    def _push_flat(self, flat_grads: jnp.ndarray) -> None:
        self.params, self.opt_state = self._jit_apply(
            self.params, self.opt_state, flat_grads
        )

    def _make_apply(self, masked: bool):
        vec_shard = (self.padded // self.num_shards,)
        in_specs = (self._pspec, self._opt_specs, self._pspec) + (
            (self._pspec,) if masked else ())

        clip_norm = self._clip_norm

        def apply_shard(p_shard, opt_shard, g_shard, *mask):
            if clip_norm:
                # same cross-shard global-norm clip as the fused step —
                # a clip_norm kwarg must never be a silent no-op on the
                # push()/push_keys() paths
                sumsq = jax.lax.psum(jnp.sum(g_shard * g_shard),
                                     DATA_AXIS)
                g_shard = g_shard * jnp.minimum(
                    1.0, clip_norm * jax.lax.rsqrt(
                        jnp.maximum(sumsq, 1e-16)))
            updates, new_opt = self.tx.update(g_shard, opt_shard, p_shard)
            if masked:
                m = mask[0]
                updates = updates * m

                def restore(new, old):
                    # quantized moments restore at BLOCK granularity —
                    # an elementwise where() on the codes alone leaves
                    # them paired with recomputed scales (ADVICE r4
                    # medium: silent moment drift on untouched keys)
                    if isinstance(new, Adam8bitState):
                        return masked_merge_adam8(new, old, m)
                    return (jnp.where(m > 0, new, old)
                            if getattr(new, "shape", ()) == vec_shard
                            else new)

                new_opt = jax.tree.map(
                    restore, new_opt, opt_shard,
                    is_leaf=lambda x: isinstance(x, Adam8bitState))
            return optax.apply_updates(p_shard, updates), new_opt

        return jax.jit(
            jaxcompat.shard_map(apply_shard, mesh=self.mesh, in_specs=in_specs,
                          out_specs=(self._pspec, self._opt_specs)),
            donate_argnums=(0, 1))

    @functools.cached_property
    def _jit_apply(self):
        return self._make_apply(masked=False)

    @functools.cached_property
    def _jit_apply_masked(self):
        return self._make_apply(masked=True)

    # ------------------------------------------------------------- fused step
    def make_step(
        self,
        grad_fn: Callable[[PyTree, Any], tuple[jnp.ndarray, PyTree]],
        *,
        batch_spec: Optional[PyTree] = None,
        jit: bool = True,
        comm: str = "float32",
        accum: int = 1,
        compute_dtype: Optional[Any] = None,
    ):
        """Fuse pull → grad → push → update into one SPMD program.

        ``grad_fn(params_pytree, batch_shard) -> (loss, grads_pytree)`` runs
        per worker on its batch shard; the returned ``step(params, opt,
        batch) -> (params, opt, loss)`` is the TPU-native rewrite of one hot
        loop iteration (SURVEY.md §3.3): all-gather (pull), local grad
        (worker compute on MXU), psum_scatter (push), optax on the owner
        shard (server update). BSP is implicit — the collectives are the
        barrier (SURVEY.md §2 "BSPModel").

        ``comm`` compresses the two collectives' wire format ("bfloat16" or
        "int8"; EQuARX-style, see ops/quantized_comm.py). Params and the
        optimizer update stay float32 — only bytes-on-wire change.

        ``compute_dtype`` (e.g. ``jnp.bfloat16``) runs the worker math in
        reduced precision — the MXU-native mixed-precision recipe: float32
        master weights and optimizer update on the owner shard, with
        params AND floating batch leaves cast down before ``grad_fn`` and
        the gradients cast back up before the push, so the loss surface is
        evaluated in bf16 but the update path never loses master-weight
        precision. Composes with ``comm`` (wire) and ``accum`` (the f32
        microbatch fold).

        ``accum`` > 1 splits each shard's batch into that many microbatches
        and folds their grads in float32 under one ``lax.scan`` before the
        single push/update — effective batch grows ``accum``x while
        activation memory stays one microbatch's worth (one pull, one
        push, one optimizer step per call, so PS clock semantics are
        unchanged). The leading batch dim must divide by ``accum``.
        """
        n, padded = self.num_keys, self.padded
        num_workers = self.num_shards
        clip_norm = self._clip_norm
        unravel, tx, reduce = self._unravel, self.tx, self.grad_reduce
        bspec = batch_spec if batch_spec is not None else P(DATA_AXIS)
        if accum < 1:
            raise ValueError(f"accum must be >= 1, got {accum}")
        from minips_tpu.ops.quantized_comm import (
            _check, quantized_all_gather, quantized_psum_scatter)
        _check(comm)  # eager: tracing happens on first step call

        if compute_dtype is not None:
            cd = jnp.dtype(compute_dtype)
            user_grad_fn = grad_fn

            def grad_fn(params, batch):  # noqa: F811 - deliberate wrap
                loss, grads = user_grad_fn(cast_floating(params, cd),
                                           cast_floating(batch, cd))
                return (loss.astype(jnp.float32),
                        cast_floating(grads, jnp.float32))

        def _grads_flat(params, batch):
            if accum == 1:
                loss, grads = grad_fn(params, batch)
                return loss, ravel_pytree(grads)[0]

            def to_micro(x):
                if x.shape[0] % accum:
                    raise ValueError(
                        f"batch dim {x.shape[0]} must divide by "
                        f"accum={accum}")
                return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

            micro = jax.tree.map(to_micro, batch)

            def fold(carry, mb):
                loss_sum, gsum = carry
                loss, grads = grad_fn(params, mb)
                return (loss_sum + loss, gsum + ravel_pytree(grads)[0]), None

            # fresh carries are axis-invariant but fold outputs vary
            # wherever params OR batch do (a replicated batch still yields
            # varying grads via the all-gathered params) — pcast keeps the
            # scan carry type fixed
            vma = frozenset()
            for leaf in jax.tree.leaves((params, batch)):
                vma = vma | getattr(jaxcompat.typeof(leaf), "vma", frozenset())
            loss0, g0 = jnp.zeros((), jnp.float32), jnp.zeros(n)
            need = tuple(sorted(vma))
            if need:
                loss0 = jaxcompat.pcast(loss0, need, to="varying")
                g0 = jaxcompat.pcast(g0, need, to="varying")
            (loss_sum, gsum), _ = jax.lax.scan(fold, (loss0, g0), micro)
            if reduce == "sum":
                # sum-semantics grad_fns: microbatch sums add up to the
                # full-batch sum — averaging would scale grads by 1/accum
                return loss_sum, gsum
            return loss_sum / accum, gsum / accum

        def local_step(p_shard, opt_shard, batch):
            full = quantized_all_gather(p_shard, DATA_AXIS, comm)      # pull
            loss, gflat = _grads_flat(unravel(full[:n]), batch)
            gpad = jnp.zeros(padded, gflat.dtype).at[:n].set(gflat)
            g_shard = quantized_psum_scatter(gpad, DATA_AXIS, comm)    # push
            if reduce == "mean":
                g_shard = g_shard / num_workers
            if clip_norm:
                # global-norm clip across ALL shards (the optax transform
                # would only see this shard's slice)
                sumsq = jax.lax.psum(jnp.sum(g_shard * g_shard),
                                     DATA_AXIS)
                g_shard = g_shard * jnp.minimum(
                    1.0, clip_norm * jax.lax.rsqrt(
                        jnp.maximum(sumsq, 1e-16)))
            updates, opt_shard = tx.update(g_shard, opt_shard, p_shard)
            p_shard = optax.apply_updates(p_shard, updates)
            return p_shard, opt_shard, jax.lax.pmean(loss, DATA_AXIS)

        step = jaxcompat.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(self._pspec, self._opt_specs, bspec),
            out_specs=(self._pspec, self._opt_specs, P()),
        )
        if jit:
            step = jax.jit(step, donate_argnums=(0, 1))
        return step

    def step_inplace(self, step, batch) -> jnp.ndarray:
        """Run a fused step against the table's own state."""
        self.params, self.opt_state, loss = step(self.params, self.opt_state, batch)
        return loss

    # ------------------------------------------------------------- state I/O
    def state_dict(self) -> dict:
        """Host copies for checkpointing (params + opt state). Multi-host
        safe: non-addressable (cross-process sharded) leaves are fetched
        with a process allgather — a collective, so every process must
        call this together (the reference's Dump is likewise coordinated,
        SURVEY.md §3.5)."""
        from minips_tpu.comm.cluster import host_copy

        return {
            "params": host_copy(self.params),
            "opt_state": jax.tree.map(host_copy, self.opt_state),
        }

    def global_arrays(self) -> dict:
        """The live (sharded) jax arrays, for coordinated multi-host
        checkpointing: hand these to orbax so every process writes only
        its addressable shards (no host gather, no full copy anywhere) —
        the globally-sharded checkpoint path (SURVEY.md §5.4)."""
        return {"params": self.params, "opt_state": self.opt_state}

    def load_state_dict(self, state: dict) -> None:
        self.params = jax.device_put(
            jnp.asarray(state["params"]), self._sharding)
        # Graft by leaf order, not structure: a checkpoint roundtrip turns
        # optax's namedtuple states into plain lists, but leaf order is
        # deterministic either way.
        cur_leaves, treedef = jax.tree.flatten(self.opt_state)
        # A leafless opt state (sgd: all EmptyState) writes no npz entry at
        # all, so the key may be legitimately absent from the checkpoint.
        new_leaves = jax.tree.leaves(state.get("opt_state", ()))
        if len(cur_leaves) != len(new_leaves):
            raise ValueError(
                f"opt state leaf count mismatch: table has "
                f"{len(cur_leaves)}, checkpoint has {len(new_leaves)} "
                "(different updater?)")
        self.opt_state = jax.tree.unflatten(treedef, [
            jax.device_put(jnp.asarray(new), cur.sharding)
            for cur, new in zip(cur_leaves, new_leaves)
        ])
