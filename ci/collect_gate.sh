#!/usr/bin/env bash
# Fast CI gate: the whole test tree must COLLECT clean before anything
# runs. Catches import-time breakage (a renamed module, a missing
# optional dep that should importorskip, a syntax error in a slow-tier
# file) in seconds instead of failing 8 minutes into the tier — the two
# seed collection errors this gate exists for were exactly that shape
# (`ModuleNotFoundError: hypothesis` crashed collection of two files).
#
# Usage: ci/collect_gate.sh  (exit 0 = collects clean, non-zero = gate
# failed; output is the pytest collection summary)
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m pytest tests/ --collect-only -q -p no:cacheprovider
