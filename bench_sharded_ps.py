"""Sharded multi-process PS throughput curve (VERDICT r2 #2).

Measures train/sharded_ps.py — the key-range-sharded multi-process server —
via apps/sharded_ps_bench.py workers: rows/sec and wire-bytes/sec of the
pull→push cycle per process, with model math stripped out so the number
isolates routing + serialization + bus + server-side updater (the
reference's Mailbox/ServerThread hot path, SURVEY.md §3.3 hot spots b+c).

The sweep:
- world size 1 (standalone, zero wire: the pure server-apply ceiling)
  then 2→4 real processes over loopback;
- zmq vs the native C++ TCP mailbox at world size 3;
- sparse key-slice path vs dense contiguous-range path at world size 3.

Everything here is HOST-CPU loopback — the sharded PS is the control-plane
topology (real pods put one process per node); these are deliberately NOT
chip rates and never feed vs_baseline. Emits ONE JSON line.

Usage: python bench_sharded_ps.py [--iters 60] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

def _worker_argv(path: str, iters: int, warmup: int,
                 compute: str = "none",
                 hidden: int | None = None,
                 push_comm: str = "float32",
                 pull_wire: str = "f32",
                 overlap: bool = False,
                 overlap_legs: str = "both",
                 key_dist: str = "uniform",
                 staleness: float | None = None,
                 cache_bytes: int = 0,
                 pull_dedup: bool = True,
                 push_dedup: bool = True,
                 rows: int | None = None,
                 updater: str | None = None,
                 pull_timeout: float | None = None,
                 zipf_permute_hot: bool = True,
                 trace: str | None = None) -> list[str]:
    argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", path, "--iters", str(iters), "--warmup", str(warmup)]
    if trace:
        argv += ["--trace", trace]
    if compute != "none":
        argv += ["--compute", compute]
    if hidden is not None:
        argv += ["--hidden", str(hidden)]
    if push_comm != "float32":
        argv += ["--push-comm", push_comm]
    if pull_wire != "f32":
        argv += ["--pull-wire", pull_wire]
    if overlap:
        argv += ["--overlap"]
        if overlap_legs != "both":
            argv += ["--overlap-legs", overlap_legs]
    if key_dist != "uniform":
        argv += ["--key-dist", key_dist]
    if not zipf_permute_hot:
        argv += ["--no-zipf-permute-hot"]
    if staleness is not None:
        argv += ["--staleness", str(staleness)]
    if cache_bytes:
        argv += ["--cache-bytes", str(cache_bytes)]
    if not pull_dedup:
        argv += ["--no-pull-dedup"]
    if not push_dedup:
        argv += ["--no-push-dedup"]
    if rows is not None:
        argv += ["--rows", str(rows)]
    if updater is not None:
        argv += ["--updater", updater]
    if pull_timeout is not None:
        argv += ["--pull-timeout", str(pull_timeout)]
    return argv


def _run(n: int, path: str, iters: int, warmup: int, bus: str,
         compute: str = "none", force_cpu: bool = False,
         hidden: int | None = None, push_comm: str = "float32",
         pull_wire: str = "f32", overlap: bool = False,
         overlap_legs: str = "both", key_dist: str = "uniform",
         staleness: float | None = None, cache_bytes: int = 0,
         pull_dedup: bool = True, push_dedup: bool = True,
         rows: int | None = None,
         updater: str | None = None,
         chaos: str | None = None, reliable: bool = False,
         pull_timeout: float | None = None,
         zipf_permute_hot: bool = True, rebalance: str | None = None,
         trace: str | None = None, wire_fmt: str | None = None,
         obs: str | None = None, flight: str | None = None,
         may_fail: bool = False, timeout: float = 300.0) -> dict:
    """One sweep point → {rows_per_sec_per_process, aggregate, wire...}.

    ``compute="jit"`` adds a real jitted model-grad step between pull and
    push on every worker — rank 0 on the default backend (the chip when
    alive and ``force_cpu`` is False), peers on CPU — the north-star
    topology (accelerator workers against a sharded host PS) instead of
    the bare control plane. ``hidden`` sizes that step's MLP."""
    argv = _worker_argv(path, iters, warmup, compute, hidden,
                        push_comm, pull_wire, overlap, overlap_legs,
                        key_dist, staleness, cache_bytes, pull_dedup,
                        push_dedup, rows, updater, pull_timeout,
                        zipf_permute_hot, trace)
    # ALWAYS pinned, even for the zmq arms: an armed MINIPS_BUS=shm in
    # the invoking shell must not silently move the zmq baseline arms
    # onto the shm backend (TRANSPORT-WIN would then compare shm vs shm)
    env_extra = {"MINIPS_BUS": bus}
    if force_cpu:
        env_extra["MINIPS_FORCE_CPU"] = "1"
    # chaos/reliable arms configure via env (launcher-inherited, no
    # per-app flag plumbing); explicit empty strings keep an armed
    # environment from leaking into the clean arms — MINIPS_TRACE too:
    # the traced arm uses the worker's --trace flag, and an armed
    # environment must not silently trace (and tax) every other arm
    env_extra["MINIPS_CHAOS"] = chaos or ""
    env_extra["MINIPS_RELIABLE"] = "1" if reliable else ""
    env_extra["MINIPS_REBALANCE"] = rebalance or ""
    env_extra["MINIPS_TRACE"] = ""
    # elastic membership + kill/liveness knobs pinned off for the same
    # reason: an armed environment must not leak into non-elastic arms
    env_extra["MINIPS_ELASTIC"] = ""
    env_extra["MINIPS_CHAOS_KILL"] = ""
    env_extra["MINIPS_HEARTBEAT"] = ""
    # planned redistribution schedules migration state rounds — an
    # armed MINIPS_RESHARD must not silently re-lane (or refuse, with
    # no rebalancer armed) the non-reshard arms
    env_extra["MINIPS_RESHARD"] = ""
    # multi-tenant tables ride their own sweep; an armed
    # MINIPS_TENANT must not stamp (and re-bucket) the other arms
    env_extra["MINIPS_TENANT"] = ""
    # SLO burn accounting + the open-loop traffic driver ride the
    # million_user sweep; an armed MINIPS_SLO would flex replica
    # budgets (and pressure the autoscaler) under every other arm
    env_extra["MINIPS_SLO"] = ""
    env_extra["MINIPS_TRAFFIC"] = ""
    # the in-mesh collective plane rides its own sweep via --plane; an
    # armed MINIPS_MESH must not reroute (or refuse) the wire arms
    env_extra["MINIPS_MESH"] = ""
    # the hierarchical push tree + its hybrid (agg=mesh) backend ride
    # their own sweeps; an armed MINIPS_HIER must not silently re-lane
    # every wire arm's pushes through a tree (each arm's rate would
    # then measure the tree, not the lever under test)
    env_extra["MINIPS_HIER"] = ""
    env_extra["MINIPS_HIER_MESH_COMM"] = ""
    env_extra["MINIPS_HIER_MESH_DEVS"] = ""
    # head-codec arm config (the transport sweep): explicit empty keeps
    # an armed environment from leaking a format into the other arms
    env_extra["MINIPS_WIRE_FMT"] = wire_fmt or ""
    # push-wire tier rides the --push-comm flag; the env spelling is
    # pinned EMPTY so an armed MINIPS_PUSH_COMM can't silently move a
    # baseline arm onto the compressed wire (the table's env default
    # only fires when the flag is absent — which is every f32 arm)
    env_extra["MINIPS_PUSH_COMM"] = ""
    # windowed-metrics + flight-recorder layers: empty = their DEFAULT
    # (both always-on — that is the point of this layer), "0" = off
    # (only the obs_tax_3proc off arm passes it: the honesty A/B)
    env_extra["MINIPS_OBS"] = obs or ""
    env_extra["MINIPS_FLIGHT"] = flight or ""
    if n == 1:  # standalone zero-wire baseline (no launcher, no bus)
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout,
                              env={**os.environ, **env_extra})
        if proc.returncode != 0:
            raise RuntimeError(f"standalone worker failed: {proc.stderr}")
        res = [json.loads([ln for ln in proc.stdout.splitlines()
                           if ln.startswith("{")][-1])]
    else:
        from minips_tpu import launch

        try:
            res = launch.run_local_job(
                n, argv, base_port=None,  # OS-assigned free block
                env_extra=env_extra or None,
                timeout=timeout)
        except Exception as e:  # noqa: BLE001 - may_fail arms record it
            if not may_fail:
                raise
            # the chaos sweep's retransmit-off arms are EXPECTED to die
            # (that outcome is the measurement): record the death WITHOUT
            # a rows_per_sec_per_process key — the arm's outcome is
            # bimodal by design, so it must never enter the run-to-run
            # REGRESSED/MISSING throughput gate in either direction
            return {"completed": False, "error": str(e)[:300]}
    per = [r["rows_per_sec"] for r in res]
    wire = [r["wire_push_bytes_per_sec"] + r["wire_pull_bytes_per_sec"]
            for r in res]
    out = {
        "rows_per_sec_per_process": round(statistics.mean(per), 1),
        "completed": True,
        "aggregate_rows_per_sec": round(sum(per), 1),
        "wire_bytes_per_sec_per_process": round(statistics.mean(wire), 1),
        # 1 decimal: the sweep-point resolution the artifact history uses
        # (26.7 f32 both legs → 20.0 one int8 leg → 13.3 both)
        "wire_bytes_per_row_moved": round(statistics.mean(
            [r["wire_bytes_per_row_moved"] for r in res]), 1),
        # the push leg alone (WIRE-BYTES gates it: the compressed push
        # tiers move push bytes only, so the pull leg must not dilute
        # the comparison) — same rows-moved denominator as above
        "wire_push_bytes_per_row_moved": round(statistics.mean(
            [r["wire_push_bytes_per_sec"] / max(r["rows_per_sec"], 1e-9)
             for r in res]), 3),
    }
    efs = [r.get("ef") for r in res]
    if any(e is not None for e in efs):
        out["ef_resident_rows"] = sum((e or {}).get("resident_rows", 0)
                                      for e in efs)
        out["ef_flushed_rows"] = sum(
            (e or {}).get("flushed_age", 0)
            + (e or {}).get("flushed_fence", 0)
            + (e or {}).get("flushed_overflow", 0) for e in efs)
    fracs = [r["timing"].get("pull_overlap_fraction")
             for r in res if r.get("timing")]
    fracs = [f for f in fracs if f is not None]
    if fracs:
        out["pull_overlap_fraction"] = round(statistics.mean(fracs), 4)
    if compute != "none":
        out["worker_compute"] = sorted({r.get("compute", "?")
                                        for r in res})
    # row-flow + cache observables (the dedup/cache sweep's evidence):
    # wire-row fraction from the per-rank timers; hit rate from the
    # caches (None — distinct from 0.0 — when the arm runs cache-off)
    reqs = sum(r["timing"].get("pull_rows_requested", 0) for r in res)
    wires = sum(r["timing"].get("pull_rows_wire", 0) for r in res)
    if reqs:
        out["pull_rows_wire_frac"] = round(wires / reqs, 4)
    caches = [r.get("cache") for r in res]
    if any(c is not None for c in caches):
        hits = sum(c["hits"] for c in caches if c)
        looks = sum(c["lookups"] for c in caches if c)
        out["cache_hit_rate"] = (round(hits / looks, 4) if looks
                                 else 0.0)
    # the workers echo their wire formats — a silent flag-plumbing
    # regression must not publish a float32 number labeled int8 (nor a
    # synchronous number labeled overlapped)
    echoed = {r.get("push_comm", "float32") for r in res}
    assert echoed == {push_comm}, (push_comm, echoed)
    echoed_pw = {r.get("pull_wire", "f32") for r in res}
    assert echoed_pw == {pull_wire}, (pull_wire, echoed_pw)
    echoed_ov = {bool(r.get("overlap")) for r in res}
    assert echoed_ov == {overlap}, (overlap, echoed_ov)
    echoed_legs = {r.get("overlap_legs") for r in res}
    assert echoed_legs == {overlap_legs if overlap else None}, (
        overlap_legs, echoed_legs)
    echoed_kd = {r.get("key_dist", "uniform") for r in res}
    assert echoed_kd == {key_dist}, (key_dist, echoed_kd)
    echoed_cb = {r.get("cache_bytes", 0) for r in res}
    assert echoed_cb == {cache_bytes}, (cache_bytes, echoed_cb)
    echoed_dd = {r.get("pull_dedup", True) for r in res}
    assert echoed_dd == {pull_dedup}, (pull_dedup, echoed_dd)
    echoed_pd = {r.get("push_dedup", True) for r in res}
    assert echoed_pd == {push_dedup}, (push_dedup, echoed_pd)
    echoed_ch = {r.get("chaos_spec") for r in res}
    assert echoed_ch == {chaos or None}, (chaos, echoed_ch)
    echoed_rl = {bool(r.get("reliable_on")) for r in res}
    assert echoed_rl == {bool(reliable)}, (reliable, echoed_rl)
    echoed_rb = {r.get("rebalance_spec") for r in res}
    assert echoed_rb == {rebalance or None}, (rebalance, echoed_rb)
    if n > 1:  # wire-format echo (standalone runs have no bus)
        echoed_wf = {r.get("wire_fmt") for r in res}
        assert echoed_wf == {wire_fmt or "bin"}, (wire_fmt, echoed_wf)
    if trace:  # every rank of a traced arm must have dumped its file
        assert all(r.get("trace_file") for r in res), \
            [r.get("trace_file") for r in res]
    if key_dist == "zipf":
        echoed_ph = {r.get("zipf_permute_hot") for r in res}
        assert echoed_ph == {zipf_permute_hot}, (zipf_permute_hot,
                                                 echoed_ph)
    # per-owner serve load: max/mean across ranks is the partition-
    # imbalance observable (1.0 = balanced) — the rebalance sweep's
    # REBAL-SKEW tripwire compares it between arms
    srv = [r.get("serve") for r in res]
    if all(s is not None for s in srv):
        rows_served = [s["pull_rows"] + s["push_rows"] for s in srv]
        mean_served = sum(rows_served) / len(rows_served)
        out["serve_rows_per_rank"] = rows_served
        if mean_served > 0:
            out["serve_load_imbalance"] = round(
                max(rows_served) / mean_served, 4)
    rbs = [r.get("rebalance") for r in res if r.get("rebalance")]
    if rbs:
        out["migrations"] = sum(r["blocks_in"] for r in rbs)
        out["routing_epoch"] = max(r["epoch"] for r in rbs)
    # wire-health roll-up for the resilience sweep: unrecovered loss must
    # read 0 on every completed chaos arm, and the recovery counters are
    # the evidence the layer (not luck) carried the run
    lost = sum(r.get("wire_frames_lost", 0) for r in res)
    out["wire_frames_lost"] = lost
    rels = [r.get("reliable") for r in res if r.get("reliable")]
    if rels:
        out["retransmits_got"] = sum(r["retransmits_got"] for r in rels)
        out["nacks_sent"] = sum(r["nacks_sent"] for r in rels)
        out["frames_gave_up"] = sum(r["gave_up"] for r in rels)
    chs = [r.get("chaos") for r in res if r.get("chaos")]
    if chs:
        out["chaos_dropped"] = sum(c["dropped"] for c in chs)
    if staleness is not None:
        echoed_s = {r.get("staleness") for r in res}
        assert echoed_s == {int(staleness)}, (staleness, echoed_s)
    return out


def fail_slow_arms(quick: bool = False) -> dict:
    import glob as _glob
    import tempfile

    from minips_tpu import launch as _launch

    f_iters = 30 if quick else 40
    fbase = [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_example",
             "--model", "sparse", "--mode", "ssp",
             "--staleness", "2", "--iters", str(f_iters),
             "--batch", "64",
             # the read storm aims at rank 1's hot range from step
             # 2 THROUGH the last step so the windowed (last-K-
             # rolls) p99 measures warmed steady-state reads, past
             # the cold-start replica promotion window
             "--storm-from", "2", "--storm-until", str(f_iters),
             "--storm-pulls", "6", "--storm-keys", "64"]
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_RESHARD": "",
            "MINIPS_RELIABLE": "", "MINIPS_REBALANCE": "",
            "MINIPS_TRACE": "", "MINIPS_SERVE": "",
            "MINIPS_BUS": "", "MINIPS_WIRE_FMT": "",
            "MINIPS_CHAOS_KILL": "", "MINIPS_PUSH_COMM": "",
            "MINIPS_MESH": "", "MINIPS_AUTOSCALE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
            "MINIPS_ELASTIC": "", "MINIPS_SLOW": "",
            "MINIPS_HEDGE": "", "MINIPS_OBS": "",
            "MINIPS_FLIGHT": "", "MINIPS_HEARTBEAT": "",
            # the injection: every frame FROM rank 1 arrives 40ms
            # late at both peers (replies, acks, clock gossip —
            # the whole outbound plane of a sick NIC), jittered
            # ~8ms on the 1->2 link so detection sees variance
            "MINIPS_CHAOS": "11:slow#1>0=40,slow#1>2=40~8"}
    serve = ("replicas=1,hot=200,topk=200,interval=0.05,"
             "min_heat=1")
    grid: dict = {"iters": f_iters, "sick_rank": 1,
                  "reader_rank": 0}

    def arm(name: str, extra_env: dict, flight: str = "") -> dict:
        try:
            res = _launch.run_local_job(
                3, list(fbase), base_port=None,
                env_extra={**env0, **extra_env}, timeout=240.0)
            win = [(((d.get("window") or {}).get("hist") or {})
                    .get("pull_latency") or {}) for d in res]
            sums = {d.get("param_sum") for d in res}
            hedges = [d.get("hedge") or {} for d in res]
            slw = [d.get("slowness") or {} for d in res]
            out = {
                "completed": all(d.get("event") == "done"
                                 for d in res),
                "steps_per_sec_slow": round(
                    f_iters / max(max(d["wall_s"] for d in res),
                                  1e-9), 2),
                "clock_min": min(d.get("clock", 0) for d in res),
                # the SLOW-HEDGE observable: the designated
                # reader's WARMED windowed read p99 (rank 0 — not
                # a holder, so its slow legs must hedge over the
                # wire; cumulative p99 would charge the arm for
                # the pre-promotion cold start)
                "reader_p99_ms": win[0].get("p99_ms"),
                "p99_ms_by_rank": [w.get("p99_ms") for w in win],
                "hedges_fired": sum(h.get("fired", 0)
                                    for h in hedges),
                "hedges_won": sum(h.get("won", 0)
                                  for h in hedges),
                "slow_suspects_raised": sum(
                    s.get("suspects_raised", 0) for s in slw),
                "slow_verdicts": sum(
                    (d.get("membership") or {}).get(
                        "slow_verdicts", 0) for d in res),
                "sick_blocks_out": (res[1].get("rebalance")
                                    or {}).get("blocks_out", 0),
                "slowed": sum((d.get("chaos") or {}).get(
                    "slowed", 0) for d in res),
                "wire_frames_lost": sum(
                    d.get("wire_frames_lost", 0) for d in res),
                "finals_agree": len(sums) == 1,
            }
            if flight:
                files = sorted(_glob.glob(os.path.join(
                    flight, "flight-rank*.json")))
                kinds: set = set()
                for fp in files:
                    with open(fp) as fh:
                        doc = json.load(fh)
                    kinds |= {e.get("kind")
                              for e in doc.get("events", ())}
                want = {"slow_suspect", "slow_verdict",
                        "hedge_fired", "demote"}
                out["flight_dumps"] = len(files)
                out["flight_events"] = sorted(kinds & want)
                out["flight_events_ok"] = want <= kinds
            return out
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}

    grid["unmitigated"] = arm("unmitigated", {})
    grid["hedged"] = arm("hedged", {
        "MINIPS_SERVE": serve, "MINIPS_HEDGE": "delay_ms=15"})
    with tempfile.TemporaryDirectory() as fdir:
        grid["demote"] = arm("demote", {
            "MINIPS_SERVE": serve, "MINIPS_HEDGE": "delay_ms=15",
            "MINIPS_ELASTIC": "1",
            "MINIPS_SLOW": ("factor=3,windows=2,window=5,"
                            "min_ms=15,min_samples=2,demote=4"),
            "MINIPS_REBALANCE": ("block=2048,threshold=3,"
                                 "interval=0.3,min_heat=1"),
            "MINIPS_HEARTBEAT": "interval=0.1,timeout=2.0",
            "MINIPS_FLIGHT": fdir}, flight=fdir)
    # SLOW-IDLE: hedge-armed vs off on a clean wire, bitwise
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_bench",
             "--fail-slow-idle-drill"],
            capture_output=True, text=True, timeout=300.0,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "MINIPS_FORCE_CPU": "1",
                 "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                 "MINIPS_CHAOS": "", "MINIPS_HEDGE": "",
                 "MINIPS_SLOW": ""})
        res = json.loads([ln for ln in proc.stdout.splitlines()
                          if ln.startswith("{")][-1])
        grid["idle"] = {"equal": bool(res.get("bitwise_equal")),
                        "rows_checked":
                            int(res.get("rows_checked", 0))}
        if res.get("error"):
            grid["idle"]["error"] = res["error"]
    except Exception as e:  # noqa: BLE001 - the gate reads this
        grid["idle"] = {"equal": False, "rows_checked": 0,
                        "error": str(e)[:300]}
    return grid


def tenant_arms(quick: bool = False) -> dict:
    """THE MULTI-TENANT SWEEP: one 3-proc job runs a training tenant
    (``trn`` — every rank's sparse pull+push loop at a fixed step
    pace; pace-KEPT rows/sec is the protected number) next to a
    storming zipf inference tenant (``inf`` — per-rank reader threads
    free-running ``pull_serving`` into admission). Four arms: ``solo``
    (trn alone — the protected baseline), ``isolated`` (per-tenant
    buckets: trn admission off, inf throttled into its own budget),
    ``shared`` (``shared=1`` — ONE fleet bucket, the coupling the
    per-tenant split removes), and ``idle`` (the --tenant-idle-drill
    bitwise stamp). TENANT-ISO wants isolated trn within 10% of solo
    with inf shedding into its own budget and trn's attributed
    counters ZERO (and the shared arm's coupling engaged — the
    contrast must be real); TENANT-IDLE wants the idle stamp green."""
    from minips_tpu import launch as _launch

    t_iters = 15 if quick else 40
    tbase = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
             "--tenant-bench", "--path", "sparse",
             "--iters", str(t_iters),
             "--warmup", str(max(2, t_iters // 6)),
             "--batch", "128", "--rows", "4096",
             # the storm must be heavy in REQUESTS, not in raw CPU:
             # these readers share each rank's interpreter with the
             # trainer, so a zero-think closed loop measures GIL
             # contention (which no admission split can remove), not
             # tenancy — 25ms think keeps the reader threads asleep
             # between attempts while the attempt rate still over-
             # drives the inf bucket into visible shedding
             "--storm-batch", "8", "--storm-think-ms", "25",
             # pace-kept SLO: each trn step sleeps to a 60ms deadline
             # (roughly 4x the unloaded pull+push+tick time), so
             # trn_rows_per_sec compares PACE-KEEPING across arms —
             # storm-tax jitter lands in the slack, only real stalls
             # (shared-bucket denials riding retry_ms) slip deadlines
             "--trn-step-ms", "60",
             "--staleness", "1", "--updater", "sgd",
             "--key-dist", "zipf", "--no-zipf-permute-hot",
             "--pull-timeout", "30"]
    serve = ("replicas=1,hot=16,topk=64,interval=0.05,min_heat=1,"
             "rate=40,burst=8")
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "1",
            "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
            "MINIPS_SERVE": "", "MINIPS_BUS": "",
            "MINIPS_WIRE_FMT": "", "MINIPS_ELASTIC": "",
            "MINIPS_CHAOS_KILL": "", "MINIPS_HEARTBEAT": "",
            "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
            "MINIPS_AUTOSCALE": "", "MINIPS_RESHARD": "",
            "MINIPS_SLOW": "", "MINIPS_HEDGE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""}
    # per-tenant buckets: trn's admission OFF (its SLO is throughput),
    # inf throttled into its own budget; inf reads at its OWN s=2
    # against the job's staleness=1
    iso_spec = "trn:rate=0;inf:rate=40,burst=8,s=2"
    grid: dict = {"iters": t_iters, "serve_spec": serve,
                  "isolated_spec": iso_spec}

    def arm(tenant_spec: str, storm: int) -> dict:
        argv = list(tbase) + ["--storm", str(storm),
                              "--serve", serve,
                              "--tenant", tenant_spec]
        try:
            res = _launch.run_local_job(3, argv, base_port=None,
                                        env_extra=env0, timeout=240.0)
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}
        echoed = {r.get("tenant_spec") for r in res}
        assert echoed == {tenant_spec}, (tenant_spec, echoed)
        tb = [r.get("tenant") or {} for r in res]

        def tcnt(tname: str, key: str) -> int:
            return sum(((b.get("tenants") or {}).get(tname) or {})
                       .get(key, 0) for b in tb)

        rep = [(r["serve"] or {}).get("replica") for r in res]
        return {
            "completed": all(r.get("event") == "done" for r in res),
            # the protected number: the training tenant's fleet rate
            "trn_rows_per_sec": round(
                sum(r["trn_rows_per_sec"] for r in res), 1),
            "read_rows_per_sec": round(
                sum(r["read_rows_per_sec"] for r in res), 1),
            "shared": max(b.get("shared", 0) for b in tb),
            # per-tenant deny attribution — THE isolation evidence
            "trn_denied": (tcnt("trn", "shed")
                           + tcnt("trn", "throttle")),
            "inf_denied": (tcnt("inf", "shed")
                           + tcnt("inf", "throttle")),
            # staleness-bound evidence: zero on BOTH ledgers (the
            # tenant-attributed counter and the plane's own)
            "stale_reads": (tcnt("trn", "stale_reads")
                            + tcnt("inf", "stale_reads")
                            + sum((x or {}).get("stale_reads") or 0
                                  for x in rep)),
            "wire_frames_lost": sum(r.get("wire_frames_lost", 0)
                                    for r in res),
            "frames_dropped": sum(r.get("frames_dropped", 0)
                                  for r in res),
        }

    grid["solo"] = arm(iso_spec, 0)
    grid["isolated"] = arm(iso_spec, 2)
    # ONE fleet bucket (cfg rate=40 shared by both tenants): the
    # combined load drains tokens the quiet tenant needed — the
    # coupling the per-tenant split exists to remove
    grid["shared"] = arm("trn;inf:s=2;shared=1", 2)
    # TENANT-IDLE: bare default tenant vs off, bitwise + zero counters
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_bench",
             "--tenant-idle-drill"],
            capture_output=True, text=True, timeout=300.0,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "MINIPS_FORCE_CPU": "1",
                 "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                 "MINIPS_CHAOS": "", "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""})
        res = json.loads([ln for ln in proc.stdout.splitlines()
                          if ln.startswith("{")][-1])
        grid["idle"] = {"equal": bool(res.get("bitwise_equal")),
                        "rows_checked":
                            int(res.get("rows_checked", 0)),
                        "tenant_tids": res.get("tenant_tids"),
                        "tenant_counters": res.get("tenant_counters")}
        if res.get("error"):
            grid["idle"]["error"] = res["error"]
    except Exception as e:  # noqa: BLE001 - the gate reads this
        grid["idle"] = {"equal": False, "rows_checked": 0,
                        "error": str(e)[:300]}
    return grid


def traffic_arms(quick: bool = False) -> dict:
    """THE MILLION-USER SWEEP (million_user_3proc): the open-loop
    traffic driver (apps/traffic_driver.py) replays seeded zipf user
    streams against the ``inf`` table's ``pull_serving`` on a FIXED
    arrival schedule — latency measured from scheduled arrival, so a
    fleet that falls behind shows the queueing it caused instead of
    silently offering less load — while every rank trains ``trn`` (and
    a write stream into ``inf``) at a fixed step pace. Four arms:

    - ``open_loop_base``: flat offered rate inside capacity — the
      sched_ms/svc_ms pair should nearly agree, freshness lag samples
      flow (TRAFFIC-FRESH's calibration leg);
    - ``flash_crowd``: a mid-window rate spike (``crowd=``) against
      replicas=1 + a tight read SLO — the crowd must degrade to
      LATENCY (zero stale reads, zero poison, completion) while the
      burning tenant's promotion budget provably flexes ABOVE the
      configured replica count (max_budget > 1: the "replica budgets
      ride demand" acceptance);
    - ``overload_shed``: offered rate over the inf tenant's own
      admission budget — sheds land in inf's attributed counters (trn
      zero), and the burn edge leaves an ``slo_burn`` flight-recorder
      box with zero pre-arming (TRAFFIC-SHED);
    - ``idle``: the --traffic-idle-drill bitwise stamp (TRAFFIC-IDLE:
      a rate-0 armed driver schedules and issues NOTHING).

    Open-loop rates are offered, not achieved, so no arm publishes a
    throughput point — the gates read latency quantiles, freshness
    samples, budget maxima, and attributed counters (absolute checks,
    never the run-to-run ±10% comparison)."""
    import glob as _glob
    import tempfile

    from minips_tpu import launch as _launch

    t_iters = 18 if quick else 40
    warm = max(2, t_iters // 6)
    timed_s = (t_iters - warm) * 0.1     # 100ms pace, the window below
    tbase = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
             "--traffic-bench", "--path", "sparse",
             "--iters", str(t_iters), "--warmup", str(warm),
             "--batch", "128", "--rows", "4096",
             # 100ms deadline pace: the timed window's wall clock IS
             # the driver's schedule horizon, so the crowd's [at,
             # at+dur) lands at a knowable second of the measurement
             "--trn-step-ms", "100",
             "--staleness", "1", "--updater", "sgd",
             "--pull-timeout", "30"]
    # replicas=1 deliberately: the flash-crowd arm's budget proof needs
    # headroom ABOVE the configured count (3 live ranks, so a burning
    # boost can grant 2 holders where calm grants 1)
    serve = ("replicas=1,hot=16,topk=64,interval=0.05,min_heat=1")
    tenant = "trn:rate=0;inf:s=2"
    # fast=2/slow=4 rolls at the 100ms tick: burn verdicts settle in
    # ~0.4s — inside even the quick arm's window
    slo = "read_ms=5,shed_rate=2,fast=2,slow=4,boost=1"
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "1",
            "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
            "MINIPS_SERVE": "", "MINIPS_BUS": "",
            "MINIPS_WIRE_FMT": "", "MINIPS_ELASTIC": "",
            "MINIPS_CHAOS_KILL": "", "MINIPS_HEARTBEAT": "",
            "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
            "MINIPS_AUTOSCALE": "", "MINIPS_RESHARD": "",
            "MINIPS_SLOW": "", "MINIPS_HEDGE": "",
            "MINIPS_TENANT": "", "MINIPS_SLO": "",
            "MINIPS_TRAFFIC": "", "MINIPS_FLIGHT": ""}
    grid: dict = {"iters": t_iters, "timed_s": round(timed_s, 2),
                  "serve_spec": serve, "tenant_spec": tenant,
                  "slo_spec": slo}

    def arm(traffic_spec: str, flight: str = "",
            slo_spec: str = slo, tenant_spec: str = tenant) -> dict:
        argv = list(tbase) + ["--serve", serve,
                              "--tenant", tenant_spec,
                              "--slo", slo_spec,
                              "--traffic", traffic_spec]
        env = dict(env0)
        if flight:
            env["MINIPS_FLIGHT"] = flight
        try:
            res = _launch.run_local_job(3, argv, base_port=None,
                                        env_extra=env, timeout=240.0)
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}
        echoed = {r.get("traffic_spec") for r in res}
        assert echoed == {traffic_spec}, (traffic_spec, echoed)
        tr = [r.get("traffic") or {} for r in res]
        fresh = [r.get("freshness") or {} for r in res]
        fleet = [f.get("fleet") or {} for f in fresh]
        slo_b = [r.get("slo") or {} for r in res]
        tb = [r.get("tenant") or {} for r in res]
        rep = [(r.get("serve") or {}).get("replica") for r in res]

        def tcnt(tname: str, key: str) -> int:
            return sum(((b.get("tenants") or {}).get(tname) or {})
                       .get(key, 0) for b in tb)

        def budget_max(tname: str) -> int:
            return max((((b.get("tenants") or {}).get(tname) or {})
                        .get("max_budget", 0)) for b in slo_b)

        p99s = [((t.get("sched_ms") or {}).get("p99_ms") or 0.0)
                for t in tr]
        fp99 = [((f.get("lag") or {}).get("p99_ms") or 0.0)
                for f in fleet]
        out = {
            "completed": all(r.get("event") == "done" for r in res),
            # offered vs issued: unissued > 0 means the run ended
            # with schedule left over (a gate problem, not a shed)
            "scheduled": sum(t.get("scheduled", 0) for t in tr),
            "requests": sum(t.get("requests", 0) for t in tr),
            "unissued": sum(t.get("unissued", 0) for t in tr),
            # summed dispatcher count: the gate's stop-boundary
            # allowance (each thread abandons <= 1 claimed arrival)
            "conc": sum(t.get("conc", 0) for t in tr),
            "errors": sum(t.get("errors", 0) for t in tr),
            "late_issues": sum(t.get("late_issues", 0) for t in tr),
            # the honest tail (max across ranks): scheduled-arrival ->
            # completion, next to bare service time
            "sched_p99_ms": round(max(p99s), 3) if p99s else None,
            "svc_p99_ms": round(max(
                ((t.get("svc_ms") or {}).get("p99_ms") or 0.0)
                for t in tr), 3),
            # TRAFFIC-FRESH evidence: push-visible-at-replica lag
            "freshness_samples": sum(f.get("lag_samples", 0)
                                     for f in fleet),
            "freshness_p99_ms": round(max(fp99), 3) if fp99 else None,
            "stamped_frames": sum(f.get("stamped_frames", 0)
                                  for f in fleet),
            # SLO burn accounting + the budget-flex proof
            "slo_burns": sum(b.get("burns", 0) for b in slo_b),
            "slo_clears": sum(b.get("clears", 0) for b in slo_b),
            "boost_ticks": sum(b.get("boost_ticks", 0)
                               for b in slo_b),
            "inf_max_budget": budget_max("inf"),
            # tenant-attributed admission evidence (TRAFFIC-SHED)
            "trn_denied": (tcnt("trn", "shed")
                           + tcnt("trn", "throttle")),
            "inf_denied": (tcnt("inf", "shed")
                           + tcnt("inf", "throttle")),
            "stale_reads": (tcnt("trn", "stale_reads")
                            + tcnt("inf", "stale_reads")
                            + sum((x or {}).get("stale_reads") or 0
                                  for x in rep)),
            "trn_rows_per_sec": round(
                sum(r.get("trn_rows_per_sec", 0) for r in res), 1),
            "wire_frames_lost": sum(r.get("wire_frames_lost", 0)
                                    for r in res),
            "frames_dropped": sum(r.get("frames_dropped", 0)
                                  for r in res),
        }
        if flight:
            files = sorted(_glob.glob(os.path.join(
                flight, "flight-rank*.json")))
            burn_events = []
            for fp in files:
                with open(fp) as fh:
                    doc = json.load(fh)
                burn_events += [e.get("args", {}).get("tenant")
                                for e in doc.get("events", ())
                                if e.get("kind") == "slo_burn"]
            out["flight_dumps"] = len(files)
            out["flight_slo_burns"] = len(burn_events)
            out["flight_burn_tenants"] = sorted(
                {t for t in burn_events if t})
        return out

    # schedule shapes: per-rank offered rates (3 ranks run one driver
    # each); the crowd lands mid-window and must FIT inside it
    c_at = round(timed_s * 0.3, 2)
    c_for = round(timed_s * 0.3, 2)
    base_spec = "rate=60,users=1000000,alpha=1.2,batch=8,conc=2,seed=11"
    crowd_spec = base_spec + f",crowd={c_at}+{c_for}x8"
    # overload: offered far above the inf bucket below — rate-limited
    # admission sheds into inf's own budget, the burn edge dumps
    overload_tenant = "trn:rate=0;inf:rate=20,burst=4,s=2"
    grid["crowd"] = {"at": c_at, "for": c_for, "x": 8}
    grid["open_loop_base"] = arm(base_spec)
    grid["flash_crowd"] = arm(crowd_spec)
    with tempfile.TemporaryDirectory() as fdir:
        grid["overload_shed"] = arm(
            "rate=400,users=1000000,alpha=1.2,batch=8,conc=4,seed=13",
            flight=fdir, tenant_spec=overload_tenant)
    grid["overload_tenant_spec"] = overload_tenant
    # TRAFFIC-IDLE: rate-0 armed driver vs off, bitwise + zero issued
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_bench",
             "--traffic-idle-drill"],
            capture_output=True, text=True, timeout=300.0,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "MINIPS_FORCE_CPU": "1",
                 "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                 "MINIPS_CHAOS": "", "MINIPS_TENANT": "",
                 "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""})
        res = json.loads([ln for ln in proc.stdout.splitlines()
                          if ln.startswith("{")][-1])
        grid["idle"] = {"equal": bool(res.get("bitwise_equal")),
                        "rows_checked":
                            int(res.get("rows_checked", 0)),
                        "traffic_requests":
                            res.get("traffic_requests"),
                        "traffic_scheduled":
                            res.get("traffic_scheduled")}
        if res.get("error"):
            grid["idle"]["error"] = res["error"]
    except Exception as e:  # noqa: BLE001 - the gate reads this
        grid["idle"] = {"equal": False, "rows_checked": 0,
                        "error": str(e)[:300]}
    return grid


def reshard_arms(quick: bool = False) -> dict:
    """RESHARD-MEM / RESHARD-SAFE (planned collective redistribution,
    balance/redistribute.py): the memory-bounded N->M resharding plane
    drilled four ways.

    - ``mem``: the streaming checkpoint-restore drill (mover (c)) at a
      RAM-visible table size — capped read bitwise-equal to uncapped,
      measured peak staging <= cap, legacy whole-member staging > cap.
    - ``drain_planned`` vs ``drain_p2p``: the SAME whole-rank drain
      (rank 0 hands its shard over mid-run) with the planner armed at a
      small cap vs the legacy one-shot p2p ship. Both complete bitwise;
      the planned arm's measured ``reshard.peak_stage_bytes`` stays
      under the cap while the p2p arm's ``rebalance.peak_stage_bytes``
      (the whole staged shard) provably exceeds it at the same size —
      RESHARD-MEM's live-wire leg.
    - ``kill``: seeded SIGKILL of a gainer mid-run with the planner and
      an aggressive rebalancer armed; survivors restore the dead
      ranges from the elastic checkpoint and finish with zero
      unrecovered frames and agreeing finals — RESHARD-SAFE's crash
      leg (the exact mid-round resume/abort semantics are pinned by
      tests/test_reshard.py; this arm pins process-level survival).
    - ``part``: a seeded link cut opens across the drain window
      (sender->gainer) with the reliable plane armed; the plan's slice
      rounds retransmit through the heal, everyone completes with zero
      unrecovered frames, and the post-mortem flight boxes carry the
      ``reshard_round`` evidence with ZERO pre-arming.
    """
    import glob as _glob
    import tempfile

    from minips_tpu import launch as _launch

    cap = 4096                       # bytes: far below one shard
    r_iters = 20 if quick else 30
    drain_at = 8
    base = [sys.executable, "-m",
            "minips_tpu.apps.sharded_ps_example",
            "--model", "sparse", "--mode", "ssp",
            "--staleness", "2", "--iters", str(r_iters),
            "--batch", "64", "--checkpoint-every", "5",
            "--drain-rank", "0", "--drain-at", str(drain_at)]
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "",
            "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
            "MINIPS_SERVE": "", "MINIPS_BUS": "",
            "MINIPS_WIRE_FMT": "", "MINIPS_CHAOS_KILL": "",
            "MINIPS_HEARTBEAT": "interval=0.1,timeout=2.0",
            "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
            "MINIPS_AUTOSCALE": "1", "MINIPS_OBS": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
            "MINIPS_FLIGHT": "", "MINIPS_SLOW": "",
            "MINIPS_HEDGE": "", "MINIPS_ELASTIC": "1",
            "MINIPS_RESHARD": ""}
    grid: dict = {"iters": r_iters, "cap": cap,
                  "drain_at": drain_at}

    def drain_arm(extra_env: dict, flight: str = "") -> dict:
        try:
            with tempfile.TemporaryDirectory() as ck:
                rc, events = _launch.run_local_job_raw(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None, env_extra={**env0, **extra_env},
                    timeout=240.0, kill_on_failure=False)
            by_last = {r: (ev[-1] if ev else {})
                       for r, ev in enumerate(events)}
            dones = [by_last[r] for r in (1, 2)
                     if by_last[r].get("event") == "done"]
            if rc != 0 or len(dones) != 2:
                return {"completed": False,
                        "error": f"rc={rc}: {by_last}"[:400]}
            stamps = list(by_last.values())
            rsh = [d.get("reshard") for d in stamps]
            reb = [d.get("rebalance") or {} for d in stamps]
            sums = {d.get("param_sum") for d in dones}
            out = {
                "completed": True,
                "leaver_drained":
                    by_last[0].get("event") == "drained",
                "blocks_moved": sum(r.get("blocks_out", 0)
                                    for r in reb),
                # max, not sum: the cap bounds each rank's worst
                # simultaneous snapshot
                "peak_p2p": max(r.get("peak_stage_bytes", 0)
                                for r in reb),
                "wire_frames_lost": sum(
                    d.get("wire_frames_lost", 0) for d in dones),
                "finals_agree": len(sums) == 1,
            }
            if any(r is not None for r in rsh):
                live = [r for r in rsh if r]
                out["reshard"] = {
                    "plans": sum(r.get("plans", 0) for r in live),
                    "rounds": sum(r.get("rounds", 0) for r in live),
                    "slices": sum(r.get("slices", 0) for r in live),
                    "dup_slices": sum(r.get("dup_slices", 0)
                                      for r in live),
                    "aborts": sum(r.get("aborts", 0) for r in live),
                    "peak_planned": max(r.get("peak_stage_bytes", 0)
                                        for r in live),
                }
            else:
                out["reshard_absent"] = all(r is None for r in rsh)
            if flight:
                files = sorted(_glob.glob(os.path.join(
                    flight, "flight-rank*.json")))
                kinds: set = set()
                for fp in files:
                    with open(fp) as fh:
                        doc = json.load(fh)
                    kinds |= {e.get("kind")
                              for e in doc.get("events", ())}
                seen = {"reshard_round", "reshard_resume",
                        "reshard_abort"}
                out["flight_dumps"] = len(files)
                out["flight_events"] = sorted(kinds & seen)
                out["flight_events_ok"] = "reshard_round" in kinds
            return out
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}

    # -------- the live-wire staging A/B: same drain, planner on/off
    grid["drain_planned"] = drain_arm(
        {"MINIPS_RESHARD": f"cap={cap}"})
    grid["drain_p2p"] = drain_arm({})

    # -------- kill: seeded SIGKILL of gainer rank 2 mid-run; the
    # planner and an eager rebalancer are both armed so state rounds
    # are in flight around the kill window
    kill_step = max(2, r_iters // 3)
    grid["kill_step"] = kill_step
    try:
        with tempfile.TemporaryDirectory() as ck:
            kbase = [sys.executable, "-m",
                     "minips_tpu.apps.sharded_ps_example",
                     "--model", "sparse", "--mode", "ssp",
                     "--staleness", "2", "--iters", str(r_iters),
                     "--batch", "64", "--checkpoint-every", "5",
                     "--checkpoint-dir", ck]
            rc, events = _launch.run_local_job_raw(
                3, kbase, base_port=None,
                env_extra={**env0,
                           "MINIPS_RESHARD": f"cap={cap}",
                           "MINIPS_REBALANCE":
                               ("block=2048,threshold=3,"
                                "interval=0.3,min_heat=1"),
                           "MINIPS_CHAOS_KILL":
                               f"7:rank=2,step={kill_step}",
                           "MINIPS_HEARTBEAT":
                               "interval=0.1,timeout=1.0"},
                timeout=240.0, kill_on_failure=False)
        dones = [ev[-1] for r, ev in enumerate(events)
                 if r != 2 and ev and ev[-1].get("event") == "done"]
        if len(dones) == 2:
            sums = {d.get("param_sum") for d in dones}
            grid["kill"] = {
                "completed": True,
                "blocks_restored": sum(
                    (d.get("membership") or {}).get(
                        "blocks_restored", 0) for d in dones),
                "reshard_aborts": sum(
                    (d.get("reshard") or {}).get("aborts", 0)
                    for d in dones),
                "wire_frames_lost": sum(
                    d.get("wire_frames_lost", 0) for d in dones),
                "finals_agree": len(sums) == 1,
            }
        else:
            grid["kill"] = {"completed": False,
                            "error": f"survivors rc={rc}: "
                                     f"{events}"[:300]}
    except Exception as e:  # noqa: BLE001 - completion-gated
        grid["kill"] = {"completed": False, "error": str(e)[:300]}

    # -------- part: the 0->2 link (sender -> one gainer) cut for 1s
    # across the drain window; reliable retransmits carry the slice
    # rounds through the heal; flight boxes carry the evidence
    with tempfile.TemporaryDirectory() as fdir:
        grid["part"] = drain_arm(
            {"MINIPS_RESHARD": f"cap={cap}",
             "MINIPS_RELIABLE":
                 "budget=4,backoff_ms=25,backoff_max_ms=150,"
                 "advert_ms=100",
             "MINIPS_CHAOS":
                 f"9:part=1,links=0-2,at={drain_at},for=1.0s",
             "MINIPS_FLIGHT": fdir},
            flight=fdir)

    # -------- mem: the streaming restore drill (subprocess stamp)
    try:
        proc = subprocess.run(
            [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_bench",
             "--reshard-mem-drill"],
            capture_output=True, text=True, timeout=300.0,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "MINIPS_FORCE_CPU": "1",
                 "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                 "MINIPS_CHAOS": "", "MINIPS_RESHARD": ""})
        res = json.loads([ln for ln in proc.stdout.splitlines()
                          if ln.startswith("{")][-1])
        grid["mem"] = {
            "equal": bool(res.get("bitwise_equal")),
            "cap": int(res.get("cap", 0)),
            "peak_planned": res.get("peak_planned"),
            "peak_p2p": res.get("peak_p2p"),
            "chunks": int(res.get("chunks", 0)),
        }
        if res.get("error"):
            grid["mem"]["error"] = res["error"]
    except Exception as e:  # noqa: BLE001 - the gate reads this
        grid["mem"] = {"equal": False, "error": str(e)[:300]}
    return grid


def hier_arms(quick: bool = False) -> dict:
    """HIER-WIN / HIER-IDLE (the two-level push tree, balance/hier.py):
    3 procs with host groups {0,1} | {2} — ranks 0 and 1 are co-host
    workers whose owner-2 slices ride the tree; rank 2 is a singleton
    (always flat, the degenerate clause). Both arms run the SAME seeded
    sparse workload under topk8:

    - ``hier``  (``group=2``):       member->leader exact contributions,
      ONE compressed frame per owner per boundary from the leader;
    - ``flat``  (``group=2,agg=0``): accounting-only — per-worker flat
      frames with the SAME per-level byte classification, so the two
      arms' ``l2_tx_bytes`` (the cross-host leader leg, summed over the
      tree ranks 0+1) are like-for-like.

    The win is overlap capture: co-host workers drawing zipf-skewed
    keys hit mostly the SAME rows, and the leader ships the union once
    instead of each worker shipping its own copy. The gate (HIER-WIN,
    ci/bench_regression.py) wants flat/hier l2 bytes >= 1.7x and the
    loss trajectories matching; the bitwise drills below are the
    exactness legs (compression off: tree == flat bit-for-bit; armed-
    idle == off bit-for-bit).

    No alternating-median reps here, deliberately: the comparison is a
    seeded BYTE count and a seeded loss stream (both bit-deterministic
    given the workload seeds), not a rows/sec timing number — the
    drifting-host honesty rules buy nothing, and rates from this sweep
    are never published as throughput points."""
    from minips_tpu import launch as _launch

    h_iters = 25 if quick else 40
    hbase = [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_example",
             "--model", "sparse", "--mode", "bsp",
             # 256 rows / batch 128 x 14 nnz: each worker's draws
             # cover most of owner 2's shard every step — the co-host
             # overlap regime the tree exists for (one union frame vs
             # two near-identical per-worker frames)
             "--dim", "256", "--batch", "128",
             "--iters", str(h_iters)]
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_RESHARD": "",
            "MINIPS_RELIABLE": "", "MINIPS_REBALANCE": "",
            "MINIPS_TRACE": "", "MINIPS_SERVE": "",
            "MINIPS_BUS": "", "MINIPS_WIRE_FMT": "",
            "MINIPS_CHAOS": "", "MINIPS_CHAOS_KILL": "",
            "MINIPS_MESH": "", "MINIPS_AUTOSCALE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
            "MINIPS_ELASTIC": "", "MINIPS_SLOW": "",
            "MINIPS_HEDGE": "", "MINIPS_OBS": "",
            "MINIPS_FLIGHT": "", "MINIPS_HEARTBEAT": "",
            "MINIPS_PUSH_COMM": "topk8"}
    grid: dict = {"iters": h_iters, "group": 2,
                  "tree_ranks": [0, 1], "owner_rank": 2}

    def arm(name: str, hier_spec: str) -> dict:
        try:
            res = _launch.run_local_job(
                3, list(hbase), base_port=None,
                env_extra={**env0, "MINIPS_HIER": hier_spec},
                timeout=240.0)
            hier = [d.get("hier") or {} for d in res]
            sums = {d.get("param_sum") for d in res}
            return {
                "completed": all(d.get("event") == "done"
                                 for d in res),
                "hier_spec": hier_spec,
                # the HIER-WIN observable: cross-host bytes/frames
                # out of the multi-rank group (ranks 0+1 — rank 2's
                # singleton sends stay flat in both arms and would
                # dilute the comparison)
                "l2_tx_bytes": sum(hier[r].get("l2_tx_bytes", 0)
                                   for r in (0, 1)),
                "l2_frames": sum(hier[r].get("l2_frames", 0)
                                 for r in (0, 1)),
                "l1_tx_bytes": sum(hier[r].get("l1_tx_bytes", 0)
                                   for r in (0, 1)),
                "agg_frames": sum(h.get("agg_frames", 0)
                                  for h in hier),
                "contribs": sum(h.get("contribs", 0) for h in hier),
                "fallbacks": sum(h.get("fallbacks", 0) for h in hier),
                # trajectory leg: same seeds, same draws — the arms'
                # loss streams must tell the same story
                "loss_first": res[0].get("loss_first"),
                "loss_last": res[0].get("loss_last"),
                "loss_last_by_rank": [d.get("loss_last") for d in res],
                "finals_agree": len(sums) == 1,
                "wire_frames_lost": sum(
                    d.get("wire_frames_lost", 0) for d in res),
            }
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}

    grid["hier"] = arm("hier", "group=2")
    grid["flat"] = arm("flat", "group=2,agg=0")
    hb, fb = (grid["hier"].get("l2_tx_bytes") or 0,
              grid["flat"].get("l2_tx_bytes") or 0)
    grid["l2_bytes_ratio"] = round(fb / hb, 3) if hb else None

    # the exactness legs: compression-off tree bitwise == flat, and
    # armed-idle bitwise == off (subprocess drills, stamp protocol)
    def drill(flag: str) -> dict:
        try:
            proc = subprocess.run(
                [sys.executable, "-m",
                 "minips_tpu.apps.sharded_ps_bench", flag],
                capture_output=True, text=True, timeout=300.0,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env={**os.environ, "MINIPS_FORCE_CPU": "1",
                     "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                     "MINIPS_HIER": "", "MINIPS_PUSH_COMM": ""})
            res = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
            out = {"equal": bool(res.get("bitwise_equal")),
                   "rows_checked": int(res.get("rows_checked", 0)),
                   "agg_frames": res.get("agg_frames")}
            if res.get("error"):
                out["error"] = res["error"]
            return out
        except Exception as e:  # noqa: BLE001 - the gate reads this
            return {"equal": False, "rows_checked": 0,
                    "error": str(e)[:300]}

    grid["bitwise"] = drill("--hier-bitwise-drill")
    grid["idle"] = drill("--hier-idle-drill")
    return grid


def hybrid_arms(quick: bool = False) -> dict:
    """HYBRID-WIN / HYBRID-IDLE (the hybrid data plane: the PR16 tree
    with the leader's host-side f64 dedup loop swapped for a device
    reduce over the in-host mesh, ``agg=mesh``). Three legs:

    - TIMED: the bench worker, 3 procs, the seeded zipf sparse point
      rows=128/dim=4096/batch=32 — small table, fat rows, small
      batches: the host kernel's per-dim Python bincount loop costs
      ~dim interpreter calls per owner per flush REGARDLESS of row
      count, which is exactly what one jitted segment-sum +
      reduce-scatter amortizes. f32 mesh comm (the quantizer is a net
      tax on CPU hosts — docs/architecture.md carries the caveat; on a
      real accelerator the blk8 tier is the bytes win). Alternating
      rep pairs, median of rows/sec/proc: HYBRID-WIN wants hybrid
      STRICTLY above the host-agg tree with cross-host bytes no worse
      (identical flush protocol — the reduce backend never touches the
      wire, so l2 bytes must match, not just not-regress).
    - LOSS: the example-app trajectory leg (hier_arms' convention,
      same seeds both arms) — the speed must not come from different
      math.
    - DRILLS: armed-idle (group=1,agg=mesh == off bitwise) and the
      one-device degenerate mesh (== agg=host bitwise — THE shared
      f64 kernel, deposit order preserved)."""
    from minips_tpu import launch as _launch

    reps = 2 if quick else 5
    workload = {"path": "sparse", "rows": 128, "dim": 4096,
                "batch": 32, "iters": 36, "warmup": 12,
                "key_dist": "zipf", "staleness": 2,
                "mesh_comm": "float32", "mesh_devices": 2}
    argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
            "--path", "sparse", "--rows", "128", "--dim", "4096",
            "--batch", "32", "--iters", "36", "--warmup", "12",
            "--key-dist", "zipf", "--staleness", "2"]
    env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
            "MINIPS_RESHARD": "",
            # 2 host devices per proc: the in-host mesh the leader's
            # reduce-scatter runs over (members' slots map onto it)
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "MINIPS_HIER_MESH_COMM": "float32",
            "MINIPS_HIER_MESH_DEVS": "",
            "MINIPS_RELIABLE": "", "MINIPS_REBALANCE": "",
            "MINIPS_TRACE": "", "MINIPS_SERVE": "",
            "MINIPS_BUS": "", "MINIPS_WIRE_FMT": "",
            "MINIPS_CHAOS": "", "MINIPS_CHAOS_KILL": "",
            "MINIPS_MESH": "", "MINIPS_AUTOSCALE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
            "MINIPS_ELASTIC": "", "MINIPS_SLOW": "",
            "MINIPS_HEDGE": "", "MINIPS_OBS": "",
            "MINIPS_FLIGHT": "", "MINIPS_HEARTBEAT": "",
            "MINIPS_PUSH_COMM": ""}

    def arm_once(hier_spec: str) -> dict:
        try:
            res = _launch.run_local_job(
                3, list(argv), base_port=None,
                env_extra={**env0, "MINIPS_HIER": hier_spec},
                timeout=240.0)
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}
        hier = [d.get("hier") or {} for d in res]
        hyb = [d.get("hybrid") or {} for d in res]
        return {
            "completed": all(d.get("event") == "done" for d in res),
            "hier_spec": hier_spec,
            "rows_per_sec_per_process": round(statistics.mean(
                [d["rows_per_sec"] for d in res]), 1),
            # cross-host evidence: the leader leg out of the tree
            # ranks (0+1) — identical flush protocol, so the arms'
            # bytes must MATCH (the no-worse gate reads both)
            "l2_tx_bytes": sum(hier[r].get("l2_tx_bytes", 0)
                               for r in (0, 1)),
            "agg_frames": sum(h.get("agg_frames", 0) for h in hier),
            "contribs": sum(h.get("contribs", 0) for h in hier),
            "fallbacks": sum(h.get("fallbacks", 0) for h in hier),
            # hybrid-block evidence (None-vs-zeros per wire_record):
            # the mesh arm must show reduces on a REAL (>=2 device)
            # mesh with zero fallbacks/demotions; the tree arm None
            "mesh_reduces": sum(h.get("mesh_reduces", 0)
                                for h in hyb),
            "mesh_agg_fallbacks": sum(h.get("mesh_agg_fallbacks", 0)
                                      for h in hyb),
            "domain_demotions": sum(h.get("domain_demotions", 0)
                                    for h in hyb),
            "backend_mesh": max((h.get("backend_mesh", 0)
                                 for h in hyb), default=0),
            "wire_frames_lost": sum(d.get("wire_frames_lost", 0)
                                    for d in res),
        }

    # alternating rep PAIRS (the drifting-host honesty rule): each rep
    # runs tree then hybrid back-to-back, so thermal/background drift
    # taxes both arms alike; the median rep is what the gate reads
    runs: dict[str, list[dict]] = {"tree": [], "hybrid": []}
    for _ in range(reps):
        runs["tree"].append(arm_once("group=2"))
        runs["hybrid"].append(arm_once("group=2,agg=mesh"))

    def med(a: str) -> dict:
        ok = [r for r in runs[a] if r.get("completed")]
        if not ok:
            return runs[a][-1]
        by = sorted(ok, key=lambda r: r["rows_per_sec_per_process"])
        return {**by[len(by) // 2], "reps": reps}

    grid: dict = {"workload": workload, "group": 2,
                  "tree_ranks": [0, 1], "owner_rank": 2,
                  "tree": med("tree"), "hybrid": med("hybrid")}
    t, h = grid["tree"], grid["hybrid"]
    if t.get("completed") and h.get("completed"):
        grid["rows_ratio"] = round(
            h["rows_per_sec_per_process"]
            / max(t["rows_per_sec_per_process"], 1e-9), 3)

    # the trajectory leg: the example app's seeded loss stream under
    # both backends (hier_arms' convention — dim-1 table, so this leg
    # carries NO timing signal, deliberately: it answers "same math?",
    # the timed leg above answers "faster?")
    l_iters = 25 if quick else 40
    lbase = [sys.executable, "-m",
             "minips_tpu.apps.sharded_ps_example",
             "--model", "sparse", "--mode", "bsp",
             "--dim", "256", "--batch", "128",
             "--iters", str(l_iters)]

    def loss_arm(hier_spec: str) -> dict:
        try:
            res = _launch.run_local_job(
                3, list(lbase), base_port=None,
                env_extra={**env0, "MINIPS_PUSH_COMM": "topk8",
                           "MINIPS_HIER": hier_spec},
                timeout=240.0)
            sums = {d.get("param_sum") for d in res}
            return {
                "completed": all(d.get("event") == "done"
                                 for d in res),
                "loss_first": res[0].get("loss_first"),
                "loss_last": res[0].get("loss_last"),
                "finals_agree": len(sums) == 1,
                "mesh_reduces": sum((d.get("hybrid") or {}).get(
                    "mesh_reduces", 0) for d in res),
            }
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}

    grid["loss_tree"] = loss_arm("group=2")
    grid["loss_hybrid"] = loss_arm("group=2,agg=mesh")

    # the exactness legs (subprocess drills, stamp protocol): armed-
    # idle == off bitwise; one-device degenerate mesh == host bitwise
    def drill(flag: str) -> dict:
        try:
            proc = subprocess.run(
                [sys.executable, "-m",
                 "minips_tpu.apps.sharded_ps_bench", flag],
                capture_output=True, text=True, timeout=300.0,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env={**os.environ, "MINIPS_FORCE_CPU": "1",
                     "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
                     "MINIPS_HIER": "", "MINIPS_PUSH_COMM": "",
                     "MINIPS_HIER_MESH_DEVS": ""})
            res = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
            out = {"equal": bool(res.get("bitwise_equal")),
                   "rows_checked": int(res.get("rows_checked", 0)),
                   "agg_frames": res.get("agg_frames"),
                   "mesh_reduces": res.get("mesh_reduces"),
                   "mesh_agg_fallbacks": res.get("mesh_agg_fallbacks"),
                   "domain_demotions": res.get("domain_demotions")}
            if res.get("error"):
                out["error"] = res["error"]
            return out
        except Exception as e:  # noqa: BLE001 - the gate reads this
            return {"equal": False, "rows_checked": 0,
                    "error": str(e)[:300]}

    grid["idle"] = drill("--hybrid-idle-drill")
    grid["degenerate"] = drill("--hybrid-degenerate-drill")
    return grid


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--quick", action="store_true",
                    help="short iters (harness validation, not numbers)")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="artifact dir for the traced arm's per-rank "
                         "wire traces + merged_trace.json (default: a "
                         "tempdir; the merged path is recorded in the "
                         "bench JSON either way)")
    args = ap.parse_args()
    iters = 15 if args.quick else args.iters
    warmup = max(2, iters // 6)

    curve = {}  # world-size scaling, sparse path, zmq
    for n in (1, 2, 3, 4):
        curve[str(n)] = _run(n, "sparse", iters, warmup, "zmq")
    buses = {"zmq": curve["3"],
             "native": _run(3, "sparse", iters, warmup, "native")}

    # THE TRANSPORT COMPARISON (this PR): seed JSON framing over zmq vs
    # binary framing over zmq vs the shared-memory ring transport —
    # same workload, back-to-back, alternating medians (the standard
    # honesty rules on this drifting host). The claims the TRANSPORT-*
    # tripwires (ci/bench_regression.py) gate: the shm arm's rows/sec
    # strictly above zmq-json (the loopback bench finally measures
    # protocol cost, not codec cost) with bytes/row UNCHANGED across
    # arms (framing moves head bytes, never blob bytes), and the
    # compose arm — seeded chaos drop>=1% + retransmit ON the shm
    # backend — must COMPLETE with zero unrecovered frames (the
    # chaos/reliable/trace layers wrap the bus, so they must stack on
    # the new transport unchanged; its lossy-arm rate stays
    # gate-invisible like every chaos arm's).
    def _transport_arms(reps: int) -> dict:
        arms = {"zmq_json": {"bus": "zmq", "wire_fmt": "json"},
                "zmq_bin": {"bus": "zmq", "wire_fmt": "bin"},
                "shm": {"bus": "shm", "wire_fmt": "bin"}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(3, "sparse", iters, warmup,
                                    kw["bus"], wire_fmt=kw["wire_fmt"]))

        def med(arm: str) -> dict:
            by = sorted(runs[arm],
                        key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}
        grid = {a: med(a) for a in arms}
        compose = _run(3, "sparse", iters, warmup, "shm",
                       wire_fmt="bin", chaos="1234:drop=0.01,dup=0.005",
                       reliable=True, pull_timeout=8.0, may_fail=True,
                       timeout=120.0)
        if "rows_per_sec_per_process" in compose:
            # completion gate, not a comparable throughput point
            compose["rows_per_sec_lossy"] = compose.pop(
                "rows_per_sec_per_process")
        grid["shm_compose"] = compose
        return grid

    transport_grid = _transport_arms(3 if not args.quick else 1)
    paths = {"sparse": curve["3"],
             "dense": _run(3, "dense", iters, warmup, "zmq")}
    # the compressed push wire: same rows/sec workload, int8 codes on the
    # cross-process push leg — wire bytes/sec drops toward the codec
    # ratio while the pull leg is whatever --pull-wire says (f32 here).
    # Both wire comparisons measure their arms BACK-TO-BACK rather than
    # reusing curve["3"] from minutes earlier: shared-host drift would
    # otherwise dominate the rows/sec column (B/row is drift-immune, the
    # throughput comparison is not).
    wires = {"float32": _run(3, "sparse", iters, warmup, "zmq"),
             "int8": _run(3, "sparse", iters, warmup, "zmq",
                          push_comm="int8")}
    # the compressed PULL wire (this PR): pull REPLIES ship int8 codes +
    # per-row f32 scales instead of raw f32 rows — the other half of the
    # bytes/row story (the pull leg dominates sparse wire volume: reply
    # rows outweigh the 8B key slices going out)
    pull_wires = {"f32": _run(3, "sparse", iters, warmup, "zmq"),
                  "int8": _run(3, "sparse", iters, warmup, "zmq",
                               pull_wire="int8")}
    # overlapped pipeline, three arms: off (fully synchronous cycle) vs
    # pull (double-buffered prefetch only) vs on (prefetch + async ack-
    # windowed push) — the latency levers, orthogonal to the wire
    # codecs, measured in the north-star shape (--compute jit: real
    # model math between pull and push; CPU-forced so all arms run
    # identical backends). READ THE NUMBERS WITH THE HOST IN MIND: on a
    # host whose cores are OVERSUBSCRIBED by the world size (every CI
    # container this has run on so far), the sync arm's blocked time is
    # not idle — the scheduler hands it to the other processes — so
    # overlap has nothing to reclaim and its remaining cost shows as a
    # deficit: measured on 2 cores, pull ~TIES off (the prefetch is
    # near-free) while on trails by ~10-15% (the sender thread + ack
    # settling contend for the GIL/cores three ways). The lever the
    # arms prove regardless is pull_overlap_fraction: ~0 sync vs ~0.8+
    # overlapped — the pull RTT genuinely left the critical path, which
    # converts to rows/sec only where worker compute and PS serving
    # have their own hardware (real pods; an accelerator-backed
    # worker). The _fit point (min(3, cores)) pins the least-
    # oversubscribed topology this host can host so the crossover is
    # visible the day the measurement environment grows headroom.
    def _overlap_arms(n: int, reps: int) -> dict:
        # shared-CI hosts drift (cgroup bursts swing absolute rates 2-4x
        # within minutes), so one off-run vs one on-run can crown either
        # arm by luck. ALTERNATE the arms rep-by-rep — adjacent runs see
        # near-identical machine state — and report each arm's MEDIAN
        # rep, so a throttle window contaminates at most one rep of each
        # arm, never a whole arm.
        arms = {"off": {}, "pull": {"overlap": True, "overlap_legs": "pull"},
                "on": {"overlap": True}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(n, "sparse", iters, warmup, "zmq",
                                    compute="jit", force_cpu=True, **kw))

        def med(arm: str) -> dict:
            by_rate = sorted(runs[arm],
                             key=lambda r: r["rows_per_sec_per_process"])
            return {**by_rate[len(by_rate) // 2], "reps": reps}
        return {a: med(a) for a in arms}

    o_reps = 1 if args.quick else 3
    over = _overlap_arms(3, o_reps)
    n_fit = min(3, os.cpu_count() or 3)
    over_fit = _overlap_arms(n_fit, o_reps) if n_fit != 3 else over

    # client row cache + deduplicated pull wire: "off" is the SEED wire
    # (duplicate keys verbatim, no cache) — the before/after this PR's
    # tentpole is judged on; "on" is unique-key wire + clock-versioned
    # row cache. The grid crosses key distribution with staleness
    # because the cache's validity window IS the staleness budget: the
    # uniform arms keep the standard 64k-row table (keys essentially
    # never recur — the no-win control, dedup/locality only), the zipf
    # arms shrink the table to the HOT WORKING SET a zipf(1.1) head
    # concentrates on, so re-draws land within the staleness window.
    # Same alternating-median honesty rules as the overlap sweep.
    # Fixed knobs: sgd updater + f32 push wire (the write-through
    # regime — adagrad/adam invalidate on push, pinning hit rate to ~0
    # in a pull+push cycle; see docs/consistency.md); cache ample (no
    # LRU pressure — the byte bound has its own tests). READ THE
    # ROWS/SEC COLUMN WITH THE HOST IN MIND (the overlap sweep's
    # caveat, again): on this CPU-loopback container wire bytes are
    # memcpys — shipping 5x the rows costs almost nothing — so the
    # on-arm's saved bytes buy no wall-clock, while its bursty misses
    # (same-step fills share a stamp and expire TOGETHER) hit the
    # owner park / gate wake instead of riding an amortized stream:
    # measured medians put the zipf on-arm ~5-15% under the off-arm
    # at s>=1 (with --compute jit filling the freed time the arms tie
    # within drift). The levers this sweep PROVES are hit rate > 0
    # rising with s (the staleness budget buying locality) and
    # B/row-moved down ~84% on zipf — the currency that converts to
    # rows/sec exactly where the wire is a real network or the worker
    # has its own compute, the deployments the north star names.
    ZIPF_ROWS, CACHE_BYTES = 2048, 1 << 22

    def _cache_arms(reps: int) -> dict:
        arms = {"off": {"cache_bytes": 0, "pull_dedup": False,
                        "push_dedup": False},  # = the full seed wire
                "on": {"cache_bytes": CACHE_BYTES}}
        dists = {"uniform": None, "zipf": ZIPF_ROWS}  # dist -> rows
        runs: dict[tuple, list[dict]] = {}
        for _ in range(reps):
            for dist, rows in dists.items():
                for s in (0, 1, 2):
                    for a, kw in arms.items():
                        runs.setdefault((dist, s, a), []).append(
                            _run(3, "sparse", iters, warmup, "zmq",
                                 key_dist=dist, staleness=s,
                                 rows=rows, updater="sgd", **kw))
        grid: dict = {"zipf_rows": ZIPF_ROWS, "cache_bytes": CACHE_BYTES}
        for (dist, s, a), rs in runs.items():
            by = sorted(rs, key=lambda r: r["rows_per_sec_per_process"])
            point = {**by[len(by) // 2], "reps": reps}
            grid.setdefault(dist, {}).setdefault(f"s{s}", {})[a] = point
        return grid

    cache_grid = _cache_arms(o_reps)

    # THE COMPRESSED PUSH WIRE (this PR): the wire ladder's sparse tiers
    # measured where they earn their keep — the zipf HOT-SET workload
    # (same shape as the cache sweep's zipf arms: hot rows re-drawn
    # every step) under SSP(1), sgd. Arms: f32 (seed), int8 (per-row
    # absmax), topk8/topk4 (sparse top-k index+code streams with
    # blockwise sub-8-bit quantization + error-feedback residuals,
    # train/sharded_ps.ResidualStore). The number the WIRE-BYTES
    # tripwire (ci/bench_regression.py) gates is PUSH bytes/row-moved:
    # topk8 must beat int8 by >= 2x — rows/sec columns carry the same
    # CPU-loopback caveat as the cache sweep (saved bytes are memcpys
    # here; the byte lever converts to wall-clock on a real wire).
    # WIRE-CONVERGE gates the convergence drill below: error feedback
    # must pin the lr loss trajectory to the dense wire within
    # tolerance, with zero residual mass stranded at finalize.
    def _wire_comp_arms(reps: int) -> dict:
        arms = {"f32": {}, "int8": {"push_comm": "int8"},
                "topk8": {"push_comm": "topk8"},
                "topk4": {"push_comm": "topk4"}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(3, "sparse", iters, warmup, "zmq",
                                    key_dist="zipf", staleness=1,
                                    rows=ZIPF_ROWS, updater="sgd",
                                    **kw))

        def med(arm: str) -> dict:
            by = sorted(runs[arm],
                        key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}
        grid: dict = {"zipf_rows": ZIPF_ROWS}
        grid.update({a: med(a) for a in arms})
        grid["converge"] = _wire_converge()
        return grid

    def _wire_converge() -> dict:
        """The convergence drill arm (WIRE-CONVERGE): the sparse-LR
        example at SSP(1), dense wire vs topk8 + error feedback —
        completion-gated (no rows/sec key), the gate compares final
        losses and asserts zero resident residual mass at exit."""
        from minips_tpu import launch as _launch

        e_iters = 15 if args.quick else 40
        base = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_example",
                "--model", "sparse", "--mode", "ssp",
                "--staleness", "1", "--iters", str(e_iters),
                "--batch", "256", "--updater", "sgd"]
        env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                "MINIPS_RESHARD": "",
                "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "",
                "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
                "MINIPS_SERVE": "", "MINIPS_BUS": "",
                "MINIPS_WIRE_FMT": "", "MINIPS_ELASTIC": "",
                "MINIPS_CHAOS_KILL": "", "MINIPS_HEARTBEAT": "",
                "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
                "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""}
        out: dict = {"iters": e_iters}
        for arm, comm in (("f32", "float32"), ("topk8", "topk8")):
            try:
                res = _launch.run_local_job(
                    3, base + ["--push-comm", comm], base_port=None,
                    env_extra=env0, timeout=240.0)
                losses = [r.get("loss_last") for r in res
                          if r.get("loss_last") is not None]
                fps = {r.get("param_fingerprint") for r in res}
                efs = [r.get("ef") for r in res]
                out[arm] = {
                    "completed": True,
                    "loss_last": max(losses) if losses else None,
                    "finals_agree": len(fps) <= 1,
                    "ef_resident_rows": sum(
                        (e or {}).get("resident_rows", 0)
                        for e in efs),
                    "wire_frames_lost": sum(
                        r.get("wire_frames_lost", 0) for r in res),
                }
            except Exception as e:  # noqa: BLE001 - completion-gated
                out[arm] = {"completed": False, "error": str(e)[:300]}
        return out

    wire_comp_grid = _wire_comp_arms(o_reps)

    # chaos resilience (this PR): seeded frame loss on the live wire,
    # drop ∈ {0, 1%, 5%} × retransmit on/off, against a clean reference.
    # The claims each arm pins: "clean" vs "drop0_on" bounds the reliable
    # layer's TAX on a lossless wire (ci/bench_regression CHAOS-TAX
    # tripwire: must stay within slack); the drop>0 "_on" arms must
    # COMPLETE with zero unrecovered loss (rows/sec > 0 — loss became
    # latency); the drop>0 "_off" arms are EXPECTED to die through the
    # existing poison path (recorded as completed=False, rate 0 — the
    # honest before/after of the retransmit protocol). Short pull
    # deadline so the off arms die in seconds, not the default minute.
    def _chaos_arms(reps: int) -> dict:
        grid: dict = {"drop_rates": {"drop1": 0.01, "drop5": 0.05},
                      "seed": 1234}
        # the CHAOS-TAX pair (clean vs drop0_on) is a throughput
        # COMPARISON, so it gets the same alternating-median treatment
        # as the overlap/cache sweeps — adjacent reps see near-identical
        # machine state, and a single-run pair on this drifting host has
        # crowned either arm by 2x in both directions
        pair = {"clean": {}, "drop0_on": {"chaos": "1234:drop=0",
                                          "reliable": True}}
        runs: dict[str, list[dict]] = {a: [] for a in pair}
        for _ in range(reps):
            for a, kw in pair.items():
                runs[a].append(_run(3, "sparse", iters, warmup, "zmq",
                                    pull_timeout=8.0, **kw))
        for a in pair:
            by = sorted(runs[a],
                        key=lambda r: r["rows_per_sec_per_process"])
            grid[a] = {**by[len(by) // 2], "reps": reps}
        # the drop>0 arms are COMPLETION gates (on must finish clean,
        # off is expected to die) — one run each is the measurement
        arms = [("drop0_off", 0.0, False)]
        for label, rate in (("drop1", 0.01), ("drop5", 0.05)):
            arms += [(f"{label}_on", rate, True),
                     (f"{label}_off", rate, False)]
        for arm, rate, rel in arms:
            res = _run(3, "sparse", iters, warmup, "zmq",
                       chaos=f"1234:drop={rate}", reliable=rel,
                       pull_timeout=8.0,
                       may_fail=rate > 0, timeout=120.0)
            if rate > 0 and res.get("completed"):
                # drop>0 arms are COMPLETION gates, not comparable
                # throughput points: single runs under active loss (on)
                # or lucky survivals (off) must not enter the run-to-run
                # ±10% REGRESSED/MISSING gate — their rate lives under a
                # gate-invisible key (CHAOS-DEAD checks it absolutely)
                key = ("rows_per_sec_lossy" if rel
                       else "rows_per_sec_survived")
                res[key] = res.pop("rows_per_sec_per_process")
            grid[arm] = res
        return grid

    chaos_grid = _chaos_arms(o_reps)

    # heat-aware rebalancing (this PR): UNPERMUTED zipf(1.1) — the whole
    # head inside shard 0's range, the pathology the permuted default
    # hides — static partition vs MINIPS_REBALANCE on, SSP(1). These are
    # IMBALANCE/COMPLETION gates, not throughput comparisons: a skewed
    # arm's rows/sec is one hot owner's serial serve rate and swings
    # with scheduling luck, so it lives under a gate-invisible key
    # (rows_per_sec_skewed) exactly like the chaos arms' — the numbers
    # the REBAL-SKEW tripwire (ci/bench_regression.py) gates are
    # serve_load_imbalance (max/mean per-shard serve rows: rebalance arm
    # strictly below static), migrations >= 1, and zero drops/losses.
    # The permuted arm rides along as the balanced reference point.
    REBAL_SPEC = ("interval=0.25,threshold=1.2,max_blocks=16,"
                  "block=16,topk=64")

    def _rebalance_arms() -> dict:
        grid: dict = {"spec": REBAL_SPEC}
        arms = {
            "permuted": {"key_dist": "zipf"},
            "static": {"key_dist": "zipf", "zipf_permute_hot": False},
            "rebalance": {"key_dist": "zipf", "zipf_permute_hot": False,
                          "rebalance": REBAL_SPEC},
        }
        for name, kw in arms.items():
            # skewed arms record failure as completed=False (the
            # REBAL-DEAD tripwire's input) instead of killing the whole
            # artifact — same contract as the chaos arms
            res = _run(3, "sparse", iters, warmup, "zmq", staleness=1,
                       may_fail=(name != "permuted"), timeout=240.0,
                       **kw)
            if name != "permuted" and "rows_per_sec_per_process" in res:
                res["rows_per_sec_skewed"] = res.pop(
                    "rows_per_sec_per_process")
            grid[name] = res
        return grid

    rebalance_grid = _rebalance_arms()

    # wire tracing (this PR): the TRACE-TAX pair — untraced vs
    # MINIPS_TRACE-armed, same workload, alternating-median like every
    # other throughput comparison on this drifting host. The traced
    # arm's per-rank Chrome traces land in the artifact dir
    # (--trace, default a tempdir), the merge CLI combines them, and
    # the merged path + flow-link count ride the bench JSON — the
    # ci/bench_regression TRACE-TAX/TRACE-MERGE tripwires gate both
    # (tracing may not tax the wire beyond 15%, and the traces it
    # pays for must actually merge with >= 1 cross-rank flow).
    def _trace_arms(reps: int) -> dict:
        import tempfile

        trace_root = args.trace or tempfile.mkdtemp(
            prefix="minips-trace-")
        trace_dir = os.path.join(trace_root, "traced_3proc")
        arms = {"untraced": {}, "traced": {"trace": trace_dir}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(3, "sparse", iters, warmup, "zmq",
                                    staleness=1, **kw))

        def med(arm: str) -> dict:
            by = sorted(runs[arm],
                        key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}
        grid = {a: med(a) for a in arms}
        # merge the LAST rep's per-rank traces (each rep's dump
        # overwrites rank-wise: one coherent set remains)
        merged_path = os.path.join(trace_dir, "merged_trace.json")
        proc = subprocess.run(
            [sys.executable, "-m", "minips_tpu.obs.merge", trace_dir,
             "-o", merged_path],
            capture_output=True, text=True, timeout=120.0)
        summary = {}
        if proc.returncode == 0:
            try:
                summary = json.loads(proc.stdout.splitlines()[-1])
            except (json.JSONDecodeError, IndexError):
                pass
        grid["traced"].update({
            "trace_dir": trace_dir,
            "merged_trace": merged_path if proc.returncode == 0
            else None,
            "merge_ok": proc.returncode == 0,
            "flows_linked": summary.get("flows_linked", 0),
        })
        return grid

    trace_grid = _trace_arms(o_reps)

    # ALWAYS-ON OBSERVABILITY TAX (this PR): the windowed-metrics layer
    # + flight recorder are on by DEFAULT, so unlike TRACE-TAX (where
    # the armed arm is the special one) here the DEFAULT arm is the
    # measured product and the off arm (MINIPS_OBS=0 MINIPS_FLIGHT=0)
    # exists only to price it. Same alternating-median honesty rules;
    # the ci/bench_regression OBS-TAX tripwire holds the on arm within
    # the TRACE-TAX-style band of off.
    def _obs_tax_arms(reps: int) -> dict:
        arms = {"obs_off": {"obs": "0", "flight": "0"}, "obs_on": {}}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, kw in arms.items():
                runs[a].append(_run(3, "sparse", iters, warmup, "zmq",
                                    staleness=1, **kw))

        def med(arm: str) -> dict:
            by = sorted(runs[arm],
                        key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}
        return {a: med(a) for a in arms}

    obs_tax_grid = _obs_tax_arms(o_reps)

    # THE PULL STORM (this PR): the PS measured as a SERVICE — 6 read-
    # only clients (2 threads x 3 ranks) firing request-sized zipf
    # reads (8 keys: a user lookup, not a training batch) against 1
    # pusher, unpermuted zipf(1.1) so the hot head sits in shard 0.
    # Arms: replicas OFF (every hot read pays a wire RTT to the one
    # hot owner) vs the serving plane ON (owners promote the warm
    # working set to replica ranks; a reader holding a replica serves
    # hot keys LOCALLY, zero wire) vs SHED (admission rate throttled
    # so the owner sheds/backpressures — the refuse-with-retry path
    # must complete, never poison). Alternating medians like every
    # throughput pair. Storm rates live under gate-invisible keys
    # (read_rows_per_sec) — the absolute SERVE-* tripwires in
    # ci/bench_regression.py gate them, not the ±10% run-to-run
    # comparison (the off arm is one hot owner's serve rate, which
    # swings like the rebalance static arm). HONESTY NOTE (the PR1
    # overlap caveat again): on this 2-core container both arms'
    # latency TAILS are scheduler noise that swings integer factors
    # run to run — reads/sec and p50 separate the arms robustly
    # (local replica hits are ~free), p99 only within a slack band.
    STORM_SPEC = ("replicas=2,hot=512,interval=0,min_heat=0.5,"
                  "decay=0.9,lease=2.0")
    STORM_SHED_SPEC = STORM_SPEC + ",rate=50,burst=4"

    def _storm_args() -> list:
        return ["--storm", "2", "--storm-pushers", "1",
                "--storm-batch", "8", "--storm-think-ms", "2",
                "--storm-step-s", "0.03", "--batch", "128",
                "--rows", "4096", "--key-dist", "zipf",
                "--no-zipf-permute-hot", "--staleness", "1",
                "--updater", "sgd", "--pull-timeout", "30"]

    def _run_storm(serve: str | None, iters_s: int,
                   timeout: float = 240.0) -> dict:
        argv = [sys.executable, "-m", "minips_tpu.apps.sharded_ps_bench",
                "--path", "sparse", "--iters", str(iters_s),
                "--warmup", str(max(2, iters_s // 6))] \
            + _storm_args()
        if serve:
            argv += ["--serve", serve]
        from minips_tpu import launch

        try:
            res = launch.run_local_job(
                3, argv, base_port=None,
                env_extra={"MINIPS_CHAOS": "", "MINIPS_RELIABLE": "",
                           "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
                           "MINIPS_SERVE": "", "MINIPS_BUS": "",
                           "MINIPS_WIRE_FMT": "", "MINIPS_ELASTIC": "",
                           "MINIPS_CHAOS_KILL": "",
                           "MINIPS_HEARTBEAT": "",
                           "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
                           "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""},
                timeout=timeout)
        except Exception as e:  # noqa: BLE001 - completion-gated arms
            return {"completed": False, "error": str(e)[:300]}
        echoed_sv = {r.get("serve_spec") for r in res}
        assert echoed_sv == {serve or None}, (serve, echoed_sv)
        rep = [r["serve"]["replica"] for r in res]

        def tot(k: str) -> int:
            return sum((x or {}).get(k) or 0 for x in rep)
        hists = [r["hist"]["pull_latency_ms"] or {} for r in res]
        out = {
            "completed": True,
            "read_rows_per_sec": round(
                sum(r["read_rows_per_sec"] for r in res), 1),
            "pull_p50_ms": max((h.get("p50_ms") or 0.0)
                               for h in hists),
            "pull_p99_ms": max((h.get("p99_ms") or 0.0)
                               for h in hists),
            "wire_frames_lost": sum(r["wire_frames_lost"]
                                    for r in res),
            "frames_dropped": sum(r["frames_dropped"] for r in res),
        }
        if serve:
            out.update({
                "replica_local_rows": tot("replica_local_rows"),
                "replica_wire_rows": tot("replica_served_rows"),
                "stale_reads": tot("stale_reads"),
                "shed_redirects": tot("shed_redirects"),
                "backpressure": tot("backpressure"),
                "lease_refused": (tot("lease_refused")
                                  + tot("stale_refused")),
            })
        return out

    def _storm_grid(reps: int) -> dict:
        s_iters = 15 if args.quick else 60
        arms = {"off": None, "on": STORM_SPEC}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, spec in arms.items():
                runs[a].append(_run_storm(spec, s_iters))

        def med(arm: str) -> dict:
            ok = [r for r in runs[arm] if r.get("completed")]
            if not ok:
                return runs[arm][-1]
            by = sorted(ok, key=lambda r: r["read_rows_per_sec"])
            return {**by[len(by) // 2], "reps": reps}
        grid = {"spec": STORM_SPEC, "off": med("off"), "on": med("on")}
        # the shed arm is a COMPLETION gate (SERVE-SHED): with the
        # admission bucket throttled the run must still finish —
        # refusals become explicit redirects/backoffs, never timeouts
        grid["shed"] = _run_storm(STORM_SHED_SPEC, s_iters)
        grid["shed"]["spec"] = STORM_SHED_SPEC
        return grid

    storm_grid = _storm_grid(o_reps)

    # ELASTIC MEMBERSHIP (this PR): the join/leave/death state machine
    # (balance/membership.py) drilled as bench arms on the example app
    # (it owns the checkpoint/recovery protocol the death path needs).
    # These are COMPLETION gates, not throughput comparisons — the
    # kill arm's wall-clock contains a heartbeat-detection stall and
    # the join arm changes world size mid-run, so no arm carries
    # rows_per_sec_per_process (steps/sec rides a gate-invisible key,
    # the PR3 lossy-arm convention). The ci/bench_regression ELASTIC-*
    # tripwires gate: ELASTIC-DEAD — the seeded-SIGKILL arm's
    # survivors complete with >= 1 range restored from the elastic
    # checkpoint, zero unrecovered frames, and a finite final loss;
    # ELASTIC-JOIN — the standby-admission arm completes with the
    # joiner serving > 0 rows.
    def _elastic_arms() -> dict:
        import tempfile

        from minips_tpu import launch as _launch

        e_iters = 15 if args.quick else 30
        base = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_example",
                "--model", "sparse", "--mode", "ssp",
                "--staleness", "2", "--iters", str(e_iters),
                "--batch", "128", "--checkpoint-every", "5"]
        env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                "MINIPS_RESHARD": "",
                "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "",
                "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
                "MINIPS_SERVE": "", "MINIPS_BUS": "",
                "MINIPS_WIRE_FMT": "", "MINIPS_CHAOS_KILL": "",
                "MINIPS_HEARTBEAT": "", "MINIPS_PUSH_COMM": "",
                "MINIPS_MESH": "", "MINIPS_AUTOSCALE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
                "MINIPS_OBS": "", "MINIPS_FLIGHT": ""}
        kill_step = max(2, e_iters // 3)
        grid: dict = {"iters": e_iters, "kill_step": kill_step}

        def summarize(dones: list[dict]) -> dict:
            sums = [d.get("param_sum") for d in dones]
            losses = [d.get("loss_last") for d in dones
                      if d.get("loss_last") is not None]
            mships = [d.get("membership") or {} for d in dones]
            return {
                "completed": True,
                "steps_per_sec_elastic": round(
                    e_iters / max(max(d["wall_s"] for d in dones),
                                  1e-9), 2),
                "wire_frames_lost": sum(d.get("wire_frames_lost", 0)
                                        for d in dones),
                "frames_dropped": sum(d.get("frames_dropped", 0)
                                      for d in dones),
                "loss_last": max(losses) if losses else None,
                "blocks_restored": sum(m.get("blocks_restored", 0)
                                       for m in mships),
                "finals_agree": len({s for s in sums
                                     if s is not None}) <= 1,
            }

        # -------- steady: armed but idle — the plane's tax must be
        # invisible (the bitwise lockstep drill pins the numerics;
        # this arm pins that an armed fleet completes cleanly)
        with tempfile.TemporaryDirectory() as ck:
            try:
                res = _launch.run_local_job(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "1"},
                    timeout=240.0)
                grid["steady"] = summarize(res)
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["steady"] = {"completed": False,
                                  "error": str(e)[:300]}
        # -------- kill: seeded SIGKILL of rank 2 mid-run; survivors
        # restore its ranges from the elastic checkpoint and finish
        with tempfile.TemporaryDirectory() as ck:
            try:
                rc, events = _launch.run_local_job_raw(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "1",
                               "MINIPS_CHAOS_KILL":
                                   f"7:rank=2,step={kill_step}",
                               "MINIPS_HEARTBEAT":
                                   "interval=0.1,timeout=1.0"},
                    timeout=240.0, kill_on_failure=False)
                dones = [ev[-1] for r, ev in enumerate(events)
                         if r != 2 and ev
                         and ev[-1].get("event") == "done"]
                if len(dones) == 2:
                    grid["kill"] = summarize(dones)
                else:
                    grid["kill"] = {"completed": False,
                                    "error": f"survivors rc={rc}: "
                                             f"{events}"[:300]}
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["kill"] = {"completed": False,
                                "error": str(e)[:300]}
        # -------- join: 3 live + 1 standby admitted mid-run; the
        # joiner must end OWNING blocks and SERVING pulls
        with tempfile.TemporaryDirectory() as ck:
            try:
                res = _launch.run_local_job(
                    4, base + ["--checkpoint-dir", ck, "--join-at",
                               str(kill_step)],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "live=0-2"},
                    timeout=240.0)
                point = summarize(res)
                joiner = res[3].get("serve") or {}
                point["joiner_serve_rows"] = joiner.get("pull_rows", 0)
                point["joiner_serve_requests"] = joiner.get(
                    "pull_requests", 0)
                grid["join"] = point
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["join"] = {"completed": False,
                                "error": str(e)[:300]}
        return grid

    elastic_grid = _elastic_arms()

    # PRODUCTION CONTROL PLANE (this PR): the coordinator LEASE
    # (balance/control_plane.py) + the closed-loop autoscaler
    # (balance/autoscaler.py), drilled as three COMPLETION arms on the
    # example app. Rates ride the gate-invisible ``steps_per_sec_ctrl``
    # key (the chaos-arm convention — the kill arm's wall contains a
    # detection stall and the storm arm changes world size mid-run).
    # The ci/bench_regression CTRL-* tripwires gate: CTRL-FAILOVER —
    # the rank-0 (lease holder) seeded-SIGKILL arm's survivors finish
    # the FULL step count with the lease advanced exactly once, >= 1
    # range restored, zero unrecovered frames, bitwise agreement;
    # CTRL-SCALE — the storm arm completes with >= 1 autoscaler admit
    # and >= 1 drain and post-admit shed rate at or below pre-admit;
    # the steady armed-idle arm completes with zero membership changes.
    def _control_plane_arms() -> dict:
        import tempfile

        from minips_tpu import launch as _launch

        c_iters = 20 if args.quick else 40
        base = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_example",
                "--model", "sparse", "--mode", "ssp",
                "--staleness", "2", "--iters", str(c_iters),
                "--batch", "128", "--checkpoint-every", "5"]
        env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                "MINIPS_RESHARD": "",
                "MINIPS_CHAOS": "", "MINIPS_RELIABLE": "",
                "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
                "MINIPS_SERVE": "", "MINIPS_BUS": "",
                "MINIPS_WIRE_FMT": "", "MINIPS_CHAOS_KILL": "",
                "MINIPS_HEARTBEAT": "", "MINIPS_PUSH_COMM": "",
                "MINIPS_MESH": "", "MINIPS_AUTOSCALE": "",
            "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": "",
                "MINIPS_OBS": "", "MINIPS_FLIGHT": ""}
        grid: dict = {"iters": c_iters}

        def rate(dones: list[dict]) -> float:
            return round(c_iters / max(max(d["wall_s"] for d in dones),
                                       1e-9), 2)

        # -------- steady: lease + autoscaler armed, zero load — must
        # complete with ZERO membership changes (hysteresis honesty;
        # the in-proc lockstep drill pins the numerics bitwise)
        with tempfile.TemporaryDirectory() as ck:
            try:
                res = _launch.run_local_job(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "1",
                               "MINIPS_AUTOSCALE": "1"},
                    timeout=240.0)
                mships = [d.get("membership") or {} for d in res]
                ascale = [d.get("autoscale") or {} for d in res]
                grid["steady"] = {
                    "completed": True,
                    "steps_per_sec_ctrl": rate(res),
                    "joins": sum(m.get("joins", 0) for m in mships),
                    "leaves": sum(m.get("leaves", 0) for m in mships),
                    "admits": sum(a.get("admits", 0) for a in ascale),
                    "drains": sum(a.get("drains", 0) for a in ascale),
                    "wire_frames_lost": sum(
                        d.get("wire_frames_lost", 0) for d in res),
                }
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["steady"] = {"completed": False,
                                  "error": str(e)[:300]}
        # -------- kill: seeded SIGKILL of RANK 0, the lease holder.
        # Survivors must elect rank 1 exactly once (every done line's
        # lease term == 1), restore the corpse's ranges, and lose no
        # step — the anti-SPOF acceptance.
        kill_step = max(8, c_iters // 3)
        with tempfile.TemporaryDirectory() as ck:
            try:
                # the flight recorder is ALWAYS ON — the kill arm only
                # pins its dump DIR so the FLIGHT-DUMP gate can count
                # the survivors' black boxes and run the merge CLI on
                # them (the gate's whole claim: a chaos kill leaves a
                # post-mortem artifact with zero pre-arming)
                fdir = os.path.join(ck, "flight")
                rc, events = _launch.run_local_job_raw(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "1",
                               "MINIPS_FLIGHT": fdir,
                               "MINIPS_CHAOS_KILL":
                                   f"7:rank=0,step={kill_step}",
                               "MINIPS_HEARTBEAT":
                                   "interval=0.1,timeout=1.0"},
                    timeout=240.0, kill_on_failure=False)
                import glob as _glob

                flight_files = sorted(_glob.glob(
                    os.path.join(fdir, "flight-rank*.json")))
                fproc = subprocess.run(
                    [sys.executable, "-m", "minips_tpu.obs.flight",
                     fdir], capture_output=True, text=True,
                    timeout=60.0)
                dones = [ev[-1] for r, ev in enumerate(events)
                         if r != 0 and ev
                         and ev[-1].get("event") == "done"]
                if len(dones) == 2:
                    terms = [((d.get("membership") or {}).get("lease")
                              or {}).get("term") for d in dones]
                    sums = {d.get("param_sum") for d in dones}
                    grid["kill"] = {
                        "completed": True,
                        "steps_per_sec_ctrl": rate(dones),
                        "lease_term": max(t for t in terms
                                          if t is not None),
                        "terms_agree": len(set(terms)) == 1,
                        "clock_min": min(d["clock"] for d in dones),
                        "iters": c_iters,
                        "blocks_restored": sum(
                            (d.get("membership") or {}).get(
                                "blocks_restored", 0) for d in dones),
                        "wire_frames_lost": sum(
                            d.get("wire_frames_lost", 0)
                            for d in dones),
                        "finals_agree": len(sums) == 1,
                        # FLIGHT-DUMP gate inputs: >= 1 valid dump per
                        # survivor (the SIGKILLed rank 0 leaves none —
                        # nothing can) and the merge CLI exits 0
                        "flight_dumps": len(flight_files),
                        "flight_merge_ok": fproc.returncode == 0,
                    }
                else:
                    grid["kill"] = {"completed": False,
                                    "error": f"survivors rc={rc}: "
                                             f"{events}"[:300]}
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["kill"] = {"completed": False,
                                "error": str(e)[:300]}
        # -------- storm: 3 live + 1 held standby; a pull storm trips
        # admission shedding at the hot owner, the autoscaler admits
        # the standby under load (heat-aware placement), the storm ebbs
        # and the autoscaler drains its own growth — the closed loop.
        s_from = 4 if args.quick else 8
        s_until = (c_iters - 10) if args.quick else (c_iters - 14)
        with tempfile.TemporaryDirectory() as ck:
            try:
                res = _launch.run_local_job(
                    4, base + ["--checkpoint-dir", ck,
                               # pace the fleet so the serve rate below
                               # clears steady traffic on any host —
                               # only the storm sheds, so the drain's
                               # calm streak is clean calm
                               "--slow-rank", "1", "--slow-ms", "15",
                               "--storm-from", str(s_from),
                               "--storm-until", str(s_until),
                               # 12 pulls/step: the 3-rank storm sheds
                               # decisively at any step rate above
                               # ~6/s against rate=200, while steady
                               # traffic (3 legs/step/owner) stays
                               # inside the bucket up to the pacing cap
                               "--storm-pulls", "12",
                               "--storm-keys", "64"],
                    base_port=None,
                    env_extra={**env0, "MINIPS_ELASTIC": "live=0-2",
                               "MINIPS_AUTOSCALE":
                                   "up_shed=4,up_after=2,"
                                   "down_after=4,cool=2",
                               "MINIPS_SERVE":
                                   "rate=200,burst=16,min_heat=1e9"},
                    timeout=300.0)
                dones = [d for d in res if d.get("event") == "done"]
                ascale = [d.get("autoscale") or {} for d in res]
                pre = [a.get("shed_rate_pre") for a in ascale
                       if a.get("shed_rate_pre") is not None]
                post = [a.get("shed_rate_post") for a in ascale
                        if a.get("shed_rate_post") is not None]
                grid["storm"] = {
                    "completed": len(dones) == 3,
                    "steps_per_sec_ctrl": rate(dones) if dones else None,
                    "admits": sum(a.get("admits", 0) for a in ascale),
                    "drains": sum(a.get("drains", 0) for a in ascale),
                    "shed_rate_pre": pre[0] if pre else None,
                    "shed_rate_post": post[0] if post else None,
                    "joiner_drained": res[3].get("event") == "drained",
                    "wire_frames_lost": sum(
                        d.get("wire_frames_lost", 0) for d in res),
                }
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["storm"] = {"completed": False,
                                 "error": str(e)[:300]}
        return grid

    control_grid = _control_plane_arms()

    # THE PARTITION-TOLERANCE SWEEP (this PR): (1) fence_heal — a
    # seeded symmetric link cut isolates rank 0 (the lease holder) for
    # a wall-clock window; the majority convicts it by suspicion
    # QUORUM (the minority island, suspecting everyone, convicts
    # nobody — it cannot mint a term), rank 1 takes the lease, the
    # corpse-that-isn't restores from checkpoint, and post-heal the
    # reliable layer recovers every cut frame — including the stale
    # plan the ex-holder issued INSIDE the window (--coord-plan-at),
    # which must be FENCED by term at every survivor while the
    # ex-holder itself exits fenced_out (rc 44). (2) handover — the
    # holder drains ITSELF: lease transferred (term 1 exactly once,
    # coordinator state shipped in the mbH frame), then the PR8 drain
    # path, rc 0, zero deaths.
    def _partition_arms() -> dict:
        import tempfile

        from minips_tpu import launch as _launch

        p_iters = 40 if args.quick else 80
        part_at = 8                      # cut opens at receiver clock 8
        plan_at = part_at + 2            # the ex-holder's stale plan:
        # issued at A+2, the deepest boundary its own gate (s=2) can
        # reach once the cut freezes the peers' clocks it heard at A
        base = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_example",
                "--model", "sparse", "--mode", "ssp",
                "--staleness", "2", "--iters", str(p_iters),
                "--batch", "64", "--checkpoint-every", "4",
                # rank 0 trails (its stale plan must fire while BOTH
                # peers are already inside their cut windows) and pulls
                # only its own shard (no remote pull legs: it wedges at
                # its gate ~A+2, late enough to issue the plan)
                "--slow-rank", "0", "--slow-ms", "20",
                "--own-keys-rank", "0",
                "--coord-plan-at", str(plan_at),
                # survivors pace ~25ms/step so they are still training
                # when the window heals — the stale-plan recovery needs
                # live receivers
                "--jitter-ms", "30", "--jitter-prob", "0.8"]
        env0 = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                "MINIPS_RESHARD": "",
                "MINIPS_REBALANCE": "", "MINIPS_TRACE": "",
                "MINIPS_SERVE": "", "MINIPS_BUS": "",
                "MINIPS_WIRE_FMT": "", "MINIPS_CHAOS_KILL": "",
                "MINIPS_PUSH_COMM": "", "MINIPS_MESH": "",
                "MINIPS_AUTOSCALE": "", "MINIPS_OBS": "",
                "MINIPS_FLIGHT": "", "MINIPS_TENANT": "",
            "MINIPS_SLO": "", "MINIPS_TRAFFIC": ""}
        grid: dict = {"iters": p_iters}

        def rate(dones: list[dict]) -> float:
            return round(p_iters / max(max(d["wall_s"] for d in dones),
                                       1e-9), 2)

        with tempfile.TemporaryDirectory() as ck:
            try:
                rc, events = _launch.run_local_job_raw(
                    3, base + ["--checkpoint-dir", ck],
                    base_port=None,
                    env_extra={
                        **env0, "MINIPS_ELASTIC": "1",
                        # small budget + fast backoff: gaps opened
                        # against the cut exhaust INSIDE the window
                        # (give-up), so the post-heal advert must
                        # REOPEN them — the satellite path, engaged on
                        # the committed artifact
                        "MINIPS_RELIABLE":
                            "budget=4,backoff_ms=25,backoff_max_ms=150,"
                            "advert_ms=100",
                        "MINIPS_CHAOS":
                            f"5:part=1,links=0-1+0-2,at={part_at},"
                            "for=1.5s",
                        "MINIPS_HEARTBEAT":
                            "interval=0.1,timeout=0.7"},
                    timeout=300.0, kill_on_failure=False)
                by_last = {r: (ev[-1] if ev else {})
                           for r, ev in enumerate(events)}
                dones = [by_last[r] for r in (1, 2)
                         if by_last[r].get("event") == "done"]
                if len(dones) == 2:
                    mships = [d.get("membership") or {} for d in dones]
                    terms = [(m.get("lease") or {}).get("term")
                             for m in mships]
                    sums = {d.get("param_sum") for d in dones}
                    grid["fence_heal"] = {
                        "completed": True,
                        "steps_per_sec_ctrl": rate(dones),
                        "iters": p_iters,
                        "clock_min": min(d["clock"] for d in dones),
                        "lease_term": max(t for t in terms
                                          if t is not None),
                        "terms_agree": len(set(terms)) == 1,
                        # the PARTITION-FENCE evidence: stale-term
                        # frames dropped at the survivors (lease admit
                        # fence + rbP plan fence)
                        "fenced_total": sum(
                            (m.get("lease") or {}).get("fenced", 0)
                            for m in mships) + sum(
                            (d.get("rebalance") or {}).get(
                                "stale_plans_fenced", 0)
                            for d in dones),
                        "ex_coord_fenced_out":
                            by_last[0].get("event") == "fenced_out",
                        "part_dropped": sum(
                            (d.get("chaos") or {}).get(
                                "part_dropped", 0) for d in dones),
                        "reliable_reopened": sum(
                            (d.get("reliable") or {}).get(
                                "reopened", 0) for d in dones),
                        "blocks_restored": sum(
                            m.get("blocks_restored", 0)
                            for m in mships),
                        "wire_frames_lost": sum(
                            d.get("wire_frames_lost", 0)
                            for d in dones),
                        "finals_agree": len(sums) == 1,
                    }
                else:
                    grid["fence_heal"] = {
                        "completed": False,
                        "error": f"rc={rc}: {by_last}"[:400]}
            except Exception as e:  # noqa: BLE001 - completion-gated
                grid["fence_heal"] = {"completed": False,
                                      "error": str(e)[:300]}
        # -------- handover: the holder drains itself mid-run
        h_iters = 20 if args.quick else 30
        hbase = [sys.executable, "-m",
                 "minips_tpu.apps.sharded_ps_example",
                 "--model", "sparse", "--mode", "ssp",
                 "--staleness", "2", "--iters", str(h_iters),
                 "--batch", "64",
                 "--drain-rank", "0", "--drain-at", "10"]
        try:
            rc, events = _launch.run_local_job_raw(
                3, hbase, base_port=None,
                env_extra={**env0, "MINIPS_ELASTIC": "1",
                           "MINIPS_AUTOSCALE": "1",
                           "MINIPS_HEARTBEAT":
                               "interval=0.1,timeout=2.0"},
                timeout=240.0, kill_on_failure=False)
            by_last = {r: (ev[-1] if ev else {})
                       for r, ev in enumerate(events)}
            dones = [by_last[r] for r in (1, 2)
                     if by_last[r].get("event") == "done"]
            if rc == 0 and len(dones) == 2:
                mships = [d.get("membership") or {} for d in dones]
                terms = [(m.get("lease") or {}).get("term")
                         for m in mships]
                sums = {d.get("param_sum") for d in dones}
                drained = by_last[0]
                grid["handover"] = {
                    "completed": True,
                    "steps_per_sec_ctrl": round(
                        h_iters / max(max(d["wall_s"] for d in dones),
                                      1e-9), 2),
                    "lease_term": max(t for t in terms
                                      if t is not None),
                    "terms_agree": len(set(terms)) == 1,
                    "leaver_drained":
                        drained.get("event") == "drained",
                    "leaver_handovers": ((drained.get("membership")
                                          or {}).get("lease")
                                         or {}).get("handovers"),
                    "deaths": sum(m.get("deaths", 0) for m in mships),
                    "clock_min": min(d["clock"] for d in dones),
                    "iters": h_iters,
                    "wire_frames_lost": sum(
                        d.get("wire_frames_lost", 0) for d in dones),
                    "finals_agree": len(sums) == 1,
                }
            else:
                grid["handover"] = {"completed": False,
                                    "error": f"rc={rc}: {by_last}"[:400]}
        except Exception as e:  # noqa: BLE001 - completion-gated
            grid["handover"] = {"completed": False,
                                "error": str(e)[:300]}
        return grid

    partition_grid = _partition_arms()

    # THE IN-MESH COLLECTIVE DATA PLANE (this PR): the fused sweep
    # point — dense pull_all/push_dense cycles, the lrmlp weight-vector
    # shape — measured on the host wire (3 procs, zmq, ASP: its best
    # case) vs the mesh plane (one process, 3 logical ranks over 3
    # devices, push/pull as reduce-scatter/all-gather with pjit-sharded
    # table + updater state, BSP: the collective IS the barrier) vs the
    # mesh quantized tier (blk8: blockwise absmax int8 inside the
    # collective — the PR9 wire codec's second transport). Alternating
    # medians like every throughput pair. The ci/bench_regression
    # MESH-* tripwires gate: MESH-WIN — the mesh arm's rows/sec/rank
    # strictly above the wire arm's (the whole point: the data plane
    # stops paying socket+codec+frame tax and bridges toward the
    # fused-SPMD numbers); MESH-BITWISE — the BSP zmq-vs-mesh lockstep
    # drill (run in a subprocess against this tree) must report
    # bitwise-equal finals, so the transport swap provably preserves
    # the consistency contract. NOTE the rows/sec columns compare a
    # process boundary against a device mesh — integer factors by
    # design, which is the measurement (same caveat family as the
    # overlap sweep: the wire's deficit here is protocol cost).
    MESH_RANKS = 3

    def _run_mesh_arm(comm: str) -> dict:
        argv = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_bench",
                "--path", "dense", "--plane", "mesh",
                "--mesh-ranks", str(MESH_RANKS), "--mesh-comm", comm,
                "--iters", str(iters), "--warmup", str(warmup),
                "--staleness", "0"]
        env = {**os.environ, "MINIPS_FORCE_CPU": "1",
               "JAX_PLATFORMS": "cpu", "MINIPS_MESH": ""}
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=300.0, env=env)
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr[-300:])
            res = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}
        assert res.get("plane") == "mesh" and \
            res.get("mesh_comm") == comm, res
        return {
            "completed": True,
            "plane": "mesh", "mesh_comm": comm,
            "mesh_ranks": res["mesh_ranks"],
            "device_count": res["device_count"],
            "jax_backend": res["jax_backend"],
            "rows_per_sec_per_process": res["rows_per_sec"],
            "aggregate_rows_per_sec": res["aggregate_rows_per_sec"],
            "waves": res["waves"],
            "collective_bytes_per_row_moved":
                res["collective_bytes_per_row_moved"],
        }

    # the deposit-buffer A/B (this PR): the SPARSE path at the
    # embedding shape — a big table (64Ki rows) of skinny rows where
    # each wave touches a few hundred keys. The dense deposit stages a
    # full [rows, dim] host buffer per logical rank regardless; the
    # sparse deposit stages COO streams and densifies via segment-sum
    # scatter ON DEVICE, so peak host bytes scale with TOUCHED rows.
    # MESH-SPARSE gates: >= 4x peak-byte reduction, throughput no
    # worse (same collective — the exchange is untouched, only the
    # staging layout changes)
    def _run_mesh_deposit_arm(dep: str) -> dict:
        argv = [sys.executable, "-m",
                "minips_tpu.apps.sharded_ps_bench",
                "--path", "sparse", "--plane", "mesh",
                "--mesh-ranks", "2", "--mesh-comm", "float32",
                "--mesh-deposit", dep,
                "--rows", str(1 << 16), "--dim", "8", "--batch", "64",
                "--iters", str(iters), "--warmup", str(warmup),
                "--staleness", "0"]
        env = {**os.environ, "MINIPS_FORCE_CPU": "1",
               "JAX_PLATFORMS": "cpu", "MINIPS_MESH": "",
               "MINIPS_MESH_SPARSE": ""}
        try:
            proc = subprocess.run(argv, capture_output=True, text=True,
                                  timeout=300.0, env=env)
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr[-300:])
            res = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
        except Exception as e:  # noqa: BLE001 - completion-gated
            return {"completed": False, "error": str(e)[:300]}
        assert res.get("deposit") == dep, res
        return {
            "completed": True, "deposit": dep,
            "rows_per_sec_per_process": res["rows_per_sec"],
            "peak_deposit_bytes": res["peak_deposit_bytes"],
            "sparse_waves": res["sparse_waves"],
            "collective_bytes_per_row_moved":
                res["collective_bytes_per_row_moved"],
        }

    def _mesh_sparse_arms(reps: int) -> dict:
        runs: dict[str, list[dict]] = {"dense": [], "sparse": []}
        for _ in range(reps):  # alternating pairs, like every A/B
            runs["dense"].append(_run_mesh_deposit_arm("dense"))
            runs["sparse"].append(_run_mesh_deposit_arm("sparse"))

        def med(a: str) -> dict:
            ok = [r for r in runs[a] if r.get("completed")]
            if not ok:
                return runs[a][-1]
            by = sorted(ok,
                        key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}

        g = {"workload": {"path": "sparse", "rows": 1 << 16,
                          "dim": 8, "batch": 64, "mesh_ranks": 2,
                          "mesh_comm": "float32"},
             "dense": med("dense"), "sparse": med("sparse")}
        dn, sp = g["dense"], g["sparse"]
        if dn.get("completed") and sp.get("completed"):
            g["peak_bytes_ratio"] = round(
                dn["peak_deposit_bytes"]
                / max(sp["peak_deposit_bytes"], 1), 3)
            g["rows_ratio"] = round(
                sp["rows_per_sec_per_process"]
                / max(dn["rows_per_sec_per_process"], 1e-9), 3)
        return g

    def _mesh_arms(reps: int) -> dict:
        arms = {"wire": lambda: {
                    **_run(3, "dense", iters, warmup, "zmq"),
                    "plane": "wire"},
                "mesh": lambda: _run_mesh_arm("float32"),
                "mesh_blk8": lambda: _run_mesh_arm("blk8")}
        runs: dict[str, list[dict]] = {a: [] for a in arms}
        for _ in range(reps):
            for a, fn in arms.items():
                runs[a].append(fn())

        def med(arm: str) -> dict:
            ok = [r for r in runs[arm] if r.get("completed")]
            if not ok:
                return runs[arm][-1]
            by = sorted(ok, key=lambda r: r["rows_per_sec_per_process"])
            return {**by[len(by) // 2], "reps": reps}
        grid = {a: med(a) for a in arms}
        # MESH-BITWISE: the zmq-vs-mesh BSP lockstep drill, run from the
        # repo root (it drives the tests/ harness) in a subprocess so
        # the driver never initializes a jax backend itself
        drill_argv = [sys.executable, "-m",
                      "minips_tpu.apps.sharded_ps_bench",
                      "--mesh-bitwise-drill"]
        try:
            proc = subprocess.run(
                drill_argv, capture_output=True, text=True,
                timeout=300.0,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env={**os.environ, "MINIPS_FORCE_CPU": "1",
                     "JAX_PLATFORMS": "cpu", "MINIPS_MESH": ""})
            res = json.loads([ln for ln in proc.stdout.splitlines()
                              if ln.startswith("{")][-1])
            grid["bitwise"] = {"equal": bool(res.get("bitwise_equal")),
                               "rows_checked":
                                   int(res.get("rows_checked", 0))}
            if res.get("error"):
                grid["bitwise"]["error"] = res["error"]
        except Exception as e:  # noqa: BLE001 - the gate reads this
            grid["bitwise"] = {"equal": False, "rows_checked": 0,
                               "error": str(e)[:300]}
        grid["sparse_deposit"] = _mesh_sparse_arms(reps)
        return grid

    mesh_grid = _mesh_arms(o_reps)

    # THE FAIL-SLOW SWEEP (this PR): a seeded slow# link tax makes
    # rank 1 (the storm range's owner) slow-but-alive — its beats
    # land, nothing dies, every read to it rides the tax. Three arms +
    # the armed-idle bitwise stamp: (1) unmitigated — the gray failure
    # as the pre-this-PR fleet lives it (reads pay the tail, steps
    # complete); (2) hedged — serve-plane replicas + MINIPS_HEDGE:
    # rank 0 (the designated reader: NOT a holder — rank 2 holds the
    # sick rank's replicas and serves itself locally) must land its
    # warmed windowed read p99 STRICTLY below the unmitigated arm's
    # (SLOW-HEDGE); (3) demote — + MINIPS_SLOW detection, quorum slow
    # verdict over heartbeat ballots, and the rebalancer's demote pass
    # migrating the sick rank's hot blocks off it (SLOW-DRAIN: >= 1
    # block out of rank 1, zero lost steps, bitwise survivors, the
    # four flight events in the post-mortem boxes). SLOW-IDLE rides
    # the --fail-slow-idle-drill lockstep stamp.
    fail_slow_grid = fail_slow_arms(quick=args.quick)

    reshard_grid = reshard_arms(quick=args.quick)

    # THE HIER SWEEP (this PR): the two-level push tree vs the flat
    # per-worker wire on the same seeded zipf-overlap workload —
    # HIER-WIN wants the tree's cross-host leader leg >= 1.7x fewer
    # bytes with matching loss; the bitwise/idle drills pin exactness
    hier_grid = hier_arms(quick=args.quick)

    # THE HYBRID SWEEP (this PR): the tree's leader reduce moved onto
    # the in-host device mesh — HYBRID-WIN wants the hybrid arm
    # strictly faster than the host-agg tree at matching loss with
    # cross-host bytes no worse; HYBRID-IDLE and the one-device
    # degenerate drill pin exactness
    hybrid_grid = hybrid_arms(quick=args.quick)

    # THE MULTI-TENANT SWEEP (this PR): a training tenant next to a
    # storming zipf inference tenant in ONE job — TENANT-ISO wants the
    # isolated arm's trn throughput within 10% of its solo arm with
    # inf shedding into its OWN budget (trn's attributed counters
    # zero, the shared-bucket contrast arm visibly coupled);
    # TENANT-IDLE wants the bare-default-tenant lockstep bitwise
    tenant_grid = tenant_arms(quick=args.quick)

    # THE MILLION-USER SWEEP (this PR): an open-loop zipf traffic
    # driver on a fixed arrival schedule against pull_serving while
    # training runs — TRAFFIC-FRESH wants the flash crowd degrading to
    # latency (zero stale reads, bounded freshness p99, replica budget
    # provably flexed above its configured count); TRAFFIC-SHED wants
    # overload shedding into the inf tenant's own budget with an
    # slo_burn flight event; TRAFFIC-IDLE wants the rate-0 armed
    # driver bitwise-identical to off with zero requests scheduled
    traffic_grid = traffic_arms(quick=args.quick)

    # resolved JAX backend stamp (satellite): probed in a SUBPROCESS so
    # the driver never grabs the TPU out from under a worker (libtpu is
    # exclusive per process) — ci/bench_regression.py refuses to
    # compare artifacts whose backends differ (the r03-r05
    # cpu-fallback runs were silently incomparable to r01/r02)
    def _resolve_jax_backend() -> str:
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, sys; sys.stdout.write("
                 "jax.default_backend())"],
                capture_output=True, text=True, timeout=120.0,
                env={**os.environ, "JAX_PLATFORMS": os.environ.get(
                    "JAX_PLATFORMS", "")})
            out = (probe.stdout or "").strip().splitlines()
            return out[-1] if probe.returncode == 0 and out \
                else "unknown"
        except Exception:  # noqa: BLE001 - a stamp, not a gate
            return "unknown"

    # resolved mesh/device SHAPE stamp (satellite): backend:device-count
    # as the mesh arms saw it — ci/bench_regression.py refuses to
    # compare artifacts across shapes the way it refuses cross-backend
    # pairs (a mesh point at 8 devices is incomparable to one at 3; the
    # collective cost scales with the ring)
    def _resolve_device_shape() -> str:
        shape = (mesh_grid.get("mesh") or {})
        if shape.get("completed"):
            return f"{shape['jax_backend']}:{shape['device_count']}"
        return "unknown"

    headline = curve["3"]["rows_per_sec_per_process"]
    print(json.dumps({
        "metric": "sharded-PS rows/sec/process (sparse pull+push, "
                  "3 procs, zmq, CPU loopback control plane)",
        "value": headline,
        "unit": "rows/sec/process",
        "vs_baseline": None,  # control-plane rate; not a chip number
        "device": "cpu-loopback",
        # the resolved JAX platform these numbers were measured under:
        # the regression gate refuses cross-backend comparisons
        "jax_backend": _resolve_jax_backend(),
        # the mesh/device shape the collective-plane arms ran at
        # (backend:device-count) — the gate refuses cross-shape
        # comparisons the same way
        "device_shape": _resolve_device_shape(),
        "scaling_sparse_zmq": curve,
        "bus_comparison_3proc": buses,
        "transport_comparison_3proc": transport_grid,
        "path_comparison_3proc": paths,
        "push_wire_comparison_3proc": wires,
        "pull_wire_comparison_3proc": pull_wires,
        "overlap_on_off_3proc": over,
        "overlap_on_off_fit": {"nprocs": n_fit, **over_fit},
        "cache_comparison_3proc": cache_grid,
        "wire_compression_3proc": wire_comp_grid,
        "chaos_resilience_3proc": chaos_grid,
        "rebalance_3proc": rebalance_grid,
        "trace_overhead_3proc": trace_grid,
        "obs_tax_3proc": obs_tax_grid,
        "pull_storm_3proc": storm_grid,
        "elastic_membership_3proc": elastic_grid,
        "control_plane_3proc": control_grid,
        "partition_3proc": partition_grid,
        "fail_slow_3proc": fail_slow_grid,
        "reshard_3proc": reshard_grid,
        "hier_agg_3proc": hier_grid,
        "hybrid_agg_3proc": hybrid_grid,
        "multi_tenant_3proc": tenant_grid,
        "million_user_3proc": traffic_grid,
        "mesh_plane_fused": mesh_grid,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
