"""word2vec_example — skip-gram negative sampling on enwiki-shaped text
(BASELINE.json:11: "Word2Vec skip-gram on enwiki, negative sampling, async
push"). Input/output embeddings in two SparseTables; negatives sampled
host-side from unigram^0.75; fused SPMD step pushes rows asynchronously
w.r.t. the host (dispatch is async; data dependencies order updates).

Usage: python -m minips_tpu.apps.word2vec_example --num_iters 200
"""

from __future__ import annotations

import numpy as np

from minips_tpu.apps.common import app_main
from minips_tpu.core.config import Config, TableConfig, TrainConfig
from minips_tpu.data import synthetic
from minips_tpu.models import word2vec as w2v
from minips_tpu.parallel.mesh import make_mesh
from minips_tpu.tables.sparse import SparseTable
from minips_tpu.train.loop import TrainLoop
from minips_tpu.train.ps_step import PSTrainStep

DEFAULT = Config(
    table=TableConfig(name="emb", kind="sparse", consistency="asp",
                      updater="sgd", lr=0.05, dim=64, num_slots=1 << 14),
    train=TrainConfig(batch_size=1024, num_iters=200),
)
NEG = 5


def _pair_batches(cfg, args, vocab=10_000):
    path = getattr(args, "data_file", None)
    if path:  # real text corpus (enwiki-style), word-level tokens
        from minips_tpu.data.text import word_tokens
        tokens, counts = word_tokens(path, vocab_size=vocab)
    else:
        tokens, counts = synthetic.text_corpus(vocab, seed=cfg.train.seed)
    t = getattr(args, "subsample", 0.0)
    if t > 0:  # classic frequent-word subsampling (t=1e-5 at enwiki scale)
        tokens = w2v.subsample_frequent(tokens, counts, t=t,
                                        seed=cfg.train.seed)
    centers, contexts = synthetic.skipgram_pairs(tokens,
                                                 seed=cfg.train.seed)
    sampler = w2v.UnigramSampler(counts, seed=cfg.train.seed)
    B = cfg.train.batch_size
    rng = np.random.default_rng(cfg.train.seed)

    def gen():
        n = len(centers)
        while True:
            sel = rng.integers(0, n, size=B)
            yield {"center": centers[sel], "pos": contexts[sel],
                   "neg": sampler.sample((B, NEG)).astype(np.int32)}

    return gen()


def run(cfg: Config, args, metrics) -> dict:
    mesh = make_mesh()
    in_t = SparseTable(cfg.table.num_slots, cfg.table.dim, mesh, name="in",
                       updater=cfg.table.updater, lr=cfg.table.lr,
                       init_scale=0.01, seed=1)
    out_t = SparseTable(cfg.table.num_slots, cfg.table.dim, mesh, name="out",
                        updater=cfg.table.updater, lr=cfg.table.lr,
                        init_scale=0.0, seed=2)
    import jax.numpy as jnp

    def loss_fn(dense_params, rows, batch):
        # rows["out"]: [B, 1+K, dim] (keys were [B, 1+K])
        return w2v.sgns_loss(rows["in"], rows["out"][:, 0],
                             rows["out"][:, 1:])

    # grad_scale=B: the mean-loss gradient underscales per-row updates by
    # the batch size; scaling restores the reference's per-sample SGD
    # magnitude (classic per-pair word2vec updates at this lr).
    ps = PSTrainStep(
        loss_fn, sparse={"in": in_t, "out": out_t},
        key_fns={"in": lambda b: b["center"],
                 "out": lambda b: jnp.concatenate(
                     [b["pos"][:, None], b["neg"]], axis=1)},
        grad_scale=cfg.train.batch_size)
    batches = _pair_batches(cfg, args)
    loop = TrainLoop(lambda b: ps(ps.shard_batch(b)), batches,
                     metrics=metrics, log_every=cfg.train.log_every,
                     batch_size=cfg.train.batch_size)
    losses = loop.run(cfg.train.num_iters)
    metrics.log(final_loss=losses[-1])
    return {"losses": losses, "samples_per_sec": loop.timer.samples_per_sec,
            "tables": (in_t, out_t)}


def _flags(parser):
    parser.add_argument("--data_file", default=None,
                        help="text file (enwiki-style) tokenized at word "
                             "level instead of the synthetic corpus")
    parser.add_argument("--subsample", type=float, default=0.0,
                        help="frequent-word subsampling threshold t "
                             "(classic 1e-5 for enwiki-scale corpora; "
                             "0 disables)")


def main():
    return app_main("word2vec_example", DEFAULT, run, extra_flags=_flags)


if __name__ == "__main__":
    main()
