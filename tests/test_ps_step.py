"""PSTrainStep: fused dense+sparse step on the fake-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minips_tpu.tables.dense import DenseTable
from minips_tpu.tables.sparse import SparseTable
from minips_tpu.train.ps_step import PSTrainStep


def test_sparse_only_lr_converges(mesh8):
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=64).astype(np.float32)
    idx = rng.integers(0, 64, size=(2048, 6)).astype(np.int32)
    val = np.abs(rng.normal(size=(2048, 6))).astype(np.float32)
    y = ((w_true[idx] * val).sum(-1) > 0).astype(np.float32)
    t = SparseTable(128, 1, mesh8, updater="adagrad", lr=0.5, init_scale=0.0)

    def loss_fn(dense_params, rows, batch):
        logits = jnp.sum(rows["w"][..., 0] * batch["val"], axis=-1)
        return jnp.mean(jnp.logaddexp(0.0, logits) - batch["y"] * logits)

    ps = PSTrainStep(loss_fn, sparse={"w": t},
                     key_fns={"w": lambda b: b["idx"]})
    batch = ps.shard_batch({"idx": idx, "val": val, "y": y})
    losses = [float(ps(batch)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7


def test_dense_plus_sparse_joint_step(mesh8):
    """Both tables must receive updates from one fused step."""
    dense = DenseTable({"w": jnp.zeros(8), "b": jnp.zeros(())}, mesh8,
                       updater="sgd", lr=0.1)
    emb = SparseTable(64, 4, mesh8, updater="sgd", lr=0.1, init_scale=0.01,
                      seed=3)
    emb0 = np.asarray(emb.emb).copy()

    def loss_fn(dp, rows, batch):
        feats = jnp.concatenate(
            [rows["e"].reshape(rows["e"].shape[0], -1),
             jnp.ones((rows["e"].shape[0], 4))], axis=-1)
        logits = feats @ dp["w"] + dp["b"]
        return jnp.mean((logits - batch["y"]) ** 2)

    ps = PSTrainStep(loss_fn, dense=dense, sparse={"e": emb},
                     key_fns={"e": lambda b: b["k"]})
    rng = np.random.default_rng(0)
    batch = ps.shard_batch({"k": np.arange(16, dtype=np.int32),
                            "y": rng.normal(size=16).astype(np.float32)})
    l0 = float(ps(batch))
    for _ in range(20):
        l = float(ps(batch))
    assert l < l0
    assert not np.allclose(np.asarray(dense.params), 0.0)
    assert np.abs(np.asarray(emb.emb) - emb0).max() > 1e-6


def test_step_preserves_sharding(mesh8):
    """Donated state must come back with the same shardings (no silent
    re-layout drift across steps)."""
    dense = DenseTable({"w": jnp.zeros(16)}, mesh8, updater="sgd", lr=0.1)

    def loss_fn(dp, rows, batch):
        return jnp.mean((batch["x"] @ dp["w"]) ** 2)

    ps = PSTrainStep(loss_fn, dense=dense)
    batch = ps.shard_batch({"x": np.ones((8, 16), np.float32)})
    before = dense.params.sharding
    ps(batch)
    assert dense.params.sharding.is_equivalent_to(before, dense.params.ndim)


def test_missing_key_fn_raises(mesh8):
    t = SparseTable(64, 2, mesh8)
    with pytest.raises(ValueError, match="missing key_fns"):
        PSTrainStep(lambda d, r, b: 0.0, sparse={"t": t})


def test_reserved_dense_name_rejected(mesh8):
    t = SparseTable(64, 2, mesh8)
    with pytest.raises(ValueError, match="reserved"):
        PSTrainStep(lambda d, r, b: 0.0, sparse={"dense": t},
                    key_fns={"dense": lambda b: b["k"]})


def test_compute_dtype_bfloat16_joint_step(mesh8):
    """compute_dtype=bfloat16: the loss_fn provably sees bf16 dense
    params, rows, and batch floats; master state stays f32; the bf16
    trajectory tracks the f32 one."""
    seen = []

    def loss_fn(dp, rows, batch):
        seen.append((dp["w"].dtype, rows["e"].dtype, batch["y"].dtype,
                     batch["k"].dtype))
        feats = jnp.concatenate(
            [rows["e"].reshape(rows["e"].shape[0], -1),
             jnp.ones((rows["e"].shape[0], 4), rows["e"].dtype)], axis=-1)
        logits = feats @ dp["w"] + dp["b"]
        return jnp.mean((logits - batch["y"]) ** 2)

    rng = np.random.default_rng(0)
    raw = {"k": np.arange(16, dtype=np.int32),
           "y": rng.normal(size=16).astype(np.float32)}
    finals = {}
    for label, cd in [("f32", None), ("bf16", jnp.bfloat16)]:
        dense = DenseTable({"w": jnp.zeros(8), "b": jnp.zeros(())}, mesh8,
                           updater="sgd", lr=0.1)
        emb = SparseTable(64, 4, mesh8, updater="adagrad", lr=0.1,
                          init_scale=0.01, seed=3)
        ps = PSTrainStep(loss_fn, dense=dense, sparse={"e": emb},
                         key_fns={"e": lambda b: b["k"]},
                         compute_dtype=cd)
        batch = ps.shard_batch(raw)
        l0 = float(ps(batch))
        for _ in range(25):
            l = float(ps(batch))
        finals[label] = (l0, l)
        assert dense.params.dtype == jnp.float32
        assert emb.emb.dtype == jnp.float32
    assert (jnp.bfloat16, jnp.bfloat16, jnp.bfloat16, jnp.int32) in seen
    for label, (l0, l) in finals.items():
        assert l < l0, (label, l0, l)
    assert abs(finals["bf16"][1] - finals["f32"][1]) < 0.05, finals


def test_grad_scale_matches_sum_loss(mesh8):
    """grad_scale=B with a mean loss produces exactly the updates of a
    sum loss (the reference's per-sample server-add semantics), while the
    reported loss stays the mean."""
    raw = {"k": np.arange(16, dtype=np.int32),
           "y": np.random.default_rng(0).normal(size=16).astype(np.float32)}

    def mean_loss(dp, rows, batch):
        pred = rows["e"].sum(axis=-1)
        return jnp.mean((pred - batch["y"]) ** 2)

    def sum_loss(dp, rows, batch):
        pred = rows["e"].sum(axis=-1)
        return jnp.sum((pred - batch["y"]) ** 2)

    embs = {}
    losses = {}
    for label, (fn, gs) in [("scaled_mean", (mean_loss, 16.0)),
                            ("sum", (sum_loss, 1.0))]:
        t = SparseTable(64, 4, mesh8, updater="sgd", lr=0.01,
                        init_scale=0.01, seed=7)
        ps = PSTrainStep(fn, sparse={"e": t},
                         key_fns={"e": lambda b: b["k"]}, grad_scale=gs)
        batch = ps.shard_batch(raw)
        losses[label] = float(ps(batch))
        embs[label] = np.asarray(t.emb)
    np.testing.assert_allclose(embs["scaled_mean"], embs["sum"],
                               rtol=1e-5, atol=1e-7)
    assert losses["scaled_mean"] == pytest.approx(losses["sum"] / 16, 1e-5)
