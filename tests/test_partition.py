import numpy as np
import pytest

from minips_tpu.parallel.mesh import padded_size
from minips_tpu.parallel.partition import (BlockRouter, HashPartitioner,
                                           RangePartitioner)


def test_padded_size():
    assert padded_size(10, 4) == 12
    assert padded_size(8, 4) == 8
    assert padded_size(1, 8) == 8
    assert padded_size(0, 4) == 4  # empty tables still get one row per shard


def test_contiguous_ranges():
    p = RangePartitioner(num_keys=10, num_shards=4)
    assert p.padded == 12 and p.shard_size == 3
    keys = np.arange(10)
    np.testing.assert_array_equal(
        p.shard_of(keys), [0, 0, 0, 1, 1, 1, 2, 2, 2, 3])


def test_split_preserves_order_and_partition():
    p = RangePartitioner(num_keys=100, num_shards=8)
    keys = np.array([5, 99, 13, 0, 64, 63, 12])
    slices = p.split(keys)
    assert len(slices) == 8
    merged = np.concatenate([s for s in slices])
    assert sorted(merged.tolist()) == sorted(keys.tolist())
    for s, sl in enumerate(slices):
        assert (p.shard_of(sl) == s).all()


def test_local_offset_roundtrip():
    p = RangePartitioner(num_keys=64, num_shards=8)
    keys = np.arange(64)
    recon = p.shard_of(keys) * p.shard_size + p.local_offset(keys)
    np.testing.assert_array_equal(recon, keys)


# ------------------------------------------------ partition properties
# (previously only exercised incidentally: align > 1 padding and
# non-divisible num_keys must keep split/local_offset/shard_of
# coherent). Seeded randomized sweeps, not hypothesis: the property
# must RUN even where the test extra isn't installed.
def _partition_specs(n=120, seed=42):
    rng = np.random.default_rng(seed)
    specs = [(int(rng.integers(0, 500)),   # num_keys (0 = empty ok)
              int(rng.integers(1, 10)),    # num_shards
              int(rng.integers(1, 6)))     # align
             for _ in range(n)]
    # pin the classic corners alongside the random sweep
    return specs + [(0, 4, 1), (1, 8, 3), (10, 4, 1), (7, 3, 5),
                    (500, 9, 5)]


def test_range_partitioner_roundtrip_properties():
    for num_keys, shards, align in _partition_specs():
        p = RangePartitioner(num_keys, shards, align=align)
        # padding invariants: every shard padded to a multiple of
        # align, the padded space covers num_keys
        assert p.padded >= max(num_keys, 1)
        assert p.padded == p.shard_size * shards
        assert p.shard_size % align == 0
        keys = np.arange(p.padded)
        owners = p.shard_of(keys)
        assert owners.min() >= 0 and owners.max() < shards
        # shard_of * shard_size + local_offset round-trips every key
        np.testing.assert_array_equal(
            owners * p.shard_size + p.local_offset(keys), keys)
        # split() is a partition: disjoint, order-preserving, complete
        sl = p.split(keys)
        assert len(sl) == shards
        np.testing.assert_array_equal(np.concatenate(sl), keys)
        for s, part in enumerate(sl):
            assert (p.shard_of(part) == s).all()
            assert part.size == p.shard_size


def test_hash_partitioner_roundtrip_properties():
    for num_keys, shards, align in _partition_specs():
        p = HashPartitioner(num_keys, shards, align=align)
        keys = np.arange(max(num_keys, 1))
        owners = p.shard_of(keys)
        assert owners.min() >= 0 and owners.max() < shards
        # interleave round-trip: key = local_offset * shards + owner
        np.testing.assert_array_equal(
            p.local_offset(keys) * shards + owners, keys)
        sl = p.split(keys)
        np.testing.assert_array_equal(np.sort(np.concatenate(sl)), keys)
        for s, part in enumerate(sl):
            assert (p.shard_of(part) == s).all()
            if part.size > 1:  # order preserved (Gen(keys) contract)
                assert (np.diff(part) > 0).all()


def test_hash_partitioner_spreads_contiguous_hot_range():
    """The static answer to head skew: a contiguous hot range lands on
    EVERY shard (vs all-on-shard-0 under range partition)."""
    h = HashPartitioner(1 << 12, 4)
    r = RangePartitioner(1 << 12, 4)
    hot = np.arange(64)  # the zipf head
    assert set(h.shard_of(hot).tolist()) == {0, 1, 2, 3}
    assert set(r.shard_of(hot).tolist()) == {0}


# ------------------------------------------------------- block router
def test_block_router_spans_tile_each_shard():
    rng = np.random.default_rng(7)
    cases = [(num_keys, shards, align, int(rng.integers(1, 41)))
             for num_keys, shards, align in _partition_specs(60, seed=9)]
    for num_keys, shards, align, block_size in cases:
        part = RangePartitioner(num_keys, shards, align=align)
        r = BlockRouter(part, block_size)
        # block spans tile the padded key space disjointly and
        # completely, never straddling a shard boundary
        covered = np.zeros(part.padded, bool)
        for b in range(r.num_blocks):
            lo, ln = r.block_span(b)
            assert ln >= 1
            assert not covered[lo:lo + ln].any()
            covered[lo:lo + ln] = True
            assert lo // part.shard_size \
                == (lo + ln - 1) // part.shard_size
            assert r.home_of(b) == lo // part.shard_size
            keys = np.arange(lo, lo + ln)
            assert (r.blocks_of(keys) == b).all()
        assert covered.all()


def test_block_router_overlay_routing_and_epochs():
    part = RangePartitioner(64, 4)  # shard_size 16
    r = BlockRouter(part, 8)        # 2 blocks per shard
    keys = np.arange(64)
    np.testing.assert_array_equal(r.shard_of(keys), part.shard_of(keys))
    assert r.apply(1, {0: 3}) == {}          # adopted; previous empty
    assert (r.shard_of(np.arange(0, 8)) == 3).all()   # block 0 moved
    assert (r.shard_of(np.arange(8, 16)) == 0).all()  # block 1 home
    assert r.apply(1, {0: 2}) is None        # stale epoch: ignored
    assert r.apply(0, {}) is None
    assert r.shard_of(np.array([0]))[0] == 3
    # newer table replaces wholesale; returns the PREVIOUS overlay
    assert r.apply(2, {4: 1}) == {0: 3}
    assert r.shard_of(np.array([0]))[0] == 0          # moved back home
    assert (r.shard_of(np.arange(32, 40)) == 1).all()  # block 4 moved
    owners = r.owner_of_blocks()
    assert owners[4] == 1 and owners[0] == 0
    ep, ov = r.table()
    assert ep == 2 and ov == {4: 1}


def test_block_router_rejects_bad_overlays():
    r = BlockRouter(RangePartitioner(64, 4), 8)
    with pytest.raises(ValueError, match="home"):
        r.apply(1, {0: 0})  # block 0's home IS shard 0
    with pytest.raises(ValueError, match="out of range"):
        r.apply(1, {999: 1})
    with pytest.raises(ValueError, match="out of range"):
        r.apply(1, {0: 9})
