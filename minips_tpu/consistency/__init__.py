from minips_tpu.consistency.tracker import PendingBuffer, ProgressTracker  # noqa: F401
from minips_tpu.consistency.controllers import (  # noqa: F401
    ASP,
    BSP,
    SSP,
    ConsistencyController,
    make_controller,
)
