"""Black-box flight recorder — always on, dumped on every poison path.

The tracer (obs/tracer.py) answers "show me everything" but is OFF by
default: a post-mortem after a seeded SIGKILL (or a real OOM kill) has
nothing unless ``MINIPS_TRACE`` was armed BEFORE the failure. This
module is the aviation answer: a bounded typed event ring every rank
keeps ALWAYS (same off-path discipline as the tracer — one module-attr
load + one branch at quiet call sites; the on-path record is a
``monotonic()`` + tuple + GIL-atomic deque append), recording only the
DECISIONS and DEATHS of the stack:

========== ===================== =================================
cat        kind                  meaning (key args)
========== ===================== =================================
hb         hb_death              heartbeat verdict against a peer
                                 (rank, owns)
hb         hb_stall_forgiven     observer-stall sweep re-baselined
                                 peers (gap_s)
lease      term_advance          lease succession (term, holder,
                                 dead, live)
lease      lease_fenced          stale-term frame dropped (lt, term)
membership death_plan            coordinator issued a death
                                 transition (rank, rstep)
autoscale  as_admit / as_drain   autoscaler action + the signal
                                 values that forced it (shed_rate,
                                 p99_ms, streak)
serve      sv_shed / sv_bp       admission decision + WHY (tokens
                                 denied count at decision time)
reliable   reliable_give_up      retransmission budget exhausted /
                                 journal-evicted seq (unrecovered)
poison     pull_deadline / ...   the poison that killed a wait
========== ===================== =================================

Every POISON path additionally calls :meth:`FlightRecorder.poison`,
which records the reason and atomically dumps the ring (tmp +
``os.replace`` — the tracer's rule; a reader never sees a torn file)
next to a final windowed-metrics snapshot (``snapshot_hook``). The dump
is re-entrant-safe: two poison paths firing concurrently (a gate
timeout racing the heartbeat verdict) serialize on the dump lock and
BOTH reasons land in the file. ``atexit`` dumps too, so a run that dies
by exception — or a launcher-killed straggler that still unwinds —
leaves its box. A SIGKILLed rank leaves nothing (nothing can); its
SURVIVORS' boxes carry the verdict, the term advance, and the death
plan, which is what the post-mortem needs.

Clock alignment rides for free: every heartbeat receipt min-merges
``(t_recv − t_sent)`` per sender into a tiny side table (one dict op
per beat — beats are per-second, not per-frame), and the merge CLI
derives per-rank offsets exactly like ``obs/merge.py`` does from the
tracer's hb instants (NTP two-sample, min-filtered).

CLI::

    python -m minips_tpu.obs.flight <dir-or-files...> [-o merged.json]

prints the per-rank dumps as ONE offset-aligned human-readable
timeline plus a final JSON summary line; exit 0 iff >= 1 dump loaded.

Knob (``MINIPS_FLIGHT``): unset/empty = ON at the default directory
(``<tmp>/minips-flight-<MINIPS_RUN_ID or pid>`` — zero pre-arming, the
point); ``0`` = off (the OBS-TAX honesty arm); ``<dir>[:cap=<events>]``
= explicit directory/ring depth.
"""

from __future__ import annotations

import atexit
import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Optional

__all__ = ["FlightRecorder", "FLIGHT", "maybe_init", "init", "record",
           "poison", "checkpoint", "dump_now", "default_dir",
           "reset_for_tests", "sweep_stale_dirs", "load_dumps",
           "merge_dumps", "main"]

# THE global handle (the tracer pattern): ``flight.FLIGHT is None`` is
# the whole cost at a quiet call site when the layer is disabled.
FLIGHT: "Optional[FlightRecorder]" = None

_init_lock = threading.Lock()
_DEFAULT_CAP = 4096


def default_dir() -> str:
    """Where dumps land with NOTHING armed: keyed by the launcher's
    ``MINIPS_RUN_ID`` (every rank of one job shares it; a post-mortem
    knows where to look without any pre-run setup) or this pid for
    launcher-less runs."""
    run = os.environ.get("MINIPS_RUN_ID", "").strip() or str(os.getpid())
    return os.path.join(tempfile.gettempdir(), f"minips-flight-{run}")


class FlightRecorder:
    """One per process. Events are ``(t_mono_s, kind, args)`` tuples —
    args a small dict or None, never mutated after recording. The ring
    drops OLDEST events (the tail of a dying run is the part worth
    keeping)."""

    def __init__(self, rank: int, out_dir: str,
                 cap: int = _DEFAULT_CAP):
        self.rank = int(rank)
        self.out_dir = out_dir
        self.out_path = os.path.join(out_dir,
                                     f"flight-rank{self.rank}.json")
        self.cap = int(cap)
        self._ring: deque = deque(maxlen=self.cap)
        # poison causes: never rotated with the ring, but BOUNDED — a
        # run that keeps poisoning past the cap is in a poison LOOP,
        # and the dropped counter says so louder than 10k repeats would
        self._reasons: list = []
        self.reasons_dropped = 0
        self._hb: dict = {}           # sender -> min (t_recv-t_sent) us
        self._dump_lock = threading.Lock()
        # anchors: wall time lets a human date the box; monotonic is
        # what every event carries (the merge aligns monotonic clocks)
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self.dumps = 0
        os.makedirs(out_dir, exist_ok=True)

    # ------------------------------------------------------------- record
    def ev(self, kind: str, args: dict | None = None) -> None:
        """The hot-path record: monotonic() + tuple + GIL-atomic
        append. No lock, no allocation beyond the tuple."""
        self._ring.append((time.monotonic(), kind, args))

    def hb_sample(self, sender: int, t_sent: float,
                  t_recv: float) -> None:
        """Min-merge one heartbeat's one-way delay (us) per sender —
        the merge CLI's clock-offset input. A dict get + maybe a set
        per beat; beats are ~1/s/peer, nowhere near the frame path."""
        d = (t_recv - t_sent) * 1e6
        cur = self._hb.get(sender)
        if cur is None or d < cur:
            self._hb[sender] = d

    _MAX_REASONS = 1024  # beyond this a run is poison-looping

    # -------------------------------------------------------------- poison
    def poison(self, reason: str, args: dict | None = None) -> None:
        """A poison path fired: record the reason (ring AND the
        reasons list — the ring may rotate it out, the list only stops
        growing at the poison-loop bound, counted) and dump NOW.
        Never raises."""
        t = time.monotonic()
        if len(self._reasons) < self._MAX_REASONS:
            self._reasons.append((t, reason, args))  # GIL-atomic
        else:
            self.reasons_dropped += 1
        self._ring.append((t, reason, args))
        self.dump()

    # --------------------------------------------------------------- dump
    # installed by the trainer: () -> dict, the final windowed-metrics
    # snapshot that rides every dump (None when the window layer is off)
    snapshot_hook: Optional[Callable[[], dict]] = None

    def _events_snapshot(self, ring) -> list:
        # list(deque) copies atomically under the GIL (the tracer's
        # measured result); retry guards exotic implementations
        for _ in range(16):
            try:
                return list(ring)
            except RuntimeError:
                continue
        return []

    def dump(self, path: str | None = None) -> Optional[str]:
        """Atomic, idempotent, re-entrant-safe, never-raising dump of
        the current ring + reasons + hb table + windowed snapshot.
        Concurrent poison paths serialize on the lock; each dump
        rewrites the file whole, so the LAST writer's view (which
        includes every earlier reason — the list is append-only) wins
        and the file is always complete JSON."""
        try:
            path = path or self.out_path
            with self._dump_lock:
                # snapshot UNDER the dump lock, not before it: a dump
                # that snapshots early, then loses the lock race and
                # writes LAST would overwrite the file with a view
                # missing reasons appended in between — the exact
                # torn-concurrent-poisons hole the regression test
                # hammers (caught there: 24 of 30 reasons survived)
                events = self._events_snapshot(self._ring)
                reasons = self._events_snapshot(self._reasons)
                # the hb table mutates on the heartbeat receive thread
                # — same copy-under-retry treatment as the ring, or a
                # resize mid-copy would RuntimeError the dump away
                hb = {}
                for _ in range(16):
                    try:
                        hb = dict(self._hb)
                        break
                    except RuntimeError:
                        continue
                window = None
                hook = self.snapshot_hook
                if hook is not None:
                    try:
                        window = hook()
                    except Exception:  # noqa: BLE001 - box must close
                        window = {"error": "snapshot_hook failed"}

                def row(t, kind, args):
                    e = {"t_us": round(t * 1e6, 1), "kind": kind}
                    if args:
                        e["args"] = args
                    return e

                doc = {
                    "rank": self.rank,
                    "pid": os.getpid(),
                    "run_id": os.environ.get("MINIPS_RUN_ID") or None,
                    "cap": self.cap,
                    "t0_mono_us": round(self._t0_mono * 1e6, 1),
                    "t0_wall": self._t0_wall,
                    "events": [row(*e) for e in events],
                    "reasons": [row(*r) for r in reasons],
                    "reasons_dropped": self.reasons_dropped,
                    "hb_delays_us": {str(s): round(d, 1)
                                     for s, d in sorted(hb.items())},
                    "window": window,
                }
                tmp = f"{path}.tmp{threading.get_ident()}"
                with open(tmp, "w") as f:
                    json.dump(doc, f, default=repr)
                os.replace(tmp, path)  # readers never see a torn file
                self.dumps += 1
            return path
        except Exception as e:  # noqa: BLE001 - report, don't propagate
            import sys

            print(f"flight: dump failed: {e!r}", file=sys.stderr)
            return None


# ----------------------------------------------------------- module api
def init(rank: int, out_dir: str | None = None,
         cap: int = _DEFAULT_CAP) -> FlightRecorder:
    """Arm explicitly. Idempotent per process — the first caller wins
    and later callers get the same recorder (in-process multi-rank test
    rigs share one box, exactly like the tracer)."""
    global FLIGHT
    with _init_lock:
        if FLIGHT is not None:
            return FLIGHT
        FLIGHT = FlightRecorder(rank, out_dir or default_dir(), cap=cap)
        atexit.register(_dump_at_exit)
        return FLIGHT


def _parse_spec(spec: str) -> tuple[Optional[str], dict]:
    """``<dir>[:cap=<n>]`` — THE tracer's spec grammar (one parser,
    two knobs); empty dir means the default directory."""
    from minips_tpu.obs.tracer import _parse_spec as _parse

    if not spec:
        return None, {}
    out_dir, kw = _parse(spec, env="MINIPS_FLIGHT")
    return out_dir or None, kw


def maybe_init(rank: int) -> Optional[FlightRecorder]:
    """Arm from ``$MINIPS_FLIGHT`` — which, unlike every other obs
    knob, defaults to ON (empty/unset = default directory): the whole
    point is a post-mortem artifact with zero pre-arming. ``"0"``
    disables (the OBS-TAX off arm)."""
    if FLIGHT is not None:
        return FLIGHT
    spec = os.environ.get("MINIPS_FLIGHT", "").strip()
    if spec == "0":
        return None
    out_dir, kw = _parse_spec(spec)
    return init(rank, out_dir, **kw)


def record(kind: str, args: dict | None = None) -> None:
    """Module-level convenience for call sites that fire rarely (lease
    fences, death plans): one global load + branch when disabled."""
    fl = FLIGHT
    if fl is not None:
        fl.ev(kind, args)


def poison(reason: str, args: dict | None = None) -> None:
    """Record a poison + dump; no-op when disabled, never raises."""
    fl = FLIGHT
    if fl is not None:
        fl.poison(reason, args)


def checkpoint(kind: str, args: dict | None = None) -> None:
    """Record a NON-poison decision and dump the box (autoscaler
    actions: worth a fresh dump so the artifact always carries the
    latest decision, but NOT a failure — it stays out of the reasons
    list and is never flagged on the merged timeline)."""
    fl = FLIGHT
    if fl is not None:
        fl.ev(kind, args)
        fl.dump()


def dump_now() -> Optional[str]:
    fl = FLIGHT
    return fl.dump() if fl is not None else None


def _dump_at_exit() -> None:
    try:
        dump_now()
    except Exception:  # noqa: BLE001 - never fail interpreter teardown
        pass


def reset_for_tests() -> None:
    global FLIGHT
    with _init_lock:
        FLIGHT = None


def sweep_stale_dirs() -> int:
    """Reclaim DEAD runs' default flight directories (tmp hygiene —
    the shm sweepers' contract): a dir whose run-id pid no longer
    exists is unlinked. Numeric run ids only; explicit MINIPS_FLIGHT
    directories are the operator's. Returns dirs removed."""
    import glob
    import shutil

    from minips_tpu.comm.shm_bus import _pid_alive

    removed = 0
    for d in glob.glob(os.path.join(tempfile.gettempdir(),
                                    "minips-flight-*")):
        pid_s = d.rsplit("-", 1)[-1]
        if not pid_s.isdigit():
            continue
        try:
            # the ONE portable liveness contract (shm_bus/_pid_alive,
            # shared with the shm sweepers); a number too big to be a
            # pid at all (a drill's synthetic run id) is dead
            if _pid_alive(int(pid_s)):
                continue
        except OverflowError:
            pass
        try:
            shutil.rmtree(d)
            removed += 1
        except OSError:
            pass
    return removed


# ------------------------------------------------------------ merge CLI
def load_dumps(paths: list[str],
               skipped: Optional[list] = None) -> dict[int, dict]:
    """``{rank: dump doc}`` from files and/or directories (directories
    glob ``flight-rank*.json``).

    A truncated or corrupt dump — a SIGKILL mid-write leaves a partial
    tmp file; disks fill; bit-rot happens — is SKIPPED and reported
    (appended to ``skipped`` as ``(path, reason)``), never raised: the
    merge CLI is the post-mortem tool, and a post-mortem that crashes
    on the one rank that died hardest loses every OTHER rank's box
    with it. The atomic-rename dump discipline makes corruption rare;
    the skip makes it survivable."""
    import glob

    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "flight-rank*.json"))))
        else:
            files.append(p)
    out: dict[int, dict] = {}
    for f in files:
        try:
            with open(f) as fh:
                doc = json.load(fh)
            if not isinstance(doc, dict):
                raise ValueError("dump is not a JSON object")
            rank = int(doc.get("rank", len(out)))
        except (OSError, json.JSONDecodeError, ValueError,
                TypeError) as e:
            if skipped is not None:
                skipped.append((f, f"{type(e).__name__}: {e}"))
            continue
        out[rank] = doc
    return out


def _estimate_offsets_us(dumps: dict[int, dict]
                         ) -> tuple[dict[int, float], list[int]]:
    """Per-rank monotonic-clock offset vs the lowest loaded rank, from
    the dumps' min-filtered heartbeat delay tables — the same NTP
    two-sample estimate as ``obs/merge.estimate_offsets_us``, read from
    the flight boxes instead of trace events."""
    ranks = sorted(dumps)
    if not ranks:
        return {}, []
    ref = ranks[0]
    offsets = {ref: 0.0}
    unaligned: list[int] = []

    def hb(r):
        # a structurally-broken box (hb table not a dict, delays not
        # numeric) merges unaligned at offset 0 — never crashes the
        # merge (the load_dumps skip contract, one layer down)
        t = dumps[r].get("hb_delays_us")
        return t if isinstance(t, dict) else {}

    for r in ranks[1:]:
        try:
            d_r_ref = hb(r).get(str(ref))
            d_ref_r = hb(ref).get(str(r))
            if d_r_ref is None or d_ref_r is None:
                raise ValueError("no bidirectional sample")
            offsets[r] = (float(d_r_ref) - float(d_ref_r)) / 2.0
        except (ValueError, TypeError):
            offsets[r] = 0.0
            unaligned.append(r)
    return offsets, unaligned


def merge_dumps(dumps: dict[int, dict]) -> tuple[dict, dict]:
    """(merged doc, summary): every rank's events + reasons on one
    offset-aligned timeline, sorted by aligned time."""
    offsets, unaligned = _estimate_offsets_us(dumps)
    rows: list[dict] = []
    malformed: list[int] = []
    for rank, doc in sorted(dumps.items()):
        off = offsets.get(rank, 0.0)
        try:
            # a poison lands in the ring AND the append-only reasons
            # list (the ring may rotate it out, the list never drops)
            # — on the merged timeline each appears once, flagged
            seen_reasons = {(e["t_us"], e["kind"])
                            for e in doc.get("reasons", ())}
            rank_rows = []
            for src, mark in (("events", False), ("reasons", True)):
                for e in doc.get(src, ()):
                    if not mark \
                            and (e["t_us"], e["kind"]) in seen_reasons:
                        continue
                    rank_rows.append(
                        {"t_us": round(float(e["t_us"]) - off, 1),
                         "rank": rank, "kind": e["kind"],
                         "args": e.get("args"), "poison": mark})
        except (KeyError, TypeError, ValueError):
            # a structurally-broken (but valid-JSON) box: report the
            # rank, keep every other rank's timeline — the load_dumps
            # skip contract, one layer up
            malformed.append(rank)
            continue
        rows.extend(rank_rows)
    rows.sort(key=lambda e: e["t_us"])

    def reason_kinds(doc):
        # same tolerance as the row loop: a torn-but-parsing box must
        # not crash the SUMMARY either (reproduced in review: a reason
        # entry missing "kind" survived the row loop's catch only to
        # KeyError here, losing every other rank's timeline)
        try:
            return [e["kind"] for e in doc.get("reasons", ())]
        except (KeyError, TypeError):
            return ["<malformed>"]

    def n_events(doc):
        try:
            return len(doc.get("events", ()))
        except TypeError:
            return 0

    # per-tenant SLO burn rollup (obs/slo.py edges): the burn edge is
    # WHY most of these boxes exist, so the summary names the burning
    # tenants instead of leaving the operator to grep the timeline
    slo_burns: dict = {}
    for e in rows:
        if e["kind"] != "slo_burn":
            continue
        a = e.get("args")
        tenant = a.get("tenant") if isinstance(a, dict) else None
        slo_burns[tenant or "?"] = slo_burns.get(tenant or "?", 0) + 1

    summary = {
        "ranks": sorted(dumps),
        "events": sum(n_events(d) for d in dumps.values()),
        "reasons": {r: reason_kinds(d)
                    for r, d in sorted(dumps.items())},
        "clock_offsets_us": {str(r): round(o, 1)
                             for r, o in sorted(offsets.items())},
        "unaligned_ranks": unaligned,
        "malformed_ranks": malformed,
        "slo_burns": slo_burns,
    }
    doc = {"flight": rows, "windows": {str(r): d.get("window")
                                       for r, d in sorted(dumps.items())},
           "summary": summary}
    return doc, summary


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="Merge per-rank flight-recorder dumps into one "
                    "offset-aligned post-mortem timeline")
    ap.add_argument("paths", nargs="+",
                    help="flight dirs and/or flight-rank*.json files")
    ap.add_argument("-o", "--out", default=None,
                    help="write the merged JSON doc here too")
    ap.add_argument("--tail", type=int, default=0, metavar="N",
                    help="print only the last N timeline lines")
    args = ap.parse_args(argv)
    skipped: list = []
    dumps = load_dumps(args.paths, skipped=skipped)
    for path, why in skipped:
        # skip-and-REPORT: the operator must see which rank's box was
        # torn (a SIGKILL mid-write), but the merge of the survivors'
        # boxes must proceed — exit 0 iff >= 1 dump loaded
        print(f"flight: skipped corrupt dump {path}: {why}",
              file=sys.stderr)
    if not dumps:
        print(f"flight: no loadable flight-rank*.json under "
              f"{args.paths!r}", file=sys.stderr)
        return 1
    doc, summary = merge_dumps(dumps)
    summary["skipped_files"] = [p for p, _w in skipped]
    rows = doc["flight"]
    t0 = rows[0]["t_us"] if rows else 0.0
    shown = rows[-args.tail:] if args.tail else rows
    for e in shown:
        args_s = "" if not e["args"] else " " + json.dumps(
            e["args"], sort_keys=True, default=repr)
        mark = " !POISON" if e["poison"] else ""
        print(f"+{(e['t_us'] - t0) / 1e6:10.4f}s  rank{e['rank']}  "
              f"{e['kind']}{mark}{args_s}")
    if summary["slo_burns"]:
        burns = ", ".join(f"{t} x{n}" for t, n in
                          sorted(summary["slo_burns"].items()))
        print(f"flight: SLO burn edges on this timeline: {burns}")
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, args.out)
        summary["merged"] = args.out
    print(json.dumps(summary, sort_keys=True))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
