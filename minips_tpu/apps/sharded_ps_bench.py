"""Sharded-PS throughput worker — measures the multi-process PS itself.

The correctness smokes (tests/test_sharded_ps.py) prove the key-range-
sharded server's semantics; this worker measures its THROUGHPUT: rows/sec
and wire-bytes/sec of the pull→push cycle, per process, with the model
math stripped out so the number isolates routing + serialization + bus +
server-side updater (the reference's Mailbox/ServerThread hot path,
SURVEY.md §3.3 hot spots b+c). Driven by bench_sharded_ps.py across world
sizes and bus backends; one rank standalone (no launcher) measures the
pure in-process server apply as the zero-wire baseline.

Two paths, matching the table's two wire formats:
- ``sparse``: per-iter random key batch → ``pull(keys)`` + ``push(keys,
  grads)`` — per-owner key-slice frames (the W&D/Criteo pattern).
- ``dense``: ``pull_all()`` + ``push_dense(grad)`` — contiguous range
  frames, no key lists (the LR weight-vector pattern).

Consistency is ASP (never gates) so the measurement is the PS data path,
not the staleness rule. Emits ONE JSON line per rank (launcher protocol).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def _arm_mesh_devices(n: int) -> None:
    """CPU runs (``MINIPS_FORCE_CPU`` / ``JAX_PLATFORMS=cpu``) force
    ``n`` host devices BEFORE the first backend touch (the repo's
    established pattern, tests/conftest.py) so the mesh plane's logical
    ranks each map to a device; on a real accelerator host neither knob
    is set and the plane runs on the real device list (MeshPlane raises
    with guidance when there are fewer than ``n``). A no-op when the
    flag is already armed (driver-provided env wins)."""
    if not (os.environ.get("MINIPS_FORCE_CPU")
            or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def _run_mesh_drill() -> int:
    """MESH-BITWISE: the BSP lockstep drill (tests/test_chaos_reliable.
    run_bsp_lockstep) on the zmq wire vs the mesh plane — the bench
    artifact's bitwise stamp. Emits one JSON line; any failure reports
    ``bitwise_equal: false`` so the CI gate fails loudly instead of
    silently skipping the check."""
    out = {"event": "drill", "bitwise_equal": False, "rows_checked": 0}
    try:
        # the canonical harness lives with the transport drills in
        # tests/ (the ISSUE-pinned home every backend's bitwise drill
        # shares); resolve the source checkout from the package path so
        # the drill works from any cwd — a tests-less install reports
        # the ImportError loudly through the stamp below
        import minips_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(minips_tpu.__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tests.test_chaos_reliable import run_bsp_lockstep

        w_wire, lost = run_bsp_lockstep(backend="zmq")
        w_mesh, _ = run_bsp_lockstep(backend="mesh")
        eq = all(np.array_equal(a, b) for a, b in zip(w_wire, w_mesh))
        out.update({
            "bitwise_equal": bool(eq) and lost == [0, 0],
            "rows_checked": int(sum(a.shape[0] for a in w_wire)),
        })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)
    return 0 if out["bitwise_equal"] else 1


def _run_fail_slow_idle_drill() -> int:
    """SLOW-IDLE: the BSP lockstep drill with the fail-slow hedge
    plane ARMED on a clean wire vs off — armed-but-idle must be
    BITWISE equal (no slow link → the min_ms floor keeps every leg
    unhedged → the armed bookkeeping perturbs nothing). Emits one JSON
    line; failures report ``bitwise_equal: false`` so the CI gate
    fails loudly instead of silently skipping."""
    out = {"event": "drill", "bitwise_equal": False, "rows_checked": 0,
           "hedges_fired": None}
    try:
        import minips_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(minips_tpu.__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tests.test_chaos_reliable import run_bsp_lockstep

        w_off, lost_off = run_bsp_lockstep(backend="zmq")
        st: dict = {}
        w_on, lost_on = run_bsp_lockstep(backend="zmq", hedge="1",
                                         stats=st)
        eq = all(np.array_equal(a, b) for a, b in zip(w_off, w_on))
        out.update({
            "bitwise_equal": bool(eq) and lost_off == lost_on == [0, 0],
            "rows_checked": int(sum(a.shape[0] for a in w_off)),
            # armed-IDLE means zero hedges actually fired — stamp the
            # evidence, not just the bitwise verdict
            "hedges_fired": st.get("hedges_fired"),
        })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)
    return 0 if out["bitwise_equal"] else 1


def _run_tenant_idle_drill() -> int:
    """TENANT-IDLE: the BSP lockstep drill with the bare default
    tenant ARMED (``MINIPS_TENANT=1``) vs off — armed-but-idle must be
    BITWISE equal (the ``tb`` config stamp is the only armed cost;
    no override ⇒ no behavior change) with the stamp provably engaged
    (nonzero tenant ids) and zero attributed tenant counters. Emits
    one JSON stamp line; failures report ``bitwise_equal: false`` so
    the CI gate fails loudly instead of silently skipping."""
    out = {"event": "drill", "bitwise_equal": False, "rows_checked": 0,
           "tenant_tids": None, "tenant_counters": None}
    try:
        import minips_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(minips_tpu.__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tests.test_chaos_reliable import run_bsp_lockstep

        w_off, lost_off = run_bsp_lockstep(backend="zmq")
        st: dict = {}
        w_on, lost_on = run_bsp_lockstep(backend="zmq", tenant="1",
                                         stats=st)
        eq = all(np.array_equal(a, b) for a, b in zip(w_off, w_on))
        out.update({
            "bitwise_equal": bool(eq) and lost_off == lost_on == [0, 0]
            and st.get("tenant_tids") == [1, 1]
            and st.get("tenant_counters") == 0,
            "rows_checked": int(sum(a.shape[0] for a in w_off)),
            # evidence the armed arm really armed (tids engaged) and
            # really idled (zero attributed counters) — the gate
            # checks the stamps, not just the verdict
            "tenant_tids": st.get("tenant_tids"),
            "tenant_counters": st.get("tenant_counters"),
        })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)
    return 0 if out["bitwise_equal"] else 1


def _run_traffic_idle_drill() -> int:
    """TRAFFIC-IDLE: the BSP lockstep drill with the open-loop traffic
    driver ARMED at rate=0 vs off — armed-but-idle must be BITWISE
    equal (an empty schedule issues nothing; the dispatcher threads
    start, find no arrivals, and exit) with the stamp provably engaged
    (driver constructed and started) and zero issued requests. Emits
    one JSON stamp line; failures report ``bitwise_equal: false`` so
    the CI gate fails loudly instead of silently skipping."""
    out = {"event": "drill", "bitwise_equal": False, "rows_checked": 0,
           "traffic_requests": None, "traffic_scheduled": None}
    try:
        import minips_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(minips_tpu.__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tests.test_chaos_reliable import run_bsp_lockstep

        w_off, lost_off = run_bsp_lockstep(backend="zmq")
        st: dict = {}
        w_on, lost_on = run_bsp_lockstep(
            backend="zmq", traffic="rate=0,users=1000000", stats=st)
        eq = all(np.array_equal(a, b) for a, b in zip(w_off, w_on))
        out.update({
            "bitwise_equal": bool(eq) and lost_off == lost_on == [0, 0]
            and st.get("traffic_requests") == 0
            and st.get("traffic_scheduled") == 0,
            "rows_checked": int(sum(a.shape[0] for a in w_off)),
            # evidence the armed arm really armed (the driver ran) and
            # really idled (zero scheduled arrivals, zero issued) —
            # the gate checks the stamps, not just the verdict
            "traffic_requests": st.get("traffic_requests"),
            "traffic_scheduled": st.get("traffic_scheduled"),
        })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)
    return 0 if out["bitwise_equal"] else 1


def _run_reshard_mem_drill() -> int:
    """RESHARD-MEM: the streaming N->M checkpoint reshard (mover (c),
    ckpt/elastic.reshard_table_state) at a RAM-visible table size —
    the capped read must assemble BITWISE the same new shard as the
    uncapped read while its MEASURED peak transient staging stays
    under the cap, and the legacy whole-member read (what restore did
    before the planner: np.load materialises every leaf of every
    touched old shard at once) must provably EXCEED that cap at the
    same size. 2 old shards of ~12 MiB state each, cap 1 MiB, new
    world 3 ranks — the drilled shard is the middle one, straddling
    both sources. Emits one JSON stamp line; any failure reports
    ``bitwise_equal: false`` so the CI gate fails loudly instead of
    silently skipping."""
    import tempfile

    out = {"event": "drill", "bitwise_equal": False, "cap": 0,
           "peak_planned": None, "peak_p2p": None, "chunks": 0}
    try:
        from minips_tpu.ckpt.elastic import (NpzSliceReader,
                                             _shard_path,
                                             reshard_table_state)

        rows, dim, old_n, new_n = 12288, 256, 2, 3
        cap = 1 << 20                    # 1 MiB staging budget
        rng = np.random.default_rng(20260807)
        with tempfile.TemporaryDirectory() as ck:
            old_sz = -(-rows // old_n)
            for r in range(old_n):
                path = _shard_path(ck, 1, r, "t")
                os.makedirs(os.path.dirname(path))
                np.savez(path,
                         w=rng.standard_normal(
                             (old_sz, dim)).astype(np.float32),
                         acc=rng.standard_normal(
                             (old_sz, dim)).astype(np.float32),
                         lo=np.asarray(r * old_sz))
            new_sz = -(-rows // new_n)
            lo = new_sz                  # shard 1 of 3: both sources
            full = reshard_table_state(ck, 1, old_n, "t", rows,
                                       lo, new_sz)
            st: dict = {}
            capped = reshard_table_state(ck, 1, old_n, "t", rows,
                                         lo, new_sz, cap_bytes=cap,
                                         stats=st)
            eq = set(full) == set(capped) and all(
                np.array_equal(full[k], capped[k]) for k in full)
            # the legacy baseline, MEASURED not modelled: whole-member
            # staging materialises every row-aligned leaf of an old
            # shard at once — its peak is one shard's full state bytes
            peak_p2p = 0
            for r in range(old_n):
                with NpzSliceReader(_shard_path(ck, 1, r, "t")) as rd:
                    peak_p2p = max(peak_p2p, sum(
                        int(rd.read(k).nbytes) for k in rd.keys()
                        if k != "lo"))
            out.update({
                "bitwise_equal": bool(eq),
                "cap": int(cap),
                "peak_planned": int(st.get("peak_stage_bytes", 0)),
                "peak_p2p": int(peak_p2p),
                "chunks": int(st.get("chunks", 0)),
                "rows": rows, "dim": dim,
                "old_n": old_n, "new_n": new_n,
            })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    ok = (out["bitwise_equal"]
          and out["peak_planned"] is not None
          and 0 < out["peak_planned"] <= out["cap"]
          and out["peak_p2p"] is not None
          and out["peak_p2p"] > out["cap"])
    print(json.dumps(out), flush=True)
    return 0 if ok else 1


def _run_hier_drill(hier_spec: str) -> int:
    """HIER-IDLE / HIER-WIN bitwise leg: the 3-rank hier lockstep drill
    (tests/test_hier.run_hier_lockstep — host groups {0,1} | {2},
    disjoint keysets, exact f32 wire) with ``hier_spec`` armed vs off.
    Armed-idle (``"1"``) and the full tree (``"group=2"``) must BOTH be
    bitwise equal to off: the tree re-lanes identical exact
    contributions, it never changes the math. Emits one JSON stamp
    line; failures report ``bitwise_equal: false`` so the CI gate fails
    loudly instead of silently skipping."""
    out = {"event": "drill", "hier_spec": hier_spec,
           "bitwise_equal": False, "rows_checked": 0,
           "agg_frames": None, "l2_frames": None,
           "mesh_reduces": None, "mesh_agg_fallbacks": None,
           "domain_demotions": None}
    try:
        import minips_tpu

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(minips_tpu.__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from tests.test_hier import run_hier_lockstep

        w_off, lost_off = run_hier_lockstep("")
        st: dict = {}
        w_on, lost_on = run_hier_lockstep(hier_spec, stats=st)
        eq = all(np.array_equal(a, b) for a, b in zip(w_off, w_on))
        out.update({
            "bitwise_equal": bool(eq)
            and lost_off == lost_on == [0, 0, 0],
            "rows_checked": int(sum(a.shape[0] for a in w_off)),
            # evidence the armed lane really ran (or really idled):
            # the gate checks the counters, not just the verdict
            "agg_frames": st.get("agg_frames"),
            "l2_frames": st.get("l2_frames"),
            # the hybrid (agg=mesh) drills add the backend's counters:
            # the degenerate drill must show reduces with ZERO
            # fallbacks/demotions, the idle drill all-zero
            "mesh_reduces": st.get("mesh_reduces"),
            "mesh_agg_fallbacks": st.get("mesh_agg_fallbacks"),
            "domain_demotions": st.get("domain_demotions"),
        })
    except Exception as e:  # noqa: BLE001 - the gate reads the stamp
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)
    return 0 if out["bitwise_equal"] else 1


def _run_tenant_bench(args) -> int:
    """TENANT-ISO bench mode: TWO tables = two tenants in ONE job —
    ``trn`` (every rank runs the sparse pull→push training cycle at
    the ``--trn-step-ms`` deadline pace; its pace-kept rows/sec is
    THE protected number) and ``inf`` (per-rank
    storm reader threads free-run ``pull_serving`` with the shared
    zipf hot set — the noisy neighbor). The tenant spec decides the
    arm: per-tenant buckets (``trn:rate=0;inf:rate=...``) must keep
    trn's throughput within the solo arm's bound while inf sheds into
    its own budget; ``shared=1`` is the coupling contrast arm; storm
    off (``--storm 0``) is the solo arm. One done line carries trn's
    rate, inf's read rate, and the full wire_record (the ``tenant``
    block is the gate's attribution evidence)."""
    import threading

    from minips_tpu.apps.common import init_multiproc, table_wire_kwargs
    from minips_tpu.data.synthetic import make_zipf_sampler
    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)
    from minips_tpu.utils.metrics import wire_record

    rank, nprocs, bus, monitor, _ = init_multiproc("asp", 0)
    if nprocs < 2:
        print(json.dumps({"rank": 0, "event": "error",
                          "err": "--tenant-bench needs the launcher "
                                 "(n >= 2): the serve plane needs "
                                 "peers"}), flush=True)
        return 2

    def mk(name: str) -> ShardedTable:
        return ShardedTable(name, args.rows, args.dim, bus, rank,
                            nprocs, updater=args.updater, lr=0.05,
                            pull_timeout=args.pull_timeout,
                            monitor=monitor, **table_wire_kwargs(args))

    tables = {"trn": mk("trn"), "inf": mk("inf")}
    trainer = ShardedPSTrainer(tables, bus, nprocs,
                               staleness=args.staleness,
                               gate_timeout=60.0, monitor=monitor,
                               serve=args.serve, tenant=args.tenant)
    bus.handshake(nprocs)

    rng = np.random.default_rng(rank)
    B, dim = args.batch, args.dim
    grads = rng.normal(size=(B, dim)).astype(np.float32)
    # the inf tenant's readers hammer the SAME hot rows on every rank
    # (spread_seed shared — real serving skew); trn trains uniform so
    # the protected tenant's traffic is not itself promotable-hot
    zipf_sample = make_zipf_sampler(args.rows, args.zipf_alpha,
                                    spread_seed=7,
                                    permute_hot=args.zipf_permute_hot)
    storm_stop = threading.Event()
    storm_errs: list = []
    storm_counts = [0] * max(args.storm, 1)
    storm_threads: list = []

    def _inf_reader(j: int) -> None:
        rrng = np.random.default_rng((rank, j, 1717))
        SB = args.storm_batch
        think = args.storm_think_ms / 1e3
        inf = tables["inf"]
        while not storm_stop.is_set():
            if think > 0:
                time.sleep(think)
            keys = zipf_sample(rrng, SB)
            try:
                inf.pull_serving(keys)
            except Exception as e:  # noqa: BLE001 - surfaced below
                if not storm_stop.is_set():
                    storm_errs.append(repr(e))
                return
            storm_counts[j] += SB

    for j in range(args.storm):
        th = threading.Thread(target=_inf_reader, args=(j,),
                              daemon=True, name=f"inf-reader-{j}")
        storm_threads.append(th)
        th.start()

    trn = tables["trn"]
    trn_rows = 0
    read0 = 0
    t0 = 0.0
    # deadline pacing: a real trainer has a step time (compute), so
    # the protected number is PACE-KEPT throughput — each step sleeps
    # to its deadline and an overrunning step slips it (never banks
    # debt), so missed deadlines surface as rows/sec below the paced
    # rate. Flat-out (pace=0) measures leftover CPU on the shared
    # box, which no admission split can protect; pace-kept rows/sec
    # is the SLO tenancy actually promises.
    pace = args.trn_step_ms / 1e3
    next_t = time.perf_counter()
    for i in range(args.iters):
        if i == args.warmup:
            trn_rows = 0
            read0 = sum(storm_counts)
            t0 = time.perf_counter()
            next_t = t0
        keys = rng.integers(0, args.rows, size=B)
        trn.pull(keys)
        trn.push(keys, grads)
        trn_rows += 2 * B
        trainer.tick()
        if pace > 0:
            next_t += pace
            slack = next_t - time.perf_counter()
            if slack > 0:
                time.sleep(slack)
            else:
                next_t = time.perf_counter()
    dt = time.perf_counter() - t0
    read_rows = sum(storm_counts) - read0
    storm_stop.set()
    for th in storm_threads:
        th.join(timeout=30.0)
    assert not any(th.is_alive() for th in storm_threads), \
        "inf reader wedged"
    assert not storm_errs, storm_errs
    trainer.finalize(timeout=60.0)
    assert trainer.frames_dropped == 0, trainer.drop_detail()
    trainer.shutdown_barrier(timeout=15.0)

    timed = args.iters - args.warmup
    print(json.dumps({
        "rank": rank, "event": "done", "mode": "tenant_bench",
        "nprocs": nprocs,
        "tenant_spec": (args.tenant
                        or os.environ.get("MINIPS_TENANT") or None),
        "serve_spec": (args.serve or os.environ.get("MINIPS_SERVE")
                       or None),
        "storm_readers": args.storm or None,
        "storm_batch": args.storm_batch if args.storm else None,
        "trn_step_ms": args.trn_step_ms or None,
        "read_rows": int(read_rows),
        "read_rows_per_sec": round(read_rows / dt, 1),
        "staleness": (None if args.staleness == float("inf")
                      else int(args.staleness)),
        "reliable_on": os.environ.get("MINIPS_RELIABLE", "")
        not in ("", "0"),
        **wire_record(trainer),
        "rows": args.rows, "dim": args.dim, "batch": B,
        "iters_timed": timed,
        # the protected number: the training tenant's pull+push rows
        "trn_rows_per_sec": round(trn_rows / dt, 1),
        "wall_s": round(dt, 4),
    }), flush=True)
    if monitor is not None:
        monitor.stop()
    bus.close()
    return 0


def _run_traffic_bench(args) -> int:
    """MINIPS_TRAFFIC bench mode (million_user_3proc): the open-loop
    driver (apps/traffic_driver.py) replays a precomputed zipf-user
    arrival schedule against the ``inf`` table's ``pull_serving``
    while every rank trains the ``trn`` table at the ``--trn-step-ms``
    deadline pace — serving load that arrives whether or not the fleet
    keeps up, measured from SCHEDULED arrival (coordinated-omission-
    free), with training running concurrently the whole time. The
    ``--traffic`` spec decides the arm (flat base, diurnal ramp, flash
    crowd); ``--slo`` arms burn-rate accounting so a crowd provably
    flexes the replica budget and an overload provably sheds into the
    tenant's own budget with a flight-recorder ``slo_burn`` box. One
    done line carries the driver's record (sched_ms is the honest
    number), trn's pace-kept rate, and the full wire_record (the
    ``freshness``/``slo`` blocks are the gate's evidence)."""
    from minips_tpu.apps.common import init_multiproc, table_wire_kwargs
    from minips_tpu.apps.traffic_driver import TrafficDriver
    from minips_tpu.apps.traffic_driver import maybe_config as _traffic
    from minips_tpu.train.sharded_ps import (ShardedPSTrainer,
                                             ShardedTable)
    from minips_tpu.utils.metrics import wire_record

    rank, nprocs, bus, monitor, _ = init_multiproc("asp", 0)
    if nprocs < 2:
        print(json.dumps({"rank": 0, "event": "error",
                          "err": "--traffic-bench needs the launcher "
                                 "(n >= 2): the serve plane needs "
                                 "peers"}), flush=True)
        return 2
    tcfg = _traffic(args.traffic)
    if tcfg is None:
        print(json.dumps({"rank": rank, "event": "error",
                          "err": "--traffic-bench needs an armed "
                                 "--traffic/MINIPS_TRAFFIC spec"}),
              flush=True)
        return 2

    def mk(name: str) -> ShardedTable:
        return ShardedTable(name, args.rows, args.dim, bus, rank,
                            nprocs, updater=args.updater, lr=0.05,
                            pull_timeout=args.pull_timeout,
                            monitor=monitor, **table_wire_kwargs(args))

    tables = {"trn": mk("trn"), "inf": mk("inf")}
    trainer = ShardedPSTrainer(tables, bus, nprocs,
                               staleness=args.staleness,
                               gate_timeout=60.0, monitor=monitor,
                               serve=args.serve, tenant=args.tenant,
                               slo=args.slo)
    bus.handshake(nprocs)

    rng = np.random.default_rng(rank)
    B, dim = args.batch, args.dim
    grads = rng.normal(size=(B, dim)).astype(np.float32)
    # deadline pacing defines the run's wall clock, so the driver's
    # schedule horizon is exactly the timed window — the crowd lands
    # at a knowable second of the measurement, not of the warmup
    pace = args.trn_step_ms / 1e3
    timed = args.iters - args.warmup
    duration = timed * pace
    driver = TrafficDriver(tcfg, tables["inf"].pull_serving,
                           args.rows, duration_s=duration)
    # trn trains a steady write load into the INF table too (small
    # batches) so the serving reads have fresh pushes to be stale
    # AGAINST — freshness lag is only measurable on a written table
    inf = tables["inf"]
    inf_keys = rng.integers(0, args.rows, size=max(B // 4, 1))
    inf_grads = rng.normal(size=(len(inf_keys), dim)
                           ).astype(np.float32)

    trn = tables["trn"]
    trn_rows = 0
    t0 = 0.0
    next_t = time.perf_counter()
    for i in range(args.iters):
        if i == args.warmup:
            trn_rows = 0
            t0 = time.perf_counter()
            next_t = t0
            driver.start()  # schedule t=0 is the warmup boundary
        keys = rng.integers(0, args.rows, size=B)
        trn.pull(keys)
        trn.push(keys, grads)
        inf.push(inf_keys, inf_grads)  # the freshness write stream
        trn_rows += 2 * B
        trainer.tick()
        if pace > 0:
            next_t += pace
            slack = next_t - time.perf_counter()
            if slack > 0:
                time.sleep(slack)
            else:
                next_t = time.perf_counter()
    dt = time.perf_counter() - t0
    # stop the driver BEFORE finalize (post-finalize agreement is
    # exact; a still-running dispatcher would race the quiesce)
    driver.stop()
    trainer.finalize(timeout=60.0)
    assert trainer.frames_dropped == 0, trainer.drop_detail()
    trainer.shutdown_barrier(timeout=15.0)

    print(json.dumps({
        "rank": rank, "event": "done", "mode": "traffic_bench",
        "nprocs": nprocs,
        "traffic_spec": (args.traffic
                         or os.environ.get("MINIPS_TRAFFIC") or None),
        "slo_spec": (args.slo or os.environ.get("MINIPS_SLO") or None),
        "tenant_spec": (args.tenant
                        or os.environ.get("MINIPS_TENANT") or None),
        "serve_spec": (args.serve or os.environ.get("MINIPS_SERVE")
                       or None),
        "trn_step_ms": args.trn_step_ms,
        # the driver's full open-loop record: scheduled/issued/late
        # counts, sched_ms (scheduled-arrival -> done — the honest
        # tail) next to svc_ms (issue -> done)
        "traffic": driver.record(),
        "staleness": (None if args.staleness == float("inf")
                      else int(args.staleness)),
        "reliable_on": os.environ.get("MINIPS_RELIABLE", "")
        not in ("", "0"),
        **wire_record(trainer),
        "rows": args.rows, "dim": args.dim, "batch": B,
        "iters_timed": timed,
        # the protected number: the training tenant's pace-kept rows
        "trn_rows_per_sec": round(trn_rows / dt, 1),
        "wall_s": round(dt, 4),
    }), flush=True)
    if monitor is not None:
        monitor.stop()
    bus.close()
    return 0


def _run_mesh(args) -> int:
    """The in-mesh collective data plane bench: one process, ``--mesh-
    ranks`` logical ranks as threads over as many devices, pushes/pulls
    riding reduce-scatter/all-gather (train/mesh_plane.py) instead of
    the host wire. Emits ONE JSON line shaped like a done line."""
    import threading

    import jax

    from minips_tpu.train.mesh_plane import MeshPlane

    n = args.mesh_ranks
    plane = MeshPlane(n, staleness=args.staleness, comm=args.mesh_comm,
                      deposit=args.mesh_deposit)
    table = plane.add_table("b", args.rows, args.dim,
                            updater=args.updater, lr=0.05)
    B, dim = args.batch, args.dim
    rates = [0.0] * n
    rows_counts = [0] * n
    cb_at_warmup = [0] * n  # collective-bytes snapshot at each rank's
    # warmup boundary: the B/row metric must cover the same timed
    # window as the wire arms' byte counters (which snapshot
    # bytes_pushed/pulled at warmup), not the compile-warmup waves
    errs: list = []

    def worker(r: int) -> None:
        try:
            rng = np.random.default_rng(r)
            grads = rng.normal(size=(B, dim)).astype(np.float32)
            dense_grad = rng.normal(size=(args.rows, dim)
                                    ).astype(np.float32)
            h = plane.rank(r)
            t = h.tables["b"]
            moved = 0
            t0 = time.perf_counter()
            for i in range(args.iters):
                if i == args.warmup:
                    moved = 0
                    cb_at_warmup[r] = table.collective_bytes
                    t0 = time.perf_counter()
                if args.path == "sparse":
                    keys = rng.integers(0, args.rows, size=B)
                    t.pull(keys)
                    t.push(keys, grads)
                    moved += 2 * B
                else:
                    t.pull_all()
                    t.push_dense(dense_grad)
                    moved += 2 * args.rows
                h.tick()
            h.finalize(timeout=60.0)
            dt = time.perf_counter() - t0
            rates[r] = moved / dt
            rows_counts[r] = moved
        except Exception as e:  # noqa: BLE001 - surfaced below
            errs.append((r, repr(e)))

    ths = [threading.Thread(target=worker, args=(r,), name=f"mesh-{r}")
           for r in range(n)]
    t_all0 = time.perf_counter()
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=600.0)
    wall = time.perf_counter() - t_all0
    if any(th.is_alive() for th in ths) or errs:
        print(json.dumps({"event": "error", "plane": "mesh",
                          "errs": [repr(e)[:300] for e in errs]
                          or "wedged"}), flush=True)
        return 2
    stats = plane.stats()
    # timed-window collective bytes: everything after the LAST rank's
    # warmup boundary (ranks run near-lockstep under the BSP gate, so
    # the max snapshot is the tightest shared boundary)
    cb_timed = stats["collective_bytes"] - max(cb_at_warmup)
    print(json.dumps({
        "event": "done", "plane": "mesh",
        "mesh_ranks": n, "mesh_comm": args.mesh_comm,
        "device_count": len(jax.devices()),
        "jax_backend": jax.default_backend(),
        "path": args.path, "updater": args.updater,
        "staleness": (None if plane.staleness == float("inf")
                      else int(plane.staleness)),
        "rows": args.rows, "dim": args.dim, "batch": B,
        "iters_timed": args.iters - args.warmup,
        "rows_per_sec_ranks": [round(x, 1) for x in rates],
        "rows_per_sec": round(sum(rates) / n, 1),
        "aggregate_rows_per_sec": round(sum(rates), 1),
        "waves": stats["waves"]["b"],
        "gate_waits": stats["gate_waits"],
        # deposit-stage accounting (the mesh_sparse arm's evidence):
        # dense = fixed pre-stacked [rows, dim] buffers, sparse = COO
        # staging + segment-sum densify on device — peak host bytes is
        # the number the arm's >=4x reduction gate reads
        "deposit": stats["deposit"],
        "peak_deposit_bytes": stats["peak_deposit_bytes"]["b"],
        "sparse_waves": stats["sparse_waves"],
        "collective_bytes": stats["collective_bytes"],
        "collective_bytes_per_row_moved": round(
            cb_timed / max(sum(rows_counts), 1), 3),
        "wall_s": round(wall, 4),
    }), flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--path", choices=["sparse", "dense"], default="sparse")
    ap.add_argument("--rows", type=int, default=1 << 16)
    ap.add_argument("--dim", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4096,
                    help="keys per pull/push cycle (sparse path)")
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--warmup", type=int, default=10)
    ap.add_argument("--updater", choices=["sgd", "adagrad", "adam"],
                    default="adagrad")
    ap.add_argument("--key-dist", choices=["uniform", "zipf"],
                    default="uniform",
                    help="sparse-path key distribution: uniform, or "
                         "seeded zipf(--zipf-alpha) with hot ranks "
                         "spread across shards "
                         "(data/synthetic.make_zipf_sampler) — the "
                         "workload where the client row cache and the "
                         "deduplicated pull wire earn their keep")
    ap.add_argument("--zipf-alpha", type=float, default=1.1)
    ap.add_argument("--no-zipf-permute-hot", dest="zipf_permute_hot",
                    action="store_false", default=True,
                    help="draw zipf keys WITHOUT the hot-rank "
                         "permutation: the whole head lands in shard "
                         "0's range — the static-partition pathology "
                         "the heat-aware rebalancer (MINIPS_REBALANCE) "
                         "exists to fix; the rebalance_3proc sweep's "
                         "arms run this")
    ap.add_argument("--staleness", type=float, default=float("inf"),
                    help="consistency bound for the run: inf = ASP "
                         "(the default; measures the bare data path), "
                         "finite s = SSP(s) — the cache_comparison "
                         "sweep runs s in {0,1,2} because the cache's "
                         "validity window IS the staleness budget")
    ap.add_argument("--pull-timeout", dest="pull_timeout", type=float,
                    default=60.0,
                    help="table pull/ack deadline — the chaos sweep "
                         "shortens it so the retransmit-off arms die "
                         "in seconds instead of the default minute "
                         "(the poison path is the measurement there)")
    ap.add_argument("--compute", choices=["none", "jit"], default="none",
                    help="jit: between pull and push, run a REAL jitted "
                         "model-grad step on the pulled rows (rank 0 on "
                         "the default backend — the chip when alive — "
                         "peers on CPU). This measures the north-star "
                         "topology: PS wire + accelerator worker compute "
                         "overlapped, not the bare control plane")
    from minips_tpu.apps.common import add_wire_flags

    add_wire_flags(ap)
    ap.add_argument("--hidden", type=int, default=256,
                    help="--compute jit: MLP hidden width over the "
                         "pulled rows (the MXU work per cycle)")
    ap.add_argument("--storm", type=int, default=0, metavar="N",
                    help="PULL-STORM mode: N read-only client threads "
                         "per process hammer pull() while only the "
                         "first --storm-pushers ranks push — the PS "
                         "measured as a SERVICE (read fan-out) instead "
                         "of a training gang. Requires --path sparse "
                         "and a launcher run (nprocs > 1); the done "
                         "line grows read_rows_per_sec")
    ap.add_argument("--storm-pushers", type=int, default=1,
                    help="storm mode: ranks below this push every "
                         "iteration (the 'few pushers'); every rank "
                         "still ticks so clocks advance fleet-wide")
    ap.add_argument("--storm-batch", type=int, default=16,
                    help="storm mode: keys per READ request — the "
                         "serving request shape (a user lookup reads a "
                         "handful of embedding rows, not a training "
                         "batch). Small requests are what replica "
                         "fan-out converts: a request whose keys are "
                         "all held locally (own shard + replica "
                         "snapshots) completes with ZERO wire legs")
    ap.add_argument("--storm-think-ms", type=float, default=1.0,
                    help="storm mode: per-request client think time — "
                         "serving clients are open-loop (a user isn't "
                         "a spin loop), and on an oversubscribed host "
                         "a zero-think closed loop burns the CPU the "
                         "serve path needs, drowning the latency tail "
                         "in scheduler noise for both arms")
    ap.add_argument("--storm-step-s", type=float, default=0.02,
                    help="storm mode: main-loop pacing per iteration — "
                         "the pusher cadence; readers free-run")
    ap.add_argument("--trn-step-ms", type=float, default=0.0,
                    help="tenant bench: the training tenant's step "
                         "deadline — each pull+push+tick sleeps to "
                         "this pace and an overrun slips the deadline "
                         "(never banks debt), so trn_rows_per_sec is "
                         "PACE-KEPT throughput: the SLO number "
                         "admission isolation can actually protect. "
                         "0 = flat out (measures leftover CPU on a "
                         "shared box, noisy-neighbor-sensitive by "
                         "construction)")
    ap.add_argument("--serve", default=None, metavar="SPEC",
                    help="arm the read-mostly serving plane "
                         "(minips_tpu/serve/) with this MINIPS_SERVE "
                         "spec — the flag spelling of the env knob; "
                         "hot-block replicas, admission control, SLO "
                         "gate (docs/serving.md)")
    ap.add_argument("--plane", choices=["wire", "mesh"], default=None,
                    help="data plane: 'wire' (the multi-process host "
                         "bus, default) or 'mesh' — the in-mesh "
                         "collective plane (train/mesh_plane.py): one "
                         "process, --mesh-ranks logical ranks over as "
                         "many devices, push/pull as reduce-scatter/"
                         "all-gather. Env spelling: MINIPS_MESH=1 "
                         "(explicit flag wins)")
    ap.add_argument("--mesh-ranks", type=int, default=3,
                    help="mesh plane: logical ranks = mesh devices "
                         "(CPU runs force that many host devices)")
    ap.add_argument("--mesh-comm", choices=["float32", "blk8"],
                    default="float32",
                    help="mesh plane collective tier: f32 reduce-"
                         "scatter, or blk8 — blockwise absmax int8 "
                         "codes inside the collective (EQuARX-style; "
                         "the PR9 host-wire codec, second transport)")
    ap.add_argument("--mesh-deposit", choices=["dense", "sparse"],
                    default=None,
                    help="mesh plane deposit-buffer shape: 'dense' "
                         "pre-stacked [rows, dim] host buffers (the "
                         "PR11 layout), or 'sparse' — COO staging + "
                         "on-device segment-sum densify, trading a "
                         "per-wave gather for peak host memory that "
                         "scales with TOUCHED rows instead of the "
                         "table (the embedding-shaped regime). Env "
                         "spelling: MINIPS_MESH_SPARSE=1 (explicit "
                         "flag wins); default dense")
    ap.add_argument("--mesh-bitwise-drill", action="store_true",
                    help="run the BSP zmq-vs-mesh bitwise lockstep "
                         "drill and emit its stamp instead of a bench "
                         "(the artifact's MESH-BITWISE input)")
    ap.add_argument("--fail-slow-idle-drill", action="store_true",
                    help="run the BSP lockstep drill hedge-armed vs "
                         "off on a clean wire and emit its bitwise "
                         "stamp (the artifact's SLOW-IDLE input: "
                         "armed-but-idle must equal off bit-for-bit)")
    ap.add_argument("--reshard-mem-drill", action="store_true",
                    help="run the streaming N->M checkpoint reshard "
                         "drill at a RAM-visible table size and emit "
                         "its stamp (the artifact's RESHARD-MEM "
                         "input: capped read bitwise-equal to the "
                         "uncapped read with measured peak staging "
                         "<= cap, legacy whole-member staging > cap)")
    ap.add_argument("--hier-idle-drill", action="store_true",
                    help="run the 3-rank hier lockstep drill armed-"
                         "idle (MINIPS_HIER=1, group=1 — no pair in "
                         "hier mode) vs off and emit its bitwise "
                         "stamp (the artifact's HIER-IDLE input)")
    ap.add_argument("--hier-bitwise-drill", action="store_true",
                    help="run the 3-rank hier lockstep drill with the "
                         "full tree (group=2, compression off) vs off "
                         "and emit its bitwise stamp (HIER-WIN's "
                         "exactness leg: aggregation re-lanes exact "
                         "contributions, bitwise equal by "
                         "construction)")
    ap.add_argument("--hybrid-idle-drill", action="store_true",
                    help="run the 3-rank hier lockstep drill with the "
                         "hybrid plane armed-idle (group=1,agg=mesh — "
                         "every group a singleton, no flush ever runs) "
                         "vs off and emit its bitwise stamp (the "
                         "artifact's HYBRID-IDLE input: armed "
                         "bookkeeping must perturb nothing)")
    ap.add_argument("--hybrid-degenerate-drill", action="store_true",
                    help="run the 3-rank hier lockstep drill with the "
                         "hybrid plane on a ONE-device mesh "
                         "(group=2,agg=mesh + MINIPS_HIER_MESH_DEVS=1) "
                         "vs off and emit its bitwise stamp: the "
                         "degenerate tier runs THE shared f64 dedup "
                         "kernel in deposit order, so off == agg=host "
                         "== one-device mesh bit-for-bit")
    ap.add_argument("--tenant", default=None, metavar="SPEC",
                    help="arm multi-tenant tables on this worker's "
                         "trainer (MINIPS_TENANT grammar, "
                         "tenant/registry.py) — the flag spelling; "
                         "the env works too (flag wins)")
    ap.add_argument("--tenant-bench", action="store_true",
                    help="two-tenant isolation mode: a 'trn' table "
                         "trains flat out (pull+push, the protected "
                         "trn_rows_per_sec) while --storm reader "
                         "threads free-run pull_serving against an "
                         "'inf' table on the shared zipf hot set; "
                         "--tenant decides the arm (per-tenant "
                         "buckets vs shared=1 vs storm-off solo). "
                         "The multi_tenant_3proc sweep's worker")
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="arm SLO burn-rate accounting (MINIPS_SLO "
                         "grammar, obs/slo.py) on this worker's "
                         "trainer — the flag spelling; the env works "
                         "too (flag wins). Burning tenants flex the "
                         "serve plane's promotion budget and feed the "
                         "autoscaler's arming pressure")
    ap.add_argument("--traffic", default=None, metavar="SPEC",
                    help="open-loop traffic spec (MINIPS_TRAFFIC "
                         "grammar, apps/traffic_driver.py) for "
                         "--traffic-bench — zipf user population, "
                         "base rate, diurnal ramp, flash crowd; the "
                         "env spelling works too (flag wins)")
    ap.add_argument("--traffic-bench", action="store_true",
                    help="open-loop serving mode: the traffic driver "
                         "replays a precomputed arrival schedule "
                         "against an 'inf' table's pull_serving "
                         "(latency measured from SCHEDULED arrival — "
                         "coordinated-omission-free) while a 'trn' "
                         "table trains at the --trn-step-ms pace; "
                         "--traffic decides the arm (flat / ramp / "
                         "flash crowd), --slo arms burn accounting. "
                         "The million_user_3proc sweep's worker")
    ap.add_argument("--traffic-idle-drill", action="store_true",
                    help="run the BSP lockstep drill with the traffic "
                         "driver armed at rate=0 vs off and emit its "
                         "bitwise stamp + scheduled/issued evidence "
                         "(the artifact's TRAFFIC-IDLE input)")
    ap.add_argument("--tenant-idle-drill", action="store_true",
                    help="run the BSP lockstep drill with the bare "
                         "default tenant (MINIPS_TENANT=1) vs off "
                         "and emit its bitwise stamp + tenant-id/"
                         "counter evidence (the artifact's "
                         "TENANT-IDLE input)")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="write this rank's wire trace (Chrome-trace "
                         "JSON, obs/tracer.py) into DIR — the flag "
                         "spelling of MINIPS_TRACE; the bench driver's "
                         "trace arm uses it to drop per-rank traces "
                         "into the sweep artifact dir for "
                         "minips_tpu.obs.merge")
    args = ap.parse_args(argv)
    from minips_tpu.train.mesh_plane import resolve_plane

    plane_kind = resolve_plane(args.plane)
    if args.mesh_bitwise_drill:
        _arm_mesh_devices(max(args.mesh_ranks, 2))
        return _run_mesh_drill()
    if args.fail_slow_idle_drill:
        return _run_fail_slow_idle_drill()
    if args.tenant_idle_drill:
        return _run_tenant_idle_drill()
    if args.traffic_idle_drill:
        return _run_traffic_idle_drill()
    if args.traffic_bench:
        if args.path != "sparse" or args.compute != "none":
            ap.error("--traffic-bench measures the open-loop serve "
                     "path — drop --path dense/--compute")
        if args.trn_step_ms <= 0:
            ap.error("--traffic-bench needs --trn-step-ms > 0: the "
                     "paced training window defines the arrival "
                     "schedule's horizon")
        return _run_traffic_bench(args)
    if args.tenant_bench:
        if args.path != "sparse" or args.compute != "none":
            ap.error("--tenant-bench measures tenant isolation on the "
                     "sparse serve path — drop --path dense/--compute")
        return _run_tenant_bench(args)
    if args.reshard_mem_drill:
        return _run_reshard_mem_drill()
    if args.hier_idle_drill:
        return _run_hier_drill("1")
    if args.hier_bitwise_drill:
        return _run_hier_drill("group=2")
    if args.hybrid_idle_drill:
        return _run_hier_drill("group=1,agg=mesh")
    if args.hybrid_degenerate_drill:
        # pin the one-device tier BEFORE the lockstep builds its
        # aggregators — the driver may also set it; either spelling
        # lands on the same degenerate host-kernel path
        os.environ["MINIPS_HIER_MESH_DEVS"] = "1"
        return _run_hier_drill("group=2,agg=mesh")
    if plane_kind == "mesh":
        if args.storm or args.overlap or args.cache_bytes \
                or args.serve or args.compute != "none":
            ap.error("--plane mesh measures the collective data plane: "
                     "storm/overlap/cache/serve/compute are host-wire "
                     "levers (see docs/architecture.md 'device data "
                     "plane')")
        _arm_mesh_devices(max(args.mesh_ranks, 2))
        return _run_mesh(args)
    if args.compute == "jit" and args.path != "sparse":
        # the grad step runs on pulled ROWS; the dense path never calls
        # it — a dense rate must not get labeled as compute-overlapped
        ap.error("--compute jit requires --path sparse")
    if args.warmup >= args.iters:
        ap.error(f"--warmup {args.warmup} must be < --iters {args.iters} "
                 "(otherwise the timer never starts and every rate is "
                 "garbage)")
    if args.storm:
        if args.path != "sparse":
            ap.error("--storm requires --path sparse")
        if args.compute != "none":
            ap.error("--storm measures the serve path, not worker "
                     "compute — drop --compute")
        if args.storm_pushers < 1:
            ap.error("--storm-pushers must be >= 1 (clocks must advance)")

    from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable

    rank = int(os.environ.get("MINIPS_PROC_ID", "0"))
    nprocs = int(os.environ.get("MINIPS_NUM_PROCS", "1"))

    from minips_tpu.obs import tracer as _trc

    if args.trace:  # flag spelling of MINIPS_TRACE (env works too)
        _trc.init(args.trace, rank)

    grad_step = None
    backend = "none"
    if args.compute == "jit":
        # one chip in this sandbox: rank 0 takes the default backend
        # (TPU when the tunnel is alive); peers pin CPU BEFORE jax
        # initializes — libtpu is exclusive per process
        import jax

        if rank != 0 or os.environ.get("MINIPS_FORCE_CPU"):
            jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        backend = jax.default_backend()
        W1 = jnp.asarray(np.random.default_rng(7).normal(
            scale=0.05, size=(args.dim, args.hidden)), jnp.float32)
        W2 = jnp.asarray(np.random.default_rng(8).normal(
            scale=0.05, size=(args.hidden,)), jnp.float32)

        @jax.jit
        def _row_grads(rows, y):
            def loss(r):
                h = jax.nn.relu(r @ W1)
                logit = h @ W2
                return jnp.mean(
                    jnp.maximum(logit, 0) - logit * y
                    + jnp.log1p(jnp.exp(-jnp.abs(logit))))
            l, g = jax.value_and_grad(loss)(rows)
            return l, g

        def grad_step(rows, y):
            # host->device, jitted fwd+bwd, device->host: the honest
            # per-cycle cost of accelerator workers against a host PS
            l, g = _row_grads(jnp.asarray(rows), jnp.asarray(y))
            return np.asarray(g)
    if nprocs > 1:
        from minips_tpu.apps.common import init_multiproc

        rank, nprocs, bus, monitor, _ = init_multiproc("asp", 0)
    else:  # standalone: zero-wire baseline, pure server-side apply
        bus = monitor = None

    from minips_tpu.apps.common import table_wire_kwargs

    table = ShardedTable("b", args.rows, args.dim, bus, rank, nprocs,
                         updater=args.updater, lr=0.05,
                         pull_timeout=args.pull_timeout, monitor=monitor,
                         async_push=(args.overlap and
                                     args.overlap_legs != "pull"),
                         **table_wire_kwargs(args))
    if args.storm and bus is None:
        print(json.dumps({"rank": 0, "event": "error",
                          "err": "--storm needs the launcher (n >= 2): "
                                 "a standalone rank has no peers to "
                                 "read from"}), flush=True)
        return 2
    trainer = None
    if bus is not None:
        trainer = ShardedPSTrainer({"b": table}, bus, nprocs,
                                   staleness=args.staleness,
                                   gate_timeout=60.0, monitor=monitor,
                                   serve=args.serve,
                                   tenant=args.tenant)
        bus.handshake(nprocs)

    rng = np.random.default_rng(rank)
    B, dim = args.batch, args.dim
    grads = rng.normal(size=(B, dim)).astype(np.float32)
    dense_grad = rng.normal(size=(args.rows, dim)).astype(np.float32)
    zipf_sample = None
    if args.key_dist == "zipf":
        from minips_tpu.data.synthetic import make_zipf_sampler

        # spread_seed shared across ranks: every process sees the SAME
        # hot rows (a real workload's skew), scattered across shards
        zipf_sample = make_zipf_sampler(args.rows, args.zipf_alpha,
                                        spread_seed=7,
                                        permute_hot=args.zipf_permute_hot)

    y_lab = (rng.random(B) > 0.5).astype(np.float32)

    # Overlapped pipeline (--overlap): batch t+1's pull is ISSUED before
    # batch t's compute/push, stamped one clock ahead (owners admit it
    # under exactly the rule the consuming step would face — a no-op
    # here under ASP), and pushes drain on the sender thread until the
    # tick's hard drain. The synchronous cycle is the off-arm of the
    # overlap_on_off_3proc sweep.
    pending: list = [None, None]  # [keys, PullFuture]

    def draw_keys():
        if zipf_sample is not None:
            return zipf_sample(rng, B)
        return rng.integers(0, args.rows, size=B)

    # ---- pull-storm mode: N read-only client THREADS per process
    # free-run pull() against the fleet while the main thread paces
    # pushes (pusher ranks only) + ticks. Reader counts are snapshotted
    # at the warmup boundary so read_rows_per_sec covers exactly the
    # timed window. Concurrent reader pulls are safe on the table (leg
    # bookkeeping is per-group and locked; adoption stays on the
    # push-driving thread — balance/rebalancer.py adopt_now guard).
    import threading

    storm_stop = threading.Event()
    storm_errs: list = []
    storm_counts = [0] * max(args.storm, 1)
    storm_threads: list = []
    # coordinated-omission fix: each reader keeps an INTENDED-arrival
    # schedule (next_t += think, never reset from completion) and
    # records completion - intended next to bare service time. The old
    # accounting slept AFTER each completion, so a slow read silently
    # pushed every later request's start — the classic closed-loop
    # self-throttle that under-reports the tail exactly under load.
    # Both hists ride the done line (read_intended_ms / read_svc_ms).
    from minips_tpu.obs.hist import (Log2Histogram,
                                     summarize_counts as _sum_counts)

    storm_hist_intended = Log2Histogram()
    storm_hist_svc = Log2Histogram()

    def _storm_reader(j: int) -> None:
        rrng = np.random.default_rng((rank, j, 1717))
        SB = args.storm_batch
        think = args.storm_think_ms / 1e3
        next_t = time.perf_counter()
        while not storm_stop.is_set():
            if think > 0:
                next_t += think
                slack = next_t - time.perf_counter()
                if slack > 0 and storm_stop.wait(slack):
                    return
            else:
                next_t = time.perf_counter()
            keys = (zipf_sample(rrng, SB) if zipf_sample is not None
                    else rrng.integers(0, args.rows, size=SB))
            t1 = time.perf_counter()
            try:
                # the serving read clock: admission already proven
                # fleet-wide, so reads never park on the in-flight step
                table.pull_serving(keys)
            except Exception as e:  # noqa: BLE001 - surfaced below
                if not storm_stop.is_set():
                    storm_errs.append(repr(e))
                return
            t2 = time.perf_counter()
            storm_hist_intended.record_s(t2 - next_t)
            storm_hist_svc.record_s(t2 - t1)
            storm_counts[j] += SB

    if args.storm:
        for j in range(args.storm):
            th = threading.Thread(target=_storm_reader, args=(j,),
                                  daemon=True, name=f"storm-reader-{j}")
            storm_threads.append(th)
            th.start()

    def cycle():
        if args.storm:
            time.sleep(args.storm_step_s)  # pusher cadence
            if rank < args.storm_pushers:
                keys = draw_keys()
                table.push(keys, grads)
                return B
            return 0
        if args.path == "sparse":
            if args.overlap and args.overlap_legs != "push":
                if pending[1] is None:  # first iteration: nothing ahead
                    pending[0] = draw_keys()
                    pending[1] = table.prefetch_pull(pending[0],
                                                     clock_ahead=0)
                keys, fut = pending
                nxt = draw_keys()
                pending[0] = nxt
                pending[1] = table.prefetch_pull(nxt)  # overlaps below
                rows = fut.wait()
            else:
                keys = draw_keys()
                rows = table.pull(keys)
            g = (grad_step(rows, y_lab) if grad_step is not None
                 else grads)
            table.push(keys, g)
            return 2 * B  # rows moved (pulled + pushed)
        table.pull_all()
        table.push_dense(dense_grad)
        return 2 * args.rows

    rows_moved = 0
    b_push0 = b_pull0 = 0.0
    read0 = 0
    t0 = 0.0
    for i in range(args.iters):
        if i == args.warmup:
            rows_moved = 0
            b_push0, b_pull0 = table.bytes_pushed, table.bytes_pulled
            read0 = sum(storm_counts)
            t0 = time.perf_counter()
        rows_moved += cycle()
        if trainer is not None:
            trainer.tick()  # ASP: publishes clock, never waits
    table.flush_pushes()  # standalone/async tail: count only drained work
    dt = time.perf_counter() - t0
    read_rows = sum(storm_counts) - read0
    if args.storm:
        # stop the readers BEFORE finalize (post-finalize agreement is
        # exact; a still-running reader would race the quiesce)
        storm_stop.set()
        for th in storm_threads:
            th.join(timeout=30.0)
        assert not any(th.is_alive() for th in storm_threads), \
            "storm reader wedged"
        assert not storm_errs, storm_errs
    b_push1, b_pull1 = table.bytes_pushed, table.bytes_pulled
    if pending[1] is not None:
        pending[1].cancel()  # dangling last prefetch: never consumed
    if trainer is not None:
        trainer.finalize(timeout=60.0)
        assert trainer.frames_dropped == 0, trainer.drop_detail()
        trainer.shutdown_barrier(timeout=15.0)

    timed = args.iters - args.warmup
    # the full wire_record layout rides the done line (the schema test
    # pins it, scrapers rely on it); the standalone path builds the
    # SAME record through a view so the layout is defined exactly once
    from types import SimpleNamespace

    from minips_tpu.train.sharded_ps import tables_hist_stats
    from minips_tpu.utils.metrics import wire_record

    solo = SimpleNamespace(
        bytes_pushed=table.bytes_pushed,
        bytes_pulled=table.bytes_pulled,
        frames_dropped=table.frames_dropped,
        wire_frames_lost=0, wire_frames_malformed=0,
        comm_timing=table.timers.summary,
        hist_stats=lambda: tables_hist_stats([table]),
        cache_stats=table.cache_stats,
        ef_stats=table.ef_stats,
        reliable_stats=lambda: None, chaos_stats=lambda: None,
        # the standalone path has no trainer, hence no serve plane:
        # the replica sub-block is None (off) like the other layers —
        # and no clock boundary, hence no windowed layer or heartbeat
        # monitor (None = off, the same convention)
        serve_stats=lambda: {**table.serve, "replica": None},
        rebalance_stats=lambda: None,
        window_stats=lambda: None,
        heartbeat_stats=lambda: None)
    trace_file = _trc.dump_now()  # standalone has no finalize dump
    print(json.dumps({
        "rank": rank, "event": "done",
        "path": args.path, "nprocs": nprocs,
        "push_comm": table.push_comm,  # resolved (None defers to env)
        "pull_wire": args.pull_wire,   # echo: bench asserts negotiation
        "overlap": bool(args.overlap),
        "overlap_legs": args.overlap_legs if args.overlap else None,
        # cache/key-dist echo: the sweep asserts these so a flag-
        # plumbing regression can't publish a mislabeled arm
        "key_dist": args.key_dist,
        "zipf_alpha": args.zipf_alpha if args.key_dist == "zipf" else None,
        "zipf_permute_hot": (bool(args.zipf_permute_hot)
                             if args.key_dist == "zipf" else None),
        # rebalancer/chaos/reliable/trace echoes (env- or flag-
        # configured): the sweep asserts the arm config
        "rebalance_spec": os.environ.get("MINIPS_REBALANCE") or None,
        "serve_spec": (args.serve or os.environ.get("MINIPS_SERVE")
                       or None),
        "storm_readers": args.storm or None,
        "storm_pushers": args.storm_pushers if args.storm else None,
        "read_rows": int(read_rows) if args.storm else None,
        "read_rows_per_sec": (round(read_rows / dt, 1) if args.storm
                              else None),
        # storm read latency, TWO ways (schema note): read_intended_ms
        # measures from each request's INTENDED arrival (think-paced
        # schedule, coordinated-omission-free — the honest tail);
        # read_svc_ms is bare service time (issue -> completion, the
        # only number the old accounting kept). intended >= svc always;
        # a large gap means the closed loop was self-throttling.
        "read_intended_ms": (_sum_counts(storm_hist_intended.snapshot())
                             if args.storm else None),
        "read_svc_ms": (_sum_counts(storm_hist_svc.snapshot())
                        if args.storm else None),
        "staleness": (None if args.staleness == float("inf")
                      else int(args.staleness)),
        "cache_bytes": args.cache_bytes,
        "pull_dedup": bool(args.pull_dedup),
        "push_dedup": bool(args.push_dedup),
        "chaos_spec": os.environ.get("MINIPS_CHAOS") or None,
        "reliable_on": os.environ.get("MINIPS_RELIABLE", "")
        not in ("", "0"),
        "trace_file": trace_file,
        # bytes/drops/loss/timing/hist/cache/reliable/chaos/serve/
        # rebalance — the one wire-health layout (utils/metrics.py)
        **wire_record(trainer if trainer is not None else solo),
        "compute": (f"jit({backend})" if args.compute == "jit"
                    else "none"),
        "bus": os.environ.get("MINIPS_BUS", "zmq") if bus else "none",
        "wire_fmt": ((os.environ.get("MINIPS_WIRE_FMT") or "bin")
                     if bus else None),
        "rows": args.rows, "dim": args.dim, "batch": B,
        "iters_timed": timed,
        "rows_per_sec": round(rows_moved / dt, 1),
        "cycles_per_sec": round(timed / dt, 2),
        "wire_push_bytes_per_sec": round((b_push1 - b_push0) / dt, 1),
        "wire_pull_bytes_per_sec": round((b_pull1 - b_pull0) / dt, 1),
        "wire_bytes_per_row_moved": round(
            (b_push1 - b_push0 + b_pull1 - b_pull0)
            / max(rows_moved, 1), 3),
        "wall_s": round(dt, 4),
    }), flush=True)
    if monitor is not None:
        monitor.stop()
    if bus is not None:
        bus.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
