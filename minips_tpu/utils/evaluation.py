"""Evaluation metrics for the CTR workloads — streaming AUC on device.

The reference validates its apps by "loss goes down" (SURVEY.md §4,
app-level validation); its CTR configs (LR on a9a/RCV1, Wide&Deep/DeepFM on
Criteo — BASELINE.json:6-12) are exactly the workloads the CTR literature
scores by ROC-AUC. This module adds that as a first-class, TPU-friendly
observable:

- ``StreamingAUC`` bucketizes each score batch on device with a jitted
  kernel, then folds the per-batch histograms into float64 host
  accumulators — O(buckets) state no matter how many samples stream
  through, so a Criteo-1TB-sized eval pass never materialises the score
  vector, and the float64 counters stay exact far beyond 2^53 samples
  (a per-batch float32 histogram is safe because one batch's bucket
  counts never approach float32's 2^24 integer ceiling).
- AUC is computed from the histograms by the rank-sum formula with the
  within-bucket tie correction (pairs falling in the same bucket count
  0.5), which makes the estimator exact in the limit of one score per
  bucket and biased by at most O(1/buckets) otherwise.
- ``auc_exact`` is the O(n log n) host oracle used by the tests and fine
  for small evals.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=(3,))
def _batch_hists(scores, labels, weights, num_buckets):
    """Bucketize sigmoid(scores) into [0, 1); per-class batch histograms."""
    p = jax.nn.sigmoid(scores.astype(jnp.float32)).reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    weights = weights.reshape(-1).astype(jnp.float32)
    idx = jnp.clip((p * num_buckets).astype(jnp.int32), 0, num_buckets - 1)
    zeros = jnp.zeros((num_buckets,), jnp.float32)
    return (zeros.at[idx].add(weights * labels),
            zeros.at[idx].add(weights * (1.0 - labels)))


def _auc_from_hists(pos_hist, neg_hist) -> float:
    """Rank-sum AUC over score-ascending buckets with tie correction."""
    cum_neg_below = np.cumsum(neg_hist) - neg_hist
    pairs_won = np.sum(pos_hist * (cum_neg_below + 0.5 * neg_hist))
    total = np.sum(pos_hist) * np.sum(neg_hist)
    return float(pairs_won / total) if total > 0 else 0.5


class StreamingAUC:
    """Accumulate ROC-AUC over score batches with O(buckets) state.

    Scores are LOGITS (mapped through sigmoid internally, which is
    monotonic and therefore AUC-preserving); labels are {0, 1}. Optional
    per-sample weights support padded eval batches (weight 0 = ignore).
    """

    def __init__(self, num_buckets: int = 1 << 14):
        if num_buckets < 2:
            raise ValueError(f"need >= 2 buckets, got {num_buckets}")
        self.num_buckets = num_buckets
        self.reset()

    def reset(self) -> None:
        self._pos = np.zeros((self.num_buckets,), np.float64)
        self._neg = np.zeros((self.num_buckets,), np.float64)

    def update(self, logits, labels, weights=None) -> None:
        if weights is None:
            weights = jnp.ones(jnp.size(logits), jnp.float32)
        pos, neg = _batch_hists(jnp.asarray(logits), jnp.asarray(labels),
                                jnp.asarray(weights), self.num_buckets)
        self._pos += np.asarray(pos, np.float64)
        self._neg += np.asarray(neg, np.float64)

    @property
    def count(self) -> float:
        return float(self._pos.sum() + self._neg.sum())

    def result(self) -> float:
        return _auc_from_hists(self._pos, self._neg)


def auc_exact(scores, labels) -> float:
    """O(n log n) exact ROC-AUC (rank-sum with midranks for ties) — the
    host oracle for tests and small holdouts."""
    scores = np.asarray(scores, np.float64).reshape(-1)
    labels = np.asarray(labels, np.float64).reshape(-1)
    n_pos = labels.sum()
    n_neg = labels.shape[0] - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(scores, kind="mergesort")
    s, y = scores[order], labels[order]
    # midranks: average rank within each tied group
    ranks = np.empty_like(s)
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and s[j + 1] == s[i]:
            j += 1
        ranks[i:j + 1] = 0.5 * (i + j) + 1.0
        i = j + 1
    rank_sum_pos = ranks[y == 1].sum()
    return float((rank_sum_pos - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def padded_chunks(data: dict, batch_size: int):
    """Yield ``(chunk, n_valid)`` over dict-of-arrays rows: every chunk is
    repeat-padded to exactly ``batch_size`` rows (one compiled shape for
    the whole sweep; padded rows duplicate the last valid row and must be
    masked/sliced out by the consumer via ``n_valid``). Shared by
    ``evaluate_auc`` and the apps' chunked holdout scorers."""
    n = int(len(next(iter(data.values()))))
    for lo in range(0, n, batch_size):
        hi = min(lo + batch_size, n)
        pad = batch_size - (hi - lo)

        def cut(v):
            chunk = np.asarray(v)[lo:hi]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], pad, axis=0)], axis=0)
            return chunk

        yield {k: cut(v) for k, v in data.items()}, hi - lo


def evaluate_auc(predict_logits, data: dict, batch_size: int = 8192,
                 label_key: str = "y", num_buckets: int = 1 << 14) -> float:
    """Stream ``data`` through ``predict_logits(batch)->logits`` in fixed
    chunks (a ragged tail is padded and masked by weight so every chunk has
    one compiled shape) and return the streaming AUC."""
    auc = StreamingAUC(num_buckets)
    for batch, n_valid in padded_chunks(data, batch_size):
        w = np.ones((batch_size,), np.float32)
        w[n_valid:] = 0.0
        auc.update(predict_logits(batch), batch[label_key], w)
    return auc.result()
