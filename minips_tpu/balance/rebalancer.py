"""The heat-aware shard rebalancer: planner + coordinator.

The sharded PS partitions tables by STATIC contiguous key range
(parallel/partition.RangePartitioner), so zipf-skewed traffic lands its
whole head on one owner and that shard paces the system. This module
closes the loop online:

1. every owner keeps decayed per-key-block heat on its serve path
   (balance/heat.py) and gossips a bounded report to the coordinator
   (rank 0) every clock: ``rbH:{table}`` — epoch, settled flag, total
   owned heat, and its top-k hottest blocks;
2. once every live rank is SETTLED at the same routing epoch, the
   report interval has elapsed, and the max/mean per-shard heat ratio
   exceeds the hysteresis threshold, the coordinator greedily bin-packs
   hot blocks away from the hottest shard (:func:`plan_assignment`) and
   broadcasts the FULL new block→owner overlay stamped with the next
   routing epoch (``rbP:{table}``);
3. every rank adopts the plan at its next clock boundary
   (``ShardedPSTrainer.tick``) — the epoch-fenced migration itself
   (state ship, stale-frame forward/refuse, rbA/rbF fencing) lives in
   train/sharded_ps.py, where the storage and locks are.

Config rides ``MINIPS_REBALANCE`` (off by default), e.g.::

    MINIPS_REBALANCE="interval=1.0,threshold=1.3,max_blocks=8,block=64"

``"1"`` selects all defaults. Knob reference: docs/api.md; protocol and
safety argument: docs/architecture.md "Heat-aware shard rebalancer".
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from minips_tpu.obs import flight as _fl
from minips_tpu.obs import tracer as _trc

__all__ = ["RebalanceConfig", "Rebalancer", "plan_assignment"]


class RebalanceConfig:
    """Parsed ``MINIPS_REBALANCE`` knobs (all optional, ``k=v`` comma
    list; the bare string ``"1"`` = every default)."""

    def __init__(self, *, interval: float = 1.0, threshold: float = 1.3,
                 max_blocks: int = 8, block: int = 0, decay: float = 0.8,
                 topk: int = 32, min_heat: float = 1.0):
        if interval < 0:
            raise ValueError("interval must be >= 0")
        if threshold < 1.0:
            raise ValueError("threshold must be >= 1.0 (a max/mean "
                             "ratio below 1 is impossible)")
        if max_blocks < 1:
            raise ValueError("max_blocks must be >= 1")
        if block < 0:
            raise ValueError("block must be >= 0 (0 = auto)")
        self.interval = float(interval)   # min seconds between plans
        self.threshold = float(threshold)  # max/mean heat arming ratio
        self.max_blocks = int(max_blocks)  # blocks moved per plan
        self.block = int(block)            # keys per block (0 = auto)
        self.decay = float(decay)          # per-tick heat decay
        self.topk = int(topk)              # movable candidates per report
        self.min_heat = float(min_heat)    # don't plan on noise

    @classmethod
    def parse(cls, spec: str) -> "RebalanceConfig":
        spec = (spec or "").strip()
        if spec in ("", "1", "on", "true"):
            return cls()
        kw: dict = {}
        casts = {"interval": float, "threshold": float, "decay": float,
                 "min_heat": float, "max_blocks": int, "block": int,
                 "topk": int}
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(f"MINIPS_REBALANCE: expected k=v, "
                                 f"got {item!r}")
            k, v = item.split("=", 1)
            k = k.strip()
            if k not in casts:
                raise ValueError(f"MINIPS_REBALANCE: unknown knob {k!r}")
            try:
                kw[k] = casts[k](v)
            except ValueError as e:
                raise ValueError(
                    f"MINIPS_REBALANCE: bad value for {k}: {v!r}") from e
        return cls(**kw)


def plan_assignment(loads, candidates: dict, threshold: float,
                    max_blocks: int) -> list[tuple[int, int, int]]:
    """Greedy bin-pack of hot blocks, with hysteresis.

    ``loads`` is per-shard total heat; ``candidates`` maps movable
    ``block -> (current_owner, heat)``. Returns up to ``max_blocks``
    moves ``(block, src, dst)``, or ``[]`` when the imbalance is under
    ``threshold`` (hysteresis: the rebalancer only fires past the
    arming ratio, so balanced traffic never migrates anything).

    Invariants (property-tested): each move takes the hottest movable
    block of the CURRENTLY hottest shard whose heat fits strictly
    inside the hot→cool gap — so the pairwise imbalance strictly
    decreases on every move and the plan can never overshoot into a
    new, worse hotspot; a block is moved at most once per plan."""
    loads = np.asarray(loads, np.float64).copy()
    n = loads.size
    mean = loads.sum() / n if n else 0.0
    if mean <= 0.0 or loads.max() / mean < threshold:
        return []
    by_owner: dict[int, list[tuple[float, int]]] = {}
    for b, (o, h) in candidates.items():
        if h > 0.0:
            by_owner.setdefault(int(o), []).append((float(h), int(b)))
    for o in by_owner:
        by_owner[o].sort(reverse=True)
    moves: list[tuple[int, int, int]] = []
    while len(moves) < max_blocks:
        if loads.max() / mean < threshold:
            break  # balanced enough: stop early (the other hysteresis)
        hot = int(np.argmax(loads))
        cool = int(np.argmin(loads))
        gap = loads[hot] - loads[cool]
        pick = None
        for i, (h, _b) in enumerate(by_owner.get(hot, ())):
            if h < gap:  # strictly improving and non-flipping
                pick = i
                break
        if pick is None:
            break  # nothing movable improves the hottest shard
        h, b = by_owner[hot].pop(pick)
        moves.append((b, hot, cool))
        loads[hot] -= h
        loads[cool] += h
    return moves


class Rebalancer:
    """Per-trainer rebalance driver: heat reports every clock, plans at
    the coordinator (rank 0), plan adoption at each rank's own clock
    boundary. The migration mechanics (state ship, fences, stale-frame
    handling) live on the tables; this object is the control loop."""

    HEAT_KIND = "rbH"
    PLAN_KIND = "rbP"

    def __init__(self, trainer, cfg: RebalanceConfig, *,
                 plan_heat: bool = True):
        """``plan_heat=False`` arms the migration MACHINERY (router,
        heat accounting, plan adoption, fences) without the heat-driven
        planner — the elastic membership plane (balance/membership.py)
        needs the former even when nobody asked for the latter."""
        self.trainer = trainer
        self.cfg = cfg
        self.plan_heat = bool(plan_heat)
        self.bus = trainer.bus
        self.rank = trainer.bus.my_id
        self.n = trainer.num_processes
        self.coord = 0
        self.plans = 0
        self.stale_plans_fenced = 0  # rbP frames dropped by lease term
        # tenancy (tenant/registry.py): rbH frames whose tenant stamp
        # disagreed with the table they arrived on (dropped — block
        # ids are table-local, a crossed report must never feed a
        # plan), and heat plans deferred so one tenant's migration
        # never overlaps another's staging window
        self.tenant_heat_crossed = 0
        self.tenant_plans_deferred = 0
        self._stopped = False
        self._drive_thread: Optional[int] = None  # push-driving thread
        self._lock = threading.Lock()
        self._pending: dict[str, dict] = {}        # table -> newest plan
        self._reports: dict[str, dict[int, dict]] = {}  # table -> rank ->
        self._last_plan: dict[str, float] = {}
        self._t0 = time.monotonic()
        for name, t in trainer.tables.items():
            t.attach_rebalancer(self, cfg)
            self.bus.on(f"{self.PLAN_KIND}:{name}",
                        self._mk_on_plan(name))
            self.bus.on(f"{self.HEAT_KIND}:{name}",
                        self._mk_on_heat(name))

    # ------------------------------------------------------------ handlers
    def _lease(self):
        """The coordinator lease when the membership plane is armed
        (balance/control_plane.py) — plan broadcasts are stamped with
        its term and stale-term plans fenced at receive; None keeps the
        pre-lease wire for rebalance-only fleets."""
        mb = getattr(self.trainer, "membership", None)
        return mb.lease if mb is not None else None

    def _lease_stamp(self) -> dict:
        lease = self._lease()
        return lease.stamp() if lease is not None else {}

    def _mk_on_plan(self, name: str):
        def on_plan(sender: int, payload: dict) -> None:
            mb = getattr(self.trainer, "membership", None)
            if mb is not None and not mb.fence_frame(payload):
                # a partitioned ex-coordinator's post-return plan:
                # fenced by lease term, never adopted — the epoch
                # check alone cannot save us (the stale holder may
                # stamp any epoch it likes)
                self.stale_plans_fenced += 1
                return
            if mb is not None and mb.refuses_own_death_plan(payload):
                # a death plan naming THIS rank: the convicted-but-
                # alive rank (partition survivor the fleet gave up on)
                # must not adopt its own death — see membership.py
                return
            extras = {k: payload[k] for k in ("dead", "rstep")
                      if k in payload}
            self.note_plan(name, int(payload.get("ep", 0)),
                           dict(zip(payload.get("ovb", ()),
                                    payload.get("ovo", ()))),
                           extras=extras or None)
        return on_plan

    def note_plan(self, name: str, ep: int, ov: dict,
                  extras: Optional[dict] = None) -> None:
        """Stash a routing table for the table's owner thread to adopt
        at its next clock boundary / pull-wait poll. Adoption NEVER
        happens on the bus receive thread: the adoption ack's ordering
        promise ('my stale pushes all precede it') only holds from the
        thread that drives pushes. ``extras`` carry a membership
        transition's metadata (dead sources + restore step) through to
        ``adopt_table``."""
        with self._lock:
            cur = self._pending.get(name)
            if cur is None or ep > cur["ep"]:
                self._pending[name] = {"ep": ep, "ov": dict(ov),
                                       "extras": extras}

    def issue_plan(self, name: str, ep: int, ov: dict,
                   extras: Optional[dict] = None) -> None:
        """Coordinator-side plan broadcast + immediate local adoption —
        the membership plane's transition emitter (and the one path a
        plan's extras ride, so death restores dispatch identically at
        every rank). The caller must be at its clock boundary on the
        push-driving thread, like ``_maybe_plan``."""
        payload = {"ep": int(ep), "ovb": [int(b) for b in ov],
                   "ovo": [int(o) for o in ov.values()],
                   **self._lease_stamp()}
        if extras:
            payload.update(extras)
        self.bus.publish(f"{self.PLAN_KIND}:{name}", payload)
        self.plans += 1
        self.note_plan(name, ep, ov, extras=extras)
        self._adopt_one(name, self.trainer.tables[name])

    def claim_drive_thread(self) -> None:
        """Declare the CALLING thread the push-driving thread (the
        ``stop()`` rule, without stopping planning): a draining rank's
        leave loop adopts plans from its own thread after its last
        tick ran elsewhere."""
        self._drive_thread = threading.get_ident()

    def _mk_on_heat(self, name: str):
        def on_heat(sender: int, payload: dict) -> None:
            # tenancy namespace guard: block ids in a heat report are
            # TABLE-LOCAL, so a report stamped for a different tenant
            # than the table this wire belongs to (half-armed fleet,
            # divergent registry order) must never enter the planner —
            # the config stamp poisons the data wire for the same
            # divergence; this is the control wire's twin
            t = self.trainer.tables.get(name)
            tid = getattr(t, "_tenant_tid", 0) if t is not None else 0
            if int(payload.get("tb", 0)) != tid:
                self.tenant_heat_crossed += 1
                return
            with self._lock:
                self._reports.setdefault(name, {})[sender] = payload
        return on_heat

    # ------------------------------------------------------------ the loop
    def on_tick(self) -> None:
        """Called from ``ShardedPSTrainer.tick`` at the clock boundary,
        after the push drain and before the clock advances: adopt any
        pending plan (the epoch fence point), decay heat, gossip the
        report, and — at the coordinator — maybe plan."""
        now = time.monotonic()
        # the tick caller IS the push-driving thread by contract; record
        # it so adopt_now() can refuse other threads (see below)
        self._drive_thread = threading.get_ident()
        for name, t in self.trainer.tables.items():
            self._adopt_one(name, t)
            if t._heat is not None:
                t._heat.tick()
            self._send_heat(name, t)
            if self.rank == self.coord and not self._stopped:
                self._maybe_plan(name, t, now)

    def adopt_now(self) -> None:
        """Adopt pending plans outside the tick path — finalize and
        pull_all call this so a plan landing after a rank's last tick
        still gets its adoption ack (a missing ack would hold peers'
        fences open until their pull deadline poisons).

        THREAD-GUARDED (serving plane): the pull-wait poll also calls
        this, and under a read storm pulls run on READER threads
        concurrent with the training thread's pushes — an adoption from
        a reader could emit its rbA around a mid-flight old-table push
        send and void the fence (the exact bus-thread hazard PR4's
        review fixed). Once a tick has identified the push-driving
        thread, every other thread's adopt_now is a no-op; the driving
        thread's next tick (bounded — it ticks every step) adopts
        instead. Before the first tick any thread may adopt (raw-table
        drills drive no concurrent pushes)."""
        if self._drive_thread is not None \
                and self._drive_thread != threading.get_ident():
            return
        for name, t in self.trainer.tables.items():
            self._adopt_one(name, t)

    def install_reports(self, reports: dict[str, dict[int, dict]]) -> None:
        """Install a handed-over report store (graceful lease handover,
        balance/membership.Membership._on_handover): the successor's
        first coordinator boundary sees the old holder's load picture
        instead of a cold start. Fresher reports that already arrived
        here win — a transferred snapshot must never roll a rank's
        report backward past one the rank re-gossiped directly."""
        with self._lock:
            for name, by_rank in reports.items():
                store = self._reports.setdefault(name, {})
                for r, rep in by_rank.items():
                    store.setdefault(int(r), dict(rep))

    def heat_reports(self, name: str) -> dict[int, dict]:
        """Snapshot of the coordinator's stored per-rank heat reports
        for ``name`` — the membership plane's admission planner reads
        them so a joiner's placement can be heat-aware instead of
        home-blocks-only (balance/membership.plan_admission)."""
        with self._lock:
            return {r: dict(rep)
                    for r, rep in self._reports.get(name, {}).items()}

    def has_pending(self, name: str) -> bool:
        """A plan for ``name`` is noted but not yet adopted — readers
        blocked on keys the pending table re-homes wait for the
        driving thread's adoption instead of re-issuing pulls the old
        table routes straight back (train/sharded_ps._read_local)."""
        with self._lock:
            return name in self._pending

    def stop(self) -> None:
        """No further plans (finalize): migrations already in flight
        still settle through the normal fence path. The CALLING thread
        becomes the push-driving thread: finalize() drains pushes on
        this thread next, so the thread-guard must let ITS adopt_now
        through even when ticks ran elsewhere — otherwise the final
        pending plan's rbA never goes out and peers' fences hold to
        their pull deadline (the exact poison adopt_now prevents)."""
        self._stopped = True
        self._drive_thread = threading.get_ident()

    def _adopt_one(self, name: str, t) -> None:
        with self._lock:
            plan = self._pending.pop(name, None)
        if plan is None:
            return
        extras = plan.get("extras") or {}
        dead = frozenset(int(r) for r in extras.get("dead") or ())
        restore = None
        if dead:
            mb = getattr(self.trainer, "membership", None)
            if mb is not None:
                restore = mb.block_restorer(name, extras)
        t.adopt_table(plan["ep"], plan["ov"], dead=dead,
                      restore=restore)

    def _send_heat(self, name: str, t) -> None:
        ep, _ov = t.router.table()
        owned = np.nonzero(t.router.owner_of_blocks() == self.rank)[0]
        rep = t._heat.report(owned, self.cfg.topk)
        rep["ep"] = ep
        rep["settled"] = t.rebalance_settled()
        if getattr(self.trainer, "autoscaler", None) is not None:
            # autoscaler load signals ride the heat report (balance/
            # autoscaler.py): cumulative serve-plane shed counters plus
            # the pull p99 — re-gossiped every tick, so a lease
            # successor's autoscaler reconstructs the fleet load
            # picture in one boundary with no extra wire. The p99 is
            # the WINDOWED quantile (obs/window.py, rolled by the
            # trainer at this same clock boundary): an idle window
            # reports None (calm), and a storm that ENDED leaves the
            # signal within one window — the disarm the cumulative
            # hist could never produce. MINIPS_OBS=0 falls back to the
            # cumulative quantile (the pre-window behavior, kept only
            # for the tax A/B arm).
            if t._sv is not None:
                rep["sv"] = t._sv.load_signal()
            ow = getattr(self.trainer, "obs_window", None)
            if ow is not None:
                # tenancy armed: the report carries THIS tenant's own
                # windowed pull p99 (registered per table by
                # _register_window_signals), so the autoscaler's SLO
                # arming judges each tenant against its own tail
                # instead of the fleet blend
                sig = ("pull_latency" if not getattr(t, "_tenant_tid", 0)
                       else f"pull_latency:{name}")
                rep["p99"] = ow.quantile_ms(sig, 0.99)
            else:
                from minips_tpu.obs.hist import summarize_counts

                rep["p99"] = summarize_counts(
                    t.timers.snapshot()["hists"]["pull_latency"]).get(
                        "p99_ms")
        if self.rank == self.coord:
            with self._lock:
                self._reports.setdefault(name, {})[self.rank] = rep
        else:
            self.bus.send(self.coord, f"{self.HEAT_KIND}:{name}", rep)

    def _live_ranks(self) -> set[int]:
        excluded = getattr(self.trainer.gossip, "excluded", set())
        return set(range(self.n)) - set(excluded)

    def _maybe_plan(self, name: str, t, now: float) -> None:
        if not self.plan_heat:
            return
        mb = getattr(self.trainer, "membership", None)
        if mb is not None and mb.busy:
            # a membership transition is in flight: its plan must not
            # interleave with a heat plan (the planner's one-plan-at-a-
            # time quality rule; adoption itself tolerates pipelining)
            return
        if getattr(t, "_tenant_tid", 0):
            # per-tenant migration scheduling: at most ONE tenant's
            # heat migration in flight fleet-wide — a plan for this
            # table is deferred while any other table has a pending
            # plan or unsettled fences, so two tenants' state ships
            # can never stack in one staging window (the per-round
            # reshard cap bounds each table alone; overlap would sum
            # them). Membership transitions (join/drain/death) stay
            # fleet-wide — an evacuation must cover every table at
            # once, the documented honest limit.
            for oname, ot in self.trainer.tables.items():
                if oname != name and (self.has_pending(oname)
                                      or not ot.rebalance_settled()):
                    self.tenant_plans_deferred += 1
                    return
        last = self._last_plan.get(name, self._t0)
        if now - last < self.cfg.interval:
            return
        ep, ov = t.router.table()
        live = self._live_ranks()
        with self._lock:
            reports = dict(self._reports.get(name, {}))
        if not live <= set(reports):
            return
        # plan only over a SETTLED fleet at the current epoch: a rank
        # mid-migration (fences pending) or still on the old table would
        # make the diff-based adoption ambiguous — one plan in flight
        # at a time, by construction
        if any(reports[r].get("ep") != ep or not reports[r].get("settled")
               for r in live):
            return
        # plan over LIVE ranks only, in a compact index space: a dead
        # excluded rank must never appear as a zero-load migration
        # target (state shipped to a corpse is state lost), nor deflate
        # the mean into spuriously arming the threshold
        live_sorted = sorted(live)
        if len(live_sorted) < 2:
            return
        loads = np.zeros(len(live_sorted), np.float64)
        candidates: dict[int, tuple[int, float]] = {}
        for i, r in enumerate(live_sorted):
            rep = reports[r]
            loads[i] = float(rep.get("total", 0.0))
            for b, h in zip(rep.get("blocks", ()), rep.get("heat", ())):
                candidates[int(b)] = (i, float(h))
        if loads.sum() < self.cfg.min_heat:
            return
        # fail-slow DEMOTION (obs/slowness.py, the write/placement
        # mitigation): while a quorum-corroborated slow verdict stands
        # the planner runs a DEMOTE pass instead of the heat pass —
        # the sick rank's load is multiplied by the demote bias (its
        # effective capacity shrank by that factor), candidates narrow
        # to blocks the sick rank owns, and the arming ratio drops to
        # 1.0: a verdict IS the arming — demotion must move hot blocks
        # off the sick rank even when raw heat looks balanced (a
        # ratio threshold can provably never clear cfg.threshold >= 3
        # in a small fleet: one biased rank tops out at 3b/(2+b) < 3).
        # plan_assignment's strictly-inside-the-gap rule still bounds
        # every move, so demotion cannot overshoot into a new hotspot;
        # the bias lifts by itself when the verdict clears (slow_view
        # recomputes), so a recovered rank's blocks stay put.
        slow: set[int] = set()
        bias = 0.0
        if mb is not None:
            view = getattr(mb, "slow_view", None)
            if view is not None:
                slow = view()
                bias = mb.slow_demote_bias()
        sick_idx = {i for i, r in enumerate(live_sorted) if r in slow}
        if sick_idx and bias > 1.0:
            for i in sick_idx:
                loads[i] *= bias
            sick_cands = {b: ih for b, ih in candidates.items()
                          if ih[0] in sick_idx}
            moves = [(b, live_sorted[s], live_sorted[d])
                     for b, s, d in plan_assignment(
                         loads, sick_cands, 1.0, self.cfg.max_blocks)]
        else:
            moves = [(b, live_sorted[s], live_sorted[d])
                     for b, s, d in plan_assignment(
                         loads, candidates, self.cfg.threshold,
                         self.cfg.max_blocks)]
        if not moves:
            return
        demoted = sorted({s for _b, s, _d in moves if s in slow})
        if demoted:
            # the DEMOTE decision into the black box: which sick
            # rank(s) lost how many blocks, under which verdict view
            _fl.record("demote",
                       {"table": name, "ranks": demoted,
                        "blocks": sum(1 for _b, s, _d in moves
                                      if s in slow),
                        "bias": bias, "ep": ep + 1})
        new_ov = dict(ov)
        for b, _src, dst in moves:
            if dst == t.router.home_of(b):
                new_ov.pop(b, None)  # moving home: leave the base map
            else:
                new_ov[b] = dst
        new_ep = ep + 1
        tr = _trc.TRACER
        if tr is not None:
            tr.instant("rebalance", "rb_plan",
                       {"table": name, "ep": new_ep,
                        "moves": [[int(b), int(s), int(d)]
                                  for b, s, d in moves]})
        self.bus.publish(f"{self.PLAN_KIND}:{name}",
                         {"ep": new_ep,
                          "ovb": [int(b) for b in new_ov],
                          "ovo": [int(o) for o in new_ov.values()],
                          **self._lease_stamp()})
        self.plans += 1
        self._last_plan[name] = now
        # the coordinator is at its own clock boundary right now: adopt
        # immediately (peers adopt at theirs; the epoch fence covers the
        # window in between)
        t.adopt_table(new_ep, new_ov)

    # ------------------------------------------------------------- metrics
    def stats(self) -> dict:
        out = {"plans": self.plans,
               "stale_plans_fenced": self.stale_plans_fenced,
               "tenant_heat_crossed": self.tenant_heat_crossed,
               "tenant_plans_deferred": self.tenant_plans_deferred}
        per = {}
        for name, t in self.trainer.tables.items():
            per[name] = t.rebalance_table_stats()
        out["tables"] = per
        out["epoch"] = max((p["epoch"] for p in per.values()), default=0)
        for k in ("blocks_in", "blocks_out", "forwarded_pushes",
                  "refused_pulls", "migrated_rows", "blocks_restored",
                  "pushes_lost_to_dead"):
            out[k] = sum(p.get(k, 0) for p in per.values())
        # a MAX, not a sum: the staging cap bounds each rank's worst
        # simultaneous snapshot — the RESHARD-MEM gate's p2p baseline
        out["peak_stage_bytes"] = max(
            (p.get("peak_stage_bytes", 0) for p in per.values()),
            default=0)
        return out
