"""Multi-host bootstrap — the rebuild of the launch-script + mailbox bind.

The reference spawns one process per node via ssh with ``--my_id i`` and a
hostfile; the mailbox binds zmq ROUTER sockets (SURVEY.md §1 L7, §3.1). On
TPU pods the moral equivalent is ``jax.distributed.initialize`` — the
coordination service wires processes into one JAX runtime, after which the
*data plane* is XLA collectives over ICI/DCN and needs no sockets at all
(SURVEY.md §2.3). Only the SSP clock gossip + heartbeats keep a socket bus
(minips_tpu/comm/bus.py).

Single-process (this sandbox) everything degrades to no-ops.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the cluster. Mirrors the reference's ``--my_id`` flag surface:
    pass explicit args or set JAX's standard env vars; single-process if
    neither is present."""
    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return  # single-process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def barrier(name: str = "minips_barrier", timeout_s: int = 120) -> None:
    """Cluster-wide barrier (reference Engine::Barrier, SURVEY.md §3.4)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
