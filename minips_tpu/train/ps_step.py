"""PSTrainStep — one fused SPMD program for dense + sparse tables.

The reference's hot loop does four round-trips per iteration: pull sparse
keys, pull dense weights, push sparse grads, push dense grads — each a zmq
hop through server threads (SURVEY.md §3.3). Here the whole iteration is ONE
jitted GSPMD program: shardings are annotated on the table state and batch,
and XLA inserts the collectives (all-gather for pulls, reduce-scatter for
dense pushes, gather/scatter collectives for embedding traffic) over ICI —
the "pick a mesh, annotate shardings, let the compiler insert collectives"
recipe (SURVEY.md §2.3; PAPERS.md arXiv 2004.13336 for the sharded weight
update).

User contract:
    loss_fn(dense_params, rows: dict[name, [B?, F?, dim]], batch) -> loss
    key_fns[name](batch) -> integer key array for that sparse table

The step differentiates through dense params and gathered rows, applies the
dense updater on the sharded flat vector and the row-wise sparse updater on
the touched slots — identical numerics to DenseTable.push /
SparseTable.push (shared ops in minips_tpu/ops/sparse_update.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from minips_tpu.parallel.mesh import DATA_AXIS
from minips_tpu.tables.dense import DenseTable, cast_floating
from minips_tpu.tables.sparse import SparseTable

PyTree = Any


class PSTrainStep:
    """Builds and runs the fused step; owns nothing — state stays in the
    tables, flowing through the jitted function with donation."""

    def __init__(
        self,
        loss_fn: Callable[..., jnp.ndarray],
        dense: Optional[DenseTable] = None,
        sparse: Optional[dict[str, SparseTable]] = None,
        key_fns: Optional[dict[str, Callable]] = None,
        compute_dtype: Optional[Any] = None,
        grad_scale: float = 1.0,
    ):
        """``compute_dtype`` (e.g. ``jnp.bfloat16``): run ``loss_fn`` in
        reduced precision — dense params, gathered sparse rows, and
        floating batch leaves are cast down before the loss, gradients are
        cast back to float32 before the sharded optimizer / row updates,
        and master table state stays float32 throughout (same contract as
        ``DenseTable.make_step(compute_dtype=...)``).

        ``grad_scale``: multiply all gradients by this constant before the
        updates while reporting the unscaled loss. The reference's server
        SUMS per-key contributions (``updater->Update`` adds each worker
        sample's gradient at full magnitude, SURVEY.md §3.3), so a
        batch-MEAN ``loss_fn`` underscales row updates by the batch size;
        ``grad_scale=batch_size`` restores per-sample update semantics
        (classic per-pair SGD, e.g. word2vec) without distorting the
        logged loss. Note: adagrad rows are invariant to any constant
        gradient scale (the accumulator normalizes it away up to eps), so
        this knob only changes SGD-updated tables and the dense path's
        scale-sensitive optimizers."""
        self.compute_dtype = (None if compute_dtype is None
                              else jnp.dtype(compute_dtype))
        if grad_scale <= 0:
            raise ValueError(f"grad_scale must be > 0, got {grad_scale}")
        self.grad_scale = float(grad_scale)
        self.loss_fn = loss_fn
        self.dense = dense
        self.sparse = sparse or {}
        self.key_fns = key_fns or {}
        if "dense" in self.sparse:
            raise ValueError(
                "'dense' is a reserved state key; rename the sparse table")
        missing = set(self.sparse) - set(self.key_fns)
        if missing:
            raise ValueError(f"sparse tables missing key_fns: {missing}")
        if dense is None and not self.sparse:
            raise ValueError("PSTrainStep needs a dense table and/or at "
                             "least one sparse table")
        self._mesh = (dense.mesh if dense is not None
                      else next(iter(self.sparse.values())).mesh)
        self._jit_step = self._build()

    # ------------------------------------------------------------------ build
    def _collect_state(self) -> dict:
        state: dict = {}
        if self.dense is not None:
            state["dense"] = (self.dense.params, self.dense.opt_state)
        for name, t in self.sparse.items():
            state[name] = (t.emb, t.opt_state())
        return state

    def _restore_state(self, state: dict) -> None:
        if self.dense is not None:
            self.dense.params, self.dense.opt_state = state["dense"]
        for name, t in self.sparse.items():
            t.emb, opt = state[name]
            t.set_opt_state(opt)

    def _build(self):
        dense = self.dense
        sparse = dict(self.sparse)
        key_fns = dict(self.key_fns)
        loss_fn = self.loss_fn
        mesh = self._mesh
        cd = self.compute_dtype
        gscale = self.grad_scale

        def step(state, batch):
            # ----- pull phase (differentiable views of table state)
            if dense is not None:
                p_flat, opt = state["dense"]
            cbatch = cast_floating(batch, cd)

            def compute_loss(p_flat_in, rows_in):
                dp = (cast_floating(
                          dense._unravel(p_flat_in[: dense.num_keys]), cd)
                      if dense is not None else None)
                return loss_fn(dp, cast_floating(rows_in, cd),
                               cbatch).astype(jnp.float32)

            slots = {}
            rows = {}
            for name, t in sparse.items():
                keys = key_fns[name](batch)
                slots[name] = t.slots_of(keys)
                rows[name] = state[name][0][slots[name]]

            if dense is not None:
                loss, (g_flat, g_rows) = jax.value_and_grad(
                    compute_loss, argnums=(0, 1))(p_flat, rows)
            else:
                loss, g_rows = jax.value_and_grad(
                    lambda rw: compute_loss(None, rw))(rows)
            if gscale != 1.0:
                g_rows = jax.tree.map(lambda g: g * gscale, g_rows)
                if dense is not None:
                    g_flat = g_flat * gscale

            new_state = dict(state)
            # ----- dense push: reduce-scatter + sharded optax update
            if dense is not None:
                g_flat = jax.lax.with_sharding_constraint(
                    g_flat, NamedSharding(mesh, P(DATA_AXIS)))
                updates, opt = dense.tx.update(g_flat, opt, p_flat)
                new_state["dense"] = (optax.apply_updates(p_flat, updates),
                                      opt)
            # ----- sparse pushes: row-wise updater on touched slots
            # (shared transition with SparseTable.push: t.row_update)
            for name, t in sparse.items():
                emb, opt = state[name]
                new_state[name] = t.row_update(emb, opt, slots[name],
                                               g_rows[name])
            return new_state, loss

        # un-jitted pure transition, exposed for scan-chained microbenching
        # (bench.py chains K steps in one dispatch to defeat host overhead)
        self.step_fn_pure = step
        return jax.jit(step, donate_argnums=(0,))

    # -------------------------------------------------------------------- run
    def __call__(self, batch) -> float:
        """Run one fused step against the tables' live state. The batch
        should already be device_put with data-axis sharding (use
        ``shard_batch``)."""
        state = self._collect_state()
        new_state, loss = self._jit_step(state, batch)
        self._restore_state(new_state)
        return loss

    def shard_batch(self, batch: PyTree) -> PyTree:
        """device_put batch leaves sharded along the data axis (axis 0)."""
        sharding = NamedSharding(self._mesh, P(DATA_AXIS))
        return jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), sharding), batch)
