"""libsvm text format reader/writer — the reference's parser family
(SURVEY.md §2 "Data loading": libsvm/text parsers, LabeledSample).

Format: ``label idx:val idx:val ...`` per line (a9a/RCV1 ship this way —
BASELINE.json:7). The Python reader is vectorized per chunk; a C++ reader
(cpp/) accelerates the same contract when built (SURVEY.md §2.1 item 6) —
``read_libsvm`` transparently uses it when available.

Output is padded fixed-width arrays (idx [N, F], val [N, F], mask) because
TPU batches need static shapes; F = max features per row (or the given
cap, truncating the tail).
"""

from __future__ import annotations

import numpy as np


def write_libsvm(path: str, y: np.ndarray, idx: np.ndarray,
                 val: np.ndarray, mask: np.ndarray) -> None:
    with open(path, "w") as f:
        for r in range(len(y)):
            feats = " ".join(
                f"{int(i)}:{float(v):g}"
                for i, v, m in zip(idx[r], val[r], mask[r]) if m)
            f.write(f"{int(y[r])} {feats}\n")


def read_libsvm(path: str, max_features: int | None = None,
                use_native: bool = True, shared: bool = False):
    """Returns dict(y [N] float32, idx [N, F] int32, val [N, F] float32,
    mask [N, F] float32). ``shared=True``: under the launcher, only the
    host's local leader parses; colocated processes mmap the same copy
    (data/shm_store.py)."""
    if shared:
        from minips_tpu.data.shm_store import make_tag, shared_load

        tag = make_tag("libsvm", path, max_features)
        return shared_load(tag, lambda: read_libsvm(
            path, max_features, use_native=use_native, shared=False))
    if use_native:
        try:
            from minips_tpu.data.native import read_libsvm_native

            out = read_libsvm_native(path, max_features)
            if out is not None:
                return out
        except ImportError:
            pass
    with open(path) as f:
        return parse_libsvm_lines(f, max_features=max_features)


def parse_libsvm_lines(lines, max_features: int | None = None,
                       width: int | None = None) -> dict:
    """Parse an iterable of libsvm lines (str or bytes) into the same
    padded dict as :func:`read_libsvm`. ``width`` fixes the padded feature
    count — block-wise streaming (data/blocks.py) needs every block to
    produce the same static shape regardless of which rows landed in it."""
    rows = []
    for line in lines:
        if isinstance(line, bytes):
            line = line.decode()
        parts = line.split()
        if not parts:
            continue
        label = float(parts[0])
        pairs = [p.split(":") for p in parts[1:]]
        rows.append((label,
                     np.array([int(i) for i, _ in pairs], np.int32),
                     np.array([float(v) for _, v in pairs], np.float32)))
    n = len(rows)
    if width is None:
        width = max((len(r[1]) for r in rows), default=0)
        if max_features is not None:
            width = min(width, max_features)
    y = np.zeros(n, np.float32)
    idx = np.zeros((n, width), np.int32)
    val = np.zeros((n, width), np.float32)
    mask = np.zeros((n, width), np.float32)
    for r, (label, ii, vv) in enumerate(rows):
        y[r] = label
        k = min(len(ii), width)
        idx[r, :k] = ii[:k]
        val[r, :k] = vv[:k]
        mask[r, :k] = 1.0
    # normalize labels {-1,1} -> {0,1} (a9a convention)
    if y.size and y.min() < 0:
        y = (y > 0).astype(np.float32)
    return {"y": y, "idx": idx, "val": val, "mask": mask}


def parse_libsvm_block(data: bytes, width: int,
                       use_native: bool = True,
                       where: str = "<bytes>") -> dict:
    """Parse a raw bytes chunk of whole libsvm lines to the padded block
    schema at fixed ``width`` — the distributed block path's parser
    (data/blocks.py assigns byte ranges; this reads each once and parses
    natively, ~6x the Python line loop; the Python path stays as
    fallback/oracle)."""
    if use_native:
        try:
            from minips_tpu.data.native import parse_libsvm_bytes

            out = parse_libsvm_bytes(data, width, where=where)
            if out is not None:
                return out
        except ImportError:
            pass
    return parse_libsvm_lines(data.splitlines(), width=width)


def detect_one_based(data: dict) -> bool:
    """True iff every present feature index is >= 1 — the canonical
    libsvm convention (a9a/RCV1 index from 1)."""
    present = data["mask"] > 0
    return bool(present.any() and data["idx"][present].min() >= 1)


def apply_one_based_shift(data: dict) -> dict:
    """Shift present indices down by one (masked padding stays 0), in
    place. Callers that decide once per FILE (block streaming) pair this
    with :func:`detect_one_based` on a head sample."""
    present = data["mask"] > 0
    data["idx"] = np.where(present, data["idx"] - 1, 0).astype(np.int32)
    return data


def shift_one_based(data: dict) -> dict:
    """Canonical libsvm files (a9a/RCV1) index features from 1; the
    framework's key spaces are 0-based. If every present index is >= 1,
    shift down by one (masked padding cells stay 0). Without this, densify
    at dim=D silently drops feature D of a 1-based file. Returns the same
    dict, modified in place."""
    if detect_one_based(data):
        apply_one_based_shift(data)
    return data


def densify(data: dict, dim: int) -> dict:
    """Sparse rows -> dense [N, dim] matrix (the LR-on-a9a dense-ified
    minimum slice, SURVEY.md §7.3)."""
    n, width = data["idx"].shape
    X = np.zeros((n, dim), np.float32)
    rows = np.repeat(np.arange(n), width)
    cols = data["idx"].reshape(-1)
    vals = (data["val"] * data["mask"]).reshape(-1)
    # cols >= 0 too: a mistaken one-based shift of a 0-based row yields
    # idx -1, and numpy would silently wrap it into column dim-1
    keep = (cols >= 0) & (cols < dim)
    np.add.at(X, (rows[keep], cols[keep]), vals[keep])
    return {"x": X, "y": data["y"]}
