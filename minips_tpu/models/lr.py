"""Logistic regression — the reference's ``lr_example`` workload
(BASELINE.json:3,7: LR on a9a/RCV1, sparse push/pull).

Two forms, both pure functions suitable for the fused table steps:

- **dense**: ``X [B, D]`` against a dense weight table (a9a dense-ified —
  SURVEY.md §7.3's minimum end-to-end slice).
- **sparse**: libsvm-style ``(idx [B, F], val [B, F], pad mask)`` against a
  hashed SparseTable of per-feature weights — the reference's sparse
  push/pull path where only the batch's feature ids travel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init(dim: int, bias: bool = True):
    p = {"w": jnp.zeros((dim,), jnp.float32)}
    if bias:
        p["b"] = jnp.zeros((), jnp.float32)
    return p


def logits_dense(params, X):
    out = X @ params["w"]
    if "b" in params:
        out = out + params["b"]
    return out


def bce_with_logits(logits, y):
    # numerically-stable binary cross entropy; y in {0, 1}
    return jnp.mean(jnp.logaddexp(0.0, logits) - y * logits)


def loss_dense(params, batch):
    X, y = batch["x"], batch["y"]
    return bce_with_logits(logits_dense(params, X), y)


def grad_fn_dense(params, batch):
    """(loss, grads) for DenseTable.make_step."""
    loss, grads = jax.value_and_grad(loss_dense)(params, batch)
    return loss, grads


def logits_sparse(w_rows, vals, mask, bias=0.0):
    """w_rows [B, F, 1] gathered weights; vals [B, F] feature values;
    mask [B, F] 1 for real features, 0 for padding."""
    return jnp.sum(w_rows[..., 0] * vals * mask, axis=-1) + bias


def loss_sparse(w_rows, batch, bias=0.0):
    return bce_with_logits(
        logits_sparse(w_rows, batch["val"], batch["mask"], bias), batch["y"])
