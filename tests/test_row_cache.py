"""Clock-versioned client row cache + deduplicated pull/push wires
(train/sharded_ps.py tentpole).

Fast tier, threads-as-nodes over real loopback buses: the dedup wire
ships unique keys and scatters correctly; a cache hit is served without
wire traffic exactly while the SSP admission predicate admits its stamp;
pushes keep read-your-own-writes (write-through for sgd/f32, invalidate
for stateful/quantized); the LRU byte bound evicts; prefetches populate
and consult the same cache; push-side dedup pays quantization once per
row; and pull_all's wire parity with pull() is pinned.
"""

import time

import numpy as np
import pytest

from minips_tpu.ops.quantized_comm import quantize_rows_int8
from minips_tpu.train.sharded_ps import RowCache, ShardedTable


def _mk_buses(n):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n)


class Cons:
    """Controllable admission stub: my clock, my staleness, and the
    min-view I serve replies under (serving_clock)."""

    def __init__(self, clock=0, staleness=0, gmin=0):
        self.clock = clock
        self.staleness = staleness
        self.gmin = gmin

    def admit_pull(self, clk):
        from minips_tpu.consistency.gate import admits

        return admits(self.gmin, clk, self.staleness)

    def serving_clock(self, requester):
        return self.gmin


# ------------------------------------------------------- dedup pull wire
def test_pull_dedup_ships_unique_keys_and_scatters():
    """A batch with duplicate keys round-trips each unique key ONCE; the
    reply scatters back to request order — same rows the verbatim wire
    returned, a third of the bytes on a 3x-duplicated batch."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        keys = np.array([40, 41, 40, 3, 40, 41])  # 40 x3, 41 x2, 3 local
        rows = t0.pull(keys)
        for i, k in enumerate(keys):
            expect = t1._w[k - 32] if k >= 32 else t0._w[k]
            np.testing.assert_array_equal(rows[i], expect)
        # wire: 2 unique remote keys out (8B each) + 2 rows back (16B)
        assert t0.bytes_pulled == 2 * 8 + 2 * 16
        s = t0.timers.summary()
        assert s["pull_rows_requested"] == 6
        assert s["pull_rows_wire"] == 2
        assert s["pull_rows_local"] == 4  # 3 dupes + 1 own-shard row
    finally:
        for b in buses:
            b.close()


def test_pull_dedup_off_restores_verbatim_wire():
    """The bench's A/B baseline: pull_dedup=False ships every occurrence
    (the seed wire), and refuses to combine with the cache."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0,
                      pull_dedup=False)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = 5.0
        rows = t0.pull(np.array([40, 40, 40]))
        np.testing.assert_allclose(rows, 5.0)
        assert t0.bytes_pulled == 3 * 8 + 3 * 16  # all three occurrences
    finally:
        for b in buses:
            b.close()
    with pytest.raises(ValueError, match="pull_dedup"):
        ShardedTable("t", 8, 2, None, 0, 1, cache_bytes=1024,
                     pull_dedup=False)
    with pytest.raises(ValueError, match="cache_bytes"):
        ShardedTable("t", 8, 2, None, 0, 1, cache_bytes=-1)
    # async push can trail a later pull with no client-side marker —
    # the cache refuses the combination (docs/consistency.md)
    with pytest.raises(ValueError, match="async_push"):
        ShardedTable("t", 8, 2, None, 0, 1, cache_bytes=1024,
                     async_push=True)


# ----------------------------------------------------------- cache hits
def test_cache_hit_is_exactly_the_admission_window():
    """The tentpole's contract: a cached row is served while
    admits(stamp, clk, s) holds and re-fetched the moment it does not —
    the stamp carries the staleness proof, clock by clock."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0,
                      cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)
    c0 = Cons(clock=5, staleness=1, gmin=5)
    c1 = Cons(clock=5, staleness=1, gmin=5)
    t0.bind_consistency(c0)
    t1.bind_consistency(c1)
    try:
        t1._w[...] = 7.0
        keys = np.array([40, 41])
        t0.pull(keys)                      # miss: fetched, stamped gmin=5
        reqs = t0._req
        c0.clock = 6                       # next step; 5 >= 6-1 still ok
        np.testing.assert_allclose(t0.pull(keys), 7.0)
        assert t0._req == reqs, "valid cached rows went to the wire"
        c0.clock = 7                       # 5 < 7-1: window closed
        c1.gmin = 7                        # owner will serve + restamp
        t1._w[...] = 9.0
        np.testing.assert_allclose(t0.pull(keys), 9.0)
        # +2: a wire pull allocates a group id AND a per-leg id
        assert t0._req == reqs + 2, "expired rows must re-fetch"
        st = t0.cache_stats()
        assert st["hits"] == 2 and st["lookups"] == 6
    finally:
        for b in buses:
            b.close()


def test_cache_mixed_hit_miss_single_wire_leg():
    """A batch that is part hit / part miss ships ONLY the misses."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0,
                      cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = 3.0
        t0.pull(np.array([40]))            # cache row 40
        b0 = t0.bytes_pulled
        rows = t0.pull(np.array([40, 41, 40]))  # 41 is the only miss
        np.testing.assert_allclose(rows, 3.0)
        assert t0.bytes_pulled == b0 + 8 + 16  # one key out, one row in
    finally:
        for b in buses:
            b.close()


def test_tick_ages_and_finalize_clears():
    """tick() drops rows that can never be admitted again; finalize
    clears outright (post-finalize agreement is exact). Driven through
    the table-level hooks the trainer calls."""
    t = ShardedTable("t", 64, 4, None, 0, 1, cache_bytes=1 << 16)
    cons = Cons(clock=0, staleness=1)
    t.bind_consistency(cons)
    t._cache.insert(np.array([1, 2]), np.zeros((2, 4), np.float32), 3)
    t._cache.insert(np.array([3]), np.zeros((1, 4), np.float32), 9)
    cons.clock = 5
    t.cache_age()   # stamp 3 < 5-1 dies; stamp 9 survives
    assert len(t._cache) == 1
    t.cache_clear()
    assert len(t._cache) == 0


# --------------------------------------------------- push read-your-writes
def test_push_write_through_sgd_f32_tracks_server_bitwise():
    """sgd over the f32 push wire WRITE-THROUGHS: a cache hit after my
    own push returns bitwise the row a synchronous pull would (dup keys
    summed in the same np.add.at order the server uses)."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, updater="sgd", lr=0.3,
                      pull_timeout=10.0, cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, updater="sgd", lr=0.3,
                      pull_timeout=10.0)
    try:
        t1._w[...] = np.random.default_rng(0).normal(
            size=(32, 4)).astype(np.float32)
        keys = np.array([40, 40, 41])
        t0.pull(np.array([40, 41]))        # fill cache (f32 wire: exact)
        g = np.random.default_rng(1).normal(size=(3, 4)).astype(np.float32)
        w_before = t1._w[40 - 32].copy()
        t0.push(keys, g)
        deadline = time.time() + 5         # wait for the owner to apply
        while time.time() < deadline \
                and np.array_equal(t1._w[40 - 32], w_before):
            time.sleep(0.02)
        reqs = t0._req
        rows = t0.pull(np.array([40, 41]))
        assert t0._req == reqs, "write-through rows should still hit"
        np.testing.assert_array_equal(rows[0], t1._w[40 - 32])
        np.testing.assert_array_equal(rows[1], t1._w[41 - 32])
        assert t0._cache.write_throughs == 2
    finally:
        for b in buses:
            b.close()


@pytest.mark.parametrize("kw", [{"updater": "adagrad"},
                                {"updater": "sgd", "push_comm": "int8"}])
def test_push_invalidates_when_delta_not_reproducible(kw):
    """Stateful updaters (server-side accumulator decides the step) and
    quantized pushes (wire noise) cannot write through — the touched
    rows invalidate, and the next pull round-trips fresh."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0,
                      cache_bytes=1 << 16, **kw)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, pull_timeout=10.0, **kw)
    try:
        t1._w[...] = 2.0
        t0.pull(np.array([40, 41]))
        t0.push(np.array([40]), np.ones((1, 4), np.float32))
        time.sleep(0.3)
        reqs = t0._req
        t0.pull(np.array([40, 41]))
        assert t0._req == reqs + 2         # 40 invalidated: re-fetched
        assert t0._cache.invalidations == 1
        b0 = t0.bytes_pulled
        t0.pull(np.array([41]))            # 41 untouched: still cached
        assert t0.bytes_pulled == b0
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------------------ LRU bound
def test_lru_byte_bound_evicts_oldest_first():
    c = RowCache(dim=4, cache_bytes=3 * 16)  # room for exactly 3 rows
    c.insert(np.array([1, 2, 3]), np.ones((3, 4), np.float32), 0)
    c.lookup(np.array([1]), 0, 0)            # touch 1: now 2 is LRU
    c.insert(np.array([4]), np.ones((1, 4), np.float32), 0)
    assert c.evictions == 1 and len(c) == 3
    _, miss = c.lookup(np.array([1, 2, 3, 4]), 0, 0)
    np.testing.assert_array_equal(miss, [False, True, False, False])
    assert c.nbytes == 3 * 16


def test_cache_off_by_default():
    t = ShardedTable("t", 8, 2, None, 0, 1)
    assert t._cache is None and t.cache_stats() is None
    t.cache_age()    # hooks are no-ops, not crashes
    t.cache_clear()


# ------------------------------------------------------------- prefetch
def test_prefetch_populates_and_consults_the_same_cache():
    """The prefetch path rides the same cache under the same stamp rule:
    a prefetch fills it, and a prefetch whose keys all hit issues NO
    wire traffic while its wait() still returns the rows."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, pull_timeout=10.0,
                      cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, pull_timeout=10.0)
    try:
        t1._w[...] = 4.0
        keys = np.array([40, 41])
        fut = t0.prefetch_pull(keys, clock_ahead=0)
        np.testing.assert_allclose(fut.wait(), 4.0)   # populates cache
        b0 = t0.bytes_pulled
        fut2 = t0.prefetch_pull(keys, clock_ahead=0)  # fully cached
        assert t0.bytes_pulled == b0
        np.testing.assert_allclose(fut2.wait(), 4.0)
        # a future-stamped prefetch checks the cache AT ITS OWN CLOCK:
        # under s=0 a stamp-0 row cannot satisfy clock 1 — must miss
        t0.bind_consistency(Cons(clock=0, staleness=0, gmin=0))
        t1.bind_consistency(Cons(clock=0, staleness=0, gmin=1))
        fut3 = t0.prefetch_pull(keys)                 # stamped clock 1
        assert t0.bytes_pulled > b0, "stale-for-tomorrow row hit anyway"
        fut3.cancel()
    finally:
        for b in buses:
            b.close()


# ------------------------------------------------- push dedup (satellite)
def test_push_all_unique_unsorted_keys_pair_correctly():
    """Review regression: an all-unique batch in NON-sorted key order
    must keep every (key, grad) pair intact — the no-duplicates
    shortcut once paired SORTED unique keys with request-order grads,
    scrambling every gradient-row association (and the cache
    write-through with it)."""
    t = ShardedTable("t", 64, 2, None, 0, 1, updater="sgd", lr=1.0)
    keys = np.array([5, 2, 40])              # unsorted, no duplicates
    grads = np.array([[1.0, 1.0], [100.0, 100.0], [7.0, 7.0]],
                     np.float32)
    t.push(keys, grads)
    np.testing.assert_allclose(t._w[5], -1.0)
    np.testing.assert_allclose(t._w[2], -100.0)
    np.testing.assert_allclose(t._w[40], -7.0)
    # same pairing through the cache write-through path
    t2 = ShardedTable("t", 64, 2, None, 0, 1, updater="sgd", lr=1.0,
                      cache_bytes=1 << 12)
    t2._cache.insert(keys, np.zeros((3, 2), np.float32), 0)
    t2.push(keys, grads)
    rows, miss = t2._cache.lookup(keys, 0, 0)
    assert not miss.any()
    np.testing.assert_allclose(rows[:, 0], [-1.0, -100.0, -7.0])


def test_push_dense_poisons_inflight_cache_inserts():
    """Review regression: push_dense touches EVERY row, so a pull in
    flight across it must not re-populate the cache with possibly
    pre-push rows — the dense push journals a broken floor the insert
    honors, on top of clearing the live cache."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 4, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, cache_bytes=1 << 12)
    t1 = ShardedTable("t", 4, 2, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)

    class Gate:
        ok = False
        clock = 0
        staleness = 0

        def admit_pull(self, clk):
            return self.ok

        def serving_clock(self, requester):
            return 0

    g1 = Gate()
    t1.bind_consistency(g1)
    try:
        t1._w[...] = 5.0
        fut = t0.prefetch_pull(np.array([2, 3]), clock_ahead=0)  # parked
        time.sleep(0.2)
        t0.push_dense(np.ones((4, 2), np.float32))  # in-flight dense
        time.sleep(0.2)
        g1.ok = True
        t1.serve_parked()
        fut.wait()
        _, miss = t0._cache.lookup(np.array([2, 3]), 0, 0)
        assert miss.all(), "in-flight rows re-entered a dense-cleared cache"
    finally:
        for b in buses:
            b.close()


def test_push_dedup_f32_matches_unsummed_wire_to_rounding():
    """Regression vs the seed's unsummed f32 wire: client-side
    coalescing lands the state the server-side sum produced, to f32
    rounding — the client accumulates per-dim in f64 (bincount), which
    is at least as accurate as the server's old sequential f32 sum and
    can differ from it only in the last ulp of 3+-occurrence keys.
    Keys without duplicates are bitwise-untouched."""
    rng = np.random.default_rng(3)
    keys = np.array([5, 9, 5, 9, 9, 11])
    grads = rng.normal(size=(6, 4)).astype(np.float32)
    t_ref = ShardedTable("t", 64, 4, None, 0, 1, updater="sgd", lr=0.3)
    t_ded = ShardedTable("t", 64, 4, None, 0, 1, updater="sgd", lr=0.3)
    t_ref._apply_rows(keys, grads)     # the server-side (unsummed) path
    t_ded.push(keys, grads)            # client dedup + local apply
    np.testing.assert_allclose(t_ref._w, t_ded._w, rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(t_ref._w[11], t_ded._w[11])  # no dup


def test_push_dedup_off_restores_per_occurrence_wire():
    """The seed-wire A/B lever, push leg: push_dedup=False ships every
    occurrence (the server still sums, so state is unchanged)."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 2, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, push_dedup=False)
    t1 = ShardedTable("t", 64, 2, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)
    try:
        t0.push(np.array([40, 40, 40]), np.ones((3, 2), np.float32))
        deadline = time.time() + 5
        while time.time() < deadline and not t1._w[8].any():
            time.sleep(0.02)
        assert t0.bytes_pushed == 3 * (8 + 8)  # all three occurrences
        np.testing.assert_allclose(t1._w[40 - 32], -3.0)
    finally:
        for b in buses:
            b.close()


def test_push_dedup_int8_pays_quantization_once_per_row():
    """Regression vs the per-occurrence wire: k duplicate rows now
    quantize as ONE summed row, so the error versus the f32 oracle is
    bounded by a single quantization step of the SUM — the unsummed
    wire's worst case is k steps (and its rounding draws never cancel
    deterministically)."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 8, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, push_comm="int8")
    t1 = ShardedTable("t", 64, 8, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, push_comm="int8")
    try:
        k = 5
        g = np.full((k, 8), 0.37, np.float32)
        keys = np.full(k, 40)
        t0.push(keys, g)
        deadline = time.time() + 5
        while time.time() < deadline and not t1._w[40 - 32].any():
            time.sleep(0.02)
        expect = -g.sum(0)                  # f32 oracle (lr=1 sgd)
        step = np.abs(g.sum(0)).max() / 127.0
        assert np.all(np.abs(t1._w[40 - 32] - expect) <= step + 1e-7), \
            (t1._w[40 - 32], expect)
        # exactly one row on the wire: 8B key + 4B scale + 8B codes
        assert t0.bytes_pushed == 8 + 4 + 8
    finally:
        for b in buses:
            b.close()


def test_inflight_pull_insert_drops_pushed_keys():
    """Read-your-own-writes across the in-flight window (review
    finding): a prefetch issued BEFORE a push may be served by the
    owner on either side of that push — immediately (reply lacks the
    delta) or from the park after it applied (reply includes it). The
    client cannot tell which, so the cache insert must DROP the pushed
    key instead of storing a row that might silently miss this
    worker's own update; untouched keys still cache."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0, cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, updater="sgd", lr=1.0,
                      pull_timeout=10.0)
    # park the pull at the owner so the push provably lands in between
    class Gate:
        ok = False
        clock = 0
        staleness = 0

        def admit_pull(self, clk):
            return self.ok

        def serving_clock(self, requester):
            return 0

    g1 = Gate()
    t1.bind_consistency(g1)
    try:
        t1._w[...] = 5.0
        keys = np.array([40, 41])
        fut = t0.prefetch_pull(keys, clock_ahead=0)  # parked at owner
        time.sleep(0.2)
        t0.push(np.array([40]), np.ones((1, 4), np.float32))  # interim
        deadline = time.time() + 5
        while time.time() < deadline and t1._w[8, 0] == 5.0:
            time.sleep(0.02)                 # owner applied: 5 -> 4
        g1.ok = True
        t1.serve_parked()                    # NOW the pull is served
        rows = fut.wait()
        np.testing.assert_allclose(rows[1], 5.0)
        # the future's result reflects serve-time server state (4.0 —
        # this parked serve happened after the push applied)...
        np.testing.assert_allclose(rows[0], 4.0)
        # ...but the pushed key must NOT have been cached (ambiguous
        # window), while the untouched key 41 was
        _, miss = t0._cache.lookup(np.array([40, 41]), 0, 0)
        assert miss[0], "ambiguous in-flight row entered the cache"
        assert not miss[1]
        # the next pull of 40 round-trips once and caches cleanly
        reqs = t0._req
        np.testing.assert_allclose(t0.pull(np.array([40]))[0], 4.0)
        assert t0._req == reqs + 2  # one group + one leg id
        _, miss = t0._cache.lookup(np.array([40]), 0, 0)
        assert not miss[0]
    finally:
        for b in buses:
            b.close()


def test_inflight_pull_insert_drops_invalidated_rows():
    """Same window, invalidate regime (adagrad): rows pushed while the
    pull was in flight must NOT enter the cache at all — the client
    cannot reconstruct the server's accumulator step."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 4, buses[0], 0, 2, updater="adagrad",
                      pull_timeout=10.0, cache_bytes=1 << 16)
    t1 = ShardedTable("t", 64, 4, buses[1], 1, 2, updater="adagrad",
                      pull_timeout=10.0)

    class Gate:
        ok = False
        clock = 0
        staleness = 0

        def admit_pull(self, clk):
            return self.ok

        def serving_clock(self, requester):
            return 0

    g1 = Gate()
    t1.bind_consistency(g1)
    try:
        t1._w[...] = 5.0
        fut = t0.prefetch_pull(np.array([40, 41]), clock_ahead=0)
        time.sleep(0.2)
        t0.push(np.array([40]), np.ones((1, 4), np.float32))
        time.sleep(0.2)
        g1.ok = True
        t1.serve_parked()
        fut.wait()
        _, miss = t0._cache.lookup(np.array([40, 41]), 0, 0)
        assert miss[0], "invalidated-in-flight row entered the cache"
        assert not miss[1]                  # untouched row cached fine
    finally:
        for b in buses:
            b.close()


def test_write_through_requires_deduped_push_wire():
    """push_dedup=False ships per-occurrence rows the server re-sums in
    f32 — not necessarily bit-equal to the client's sum — so the cache
    must INVALIDATE on push instead of writing through."""
    t2 = ShardedTable("t", 64, 4, None, 0, 1, updater="sgd", lr=1.0,
                      cache_bytes=1 << 16, push_dedup=False)
    t2._cache.insert(np.array([7]), np.ones((1, 4), np.float32), 0)
    t2.push(np.array([7, 7]), np.ones((2, 4), np.float32))
    assert t2._cache.write_throughs == 0
    assert t2._cache.invalidations == 1
    _, miss = t2._cache.lookup(np.array([7]), 0, 0)
    assert miss[0]


# ------------------------------------------------------- BSP bitwise
def test_cache_on_off_bitwise_equal_under_bsp():
    """Under BSP, cache-on vs cache-off runs produce BITWISE-identical
    final weights: within a clock frame a hit returns exactly the bytes
    a wire pull would (no push intervened, or my own write-through is
    the server's op replayed), and across frames s=0 never serves.
    Deterministic lockstep over real loopback buses; disjoint per-rank
    key sets keep the cross-rank push/pull race out of the comparison;
    grads are a function of pulled rows so any read deviation would
    propagate into the weights."""
    def run(cache_bytes):
        buses = _mk_buses(2)

        class LockstepCons:  # shared lockstep clock vector (BSP: s = 0)
            clocks = [0, 0]
            staleness = 0

            def __init__(self, rank):
                self.rank = rank

            @property
            def clock(self):
                return self.clocks[self.rank]

            def admit_pull(self, clk):
                return min(self.clocks) >= clk

            def serving_clock(self, requester):
                return min(self.clocks)

        tables = [ShardedTable("t", 64, 2, buses[i], i, 2, updater="sgd",
                               lr=0.5, pull_timeout=10.0,
                               cache_bytes=cache_bytes)
                  for i in range(2)]
        LockstepCons.clocks = [0, 0]
        for i, t in enumerate(tables):
            t.bind_consistency(LockstepCons(i))
            t._w[...] = np.arange(32 * 2, dtype=np.float32
                                  ).reshape(32, 2) / 7.0
        # disjoint cross-shard keys: rank 0 works rows 33..47, rank 1
        # rows 1..15 — each rank's pushes touch only its OWN keys
        keysets = [np.array([33, 40, 33, 47]), np.array([1, 8, 1, 15])]
        try:
            for _ in range(4):
                rows = [tables[r].pull(keysets[r]) for r in (0, 1)]
                for r in (0, 1):  # second read, same frame: hits when on
                    again = tables[r].pull(keysets[r])
                    np.testing.assert_array_equal(again, rows[r])
                for r in (0, 1):
                    tables[r].push(keysets[r], 0.1 * rows[r] + 1.0)
                for r in (0, 1):  # read-own-writes, same frame
                    tables[r].pull(keysets[r])
                # FIFO barrier: a post-push pull on each link proves the
                # pushes applied before the next frame's reads
                tables[0].pull(np.array([32]))
                tables[1].pull(np.array([0]))
                LockstepCons.clocks[0] += 1
                LockstepCons.clocks[1] += 1
                for t in tables:
                    t.cache_age()
            return [t._w.copy() for t in tables]
        finally:
            for b in buses:
                b.close()

    w_off = run(cache_bytes=0)
    w_on = run(cache_bytes=1 << 16)
    for off, on in zip(w_off, w_on):
        np.testing.assert_array_equal(off, on)  # bitwise, not allclose


# ------------------------------------------------------- multi-process
@pytest.mark.slow
def test_cache_ssp_three_processes_trains_and_bounds_staleness():
    """The cache under a REAL SSP launcher run: training still
    converges, replicas agree after finalize, the s+1 transient skew
    bound holds, no frames drop — and the cache actually engages
    (hits > 0 under the zipf-ish sparse workload with write-through
    active)."""
    import sys

    from minips_tpu import launch
    res = launch.run_local_job(
        3, [sys.executable, "-m", "minips_tpu.apps.sharded_ps_example",
            "--iters", "40", "--model", "sparse", "--mode", "ssp",
            "--staleness", "2", "--cache-bytes", str(1 << 22)],
        base_port=None,
        env_extra={"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu"},
        timeout=240.0)
    assert all(r["event"] == "done" for r in res)
    for r in res:
        assert r["frames_dropped"] == 0, r
        assert r["wire_frames_lost"] == 0, r
        assert r["max_skew_seen"] <= 3, r  # s + 1 transient bound
        assert r["loss_last"] < r["loss_first"], r
        assert r["cache_bytes"] == 1 << 22, r  # knob echo
        cache = r["cache"]
        assert cache is not None and cache["hits"] > 0, cache
        # the done-line row-flow counters ride the timing record
        tm = r["timing"]
        assert tm["pull_rows_wire"] < tm["pull_rows_requested"], tm
    sums = [r["param_sum"] for r in res]
    assert max(sums) - min(sums) < 1e-4, sums


# ------------------------------------------- pull_all wire parity (audit)
def test_pull_all_ships_on_configured_wire():
    """Audit pin: pull_all rides the SAME configured pull wire as
    pull() — int8 shards decode within one codec step and the wire
    accounting counts compressed bytes. The cost accepted with it:
    post-finalize fingerprints agree within codec tolerance, not
    bitwise, because each rank's OWN shard stays exact f32 while
    peers' shards decode from int8 (docs/api.md)."""
    buses = _mk_buses(2)
    t0 = ShardedTable("t", 64, 8, buses[0], 0, 2, pull_timeout=10.0,
                      pull_wire="int8")
    t1 = ShardedTable("t", 64, 8, buses[1], 1, 2, pull_timeout=10.0,
                      pull_wire="int8")
    try:
        vals = np.random.default_rng(0).normal(
            size=(64, 8)).astype(np.float32)
        t0._w[...] = vals[:32]
        t1._w[...] = vals[32:]
        full0 = t0.pull_all()
        # compressed bytes: 32 remote rows x (4B scale + 8B codes)
        assert t0.bytes_pulled == 32 * (4 + 8)
        step = np.abs(vals).max(axis=1, keepdims=True) / 127.0
        assert np.all(np.abs(full0 - vals) <= step + 1e-6)
        # own shard exact, remote shard quantized — the documented trade
        np.testing.assert_array_equal(full0[:32], vals[:32])
        full1 = t1.pull_all()
        np.testing.assert_array_equal(full1[32:], vals[32:])
        assert np.all(np.abs(full0 - full1) <= 2 * step + 1e-6)
    finally:
        for b in buses:
            b.close()
