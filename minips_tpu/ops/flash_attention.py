"""Flash attention — fused blockwise causal attention for the LM family.

The reference has no attention at all (SURVEY.md §2.2: LR/MLP/MF/W&D/w2v);
the LM/transformer family is this rebuild's beyond-parity long-context
capability, and this module is its single-chip hot op. Two implementations
of the same exact math (softmax(QK^T)V, never materializing the [T, T]
score matrix in HBM):

- ``blockwise_attention`` — pure jnp, ``lax.scan`` over K/V chunks with
  online-softmax carry. Runs anywhere (CPU tests, TPU), differentiable by
  AD through the scan, O(T·block_k) live scores. This is the oracle-exact
  portable path and the backward function for the kernel below.

- ``flash_attention`` — Pallas TPU kernels. Forward: grid (batch, head,
  Q blocks, K blocks) with the K sweep innermost; the float32 online-
  softmax state (running max m, normalizer l, accumulator acc) lives in
  VMEM scratch across the sweep, blocks are pipelined HBM→VMEM by Pallas,
  scores exist only in VMEM, and the per-row logsumexp is written out for
  the backward. Backward (``jax.custom_vjp``): two kernels that recompute
  p = exp(s − lse) per block — dQ accumulates over the K sweep, dK/dV over
  the transposed Q sweep — so training memory stays O(T) and the [T, T]
  matrix never exists in either pass. Causal runs skip fully-masked blocks
  in all three kernels.

Measured on the one real chip here (2026-07-29, bf16, B=2 H=8 D=64,
T=8192): forward 5.8ms vs 12.4ms XLA full-scores; fwd+bwd 21ms vs 40ms;
end-to-end LM training (apps/lm_example --attn flash) 1.5x tokens/sec at
T=8192, and T=32768 works where full scores OOM HBM.

Layout matches the rest of the stack: q/k/v are ``[B, T, H, D]`` (the
ring-attention convention, parallel/ring_attention.py). The kernel wants
the sequence contiguous per (batch, head), so it transposes to
``[B, H, T, D]`` at the jit boundary — XLA fuses the transposes into the
surrounding program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pallas imports can fail on exotic backends; degrade to blockwise
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_NEG_INF = -1e30  # finite mask value (matches ring_attention) — avoids
                  # -inf arithmetic NaNs on fully-masked rows


# --------------------------------------------------------------- blockwise
def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_k: int = 256,
) -> jnp.ndarray:
    """Exact attention, scanning K/V in chunks of ``block_k``.

    q/k/v: [B, T, H, D]. Equals softmax(QK^T·scale)V to float tolerance;
    peak score memory is [B, Tq, block_k, H] instead of [B, Tq, Tk, H].
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if scale is None:
        scale = D ** -0.5
    bk = min(block_k, Tk)
    pad = (-Tk) % bk  # ragged tail: pad K/V and mask — never one full-width
    if pad:           # chunk, which would void the O(T*block_k) bound
        zeros = jnp.zeros((B, pad, H, D), k.dtype)
        k = jnp.concatenate([k, zeros], axis=1)
        v = jnp.concatenate([v, zeros], axis=1)
    masked = causal or pad
    nk = (Tk + pad) // bk
    qf = q.astype(jnp.float32)
    kc = k.astype(jnp.float32).reshape(B, nk, bk, H, D)
    vc = v.astype(jnp.float32).reshape(B, nk, bk, H, D)
    q_pos = jnp.arange(Tq)

    def fold(carry, blk):
        o, m, l = carry
        k_blk, v_blk, j = blk
        s = jnp.einsum("bqhd,bkhd->bqkh", qf, k_blk) * scale
        if masked:
            k_pos = j * bk + jnp.arange(bk)
            keep = k_pos[None, :] < Tk  # padding keys attend to nothing
            if causal:
                keep = keep & (q_pos[:, None] >= k_pos[None, :])
            s = jnp.where(keep[None, :, :, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=2))        # [B, Tq, H]
        p = jnp.exp(s - m_new[:, :, None, :])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=2)
        o = o * alpha[:, :, :, None] + jnp.einsum("bqkh,bkhd->bqhd", p, v_blk)
        return (o, m_new, l), None

    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    m0 = jnp.full((B, Tq, H), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Tq, H), jnp.float32)
    # Inside shard_map, fresh carries are axis-invariant while the folded
    # values vary over the mesh — pcast keeps the scan carry type fixed
    # (same VMA discipline as ring_attention_local).
    vma = tuple(sorted(getattr(jax.typeof(q), "vma", frozenset())))
    if vma:
        o0, m0, l0 = (jax.lax.pcast(x, vma, to="varying")
                      for x in (o0, m0, l0))
    (o, _, l), _ = jax.lax.scan(
        fold, (o0, m0, l0),
        (kc.swapaxes(0, 1), vc.swapaxes(0, 1), jnp.arange(nk)))
    return (o / jnp.maximum(l, 1e-30)[:, :, :, None]).astype(q.dtype)


# ----------------------------------------------------------- pallas kernel
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                  l_ref, *, scale, causal, num_k):
    # Grid (B, H, nQ, nK), K innermost and sequential on TPU: the online-
    # softmax state for one Q block lives in VMEM scratch across the nK
    # sweep. Blocks: q/o [1, 1, bq, D]; k/v [1, 1, bk, D]; lse [1, 1, bq].
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: K blocks wholly above the diagonal contribute nothing — skip
    # the matmuls (the block DMA still happens; compute dominates here)
    live = (j * bk <= (i + 1) * bq - 1) if causal else True

    @pl.when(live)
    def _fold():
        qb = q_ref[0, 0, :, :].astype(jnp.float32) * scale
        kb = k_ref[0, 0, :, :].astype(jnp.float32)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # [bq, 1]
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = (acc_ref[:] * alpha
                      + jnp.dot(p, vb, preferred_element_type=jnp.float32))
        m_ref[:] = m_new

    @pl.when(j == num_k - 1)
    def _write():
        l_safe = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0, 0, :, :] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # logsumexp per row — the backward recomputes p = exp(s - lse)
        lse_ref[0, 0, :, 0] = (m_ref[:] + jnp.log(l_safe))[:, 0]


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    """[B, T, H, D] in/out; kernel runs on [B, H, T, D]."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    grid = (B, H, Tq // bq, Tk // bk)
    # Inside shard_map the output type must declare which mesh axes it
    # varies over (VMA tracking); it varies exactly where the inputs do.
    vma = frozenset()
    for x in (q, k, v):
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())
    out, lse = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          num_k=Tk // bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype, vma=vma),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),   # acc
            pltpu.VMEM((bq, 1), jnp.float32),   # running max m
            pltpu.VMEM((bq, 1), jnp.float32),   # normalizer l
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                         dq_ref, dq_acc, *, scale, causal, num_k):
    # Grid (B, H, nQ, nK), K innermost; dQ for one Q block accumulates in
    # scratch across the K sweep. p is recomputed from the saved
    # logsumexp — the [T, T] matrix never exists.
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    i, j = pl.program_id(2), pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (j * bk <= (i + 1) * bq - 1) if causal else True

    @pl.when(live)
    def _fold():
        qb = q_ref[0, 0, :, :].astype(jnp.float32)
        kb = k_ref[0, 0, :, :].astype(jnp.float32)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        dob = do_ref[0, 0, :, :].astype(jnp.float32)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0, :, :])            # [bq, bk]
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0, :, :]) * scale
        dq_acc[:] = dq_acc[:] + jnp.dot(
            ds, kb, preferred_element_type=jnp.float32)

    @pl.when(j == num_k - 1)
    def _write():
        dq_ref[0, 0, :, :] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dvec_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                          num_q):
    # Grid (B, H, nK, nQ), Q innermost; dK/dV for one K block accumulate
    # in scratch across the Q sweep (the transposed iteration of dq).
    bq, bk = q_ref.shape[2], k_ref.shape[2]
    j, i = pl.program_id(2), pl.program_id(3)   # j: K block, i: Q block

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = ((i + 1) * bq - 1 >= j * bk) if causal else True

    @pl.when(live)
    def _fold():
        qb = q_ref[0, 0, :, :].astype(jnp.float32)
        kb = k_ref[0, 0, :, :].astype(jnp.float32)
        vb = v_ref[0, 0, :, :].astype(jnp.float32)
        dob = do_ref[0, 0, :, :].astype(jnp.float32)
        s = jnp.dot(qb, kb.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0, :, :])            # [bq, bk]
        dv_acc[:] = dv_acc[:] + jnp.dot(
            p.T, dob, preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dvec_ref[0, 0, :, :]) * scale
        dk_acc[:] = dk_acc[:] + jnp.dot(
            ds.T, qb, preferred_element_type=jnp.float32)

    @pl.when(i == num_q - 1)
    def _write():
        dk_ref[0, 0, :, :] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret):
    """dQ/dK/dV via the two backward kernels; [B, T, H, D] layout."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq = min(block_q, Tq)
    bk = min(block_k, Tk)
    qt, kt, vt, dot = (x.transpose(0, 2, 1, 3) for x in (q, k, v, g))
    # D_i = rowsum(dO * O) — tiny elementwise reduce; XLA fuses it
    dvec = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1).transpose(0, 2, 1)[..., None]      # [B, H, Tq, 1]
    vma = frozenset()
    for x in (q, k, v, g):
        vma = vma | getattr(jax.typeof(x), "vma", frozenset())

    q_spec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0))
    row_spec = pl.BlockSpec((1, 1, bq, 1), lambda b, h, i, j: (b, h, i, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal,
                          num_k=Tk // bk),
        grid=(B, H, Tq // bq, Tk // bk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype, vma=vma),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, dvec)

    # transposed grid: K outer, Q inner
    q_spec_t = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    kv_spec_t = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))
    row_spec_t = pl.BlockSpec((1, 1, bq, 1), lambda b, h, j, i: (b, h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, scale=scale,
                          causal=causal, num_q=Tq // bq),
        grid=(B, H, Tk // bk, Tq // bq),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tk, D), k.dtype, vma=vma),
            jax.ShapeDtypeStruct((B, H, Tk, D), v.dtype, vma=vma),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, dvec)
    return (dq.transpose(0, 2, 1, 3), dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)[0]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def kernel_supported(q_shape, k_shape, block_q: int, block_k: int) -> bool:
    """Static shape gate for the Pallas path: block sizes must tile the
    sequence (no ragged tails in the kernel) and D should be lane-friendly."""
    if not _HAS_PALLAS:
        return False
    B, Tq, H, D = q_shape
    Tk = k_shape[1]
    bq, bk = min(block_q, Tq), min(block_k, Tk)
    return Tq % bq == 0 and Tk % bk == 0 and D % 8 == 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused attention; same signature/semantics as
    ``ring_attention.reference_attention`` but never materializes the full
    score matrix. Uses the Pallas kernel on TPU (or ``interpret=True``
    anywhere, for tests); otherwise the blockwise scan — both exact.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = False
        use_kernel = (kernel_supported(q.shape, k.shape, block_q, block_k)
                      and jax.default_backend() == "tpu")
    else:
        use_kernel = kernel_supported(q.shape, k.shape, block_q, block_k)
    if use_kernel:
        return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return blockwise_attention(q, k, v, causal=causal, scale=scale,
                               block_k=block_k)
