"""Fail-slow plane (obs/slowness.py + serve/hedge.py + the membership
slow quorum + the rebalancer demote pass) — this PR's tentpole.

Three layers of drill, mirroring the partition plane's test shape:

- pure logic: MINIPS_SLOW / MINIPS_HEDGE spec parsing (+ seeded
  fuzzers: parse or ValueError, never a half-configured plane), the
  lower-median rule, and the SlownessMonitor judgment under an
  injected clock — suspicion after N consecutive windows, retraction
  on recovery, the 2-fleet/one-peer honest limit, the min_ms floor,
  observer-stall forgiveness, and the slow-quorum reuse of
  ``quorum_needed`` (a single complainer never convicts);
- threads-as-nodes over real loopback buses with a seeded ``slow#``
  link tax: hedged pull legs fire against replica holders, win, lose
  by rid, stay budget-bounded, keep every read inside the admission
  bound, and leave bitwise-agreeing finals — while the LATE loser
  replies still feed the slowness monitor (the hedge must not erase
  the evidence that indicts the sick rank);
- armed-idle: the BSP lockstep drill with hedging armed on a clean
  wire is BITWISE equal to off (the SLOW-IDLE claim), and a seeded
  sub-threshold ``delay@`` latency arms nothing (the false-positive
  ladder's first rung).

The full quorum-verdict → demotion → flight-post-mortem story is
pinned by the ``fail_slow_3proc`` bench sweep's SLOW-HEDGE /
SLOW-DRAIN gates (ci/bench_regression.py) and the slow-tier drill at
the bottom.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from minips_tpu.balance.control_plane import (SuspicionQuorum,
                                              quorum_needed)
from minips_tpu.obs.slowness import (SlownessConfig, SlownessMonitor,
                                     lower_median)
from minips_tpu.serve.hedge import HedgeConfig
from minips_tpu.train.sharded_ps import ShardedPSTrainer, ShardedTable


def _mk_buses(n, **kw):
    from tests.conftest import mk_loopback_buses

    return mk_loopback_buses(n, **kw)


# ------------------------------------------------------------- configs
def test_hedge_config_parses_and_refuses():
    c = HedgeConfig.parse("delay_ms=30,factor=4,min_ms=10,budget=2")
    assert (c.delay_ms, c.factor, c.min_ms, c.budget) == (30, 4, 10, 2)
    d = HedgeConfig.parse("1")
    assert d.delay_ms == 0 and d.budget >= 1 and d.min_ms > 0
    assert HedgeConfig.parse("") is None
    assert HedgeConfig.parse("0") is None
    for bad, frag in {"explode=1": "unknown knob",
                      "delay_ms": "k=v",
                      "delay_ms=abc": "bad value",
                      "min_ms=0": "min_ms",
                      "factor=0.5": "factor",
                      "budget=0": "budget",
                      "delay_ms=-1": "delay_ms"}.items():
        with pytest.raises(ValueError, match=frag):
            HedgeConfig.parse(bad)


def test_slow_config_parses_and_refuses():
    c = SlownessConfig.parse("factor=2.5,windows=4,window=6,min_ms=5,"
                             "min_samples=3,demote=8,drain_after=10,"
                             "stall=1.5")
    assert (c.factor, c.windows, c.window, c.min_ms, c.min_samples,
            c.demote, c.drain_after, c.stall) \
        == (2.5, 4, 6, 5, 3, 8, 10, 1.5)
    d = SlownessConfig.parse("1")
    assert d.factor > 1 and d.windows >= 1 and d.drain_after == 0
    assert SlownessConfig.parse("") is None
    assert SlownessConfig.parse("0") is None
    for bad, frag in {"explode=1": "unknown knob",
                      "factor": "k=v",
                      "factor=abc": "bad value",
                      "factor=1.0": "factor",
                      "windows=0": "windows",
                      "min_samples=0": "min_samples",
                      "demote=0.5": "demote",
                      "drain_after=-1": "drain_after",
                      "stall=-1": "stall"}.items():
        with pytest.raises(ValueError, match=frag):
            SlownessConfig.parse(bad)


def test_fail_slow_knob_fuzzers_parse_or_refuse_loudly():
    """Satellite: the hedge/demote knob grammars share the chaos-spec
    fuzzer contract — seeded random specs from the alphabet parse or
    raise ValueError, deterministically, never a half-configured
    plane."""
    rng = np.random.default_rng(20260804)
    keys = {"hedge": ["delay_ms", "factor", "min_ms", "budget",
                      "bogus"],
            "slow": ["factor", "windows", "window", "min_ms",
                     "min_samples", "demote", "drain_after", "stall",
                     "bogus"]}
    vals = ["0", "1", "3", "2.5", "-1", "abc", "", "1e9", "0.5"]
    parsers = {"hedge": HedgeConfig.parse, "slow": SlownessConfig.parse}
    for which, parse in parsers.items():
        vocab = keys[which]
        for _ in range(200):
            n = int(rng.integers(0, 5))
            spec = ",".join(
                f"{vocab[rng.integers(0, len(vocab))]}"
                f"={vals[rng.integers(0, len(vals))]}"
                for _ in range(n))
            outcomes = []
            for _rep in range(2):
                try:
                    c = parse(spec)
                    outcomes.append(("ok", c is None))
                except ValueError as e:
                    outcomes.append(("refused", str(e)))
                except Exception as e:  # noqa: BLE001 - the contract
                    pytest.fail(f"{which} spec {spec!r} raised "
                                f"{type(e).__name__}: {e}")
            assert outcomes[0] == outcomes[1], spec


def test_lower_median_anchors_on_the_healthy_half():
    assert lower_median([]) is None
    assert lower_median([5.0]) == 5.0
    assert lower_median([1.0, 100.0]) == 1.0   # n=2: the healthy one
    assert lower_median([1.0, 2.0, 100.0]) == 2.0
    assert lower_median([1.0, 2.0, 3.0, 100.0]) == 2.0


# ------------------------------------------------- detection judgment
def _mk_monitor(nprocs=3, rank=0, clock=None, **kw):
    cfg = SlownessConfig(**{"factor": 3.0, "windows": 2, "window": 2,
                            "min_ms": 5.0, "min_samples": 2, **kw})
    return SlownessMonitor(rank, nprocs, cfg,
                           clock=clock or time.monotonic)


def test_slowness_suspects_after_n_windows_and_retracts():
    sm = _mk_monitor()
    log: list = []
    sm.on_slow = lambda p, s: log.append((p, s))
    for _ in range(4):  # peer 1 slow (200ms), peer 2 healthy (1ms)
        for _s in range(3):
            sm.note(1, 0.200)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == {1}
    assert log[-1] == (1, True)
    assert sm.counters["suspects_raised"] == 1
    # recovery: the suspect's window falls back under the bar — the
    # suspicion RETRACTS (a slow verdict is never sticky)
    for _ in range(4):
        for _s in range(3):
            sm.note(1, 0.001)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == set()
    assert log[-1] == (1, False)
    assert sm.counters["suspects_retracted"] == 1


def test_one_consecutive_miss_resets_the_streak():
    # window=1: each roll is judged alone, so the alternation below
    # really does break the streak (a wider window would smear the
    # slow samples across rolls — correct, but not this test's claim)
    sm = _mk_monitor(windows=3, window=1)
    for i in range(5):
        for _s in range(3):
            # peer 1 alternates slow/fast: the streak never reaches 3
            sm.note(1, 0.200 if i % 2 == 0 else 0.001)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == set()


def test_single_peer_fleet_never_suspects():
    """The honest 2-fleet limit: one peer's p99 IS the median — no
    relative signal exists, so the monitor never suspects (mirror of
    the death quorum's 2-rank solo-conviction caveat, refused here
    because slowness has no binary ground truth to fall back on)."""
    sm = _mk_monitor(nprocs=2, rank=0)
    for _ in range(6):
        for _s in range(4):
            sm.note(1, 0.500)  # absurdly slow — and still no verdict
        sm.roll()
    assert sm.suspects == set()


def test_min_ms_floor_blocks_conviction():
    """Relative slowness BELOW the absolute floor is noise, not gray
    failure: 0.9ms vs 0.1ms is 9x the median and still healthy."""
    sm = _mk_monitor(min_ms=20.0)
    for _ in range(6):
        for _s in range(4):
            sm.note(1, 0.0009)
            sm.note(2, 0.0001)
        sm.roll()
    assert sm.suspects == set()


def test_no_evidence_retracts_standing_suspicion():
    """A window with fewer than min_samples has no evidence — no
    ballot: a standing suspicion retracts rather than coasting on
    stale windows (the death path owns total silence)."""
    sm = _mk_monitor()
    for _ in range(3):
        for _s in range(3):
            sm.note(1, 0.200)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == {1}
    for _ in range(3):  # evidence dries up entirely
        sm.roll()
    assert sm.suspects == set()


def test_observer_stall_forgiveness_rebaselines_and_retracts():
    now = [0.0]
    sm = _mk_monitor(stall=1.0, clock=lambda: now[0])
    for _ in range(3):
        for _s in range(3):
            sm.note(1, 0.200)
            sm.note(2, 0.001)
        now[0] += 0.1
        sm.roll()
    assert sm.suspects == {1}
    # the observer comas for 5s: every sample it took is undateable —
    # re-baseline, retract, count, judge nothing this boundary
    for _s in range(3):
        sm.note(1, 9.0)
        sm.note(2, 9.0)
    now[0] += 5.0
    sm.roll()
    assert sm.suspects == set()
    assert sm.counters["stall_forgiven"] == 1
    assert sm.stats()["streaks"] == {}


def test_retract_all_mirrors_heartbeat_forgiveness():
    sm = _mk_monitor()
    log: list = []
    sm.on_slow = lambda p, s: log.append((p, s))
    for _ in range(3):
        for _s in range(3):
            sm.note(1, 0.200)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == {1}
    sm.retract_all()
    assert sm.suspects == set() and (1, False) in log
    assert sm.counters["stall_forgiven"] == 1


def test_heartbeat_stall_fires_slow_retraction_hook(monkeypatch):
    """comm/heartbeat.py: a FORGIVEN sweep (the PR12 stall= window)
    fires ``on_stall_forgiven`` — the membership plane wires it to
    ``SlownessMonitor.retract_all`` so a coma observer's slow ballots
    die with its death suspicions."""
    from minips_tpu.comm.heartbeat import HeartbeatMonitor

    monkeypatch.setenv("MINIPS_HEARTBEAT",
                       "interval=0.5,timeout=2.0,stall=1.0")

    class _Bus:
        my_id = 0

        def on(self, *a):
            pass

        def publish(self, *a, **k):
            pass

    now = [0.0]
    mon = HeartbeatMonitor(_Bus(), [0, 1, 2], clock=lambda: now[0])
    fired = []
    mon.on_stall_forgiven = lambda: fired.append(True)
    mon.check()          # baseline sweep
    now[0] += 0.6
    mon.check()          # normal cadence: no forgiveness
    assert not fired
    now[0] += 5.0        # coma past the stall budget
    mon.check()
    assert fired and mon.stall_forgiven == 1


def test_exclude_drops_peer_and_retracts():
    sm = _mk_monitor()
    log: list = []
    sm.on_slow = lambda p, s: log.append((p, s))
    for _ in range(3):
        for _s in range(3):
            sm.note(1, 0.200)
            sm.note(2, 0.001)
        sm.roll()
    assert sm.suspects == {1}
    sm.exclude(1)
    assert sm.suspects == set() and (1, False) in log
    sm.note(1, 0.2)  # post-exclusion notes are dropped, not resurrected
    sm.roll()
    assert "1" not in sm.stats()["p99_ms"]


def test_slow_quorum_single_complainer_never_convicts():
    """The quorum rung (satellite false-positive ladder): the slow
    verdict reuses the PR14 SuspicionQuorum + quorum_needed — one bad
    inbound link makes ONE complainer, and one ballot out of a 3-rank
    live view convicts nobody; the second corroborating ballot does."""
    live = {0, 1, 2}
    assert quorum_needed(live, 1) == 2
    q = SuspicionQuorum(0)
    q.mark_local(1, True)            # my ballot alone
    assert q.convictable(live) == []
    q.vote(2, [1])                   # the corroborating peer
    assert q.convictable(live) == [1]
    q.vote(2, [])                    # peer retracts (recovered)
    assert q.convictable(live) == []


# -------------------------------------------- hedged legs, in-proc
class _Cons:
    """Shared lockstep clock vector (the run_bsp_lockstep stub)."""

    def __init__(self, clocks, rank, staleness=1):
        self._clocks = clocks
        self.rank = rank
        self.staleness = staleness

    @property
    def clock(self):
        return self._clocks[self.rank]

    def admit_pull(self, clk):
        return min(self._clocks) >= clk - self.staleness

    def serving_clock(self, requester):
        return min(self._clocks)


def _run_fail_slow(n, body, *, chaos="", serve=None, hedge=None,
                   slow=None, staleness=2, rows=96, dim=2, steps=18,
                   pace=0.002):
    """Threads-as-nodes trainer run with the fail-slow knobs passed
    EXPLICITLY (no env) — the serving-harness shape of test_serve.py
    plus hedge/slow."""
    buses = _mk_buses(n, chaos=chaos)
    tables = [ShardedTable("t", rows, dim, buses[i], i, n,
                           updater="sgd", lr=1.0, pull_timeout=20.0)
              for i in range(n)]
    trainers = [ShardedPSTrainer({"t": tables[i]}, buses[i], n,
                                 staleness=staleness, gate_timeout=30.0,
                                 serve=serve, hedge=hedge, slow=slow)
                for i in range(n)]
    finals: list = [None] * n
    errs: list = []

    def worker(r):
        try:
            for i in range(steps):
                body(r, tables[r], trainers[r], i)
                trainers[r].tick()
                if pace:
                    time.sleep(pace)
            trainers[r].finalize(timeout=30.0)
            finals[r] = tables[r].pull_all()
        except Exception as e:  # noqa: BLE001 - surfaced via errs
            import traceback

            traceback.print_exc()
            errs.append((r, repr(e)))

    try:
        ts = [threading.Thread(target=worker, args=(r,))
              for r in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        assert not any(t.is_alive() for t in ts), "run wedged"
        assert not errs, errs
        return tables, trainers, finals
    finally:
        for b in buses:
            b.close()


def test_hedged_pull_beats_slow_owner_and_agrees():
    """The read-mitigation drill: rank 1's outbound frames pay a
    seeded 80ms link tax (slow-but-alive: nothing dies, nothing
    drops); with replicas + hedging armed, rank 0's legs to rank 1
    hedge to the replica holder and WIN, every consumed read respects
    the admission bound (stale_reads == 0), the slow owner's LATE
    loser replies still feed the slowness monitor, and the finals
    agree bitwise across all ranks."""
    hot = 32 + np.arange(8, dtype=np.int64)  # rank 1's shard

    def body(r, table, trainer, i):
        table.pull(hot)
        table.push(hot, np.ones((hot.size, table.dim), np.float32))

    tables, trainers, finals = _run_fail_slow(
        3, body, chaos="9:slow#1>0=80,slow#1>2=80",
        serve="replicas=1,hot=16,interval=0,min_heat=2,lease=3.0",
        hedge="delay_ms=20", slow="factor=3,windows=2,window=3,"
                                  "min_ms=10,min_samples=2")
    fired = sum(t.hedge_counters["fired"] for t in tables)
    won = sum(t.hedge_counters["won"] for t in tables)
    assert fired > 0, "no hedge ever fired against the slow owner"
    assert won > 0, "no hedge ever won (holders refused everything?)"
    for tr in trainers:
        rep = tr.serve_stats()["replica"]
        assert (rep or {}).get("stale_reads", 0) == 0
        assert tr.wire_frames_lost == 0
        assert tr.frames_dropped == 0
    # the LATE loser replies fed the detector: rank 0 measured rank 1
    # (cumulative per-peer summary — the drill is too short to demand
    # a windowed conviction, which the 3proc bench arm pins)
    sm0 = trainers[0].slowness
    assert sm0 is not None
    assert sm0.peer_summary(1)["count"] > 0, \
        "hedging erased the slow owner's latency evidence"
    np.testing.assert_array_equal(finals[0], finals[1])
    np.testing.assert_array_equal(finals[0], finals[2])


def test_hedge_budget_denies_and_no_holder_counts():
    """White-box: the budget valve refuses a hedge when the table's
    outstanding-hedge set is full (counted ``denied``), and a leg
    whose blocks no holder covers counts ``no_holder`` and is never
    re-probed. Uses a real slow leg held open by an 800ms link tax."""
    from minips_tpu.serve.hedge import HedgeConfig as _HC
    from minips_tpu.serve.plane import ServeConfig, TableServeState

    buses = _mk_buses(2, chaos="5:slow#1>0=800")
    clocks = [0, 0]
    try:
        ts = [ShardedTable("t", 64, 1, buses[i], i, 2,
                           pull_timeout=10.0) for i in range(2)]
        for i, t in enumerate(ts):
            t.bind_consistency(_Cons(clocks, i, staleness=1))
        t0 = ts[0]
        t0.attach_hedge(_HC(delay_ms=1.0, budget=1))
        t0._sv = TableServeState(t0, None, ServeConfig())  # no holders
        fut = t0._issue_pull(np.array([40, 41], np.int64), 0)
        time.sleep(0.02)  # the leg is now overdue (delay_ms=1)
        t0._hedges_live.add(999999)  # budget exhausted by a twin
        t0._maybe_hedge(fut._req)
        assert t0.hedge_counters["denied"] == 1
        assert t0.hedge_counters["fired"] == 0
        t0._maybe_hedge(fut._req)   # a shed, not a queue: counted
        assert t0.hedge_counters["denied"] == 1  # ONCE, never re-probed
        t0._hedges_live.clear()     # (else the wait loop busy-wakes)
        fut.wait()                  # the slow reply eventually lands
        # a fresh overdue leg with budget free but NO holder coverage
        # counts the no-replica ceiling, once
        fut2 = t0._issue_pull(np.array([42, 43], np.int64), 0)
        time.sleep(0.02)
        t0._maybe_hedge(fut2._req)
        assert t0.hedge_counters["no_holder"] == 1
        t0._maybe_hedge(fut2._req)  # marked hedged: not re-probed
        assert t0.hedge_counters["no_holder"] == 1
        fut2.wait()
        # NO serve plane attached at all: the overdue leg still takes
        # the no_holder path — marked + counted, so the wait loop
        # cannot busy-wake at the 1ms floor forever
        t0._sv = None
        fut3 = t0._issue_pull(np.array([44], np.int64), 0)
        time.sleep(0.02)
        t0._maybe_hedge(fut3._req)
        assert t0.hedge_counters["no_holder"] == 2
        fut3.wait()
    finally:
        for b in buses:
            b.close()


def test_armed_idle_hedge_is_bitwise_equal_to_off():
    """SLOW-IDLE: hedging armed on a clean wire fires nothing (the
    min_ms floor) and the run is bitwise-identical to off — the
    lockstep harness, the same oracle every transport/fault layer
    pins against."""
    from tests.test_chaos_reliable import run_bsp_lockstep

    w_off, _ = run_bsp_lockstep()
    w_on, lost = run_bsp_lockstep(hedge="1")
    assert lost == [0, 0]
    for a, b in zip(w_off, w_on):
        np.testing.assert_array_equal(a, b)  # bitwise, not allclose


def test_sub_threshold_delay_arms_nothing():
    """False-positive ladder: seeded ``delay@`` latency BELOW the
    hedge threshold and the suspicion floor arms neither plane — no
    hedges, no suspects, bitwise finals."""
    hot = np.arange(8, dtype=np.int64)

    def body(r, table, trainer, i):
        table.pull(hot)
        table.push(hot, np.ones((hot.size, table.dim), np.float32))

    tables, trainers, finals = _run_fail_slow(
        3, body, chaos="7:delay=1.0,delay_ms=4",
        serve="replicas=1,hot=16,interval=0,min_heat=2,lease=3.0",
        hedge="delay_ms=60",
        slow="factor=3,windows=2,window=3,min_ms=30,min_samples=2")
    assert sum(t.hedge_counters["fired"] for t in tables) == 0
    for tr in trainers:
        assert tr.slowness.suspects == set()
        assert tr.slowness.counters["suspects_raised"] == 0
    np.testing.assert_array_equal(finals[0], finals[1])
    np.testing.assert_array_equal(finals[0], finals[2])


def test_demote_pass_moves_sick_blocks_with_threshold_unreachable():
    """balance/rebalancer.plan_assignment via the demote pass's
    calling convention: a 3-rank fleet with EQUAL loads can never
    clear a ratio threshold of 3 by biasing one rank (tops out at
    3b/(2+b) < 3) — the demote pass's threshold-1.0 call with
    sick-only candidates must still move the sick rank's blocks, and
    plan_assignment's gap rule must bound it."""
    from minips_tpu.balance.rebalancer import plan_assignment

    loads = np.array([100.0, 100.0, 100.0])
    cands = {7: (1, 30.0), 9: (1, 20.0), 3: (0, 25.0)}
    # the heat pass at threshold=3: balanced fleet, nothing moves
    assert plan_assignment(loads, dict(cands), 3.0, 8) == []
    # the demote pass: bias rank 1 by 4, restrict to its candidates,
    # threshold 1.0 — its hot blocks move off, none of rank 0's do
    biased = loads.copy()
    biased[1] *= 4.0
    sick_only = {b: ih for b, ih in cands.items() if ih[0] == 1}
    moves = plan_assignment(biased, sick_only, 1.0, 8)
    assert moves and all(src == 1 for _b, src, _d in moves)
    assert {b for b, *_ in moves} <= {7, 9}


def test_wire_record_carries_fail_slow_blocks():
    """Done-line schema: hedge/slowness are None when off (vs zeroed
    when armed-but-idle) — the off-vs-idle convention."""
    from minips_tpu.utils.metrics import wire_record

    hot = np.arange(4, dtype=np.int64)

    def body(r, table, trainer, i):
        table.pull(hot)
        table.push(hot, np.ones((hot.size, table.dim), np.float32))

    _t, trainers, _f = _run_fail_slow(2, body, steps=3)
    rec = wire_record(trainers[0])
    assert rec["hedge"] is None and rec["slowness"] is None
    _t, trainers, _f = _run_fail_slow(
        2, body, steps=3, hedge="delay_ms=50", slow="1")
    rec = wire_record(trainers[0])
    assert rec["hedge"]["fired"] == 0 and rec["hedge"]["budget"] >= 1
    assert rec["slowness"]["suspects"] == []
    assert rec["slowness"]["rolls"] >= 3


# ------------------------------------------------ slow tier: e2e drill
@pytest.mark.slow
def test_e2e_3proc_fail_slow_demote_drill():
    """ACCEPTANCE (the bench demote arm's twin): a seeded slow# link
    tax on rank 1, detection + hedging + demotion armed — the quorum
    convicts the sick rank, the rebalancer migrates >= 1 hot block off
    it, zero steps are lost, zero frames are unrecovered, and the
    survivors' finals agree bitwise."""
    import json
    import sys

    from minips_tpu import launch

    env = {"MINIPS_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
           "MINIPS_CHAOS": "11:slow#1>0=40,slow#1>2=40~8",
           "MINIPS_SERVE": ("replicas=1,hot=200,topk=200,"
                            "interval=0.05,min_heat=1"),
           "MINIPS_HEDGE": "delay_ms=15",
           "MINIPS_SLOW": ("factor=3,windows=2,window=5,min_ms=15,"
                           "min_samples=2,demote=4"),
           "MINIPS_ELASTIC": "1",
           "MINIPS_REBALANCE": ("block=2048,threshold=3,interval=0.3,"
                                "min_heat=1"),
           "MINIPS_HEARTBEAT": "interval=0.1,timeout=2.0",
           "MINIPS_RELIABLE": "", "MINIPS_TRACE": "", "MINIPS_OBS": "",
           "MINIPS_FLIGHT": "", "MINIPS_AUTOSCALE": "",
           "MINIPS_BUS": "", "MINIPS_CHAOS_KILL": ""}
    iters = 40
    res = launch.run_local_job(
        3, [sys.executable, "-m",
            "minips_tpu.apps.sharded_ps_example",
            "--model", "sparse", "--mode", "ssp", "--staleness", "2",
            "--iters", str(iters), "--batch", "64",
            "--storm-from", "2", "--storm-until", str(iters),
            "--storm-pulls", "6", "--storm-keys", "64"],
        base_port=None, env_extra=env, timeout=240.0)
    assert all(d.get("event") == "done" for d in res), \
        json.dumps([d.get("event") for d in res])
    assert min(d["clock"] for d in res) == iters  # zero lost steps
    assert sum(d.get("wire_frames_lost", 0) for d in res) == 0
    assert len({d["param_sum"] for d in res}) == 1  # bitwise
    assert sum((d.get("chaos") or {}).get("slowed", 0)
               for d in res) > 0, "the injector never engaged"
    assert sum((d.get("membership") or {}).get("slow_verdicts", 0)
               for d in res) >= 1, "the quorum never convicted"
    assert (res[1].get("rebalance") or {}).get("blocks_out", 0) >= 1, \
        "no hot block migrated off the sick rank"
    assert sum((d.get("hedge") or {}).get("fired", 0)
               for d in res) > 0
