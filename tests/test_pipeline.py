"""GPipe pipeline parallelism: stage-sharded transformer vs the unsharded
oracle (logits + grads), plus the generic schedule on a toy stage_fn.

Beyond parity (reference has no PP, SURVEY.md §2.2)."""

import functools

import jax

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from minips_tpu.utils.jaxcompat import shard_map
from minips_tpu.models import transformer as tfm
from minips_tpu.parallel.mesh import make_mesh
from minips_tpu.parallel.pipeline import gpipe, stack_layers, unstack_layers

CFG = dict(vocab=29, dim=16, heads=2, depth=4, max_len=32)
F32 = dict(compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def mesh_pp():
    # 2 data x 4 model: pipeline over the 4-way model axis
    return make_mesh(2, model_size=4)


@pytest.fixture(scope="module")
def params():
    return tfm.init(jax.random.PRNGKey(0), **CFG)


def _stacked(params):
    return {**params, "blocks": stack_layers(params["blocks"])}


def _toks(B, T, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG["vocab"], (B, T)), jnp.int32)


def test_stack_roundtrip(params):
    s = stack_layers(params["blocks"])
    back = unstack_layers(s)
    f1, _ = jax.flatten_util.ravel_pytree(params["blocks"])
    f2, _ = jax.flatten_util.ravel_pytree(back)
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))


def test_gpipe_schedule_identity():
    """With stage_fn = (x -> x + own-stage constant), the pipeline output
    is x + sum of constants, for every microbatch — the schedule routes
    every microbatch through every stage exactly once."""
    mesh = make_mesh(1, model_size=4)
    consts = jnp.arange(4.0)  # one per stage

    def run(x_mb, c):
        def shard_fn(x_, c_):
            return gpipe(lambda h: h + c_[0], x_, axis_name="model")
        return shard_map(
            shard_fn, mesh=mesh, in_specs=(P(), P("model")),
            out_specs=P())(x_mb, c)

    x = jnp.arange(3 * 2 * 2, dtype=jnp.float32).reshape(3, 2, 2)
    out = run(x, consts)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) + 6.0)


@pytest.mark.parametrize("M", [1, 2, 4])
def test_pp_logits_match_full(mesh_pp, params, M):
    tokens = _toks(4, 16)
    want = tfm.apply(params, tokens, heads=CFG["heads"], **F32)
    sp = _stacked(params)
    specs = tfm.pp_specs(sp)
    got = shard_map(
        lambda p, t: tfm.apply_pp(p, t, heads=CFG["heads"],
                                  num_microbatches=M, **F32),
        mesh=mesh_pp, in_specs=(specs, P()), out_specs=P())(sp, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow  # fast tier keeps pp logits parity (3 microbatch cfgs)
def test_pp_grad_matches_full(mesh_pp, params):
    toks = _toks(4, 17, seed=1)
    sp = _stacked(params)
    specs = tfm.pp_specs(sp)

    def pp_loss(p):
        def shard_fn(p_, t_):
            logits = tfm.apply_pp(p_, t_[:, :-1], heads=CFG["heads"],
                                  num_microbatches=2, **F32)
            logp = jax.nn.log_softmax(logits)
            return jnp.mean(
                -jnp.take_along_axis(logp, t_[:, 1:, None], axis=-1)[..., 0])
        return shard_map(shard_fn, mesh=mesh_pp,
                             in_specs=(specs, P()), out_specs=P())(p, toks)

    def full_loss(p):
        return tfm.loss(p, {"tokens": toks}, heads=CFG["heads"], **F32)

    l_pp, g_pp = jax.value_and_grad(pp_loss)(sp)
    l_f, g_f = jax.value_and_grad(full_loss)(params)
    assert abs(float(l_pp) - float(l_f)) < 1e-5
    # compare stacked grads against stacked full grads
    g_f_stacked = {**g_f, "blocks": stack_layers(g_f["blocks"])}
    f1, _ = jax.flatten_util.ravel_pytree(g_f_stacked)
    f2, _ = jax.flatten_util.ravel_pytree(g_pp)
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f1),
                               rtol=2e-4, atol=2e-4)


def test_pp_bad_microbatch_raises(mesh_pp, params):
    sp = _stacked(params)
    specs = tfm.pp_specs(sp)
    with pytest.raises(ValueError, match="microbatch"):
        shard_map(
            lambda p, t: tfm.apply_pp(p, t, heads=CFG["heads"],
                                      num_microbatches=3),
            mesh=mesh_pp, in_specs=(specs, P()), out_specs=P()
        )(sp, _toks(4, 8))


def test_pp_rope_logits_match_full(mesh_pp):
    """RoPE through the pipeline: the stage closure applies the rotation
    (the _forward wrap can't reach it) — logits must match the
    single-program oracle. depth=4 -> one block per stage."""
    p = tfm.init(jax.random.PRNGKey(12), vocab=CFG["vocab"], dim=32,
                 heads=4, depth=4, rope=True)
    tokens = _toks(4, 16, seed=12)
    want = tfm.apply(p, tokens, heads=4, **F32)
    sp = {**p, "blocks": stack_layers(p["blocks"])}
    specs = tfm.pp_specs(sp)
    got = shard_map(
        lambda q, t: tfm.apply_pp(q, t, heads=4, num_microbatches=2,
                                  **F32),
        mesh=mesh_pp, in_specs=(specs, P()), out_specs=P())(sp, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
