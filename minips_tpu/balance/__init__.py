"""Load balance + control plane for the sharded PS (train/sharded_ps.py).

Four cooperating modules, deliberately separable:

- :mod:`minips_tpu.balance.heat` — decayed per-key-block touch counters
  kept by every owner on its serve path (bounded memory, vectorized),
  the observability that makes range-partition skew measurable before
  it is fixed;
- :mod:`minips_tpu.balance.rebalancer` — the coordinator that collects
  per-shard heat, computes a new block→owner assignment (greedy
  bin-pack with hysteresis) and drives the epoch-fenced online
  migration through the tables' wire protocol (``MINIPS_REBALANCE``);
- :mod:`minips_tpu.balance.membership` — elastic membership over the
  same migration machinery: ranks join, drain, and die without killing
  the job (``MINIPS_ELASTIC``);
- :mod:`minips_tpu.balance.control_plane` +
  :mod:`minips_tpu.balance.autoscaler` — the production control plane:
  the coordinator as a LEASE with deterministic succession and
  term-fenced broadcasts, and the closed-loop autoscaler that drives
  membership from load signals (``MINIPS_AUTOSCALE``).

Knob reference in docs/api.md; protocol walkthroughs in
docs/architecture.md and docs/fault_tolerance.md.
"""

from minips_tpu.balance.heat import HeatAccountant
from minips_tpu.balance.rebalancer import (RebalanceConfig, Rebalancer,
                                           plan_assignment)

__all__ = ["HeatAccountant", "RebalanceConfig", "Rebalancer",
           "plan_assignment"]
